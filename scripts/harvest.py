"""Round-5 TPU window harvester: the WHOLE measurement ladder in ONE
tunnel claim.

Round 3's hard lesson: the axon tunnel granted exactly one ~6-minute
window in an entire round, and the one-item-per-process measurement
queue could land only a single bench number in it. This script instead
runs every queued measurement — the v5 phase attribution (VERDICT #1),
the four streaming A/Bs, the fleet shapes, a v4 ladder point and a
bookend repeat of the headline — inside one process, one backend
claim, emitting ONE JSON line per result (flushed immediately) so even
a partial window yields committed evidence.

Design rules (from rounds 2-3):
- Never kill this process mid-compile (a killed axon client can wedge
  the tunnel server); the outer watcher waits for natural exit.
- One axon claimant at a time (concurrent claimants starve each other
  on the relay).
- ``jax.block_until_ready`` does not block on the tunnel: every timed
  program reduces to a scalar and the harness forces the 4-byte
  device->host fetch (the only reliable sync).
- Trace-time kernel switches (CAUSE_TPU_SORT/GATHER/SEARCH/SCATTER)
  require
  ``jax.clear_caches()`` between configs or the A/B silently re-times
  the cached default program.

State: completed one-shot items are recorded in
``measurements/harvest_state_r5.json`` and skipped on later attempts;
the headline bench (``bench_v5``) is always re-measured — repetition
across windows is the point (VERDICT weak #1).

Round 5 adds an on-chip correctness gate (``verify_beststream``,
ADVICE.md #3): per-row avalanche digests of the full batch under the
pinned XLA baseline vs the beststream config. On MISMATCH it
attributes the culprit by re-digesting one switch at a time, and every
timing item whose config contains a suspect strategy is skipped for
the window (timing a wrong kernel is not evidence) — suspect skips
still count as attempted so the watcher can advance phases.

Usage: python -u scripts/harvest.py  [--smoke] [--allow-cpu]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path for checkout runs)

import argparse
import json
import os
import sys
import time

import numpy as np

T0 = time.monotonic()
# one id per harvest process = per window attempt; stamped into every
# result so decide_defaults can require same-window comparisons
RUN_ID = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()) + f"-{os.getpid()}"
STATE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "measurements", "harvest_state_r5.json",
)
# bump when an item NAME keeps its meaning but its config/kernel
# changes (round-5 review finding: stale done/results entries from an
# older definition must not certify a config that was never verified)
STATE_VERSION = 2

from cause_tpu import obs  # noqa: E402  (dependency-light, no jax)
from cause_tpu.switches import TRACE_SWITCHES as SWITCHES  # noqa: E402

# Every item pins the FULL switch set explicitly ("xla" = force the
# XLA-default lowering), so the ladder keeps measuring true baselines
# even after chip wins are flipped into switches.TPU_DEFAULTS —
# otherwise single-switch A/Bs would silently become winner-vs-winner
# (round-4 review finding). Module-level so the watcher derives its
# phase-2 env from here instead of restating it (drift trap).
XLA_BASE = {k: "xla" for k in SWITCHES}


def cfg_of(**over):
    out = dict(XLA_BASE)
    out.update(over)
    return out


def flips_of(cfg) -> dict:
    """The non-xla switch subset of a config — the one projection the
    certified-cfg plumbing (verify records, bench records,
    decide_defaults, certified_env) must agree on."""
    return {k: v for k, v in cfg.items() if v != "xla"}


def persisted_suspects(results) -> set:
    """Digest-gate culprits carried by certification records (the
    MATCH-REDUCED path stores the strategies its reduction dropped).
    Re-seeded at attempt start: a reduced certification puts
    verify_beststream in ``done``, so later windows run NO
    re-derivation — an unseeded gate would then time and permanently
    record the contradicted strategy (round-5 session-2 review
    finding). A later full MATCH overwrites the record and clears
    them; a MISMATCH pops the record, and the re-verify that follows
    re-derives suspects fresh."""
    out: set = set()
    for rec in results.values():
        if isinstance(rec, dict):
            out.update(rec.get("suspects", []))
    return out


ALLSTREAM = cfg_of(CAUSE_TPU_SORT="bitonic",
                   CAUSE_TPU_GATHER="rowgather",
                   CAUSE_TPU_SEARCH="matrix")
# The headline candidate CONFIG the watcher/bench ride when certified:
# XLA-ONLY streaming strategies. Round-5 window-1 evidence
# (measurements/harvest_tpu_r5.log): every Mosaic kernel submitted to
# this tunnel's remote compile helper either crashes it (HTTP 500,
# subprocess exit 1 — v5f, fphase) or HANGS it indefinitely (the
# pallas sort wedged bench_psort for 30+ min of open window). Mosaic
# -flavored items therefore sit behind HARVEST_TRY_MOSAIC=1 below, and
# the certifiable beststream contains no Mosaic strategy.
from cause_tpu.switches import BESTSTREAM_FLIPS  # noqa: E402

BESTSTREAM = cfg_of(**BESTSTREAM_FLIPS)
# CAUSE_TPU_SORT=matrix (round-5 session 2): the blocked rank-count
# sort (weaver/matsort.py) — the pure-XLA replacement for the
# comparator sorts phase E's chip profile indicts, now that the
# Mosaic pallas sort is unmeasurable here. If its digest gate fails
# on chip, verify_beststream's reduced-set fallback re-certifies the
# combination without it (the certified cfg rides the state file).
# the aspirational full-Mosaic config (VMEM-resident pallas sort +
# fused F-phase), measurable only where the compile helper supports
# Mosaic — opt in with HARVEST_TRY_MOSAIC=1
MOSAICSTREAM = cfg_of(CAUSE_TPU_SORT="pallas",
                      CAUSE_TPU_GATHER="rowgather",
                      CAUSE_TPU_SEARCH="matrix-table",
                      CAUSE_TPU_SCATTER="hint",
                      CAUSE_TPU_FPHASE="pallas")
# strategy pairs that require a Mosaic kernel compile — a DENYLIST of
# specific values (flip strings), not a restated config: the ladder
# still builds every config from BESTSTREAM_FLIPS/cfg_of
MOSAIC_VALUES = {"CAUSE_TPU_SORT=pallas", "CAUSE_TPU_FPHASE=pallas",  # causelint: disable=TID002 -- denylist of Mosaic values, not a config copy
                 "euler=walk", "kernel=v5f"}
TRY_MOSAIC = os.environ.get("HARVEST_TRY_MOSAIC", "").strip() == "1"


def emit(**obj):
    obj["t"] = round(time.monotonic() - T0, 1)
    obj["utc"] = time.strftime("%H:%M:%S", time.gmtime())
    print(json.dumps(obj), flush=True)
    # every ladder decision doubles as a structured obs event (no-op
    # unless CAUSE_TPU_OBS/--obs-out is on): certify/revoke/skip lines
    # carry the cfg and digests that justified them, so a soak log
    # opens in Perfetto with full provenance instead of raw prints
    obs.event("harvest." + str(obj.get("ev", "emit")),
              **{k: v for k, v in obj.items()
                 if k not in ("ev", "t", "utc")})


def load_state() -> tuple:
    """(done item-name set, per-item results dict). Results accumulate
    across windows; a STATE_VERSION mismatch discards everything (the
    old entries certified item definitions that no longer exist)."""
    try:
        with open(STATE_PATH) as f:
            data = json.load(f)
        if data.get("version") != STATE_VERSION:
            return set(), {}
        done = set(data["done"])
        # shipped defaults must re-certify every window: once the
        # defaults file exists, verify_beststream is never "done"
        # (round-5 review finding: a certification must not outlive
        # its evidence — without this, a post-certification kernel
        # regression would ship wrong results forever)
        if os.path.exists(defaults_file_path()):
            done.discard("verify_beststream")
        results = dict(data.get("results", {}))
        # a certification that did not record WHICH cfg it checked
        # (records from code predating the cfg field) is not
        # actionable: the static BESTSTREAM may have gained strategies
        # since, and timing/shipping them under the old verdict would
        # be certification drift — force a re-verify instead
        if not (results.get("verify_beststream") or {}).get("cfg"):
            done.discard("verify_beststream")
        return done, results
    except Exception:  # noqa: BLE001 - missing/corrupt state = fresh
        return set(), {}


def save_state(done: set, results: dict) -> None:
    os.makedirs(os.path.dirname(STATE_PATH), exist_ok=True)
    with open(STATE_PATH, "w") as f:
        json.dump({"version": STATE_VERSION, "done": sorted(done),
                   "results": results}, f)


def set_config(cfg: dict) -> None:
    """Flip the trace-time kernel switches and drop every cached traced
    program (module-level jit caches key on avals only — see bench.py's
    allstream note). No-op when the switches already match — most
    ladder transitions are default->default, and a needless
    clear_caches would recompile identical programs mid-window."""
    import jax

    current = {k: os.environ[k] for k in SWITCHES if k in os.environ}
    if current == cfg:
        return
    for k in SWITCHES:
        os.environ.pop(k, None)
    os.environ.update(cfg)
    jax.clear_caches()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="run the ladder on the CPU backend (rehearsal)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--obs-out", default="",
                    help="stream structured obs events (JSONL) to this"
                         " path — future soak logs over raw prints")
    a = ap.parse_args()
    if a.obs_out:
        obs.configure(enabled=True, out=a.obs_out)

    # defend against stale switches inherited from a caller's env: every
    # measurement here names its config explicitly
    for k in SWITCHES:
        os.environ.pop(k, None)

    # ---- host marshal BEFORE the backend claim (round-5 window
    # economy): ~60-90 s of pure numpy that must not spend granted
    # tunnel time — the axon claim is in flight from interpreter
    # start, so this overlaps the claim wait
    from cause_tpu import benchgen
    from cause_tpu.benchgen import (
        LANE_KEYS4,
        LANE_KEYS5,
        enable_compile_cache,
        merge_wave_scalar,
    )
    from cause_tpu.weaver.jaxw5 import merge_weave_kernel_v5

    if a.smoke:
        B, NB, ND, CAP = 8, 800, 100, 1024
    else:
        B, NB, ND, CAP = 1024, 9_000, 1_000, 10_240

    t0 = time.monotonic()
    batch = benchgen.batched_pair_lanes(
        n_replicas=B, n_base=NB, n_div=ND, capacity=CAP, hide_every=8
    )
    v5batch = benchgen.batched_v5_inputs(batch, CAP)
    u_budget = benchgen.v5_token_budget(v5batch)
    budget = benchgen.pair_run_budget(batch)
    emit(ev="marshal", ms=round((time.monotonic() - t0) * 1000, 1))

    # delta-native arms (PR 7), still pre-claim: window-only marshals
    # for the low/high bench_delta timing items (same document shape,
    # divergence-sized windows) plus a B=64 full+window subset for the
    # verify_delta digest gate — small enough that the gate's full
    # -kernel control compiles in a fraction of the headline compile
    t0 = time.monotonic()
    ND_LOW = max(1, min(25, ND))
    dsw_low = benchgen.delta_sweep_inputs(
        B, NB + ND - ND_LOW, ND_LOW, CAP, hide_every=8,
        include_full=False)
    dsw_high = benchgen.delta_sweep_inputs(
        B, NB, ND, CAP, hide_every=8, include_full=False)
    dsw_verify = benchgen.delta_sweep_inputs(
        min(64, B), NB, ND, CAP, hide_every=8)
    emit(ev="marshal_delta",
         ms=round((time.monotonic() - t0) * 1000, 1),
         wcap_low=dsw_low["wcap"], wcap_high=dsw_high["wcap"])

    # merge-tree fleet (PR 8), still pre-claim: REAL replica handles
    # (the flat-fold baseline must materialize through them) built
    # entirely jax-free — tree_fleet_handles weaves the shared base
    # with the PURE host weaver, so this marshal spends no granted
    # tunnel time and cannot init a wedged backend. ~10 s of host
    # Python, so a resumed run whose tree items are already done
    # skips it (the lazy fallback below covers any state drift).
    if a.smoke:
        TREE_N, TREE_NB, TREE_ND = 8, 400, 6
    else:
        TREE_N, TREE_NB, TREE_ND = 64, 10_000, 24
    _tree_fleet_cache: list = []

    def tree_fleet():
        if not _tree_fleet_cache:
            t0 = time.monotonic()
            _tree_fleet_cache.append(benchgen.tree_fleet_handles(
                TREE_N, TREE_NB, TREE_ND, hide_every=8))
            emit(ev="marshal_tree",
                 ms=round((time.monotonic() - t0) * 1000, 1),
                 replicas=TREE_N, doc=1 + TREE_NB + TREE_ND)
        return _tree_fleet_cache[0]

    _done_preview, _ = load_state()
    if not {"verify_tree", "bench_tree"} <= _done_preview:
        tree_fleet()  # pre-claim build (window economy)

    # Bounded backend claim (shared guard; see claimguard docstring):
    # hard-exit if the tunnel claim wedges past HARVEST_CLAIM_DEADLINE,
    # disarmed before any compile can be in flight.
    import claimguard

    os.environ.setdefault("HARVEST_CLAIM_DEADLINE", "3300")
    claim_disarm = claimguard.arm("harvest")

    import jax
    import jax.numpy as jnp

    if a.allow_cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        enable_compile_cache()

    # ---- backend confirm (the blocking tunnel claim happens here) ----
    plat = jax.devices()[0].platform
    claim_disarm()  # BEFORE any compile can be in flight
    obs.set_platform(plat)
    emit(ev="backend", platform=plat)
    if plat == "cpu" and not a.allow_cpu:
        emit(ev="abort", reason="cpu backend without --allow-cpu")
        sys.exit(2)
    np.asarray(jax.jit(lambda x: x + 1)(jnp.ones(8)))
    emit(ev="alive", platform=plat)

    done, results = load_state()
    reps = a.reps
    # a CPU rehearsal or a smoke-shape run must not mark ladder items
    # done: the state file gates what a real full-size window measures
    record_state = plat != "cpu" and not a.smoke

    # ---- one upload serving every full-size item --------------------
    t0 = time.monotonic()
    dev = {k: jax.device_put(batch[k])
           for k in dict.fromkeys(LANE_KEYS4)}
    for k in LANE_KEYS5:
        if k not in dev:
            dev[k] = jax.device_put(v5batch[k])
    for v in dev.values():
        v.block_until_ready()  # best effort; the sync below is real
    np.asarray(jnp.sum(dev["hi"][0, :8]))  # real sync: upload done
    emit(ev="upload", ms=round((time.monotonic() - t0) * 1000, 1),
         u_budget=int(u_budget), run_budget=int(budget))

    class _Overflow(RuntimeError):
        pass

    # budgets validated against the overflow flag by a completed
    # bench_item at this shape (overflow is data-dependent only — the
    # trace-time switches never change token/run counts — so one
    # validation per kernel family covers every config)
    validated_k: dict = {}
    # strategies that failed the on-chip digest gate this attempt,
    # keyed as "SWITCH=value" pairs ("euler=walk" for the v5w/v4w
    # kernels) — bare values would collide ("pallas" names both the
    # sort and the fphase strategy) and wrongly quarantine the other;
    # items whose config uses a suspect pair are skipped-as-attempted
    suspect_values: set = persisted_suspects(results)
    skipped_suspect: set = set()

    def effective_values(kernel, cfg) -> set:
        """The strategy pairs an item actually runs with: the explicit
        cfg, plus — for switches the cfg leaves unset (shipped-default
        items use cfg={}) — the backend defaults switches.resolve()
        would apply on TPU. Without the union, the headline/fleet items
        would bypass the suspect gate the moment a win is promoted
        into TPU_DEFAULTS."""
        from cause_tpu.switches import TPU_DEFAULTS

        vals = set()
        for k_ in SWITCHES:
            v = cfg.get(k_, "")
            if not v and plat == "tpu":
                v = TPU_DEFAULTS.get(k_, "")
            if v and v != "xla":
                vals.add(f"{k_}={v}")
        if kernel in ("v5w", "v4w"):
            vals.add("euler=walk")
        if kernel == "v5f":
            vals.add("kernel=v5f")
        return vals

    def suspect_gate(name, kernel, cfg) -> bool:
        """True (and emits the skip) when the item's effective config
        contains a strategy the digest gate flagged this attempt."""
        bad = effective_values(kernel, cfg) & suspect_values
        if bad:
            emit(ev="skip", item=name,
                 reason=f"config uses digest-mismatching strategies "
                        f"{sorted(bad)}; not timing a wrong kernel")
            skipped_suspect.add(name)
            return True
        return False

    def mosaic_gate(name, kernel, cfg) -> bool:
        """True (and emits the skip) for items needing a Mosaic kernel
        compile, unless HARVEST_TRY_MOSAIC=1. Round-5 window-1
        evidence: this tunnel's remote compile helper crashes (HTTP
        500) or hangs INDEFINITELY on Mosaic programs — bench_psort
        wedged 30+ minutes of open window with no recourse (a hung
        compile cannot be killed without risking the tunnel server).
        Gated items count as attempted so the watcher advances."""
        if TRY_MOSAIC:
            return False
        need = effective_values(kernel, cfg) & MOSAIC_VALUES
        if need:
            emit(ev="skip", item=name,
                 reason=f"needs Mosaic compile {sorted(need)}; this "
                        "tunnel's compile helper crashes or hangs on "
                        "Mosaic (set HARVEST_TRY_MOSAIC=1 to retry)")
            skipped_suspect.add(name)
            return True
        return False

    def dispatch(kernel, k):
        lanes = (LANE_KEYS5 if kernel in ("v5", "v5w", "v5f")
                 else LANE_KEYS4)
        args = [dev[name] for name in lanes]
        return merge_wave_scalar(
            *args, k_max=k, kernel=kernel,
            u_max=k if kernel in ("v5", "v5w", "v5f") else 0,
        )

    def bench_item(name, kernel, cfg, burst_n=8, record=True):
        """bench.py-methodology measurement of one kernel+config:
        single-dispatch p50 and amortized-burst p50, reps each."""
        if mosaic_gate(name, kernel, cfg) or suspect_gate(
                name, kernel, cfg):
            return
        set_config(cfg)
        k = u_budget if kernel in ("v5", "v5w", "v5f") else budget
        try:
            for _ in range(3):  # compile + warm + overflow ladder
                out = np.asarray(dispatch(kernel, k))
                if out[1]:
                    emit(ev="overflow", item=name, k=int(k))
                    k *= 2
                    continue
                break
            else:
                raise _Overflow(name)
            singles, bursts = [], []
            for _ in range(reps):
                if obs.enabled():
                    # each timed single is one wave: land its
                    # wave.cost record (dispatch accounting + the
                    # generator's KNOWN divergence of 2*ND suffix ops
                    # per pair) so harvest sidecars feed the gap
                    # report's cost-vs-divergence join
                    from cause_tpu.obs import costmodel as _cm

                    _cm.wave_begin("harvest")
                t0 = time.perf_counter()
                np.asarray(dispatch(kernel, k))
                singles.append((time.perf_counter() - t0) * 1000)
                if obs.enabled():
                    from cause_tpu.obs import costmodel as _cm

                    v5_family = kernel in ("v5", "v5w", "v5f")
                    _cm.wave_cost(
                        uuid=f"harvest:{name}", pairs=B,
                        lanes=2 * CAP * B,
                        tokens=k * B if v5_family else None,
                        token_budget=k * B if v5_family else 0,
                        delta_ops=2 * ND * B, path="full")
            # bench.py's adaptive-burst rule (window economy, and the
            # window-2 lesson — a slow kernel's 3 bursts are ~90 s of
            # window for nothing): when single > 1 s the ~64-70 ms
            # dispatch floor is noise, amortized ~= single, and one
            # burst suffices
            burst_reps = (reps if float(np.median(singles)) < 1000.0
                          else 1)
            for _ in range(burst_reps):
                t0 = time.perf_counter()
                o = None
                for _ in range(burst_n):
                    o = dispatch(kernel, k)
                np.asarray(o)
                bursts.append((time.perf_counter() - t0) * 1000 / burst_n)
            label = "+".join(
                f"{k_.split('_')[-1].lower()}={v}"
                for k_, v in sorted(cfg.items()) if v != "xla")
            rec = dict(
                item=name, kernel=kernel,
                config=label or ("xla-baseline" if cfg
                                 else "shipped-default"),
                # the non-xla switch dict, verbatim: decide_defaults
                # flips exactly what was timed, not a constant that
                # may have drifted (reduced-certification support)
                cfg=flips_of(cfg),
                p50_single_ms=round(float(np.median(singles)), 1),
                p50_amortized_ms=round(float(np.median(bursts)), 1),
                singles_ms=[round(x, 1) for x in singles],
                bursts_ms=[round(x, 1) for x in bursts],
                k_max=int(k), platform=plat, shape=f"{B}x{1+NB+ND}",
                run=RUN_ID)
            emit(ev="result", **rec)
            validated_k[kernel] = k
            if record_state:
                # results persist for decide_defaults even for the
                # always-re-measured headline items (latest wins)
                results[name] = rec
                if record:
                    done.add(name)
                save_state(done, results)
        except _Overflow:
            emit(ev="error", item=name, error="overflow at max budget")
        finally:
            set_config({})

    def xla_base_item(name):
        """The A/B anchor. With TPU_DEFAULTS empty, the shipped
        default traces the IDENTICAL program as the pinned XLA base
        (env unset resolves to "" — window 1 measured them equal to
        0.1 ms), so the headline's fresh result is copied instead of
        re-timing the same compiled program for ~2.5 min of window.
        With defaults flipped, the baseline is a different program and
        measures normally."""
        from cause_tpu.switches import TPU_DEFAULTS

        head = results.get("bench_v5", {})
        if not TPU_DEFAULTS and head.get("run") == RUN_ID:
            rec = dict(head, item=name, config="xla-baseline",
                       note="defaults empty: shipped default IS the "
                            "xla baseline; copied from bench_v5 "
                            "(same compiled program)")
            emit(ev="result", **rec)
            if record_state:
                results[name] = rec
                save_state(done, results)
            return
        bench_item(name, "v5", XLA_BASE, 8, False)

    def beststream_bench_item(name):
        """Time the config the digest gate actually certified — the
        full BESTSTREAM on MATCH, or the reduced combination on
        MATCH-REDUCED (the state file carries it across windows). A
        decide_defaults flip then ships exactly the timed cfg."""
        stored = (results.get("verify_beststream") or {}).get("cfg")
        cfg = cfg_of(**stored) if stored else dict(BESTSTREAM)
        bench_item(name, "v5", cfg, 8, False)

    def verify_item(name, cfg_a, kernel_b, cfg_b):
        """On-chip correctness gate (round-4 advisor finding): the
        streaming strategies and the Mosaic-compiled pallas kernels are
        parity-validated only in interpret/CPU mode — a wrong scatter
        hint or Mosaic lowering on real TPU would produce silently
        wrong results that the timing ladder would happily measure.
        Before any config A/B is trusted, compare the v5 family's
        scalar — which IS an exact order-independent avalanche digest
        of (rank, visibility, lane, conflict) per benchgen
        .merge_wave_scalar (a plain linear weighted sum was observed
        cancelling compensating errors into collisions) — of the FULL
        batch under the pinned XLA-baseline ``cfg_a`` (NOT the shipped
        default, which becomes suspect-vs-suspect the moment a win
        lands in switches.TPU_DEFAULTS) against ``cfg_b``. Riding the
        SAME compiled program as the timing items is the round-5
        window-economy fix: the previous separate per-row digest
        program cost two fresh compiles and ate two whole windows
        mid-compile; now the baseline digest is a dispatch of an
        already-compiled program and the candidate digest shares its
        compile with the candidate's own bench item. Requires a
        bench-validated v5 budget (same precondition as stages_item:
        truncated programs clamp identically and would certify a false
        MATCH); done only on MATCH with zero overflow on both sides."""
        if mosaic_gate(name, kernel_b, cfg_b):
            return
        if "v5" not in validated_k:
            emit(ev="error", item=name,
                 error="no bench-validated v5 budget this attempt; "
                       "skipping verify rather than digest a possibly "
                       "truncated program")
            return
        k = validated_k["v5"]

        def digests(kernel, cfg):
            set_config(cfg)
            out = np.asarray(dispatch(kernel, k))
            return int(out[0]), int(out[1])

        try:
            da, ova = digests("v5", cfg_a)
            db, ovb = digests(kernel_b, cfg_b)
            ok = da == db and ova == 0 and ovb == 0
            emit(ev="result", item=name,
                 digest_a=da, digest_b=db,
                 overflow_a=int(ova), overflow_b=int(ovb),
                 platform=plat,
                 verdict="MATCH" if ok else "MISMATCH")
            if ok:
                if record_state:
                    # the certified cfg rides the state so the timing
                    # item, decide_defaults and the watcher's phase-2
                    # env all run EXACTLY what the digest gate checked;
                    # the matched digest rides along so the provenance
                    # of every later certify/ship decision is auditable
                    results[name] = dict(
                        item=name, verdict="MATCH",
                        cfg=flips_of(cfg_b), digest=int(da),
                        run=RUN_ID, platform=plat)
                    done.add(name)
                    save_state(done, results)
                return
            # a MISMATCH revokes any certification record a previous
            # window left: certified_env()/the watcher/phase-2 must
            # never keep shipping a cfg the digest gate just
            # contradicted (same rule as decide_defaults' revocation
            # of the defaults file). A reduced re-certification below
            # writes a fresh record.
            if record_state and results.pop(name, None) is not None:
                save_state(done, results)
            # attribute the culprit: one switch (or the euler walk)
            # at a time against the same baseline digests. Snapshot
            # the suspect set first — with two verify items in the
            # ladder, suspects left by an earlier one must not
            # suppress THIS item's combination-only fallback.
            pre_suspects = set(suspect_values)
            singles = [("v5", dict(cfg_a, **{k_: v}), f"{k_}={v}")
                       for k_, v in cfg_b.items() if v != "xla"]
            if kernel_b in ("v5w", "v4w"):
                singles.append(("v5w", dict(cfg_a), "euler=walk"))
            if kernel_b == "v5f":
                singles.append(("v5f", dict(cfg_a), "kernel=v5f"))
            for kern, cfg1, val in singles:
                d1, ov1 = digests(kern, cfg1)
                m1 = int(da != d1)
                if m1 or ov1 != ova:
                    suspect_values.add(val)
                emit(ev="verify_attr", item=name, strategy=val,
                     mismatch=m1, overflow=int(ov1),
                     platform=plat)
            if not (suspect_values - pre_suspects):
                # combination-only defect: no single strategy
                # reproduces it, so every strategy in the failing
                # config is suspect — better to skip them all than to
                # time and permanently record a known-wrong config
                suspect_values.update(
                    f"{k_}={v}" for k_, v in cfg_b.items()
                    if v != "xla")
                if kernel_b in ("v5w", "v4w"):
                    suspect_values.add("euler=walk")
                if kernel_b == "v5f":
                    suspect_values.add("kernel=v5f")
                emit(ev="verify_attr", item=name,
                     strategy="combination-only",
                     note="no single culprit; all strategies of the "
                          "failing config marked suspect")
            elif name == "verify_beststream" and kernel_b == "v5":
                # reduced-set fallback: one bad strategy must not cost
                # the window its certification — re-gate the
                # combination minus the attributed culprits and
                # certify THAT (the reduced cfg rides the state file
                # to bench_beststream / decide_defaults / the watcher)
                reduced = {
                    k_: ("xla" if f"{k_}={v}" in suspect_values else v)
                    for k_, v in cfg_b.items()
                }
                if (reduced != cfg_b
                        and any(v != "xla" for v in reduced.values())):
                    dr, ovr = digests("v5", reduced)
                    okr = da == dr and ova == 0 and ovr == 0
                    emit(ev="result", item=name,
                         digest_a=da, digest_b=dr,
                         overflow_a=int(ova), overflow_b=int(ovr),
                         platform=plat,
                         verdict=("MATCH-REDUCED" if okr
                                  else "MISMATCH-REDUCED"),
                         cfg=flips_of(reduced))
                    if okr and record_state:
                        results[name] = dict(
                            item=name, verdict="MATCH-REDUCED",
                            cfg=flips_of(reduced), digest=int(dr),
                            # the strategies the reduction dropped,
                            # persisted so later windows re-seed the
                            # suspect gate (see persisted_suspects)
                            suspects=sorted(
                                set(f"{k_}={v}" for k_, v
                                    in flips_of(cfg_b).items())
                                & suspect_values),
                            run=RUN_ID, platform=plat)
                        done.add(name)
                        save_state(done, results)
            emit(ev="suspects", item=name,
                 suspects=sorted(suspect_values))
        finally:
            set_config({})

    def stages_item(name, cfg):
        """Cumulative-prefix phase attribution ON HARDWARE (jaxw5
        stage= early returns with live checksums; probe_v5_stages
        inlined so it shares this process's tunnel claim + uploads).

        Token budget: the bench_item-validated v5 budget when one
        completed earlier in the ladder (bench_v5 runs first, so in
        practice always) — the stage checksums fold the overflow flag
        into a float, so an unvalidated budget could silently time a
        truncated program."""
        if mosaic_gate(name, "v5", cfg) or suspect_gate(name, "v5", cfg):
            return
        if "v5" not in validated_k:
            # without a bench-validated budget the stage checksums could
            # silently time a truncated (overflowed) program AND mark
            # the item done; leave it unrecorded for a later window
            emit(ev="error", item=name,
                 error="no bench-validated v5 budget this attempt; "
                       "skipping stages rather than risk timing a "
                       "truncated program")
            return
        set_config(cfg)
        u_eff = validated_k["v5"]
        try:
            v5args = [dev[k] for k in LANE_KEYS5]
            prev = 0.0
            table = {}
            for stage in ("A", "B", "C", "D", "E", None):
                sname = stage or "FULL"

                def row(*xs, _stage=stage):
                    out = merge_weave_kernel_v5(
                        *xs, u_max=u_eff, k_max=u_eff, stage=_stage
                    )
                    if _stage is None:
                        rank, visible, conflict, overflow = out
                        return (jnp.sum(rank.astype(jnp.float32))
                                + jnp.sum(visible.astype(jnp.float32))
                                + conflict.astype(jnp.float32)
                                + overflow.astype(jnp.float32))
                    return out

                p = jax.jit(lambda *xs, _r=row: jnp.sum(jax.vmap(_r)(*xs)))
                np.asarray(p(*v5args))  # compile + warm
                ts = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    np.asarray(p(*v5args))
                    ts.append((time.perf_counter() - t0) * 1000)
                med = float(np.median(ts))
                table[sname] = {"prefix_ms": round(med, 1),
                                "incr_ms": round(med - prev, 1)}
                emit(ev="stage", item=name, stage=sname,
                     prefix_ms=round(med, 1),
                     incr_ms=round(med - prev, 1), platform=plat)
                prev = med
            label = "+".join(sorted(
                v for v in cfg.values() if v != "xla"))
            rec = dict(item=name, stages=table, platform=plat,
                       config=label or ("xla-baseline" if cfg
                                        else "shipped-default"),
                       u_max=int(u_eff), shape=f"{B}x{1+NB+ND}",
                       run=RUN_ID)
            emit(ev="result", **rec)
            if record_state:
                results[name] = rec
                done.add(name)
                save_state(done, results)
        finally:
            set_config({})

    def micro_item(name):
        """Primitive-strategy A/Bs at exact kernel shapes (shares this
        process's tunnel claim; scripts/tpu_microbench.py cases)."""
        if a.smoke:
            emit(ev="skip", item=name,
                 reason="microbench cases are full-size only")
            return
        import tpu_microbench as mb

        ok = True
        mosaic_skipped = False
        for case in mb.TOK_CASES:
            if not TRY_MOSAIC and case == "tokpallas":
                emit(ev="skip", item=name, case=case,
                     reason="Mosaic compile; see mosaic_gate")
                mosaic_skipped = True
                continue
            try:
                per_op, once = mb.ALL[case]()
                emit(ev="micro", item=name, case=case,
                     per_op_ms=round(per_op, 2),
                     single_dispatch_ms=round(once, 1), platform=plat)
            except Exception as e:  # noqa: BLE001 - keep measuring
                ok = False
                emit(ev="error", item=name, case=case,
                     error=f"{type(e).__name__}: {str(e)[:200]}")
        if ok and record_state:
            if mosaic_skipped:
                # attempted for THIS window's completeness, but not
                # done: a later HARVEST_TRY_MOSAIC=1 window must still
                # be able to measure the gated case
                skipped_suspect.add(name)
            else:
                done.add(name)
                save_state(done, results)

    def fleet_item(name, K, nb, nd, cap):
        from cause_tpu.weaver.jaxw5 import merge_weave_kernel_v5_jit

        try:
            lanes = benchgen.fleet_lanes(
                n_replicas=K, n_base=nb, n_div=nd, capacity=cap,
                hide_every=8,
            )
            t0 = time.monotonic()
            v5row = benchgen.v5_inputs(lanes, cap)
            marshal_ms = (time.monotonic() - t0) * 1000
            fargs = [jax.device_put(jnp.asarray(v5row[k]))
                     for k in LANE_KEYS5]
            k = benchgen.v5_token_budget(v5row)

            def step(kk):
                rank, vis, c, ovf = merge_weave_kernel_v5_jit(
                    *fargs, u_max=kk, k_max=kk
                )
                out = np.asarray(
                    jnp.stack([jnp.sum(rank.astype(jnp.float32)),
                               ovf.astype(jnp.float32)])
                )
                if out[1]:
                    raise _Overflow(kk)
                return out

            for _ in range(3):
                try:
                    step(k)
                    break
                except _Overflow:
                    emit(ev="overflow", item=name, k=int(k))
                    k *= 2
            else:
                raise RuntimeError("overflow at max fleet budget")
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                step(k)
                ts.append((time.perf_counter() - t0) * 1000)
            rec = dict(item=name,
                       metric=f"fleet v5 {K}x{1+nb+nd} -> one tree",
                       p50_ms=round(float(np.median(ts)), 1),
                       reps_ms=[round(x, 1) for x in ts],
                       lanes=K * cap, u_max=int(k),
                       marshal_ms=round(marshal_ms, 1), platform=plat,
                       run=RUN_ID)
            emit(ev="result", **rec)
            if record_state:
                results[name] = rec
                done.add(name)
                save_state(done, results)
        except Exception as e:  # noqa: BLE001 - keep harvesting
            emit(ev="error", item=name,
                 error=f"{type(e).__name__}: {str(e)[:200]}")

    def verify_delta_item(name):
        """On-chip digest gate for the delta-native weave: per-row
        convergence digests of the full v5 kernel vs the delta window
        program (prefix digest + window weave) on a B=64 subset of the
        headline shape restricted to the delta domain. MATCH means
        bit-identical uint32 digests on every row — the same gate the
        CPU equality suite pins, re-proven on the chip's own lowering.
        Deliberately NOT part of BESTSTREAM: the delta path ships into
        defaults only after this gate has certified it on hardware."""
        from cause_tpu.weaver import jaxwd
        from cause_tpu.weaver.arrays import next_pow2

        full_args = [jax.device_put(jnp.asarray(dsw_verify["full"][k]))
                     for k in LANE_KEYS5]
        u = next_pow2(benchgen.v5_token_budget(dsw_verify["full"]))
        _r, _v, dig_full, ovf = jaxwd.batched_weave_digest(
            *full_args, u_max=int(u), k_max=int(u))
        dig_full = np.asarray(dig_full)
        ov_full = int(np.asarray(ovf).sum())
        nw = 2 * dsw_verify["wcap"]
        win_args = [jax.device_put(jnp.asarray(dsw_verify["window"][k]))
                    for k in LANE_KEYS5]
        _rw, _vw, dig_d, ovw = jaxwd.batched_delta_weave(
            *win_args, jax.device_put(dsw_verify["prefix_digest"]),
            jax.device_put(dsw_verify["r0"]),
            u_max=int(nw), k_max=int(nw))
        dig_d = np.asarray(dig_d)
        ov_d = int(np.asarray(ovw).sum())
        ok = (ov_full == 0 and ov_d == 0
              and bool(np.array_equal(dig_full, dig_d)))
        rec = dict(item=name, verdict="MATCH" if ok else "MISMATCH",
                   rows=int(dig_full.shape[0]),
                   overflow_full=ov_full, overflow_delta=ov_d,
                   wcap=dsw_verify["wcap"],
                   # the gate runs on its own row subset — the shape
                   # label must say so, not claim the headline batch
                   shape=f"{int(dig_full.shape[0])}x{1+NB+ND}",
                   platform=plat, run=RUN_ID)
        emit(ev="result", **rec)
        if record_state:
            results[name] = rec
            if ok:
                done.add(name)
            save_state(done, results)

    def delta_bench_item(name, dsw, n_div_side):
        """bench.py-methodology timing of the delta-native wave
        program — window weave + incremental digest + resident splice
        — at the headline batch size and document shape, with the
        window sized to the item's divergence. Residents are device
        -allocated placeholders (the splice's cost is content
        -independent); correctness is verify_delta's gate, this item
        is the wall-clock arm of the one-claim A/B vs bench_v5."""
        from cause_tpu.weaver import jaxwd

        nw = 2 * dsw["wcap"]
        win = [jax.device_put(jnp.asarray(dsw["window"][k]))
               for k in LANE_KEYS5]
        pd = jax.device_put(dsw["prefix_digest"])
        r0v = jax.device_put(dsw["r0"])
        st = jax.device_put(dsw["starts"])
        ct = jax.device_put(dsw["counts"])
        res = [jnp.zeros((B, 2 * CAP), jnp.int32),
               jnp.zeros((B, 2 * CAP), bool)]

        def dispatch():
            rw, vw, dig, _ovf = jaxwd.batched_delta_weave(
                *win, pd, r0v, u_max=int(nw), k_max=int(nw))
            res[0], res[1] = jaxwd.splice_ranks(
                res[0], res[1], rw, vw, st, ct, r0v)
            if obs.enabled():
                from cause_tpu.obs import costmodel as _cm

                _cm.record_dispatch(f"harvest:delta:w{dsw['wcap']}",
                                    site="harvest")
                _cm.record_dispatch("harvest:delta_splice",
                                    site="harvest")
            # sync value depends on BOTH programs: fetching the digest
            # alone would let the O(doc) splice run past the timer
            return jnp.concatenate(
                [dig, res[0][:, 0].astype(jnp.uint32)])

        np.asarray(dispatch())  # compile + warm

        def _begin():
            if obs.enabled():
                from cause_tpu.obs import costmodel as _cm

                _cm.wave_begin("harvest")

        def _end():
            if obs.enabled():
                from cause_tpu.obs import costmodel as _cm

                _cm.wave_cost(
                    uuid=f"harvest:{name}", pairs=B,
                    lanes=2 * CAP * B,
                    tokens=2 * (n_div_side + 1) * B,
                    token_budget=int(nw) * B,
                    delta_ops=2 * n_div_side * B, path="delta")

        singles, bursts = benchgen.time_dispatch(
            dispatch, reps, 8, begin=_begin, end=_end)
        rec = dict(
            item=name, kernel="v5d", config="delta-native",
            cfg={},
            p50_single_ms=round(float(np.median(singles)), 2),
            p50_amortized_ms=round(float(np.median(bursts)), 2),
            singles_ms=[round(x, 2) for x in singles],
            bursts_ms=[round(x, 2) for x in bursts],
            k_max=int(nw), wcap=dsw["wcap"],
            divergence_ops=2 * n_div_side,
            platform=plat, shape=f"{B}x{1+NB+ND}", run=RUN_ID)
        emit(ev="result", **rec)
        if record_state:
            results[name] = rec
            done.add(name)
            save_state(done, results)

    def verify_tree_item(name):
        """Bit-identity gate for the merge reduction tree (PR 8): the
        TREE_N-replica fleet converged through ``parallel.tree``
        (ceil(log2(n)) batched device rounds) must equal the flat
        sequential pairwise fold bit-for-bit — weave AND node store —
        with the round count the tree promises. Both arms run on this
        chip's own lowering; the wall times ride the record so
        bench_tree can reuse the fold arm instead of paying the n-1
        sequential waves a second time (window economy)."""
        from cause_tpu.parallel import tree as tree_mod

        fleet = tree_fleet()
        t0 = time.perf_counter()
        root, rep = tree_mod.merge_tree_report(fleet)
        tree_ms = (time.perf_counter() - t0) * 1000
        t1 = time.perf_counter()
        fold = tree_mod.flat_fold(fleet)
        fold_ms = (time.perf_counter() - t1) * 1000
        rounds_expected = tree_mod.tree_rounds(len(fleet))
        rounds_ok = len(rep["levels"]) == rounds_expected
        ok = (rounds_ok and root.ct.weave == fold.ct.weave
              and root.ct.nodes == fold.ct.nodes)
        rec = dict(item=name, verdict="MATCH" if ok else "MISMATCH",
                   replicas=len(fleet),
                   rounds=len(rep["levels"]),
                   rounds_expected=rounds_expected,
                   paths=[lv["path"] for lv in rep["levels"]],
                   tree_ms=round(tree_ms, 1), fold_ms=round(fold_ms, 1),
                   shape=f"{TREE_N}x{1 + TREE_NB + TREE_ND}",
                   platform=plat, run=RUN_ID)
        emit(ev="result", **rec)
        # in-memory always (bench_tree reads the same-window verdict
        # even on CPU/smoke rehearsals); persisted only for real
        # full-size windows like every other item
        results[name] = rec
        if record_state:
            if ok:
                done.add(name)
            save_state(done, results)

    def bench_tree_item(name):
        """bench.py-methodology timing of merge-tree fleet convergence
        vs the flat fold. The tree arm re-measures (reps); the fold arm
        — n-1 SEQUENTIAL full-width waves, minutes of window — reuses
        verify_tree's same-window measurement when one exists and runs
        once otherwise."""
        from cause_tpu.parallel import tree as tree_mod

        vrec = results.get("verify_tree") or {}
        if vrec.get("verdict") != "MATCH":
            emit(ev="skip", item=name,
                 reason="no MATCH verify_tree on record; not timing an "
                        "unverified reduction")
            return
        fleet = tree_fleet()
        singles = []
        for _ in range(reps):
            t0 = time.perf_counter()
            tree_mod.merge_tree(fleet)
            singles.append((time.perf_counter() - t0) * 1000)
        if vrec.get("run") == RUN_ID and vrec.get("fold_ms"):
            fold_ms = float(vrec["fold_ms"])
            fold_src = "verify_tree (same window)"
        else:
            t0 = time.perf_counter()
            tree_mod.flat_fold(fleet)
            fold_ms = (time.perf_counter() - t0) * 1000
            fold_src = "measured"
        p50 = float(np.median(singles))
        rec = dict(item=name, kernel="v5t", config="merge-tree",
                   cfg={},
                   p50_tree_ms=round(p50, 1),
                   singles_ms=[round(x, 1) for x in singles],
                   fold_ms=round(fold_ms, 1), fold_source=fold_src,
                   tree_over_fold=round(p50 / max(fold_ms, 1e-9), 4),
                   rounds=vrec.get("rounds"),
                   replicas=len(fleet), platform=plat,
                   shape=f"{TREE_N}x{1 + TREE_NB + TREE_ND}",
                   run=RUN_ID)
        emit(ev="result", **rec)
        if record_state:
            results[name] = rec
            done.add(name)
            save_state(done, results)

    # ---- the ladder, highest information value per second first -----
    # Round-5 order after window 1: the XLA-only streaming family is
    # the only measurable candidate on this tunnel (Mosaic compiles
    # crash/hang the compile helper — see mosaic_gate), so its digest
    # gate + timing lead, then the baseline, then the single-switch
    # attribution A/Bs. The Mosaic items stay listed (gated) so a
    # tunnel that gains Mosaic support measures them via
    # HARVEST_TRY_MOSAIC=1 without a code change.
    ladder: list[tuple[str, object, tuple]] = [
        ("bench_v5", bench_item, ("bench_v5", "v5", {}, 8, False)),
        # re-derived EVERY window so decide_defaults always has a
        # same-window (same run id) anchor — a cross-window 2% margin
        # would certify day-to-day load drift (round-5 review finding)
        ("bench_xla_base", xla_base_item, ("bench_xla_base",)),
        ("verify_beststream", verify_item,
         ("verify_beststream", XLA_BASE, "v5", BESTSTREAM)),
        # record=False like the baseline: the candidate must re
        # -measure in the same window as its anchor or the same-run
        # rule could never (re-)certify after window 1
        ("bench_beststream", beststream_bench_item,
         ("bench_beststream",)),
        # delta-native weave (PR 7): the digest gate plus low/high
        # -divergence timing arms, so the FIRST window A/Bs
        # delta-native vs full weave (bench_v5 above) in one claim.
        # Not in BESTSTREAM: the delta path only ships as a default
        # once verify_delta has certified it on hardware.
        ("verify_delta", verify_delta_item, ("verify_delta",)),
        ("bench_delta_high", delta_bench_item,
         ("bench_delta_high", dsw_high, ND)),
        ("bench_delta_low", delta_bench_item,
         ("bench_delta_low", dsw_low, ND_LOW)),
        # merge reduction tree (PR 8), right after the delta items so
        # the FIRST tunnel window certifies the still-pending delta
        # weave AND the O(log n) tree in one claim: the bit-identity
        # gate (tree vs flat fold at B=64), then the timing A/B
        ("verify_tree", verify_tree_item, ("verify_tree",)),
        ("bench_tree", bench_tree_item, ("bench_tree",)),
        ("bench_rowgather", bench_item,
         ("bench_rowgather", "v5", cfg_of(CAUSE_TPU_GATHER="rowgather"))),
        ("bench_matrix", bench_item,
         ("bench_matrix", "v5", cfg_of(CAUSE_TPU_SEARCH="matrix"))),
        ("bench_mtable", bench_item,
         ("bench_mtable", "v5",
          cfg_of(CAUSE_TPU_SEARCH="matrix-table"))),
        ("bench_schint", bench_item,
         ("bench_schint", "v5", cfg_of(CAUSE_TPU_SCATTER="hint"))),
        ("bench_sortmatrix", bench_item,
         ("bench_sortmatrix", "v5", cfg_of(CAUSE_TPU_SORT="matrix"))),
        ("stages_default", stages_item, ("stages_default", XLA_BASE)),
        ("stages_beststream", stages_item,
         ("stages_beststream", BESTSTREAM)),
        ("bench_allstream", bench_item,
         ("bench_allstream", "v5", ALLSTREAM)),
        ("bench_bitonic", bench_item,
         ("bench_bitonic", "v5", cfg_of(CAUSE_TPU_SORT="bitonic"))),
        ("microbench", micro_item, ("microbench",)),
        ("fleet64", fleet_item, ("fleet64", 64, 2_000, 200, 2_560)),
        ("fleet256", fleet_item, ("fleet256", 256, 500, 64, 1_024)),
        ("bench_v4", bench_item, ("bench_v4", "v4", XLA_BASE)),
        # Mosaic-needing items (all skip-as-attempted unless
        # HARVEST_TRY_MOSAIC=1; see module comment)
        ("verify_v5f", verify_item,
         ("verify_v5f", XLA_BASE, "v5f", MOSAICSTREAM)),
        ("bench_v5f", bench_item,
         ("bench_v5f", "v5f", MOSAICSTREAM)),
        ("bench_v5f_xla", bench_item,
         ("bench_v5f_xla", "v5f", XLA_BASE)),
        ("verify_mosaicstream", verify_item,
         ("verify_mosaicstream", XLA_BASE, "v5w", MOSAICSTREAM)),
        ("bench_mosaicstream", bench_item,
         ("bench_mosaicstream", "v5w", MOSAICSTREAM)),
        ("bench_psort", bench_item,
         ("bench_psort", "v5", cfg_of(CAUSE_TPU_SORT="pallas"))),
        ("bench_v5w", bench_item, ("bench_v5w", "v5w", XLA_BASE)),
        ("bench_fphase", bench_item,
         ("bench_fphase", "v5", cfg_of(CAUSE_TPU_FPHASE="pallas"))),
        # bookend repeat of the headline (cross-window repetition)
        ("bench_v5_bookend", bench_item,
         ("bench_v5_bookend", "v5", {}, 8, False)),
    ]

    for name, fn, args in ladder:
        if name in done:
            emit(ev="skip", item=name)
            continue
        emit(ev="start", item=name)
        # wedge-triage heartbeat (PR 10): one record at every ladder
        # -item boundary, so `obs watch` over the harvest sidecar can
        # tell WHICH item a wedged tunnel round died inside (and how
        # long it had been running) without ssh archaeology — the
        # tunnel_watcher `watch` mode reads exactly these
        obs.event("run.heartbeat", item=name, stage="start",
                  elapsed=round(time.monotonic() - T0, 1))
        try:
            with obs.span("harvest.item", item=name):
                fn(*args)
            obs.event("run.heartbeat", item=name, stage="done",
                      elapsed=round(time.monotonic() - T0, 1))
        except Exception as e:  # noqa: BLE001 - emit + try next item
            obs.event("run.heartbeat", item=name, stage="error",
                      elapsed=round(time.monotonic() - T0, 1))
            emit(ev="error", item=name,
                 error=f"{type(e).__name__}: {str(e)[:300]}")

    # suspect skips count as attempted (re-measuring a digest
    # -mismatching config in a later window yields the same skip; the
    # watcher must be able to advance to phases 2-3); verify itself
    # also counts as attempted on MISMATCH — it re-runs next window
    # anyway because it is not in ``done``
    attempted = done | skipped_suspect
    if suspect_values:
        attempted.add("verify_beststream")
        attempted.add("verify_v5f")
        attempted.add("verify_mosaicstream")
    complete = all(
        name in attempted for name, _, _ in ladder
        if name not in ("bench_v5", "bench_xla_base",
                        "bench_beststream", "bench_v5_bookend")
    )

    # ---- flip shipped defaults from certified wins (VERDICT r4 weak
    # #4 / next #3): the moment a window certifies the streaming
    # config (digest-gate MATCH => "verify_beststream" in done) AND
    # measures it faster than the same-window XLA baseline, write it
    # to cause_tpu/_tpu_defaults.json — switches.TPU_DEFAULTS loads it
    # at import, so every later process (bench.py's default path, API
    # waves, user code) ships the winner with no human in the loop.
    if record_state:
        decide_defaults(done, results, plat, suspects=suspect_values)
    obs.event("run.heartbeat", item="ladder", stage="done",
              elapsed=round(time.monotonic() - T0, 1))
    emit(ev="done", complete=complete, platform=plat)
    obs.flush()


def certified_env() -> str:
    """Space-separated ``K=V`` pairs for the watcher's phase-2 wave
    run: the cfg the digest gate certified (full or reduced, from the
    state file). Import-light on purpose — the watcher calls this
    under JAX_PLATFORMS=cpu with the axon pool unset.

    Cfgless-certification guard (ADVICE r5 medium): when the RAW state
    file claims verify_beststream (the watcher's grep on it is what
    routed us here) but the record carries no cfg — a pre-migration
    file, or a version-mismatched one load_state() discarded — return
    the shipped-default sentinel (empty string) so the watcher takes
    its shipped-default branch, mirroring load_state()'s cfgless
    -record re-verify rule. The static BESTSTREAM flips (which now
    include the never-before-certified matrix sort) are the fallback
    ONLY when the state carries no verify_beststream claim at all."""
    _, results = load_state()
    stored = (results.get("verify_beststream") or {}).get("cfg")
    if stored:
        return " ".join(f"{k}={v}" for k, v in sorted(stored.items()))
    try:
        with open(STATE_PATH) as f:
            raw = json.load(f)
        claimed = ("verify_beststream" in (raw.get("done") or ())
                   or "verify_beststream" in (raw.get("results") or {}))
    except Exception:  # noqa: BLE001 - missing/corrupt = no claim
        claimed = False
    if claimed:
        return ""  # shipped-default sentinel: never ship uncertified
    flips = flips_of(BESTSTREAM)
    return " ".join(f"{k}={v}" for k, v in sorted(flips.items()))


def defaults_file_path() -> str:
    # delegate to the consumer side: writer, revoker, re-certify check
    # and every reader must act on the SAME file, including under the
    # CAUSE_TPU_DEFAULTS_FILE override
    from cause_tpu.switches import _defaults_path

    return _defaults_path()


def decide_defaults(done: set, results: dict, plat: str,
                    path: str = "", suspects=frozenset()) -> None:
    """Write (or revoke) chip-certified switch defaults.

    Rules (each closes a round-5 review finding):
    - Flip ONLY the whole v5-certified combination
      (verify_beststream + bench_beststream, kernel v5): the global
      switch defaults apply to EVERY kernel a user's wave runs —
      which is v5 — so a combination certified under v5w/v5f
      (MOSAICSTREAM) must not leak into v5 paths it was never
      digest-checked against. Mosaic wins are reported (ev=defaults,
      informational) but never shipped globally; shipping them needs
      a v5-paired digest gate first.
    - Same-window comparison: the candidate and the xla baseline must
      carry the same ``run`` id. PERF.md records ~14% cross-day drift
      (4,300 -> 3,750 ms at identical code+shape); a 2% margin across
      windows would certify pure load noise. Within one window the
      measured spread is <2%, so the margin is meaningful.
    - Revocation: if the currently-shipped defaults intersect this
      attempt's digest-MISMATCH suspects, the file is deleted — a
      certification must not outlive its evidence."""
    path = path or defaults_file_path()

    # revoke first: shipped defaults contradicted by this attempt's
    # digest gate must go regardless of what else measured
    if suspects and os.path.exists(path):
        try:
            with open(path) as f:
                shipped = json.load(f).get("switches", {})
        except Exception:  # noqa: BLE001 - corrupt file: revoke it
            shipped = {"corrupt": "file"}
        shipped_vals = {f"{k}={v}" for k, v in shipped.items()}
        if (shipped_vals & set(suspects)) or "corrupt" in shipped:
            os.remove(path)
            emit(ev="defaults", flipped=False, revoked=True,
                 reason=f"shipped defaults intersect digest suspects "
                        f"{sorted(shipped_vals & set(suspects))}")
            return

    base_rec = results.get("bench_xla_base", {})
    base = base_rec.get("p50_amortized_ms")
    if not base:
        emit(ev="defaults", flipped=False,
             reason="no xla baseline measured; flip logic cannot rule")
        return
    cand = results.get("bench_beststream", {})
    p50 = cand.get("p50_amortized_ms")
    same_window = (cand.get("run") and
                   cand.get("run") == base_rec.get("run"))
    # informational only: Mosaic-combination wins (never shipped, see
    # docstring) — same-window rule applies to the report too
    for verify, bench in (("verify_mosaicstream", "bench_mosaicstream"),
                          ("verify_v5f", "bench_v5f")):
        mrec = results.get(bench, {})
        m = mrec.get("p50_amortized_ms")
        if (verify in done and m and m < base
                and mrec.get("run") == base_rec.get("run")):
            emit(ev="defaults", flipped=False, informational=True,
                 reason=f"{bench} ({m} ms) beats base ({base} ms) but "
                        "is certified under its own kernel only; a "
                        "v5-paired digest gate is required before "
                        "shipping its switches globally")
    if not ("verify_beststream" in done and p50
            and same_window and p50 < 0.98 * base):
        emit(ev="defaults", flipped=False,
             reason="no v5-certified same-window config beat the xla "
                    f"baseline by >2% (base {base} ms, "
                    f"beststream {p50} ms, same_window={same_window})")
        return
    # the timed cfg and the digest-certified cfg must be the SAME
    # program (reduced-certification coherence: a bench record from
    # before a reduction, or any future ladder reorder, must not ship
    # switches the gate never checked)
    vrec = results.get("verify_beststream") or {}
    vcfg = vrec.get("cfg")
    if vcfg is not None and dict(vcfg) != dict(cand.get("cfg") or vcfg):
        emit(ev="defaults", flipped=False,
             reason=f"timed cfg {cand.get('cfg')} != certified cfg "
                    f"{vcfg}; not shipping an uncertified combination")
        return
    # flip exactly what was timed: the bench record carries its own
    # cfg (reduced-certification support). For records predating the
    # cfg field the fallback is the CERTIFIED vcfg — not the static
    # BESTSTREAM flips, which can differ from a reduced certification
    # and would ship exactly the drift the coherence check above
    # exists to prevent (ADVICE r5 low); the constant is the last
    # resort only when neither record carries a cfg
    flips = dict(cand.get("cfg") or vcfg or flips_of(BESTSTREAM))
    rec = {
        # committed on purpose: the framework targets exactly this
        # chip (v5e-1 behind the axon tunnel), and VERDICT r4 asks for
        # shipped defaults to come from measured winners; CPU and
        # other backends ignore these (switches.resolve backend guard)
        "switches": flips,
        "kernel": "v5",
        "evidence": {
            "p50_amortized_ms": p50,
            "xla_base_ms": base,
            "run": cand.get("run"),
            "platform": plat,
            # the digest the certification matched (None for records
            # predating the field): the flip's provenance is auditable
            # from the defaults file alone
            "digest": vrec.get("digest"),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        },
    }
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    emit(ev="defaults", flipped=True, p50_ms=p50, xla_base_ms=base,
         kernel="v5", switches=flips, cfg=flips,
         digest=vrec.get("digest"), path=path)


if __name__ == "__main__":
    main()
