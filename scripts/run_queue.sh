#!/bin/bash
# SUPERSEDED (round 4): scripts/harvest.py + scripts/tunnel_watcher.sh
# (harvest mode; the watcher_r4.sh shim is gone since PR 11) run
# the whole ladder in one tunnel claim; this per-item queue is kept for
# round-3 log provenance only. Known wart: `timeout --signal=CONT` is a
# no-op bound (GNU timeout sends SIGCONT then keeps waiting), so the
# 3600s value bounds nothing — deliberate here, since a measurement
# child must never be killed, but it means one wedged item blocks the
# queue; the round-4 harvester bounds only the pre-compile claim wait.
#
# TPU measurement recovery queue (round 3). Serialized: exactly one
# axon claimant at a time (every python process with
# PALLAS_AXON_POOL_IPS set claims a tunnel session at interpreter
# start — see tests/conftest.py note; concurrent claimants queue on
# the relay and starve each other).
#
# Usage: nohup bash scripts/run_queue.sh [pid-to-wait-for] &
# Logs into measurements/. Never kills a client (round-2 lesson:
# a killed axon client mid-compile can wedge the tunnel server).
set -u
cd "$(dirname "$0")/.."
mkdir -p measurements

WAIT_PID="${1:-}"
if [ -n "$WAIT_PID" ]; then
  echo "queue: waiting for pid $WAIT_PID to finish" >&2
  while kill -0 "$WAIT_PID" 2>/dev/null; do sleep 20; done
fi

run() {
  name="$1"; shift
  echo "queue: [$(date -u +%H:%M:%S)] start $name" >&2
  timeout --signal=CONT 3600 "$@" > "measurements/${name}.log" 2>&1
  # SIGCONT timeout = no-op kill: we only bound the queue's own wait.
  # If the child is still alive after, we wait for it (never kill).
  echo "queue: [$(date -u +%H:%M:%S)] done $name rc=$?" >&2
}

run probe_v5_stages_tpu_r3 python -u scripts/probe_v5_stages.py
run bench_v5w_tpu_r3 env BENCH_KERNEL=v5w BENCH_TIMEOUT=2400 python bench.py
run bench_v5_bitonic_tpu_r3 env CAUSE_TPU_SORT=bitonic BENCH_TIMEOUT=2400 python bench.py
run probe_v4_tpu_r3 python -u scripts/probe_v4.py
run pallas_probe_tpu_r3 python -u scripts/pallas_probe.py
run fleet_bench_tpu_r3 python -u scripts/fleet_bench.py
run microbench_tpu_r3 python -u scripts/tpu_microbench.py
echo "queue: all done" >&2
