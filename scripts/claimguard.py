"""Pre-compile backend-claim watchdog shared by the measurement
scripts the round-4 watcher launches (harvest.py, api_bench.py).

The axon tunnel claim (the first ``jax.devices()``) can hang 28-50
minutes, occasionally indefinitely. A script the watcher is waiting on
must not hold the claim past the watcher's deadline — but it also must
never be killed mid-compile (round-2 lesson: that can wedge the tunnel
server). So: arm a watchdog BEFORE backend init and disarm the moment
the backend answers, before any compile can be in flight; if the claim
exceeds ``HARVEST_CLAIM_DEADLINE`` seconds the process hard-exits
(rc=3) while still provably pre-compile.

Usage::

    disarm = claimguard.arm()
    plat = jax.devices()[0].platform   # the blocking claim
    disarm()

No-op when HARVEST_CLAIM_DEADLINE is unset/0 (interactive runs).
"""

from __future__ import annotations

import os
import sys
import threading


def arm(tag: str = "claimguard"):
    deadline = float(os.environ.get("HARVEST_CLAIM_DEADLINE", "0") or 0)
    if deadline <= 0:
        return lambda: None
    done = threading.Event()

    def _watch():
        if not done.wait(deadline):
            print(f"{tag}: backend claim past {deadline:.0f}s; "
                  "exiting before any compile starts", file=sys.stderr,
                  flush=True)
            os._exit(3)

    threading.Thread(target=_watch, daemon=True).start()
    return done.set
