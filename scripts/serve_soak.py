"""Open-loop overload soak for the sync service (PR 12's acceptance
instrument): a zipf-hot/bursty workload generator drives
``cause_tpu.serve.SyncService`` at a MULTIPLE of the measured
steady-state wave rate — the offered load, not the operator, decides
what happens next — and the run gates the service's robustness
contracts machine-to-machine:

- **bounded queue depth** — the admitted depth never exceeds
  ``--max-ops`` on any queue incarnation (exit 6);
- **every shed evidenced** — the queues' cumulative shed stats must
  equal the ``serve.shed`` events in the sidecar exactly (exit 5);
- **zero admitted ops lost / bit-identical convergence** — after the
  final drain the service state must equal an independent PURE-oracle
  replay of the write-ahead ingest journal (EDN + node bags + weave
  order), and a drain→restore round-trip must reproduce every
  tenant's converged digest bit-for-bit (exit 4). With ``--chaos``
  this holds ACROSS a seeded crash mid-steady-state and a second
  crash mid-drain: the harness drops the whole service object and
  restores from checkpoint + journal;
- **p99 admitted-op lag** — create→converged over the PR-9 tracer;
  reported always, gated when ``--slo-ms`` is given (exit 3).

The journal is PR 15's segmented CRC WAL (``serve/wal.py``):
retired segments move to a retire dir, so the oracle replays the
WHOLE admission history (retired + live segments) even after GC.
``--chaos disk`` (exit 7 on any miss) arms a committed seeded plan
(``--disk-plan``) covering all five disk fault modes plus a mid-GC
crash, drives periodic checkpoints so the WAL GC actually cycles,
and adds the storage gates:

- **zero admitted-op loss across storage faults** — refused appends
  (ENOSPC/torn) must surface as ``durability``-rung sheds with
  ``retry_after_ms`` in EXACT injected counts (the producer re-offers;
  nothing acked is lost), bit-rot must be found by the scrubber's CRC
  walk in exact count (the intact ground truth rides the chaos
  injection log back into the oracle), fsync failures and the
  checkpoint-rename failure must each land their ``serve.disk``
  evidence, and the previous manifest must stay intact;
- **replay-after-GC bit-identity** — a restore AFTER the mid-GC crash
  and an explicit end-of-run GC pass must reproduce every digest and
  the exact record list above the watermark;
- **bounded disk** — live WAL bytes sampled across >=3 checkpoint/GC
  cycles stay bounded while the cumulative appended-bytes baseline
  (what a single unrotated file would hold) grows monotonically;
- **final scrub clean** — the faulty segments sealed, retired, and
  out of the live WAL by the end of the run.

A clean run lands a ``--kind serve`` ledger row (value = p99
admitted-op lag ms; extra = p50/p99, sustained waves/sec, shed
counts by rung, admitted totals, crash count + MTTR) — or a
``--kind disk`` row (value = live WAL bytes after the final GC;
extra = the full storage-gate evidence) under ``--chaos disk``.

Usage::

    python scripts/serve_soak.py --obs-out serve.jsonl \
        [--tenants 8] [--capacity 4] [--seconds 20] [--rate-mult 2] \
        [--max-ops 256] [--seed 0] [--chaos [crash|disk]] \
        [--fsync batch] [--disk-plan measurements/disk_plan_r15.json] \
        [--slo-ms 5000] [--batched on|off] [--gate-dispatch]

PR 18 adds the cross-tenant batched tick as an A/B axis: ``--batched
on`` (the default) serves every touched tenant with ONE fused device
dispatch per pow2 bucket; ``--batched off`` keeps the per-tenant wave
path (~3 dispatches per touched tenant). ``--gate-dispatch`` turns
the collapse into a gate (exit 8): summed over the timed window,
wave dispatches must track the bucket count (with explicit fallback/
restore allowances), not the touched-tenant count — the acceptance
shape for the 8x-tenant config (e.g. ``--tenants 32 --gate-dispatch``
vs the 4-tenant smoke).

The generator is OPEN-LOOP: it offers per-site delta batches (zipf
tenant pick, occasional no-sleep bursts) on its own clock and never
waits for the service; a rejected offer simply leaves that site's
cumulative delta to be re-offered next time (exactly a real
producer's retry), so overload exercises the declared shed ladder
instead of silently throttling the load.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401

import argparse
import json
import os
import random
import shutil
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import cause_tpu as c  # noqa: E402
from cause_tpu import chaos, obs, serde, sync  # noqa: E402
from cause_tpu.collections import clist as c_list  # noqa: E402
from cause_tpu.collections.clist import CausalList  # noqa: E402
from cause_tpu.ids import new_site_id  # noqa: E402
from cause_tpu.collections import shared as _shared  # noqa: E402
from cause_tpu.obs import lag as _lag  # noqa: E402
from cause_tpu.serve import (IngestQueue, ResidencyManager,  # noqa: E402
                             ServiceCrashed, SyncService,
                             WriteAheadLog)
from cause_tpu.serve import wal as wal_mod  # noqa: E402
from cause_tpu.serve.scrub import scrub_wal  # noqa: E402
from cause_tpu.serve.service import MANIFEST_NAME  # noqa: E402

# exit codes (soak.py's vocabulary, extended)
EXIT_LAG = 3
EXIT_CONVERGENCE = 4
EXIT_UNEVIDENCED_SHED = 5
EXIT_DEPTH = 6
EXIT_DISK = 7
EXIT_DISPATCH = 8


class _SiteState:
    """One producing site's client-side state: its own yarn tail (the
    causal anchor every new op hangs off — a site types a run, no
    weave needed) and the UNACKED ops minted so far. A rejected offer
    keeps them pending, so the next offer re-ships the cumulative
    suffix — the producer retry loop. Minting is O(1): a real client
    is a thin front-end, not a replica with an accelerator."""

    __slots__ = ("site", "last_id", "ts", "pending")

    def __init__(self, handle):
        self.site = str(handle.ct.site_id)
        yarn = handle.ct.yarns[self.site]
        self.last_id = yarn[-1][0]
        self.ts = int(self.last_id[0])
        self.pending = []

    def mint(self, value):
        self.ts += 1
        nid = (self.ts, self.site, 0)
        self.pending.append((nid, self.last_id, value))
        self.last_id = nid
        return nid


class _Tenant:
    __slots__ = ("uuid", "sites", "minted_ops")

    def __init__(self, uuid, left, right):
        self.uuid = uuid
        self.sites = [_SiteState(left), _SiteState(right)]
        self.minted_ops = 0


def _offer_pending(queue, tenant, st):
    """Offer one site's cumulative unacked suffix; on admission the
    pending list clears (the service owns those ops now — they are
    journaled)."""
    items = serde.encode_node_items(
        {nid: (cause, value) for nid, cause, value in st.pending})
    adm = queue.offer(tenant.uuid, st.site, items,
                      crc=sync.payload_checksum(items))
    if adm.admitted:
        st.pending = []
    return adm


def _zipf_weights(n: int, alpha: float):
    w = [1.0 / ((i + 1) ** alpha) for i in range(n)]
    total = sum(w)
    return [x / total for x in w]


class Generator(threading.Thread):
    """The open-loop producer. ``holder["queue"]`` indirection lets
    the harness swap in a restored service's queue after a chaos
    crash — offers during the outage land on the CLOSED old queue and
    are refused with evidence, exactly a real front-end's view of a
    restarting backend."""

    def __init__(self, holder, tenants, rate_per_s, seed, alpha=1.2,
                 burst_p=0.15):
        super().__init__(name="serve-soak-gen", daemon=True)
        self.holder = holder
        self.tenants = tenants
        self.interval_s = 1.0 / max(1e-6, rate_per_s)
        self.rng = random.Random(seed)
        self.weights = _zipf_weights(len(tenants), alpha)
        self.burst_p = burst_p
        self.stop_evt = threading.Event()
        self.offered = 0
        self.admitted = 0
        self.refused = 0

    def _mint_and_offer(self):
        t = self.rng.choices(self.tenants, weights=self.weights)[0]
        st = t.sites[self.rng.randrange(2)]
        n_ops = self.rng.randrange(1, 4)
        ids = [st.mint(f"g{self.offered}.{j}") for j in range(n_ops)]
        t.minted_ops += n_ops
        if obs.enabled():
            # the create-side lag stamp a handle append would have
            # minted (the queue wait is part of admitted-op lag)
            _lag.op_created(t.uuid, ids)
        adm = _offer_pending(self.holder["queue"], t, st)
        self.offered += 1
        if adm.admitted:
            self.admitted += 1
        else:
            self.refused += 1

    def run(self):
        while not self.stop_evt.is_set():
            try:
                self._mint_and_offer()
            except Exception as e:  # noqa: BLE001 - surfaced in main
                self.holder.setdefault("gen_errors", []).append(
                    f"{type(e).__name__}: {e}")
                return
            if self.rng.random() < self.burst_p:
                continue  # burst: no sleep, back-to-back offers
            self.stop_evt.wait(self.interval_s)


def _mk_fleet(n_tenants: int, doc: int):
    """``n_tenants`` distinct documents, each a (left, right) replica
    pair at one shared doc size (one compile bucket)."""
    out = []
    for i in range(n_tenants):
        fresh = CausalList(
            c.clist(weaver="jax").extend(
                [f"w{i}.{j}" for j in range(doc)]).ct)
        fresh = CausalList(c_list.weave(fresh.ct))
        fresh.ct.lanes.segments()
        a = CausalList(fresh.ct.evolve(site_id=new_site_id())).conj(
            f"A{i}")
        b = CausalList(fresh.ct.evolve(site_id=new_site_id())).conj(
            f"B{i}")
        out.append((a, b))
    return out


def _pure(h):
    return CausalList(h.ct.evolve(weaver="pure", lanes=None))


def _wal_entries(wal_dir, retired_dir):
    """Every admitted record the storage layer ever held, seq-sorted:
    live PLUS retired segments (GC MOVES sealed segments into the
    retire dir, so the union is the whole admission history), with
    bit-rotted records' intact ground truth read back from the chaos
    injection log — the durable copy is wrong ON PURPOSE; the oracle
    replays what was acknowledged, not what the rot left behind."""
    entries = {}
    for d in (retired_dir, wal_dir):
        if not d or not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if not (name.startswith("wal-") and name.endswith(".seg")):
                continue
            for kind, e in wal_mod.scan_segment_file(
                    os.path.join(d, name)):
                if kind in ("rec", "legacy") and isinstance(e, dict) \
                        and "seq" in e:
                    entries[int(e["seq"])] = e
    for r in chaos.injected():
        if r.get("family") == "disk" and r.get("mode") == "bitrot" \
                and isinstance(r.get("rec"), dict):
            rec = r["rec"]
            entries[int(rec["seq"])] = rec
    return [entries[k] for k in sorted(entries)]


def _journal_oracle(pairs_init, wal_dir, retired_dir):
    """The independent no-loss oracle: each tenant's initial PURE
    pair merge, plus a pure replay of EVERY journaled entry (the
    write-ahead log is the authoritative record of admission) —
    computed with chaos suspended and obs off so the replay neither
    consumes fault counters nor pollutes the lag stream."""
    out = {}
    for uuid, (a, b) in pairs_init.items():
        out[uuid] = _pure(a).merge(_pure(b))
    entries = _wal_entries(wal_dir, retired_dir)
    for e in entries:
        uuid = str(e.get("uuid"))
        if uuid not in out:
            continue
        sync.validate_node_items(e["items"])
        nodes = serde.decode_node_items(e["items"])
        out[uuid] = sync.apply_delta(out[uuid], nodes,
                                     _count_as_delta=False)
    return out, len(entries)


def _doc_equal(dev_handle, pure_handle) -> bool:
    """The chaos-soak convergence gate: EDN + node bags + weave
    order."""
    return (c.causal_to_edn(dev_handle) == c.causal_to_edn(pure_handle)
            and dict(dev_handle.ct.nodes) == dict(pure_handle.ct.nodes)
            and [n[0] for n in dev_handle.get_weave()]
            == [n[0] for n in pure_handle.get_weave()])


def _restart(svc, ckpt_dir, capacity, d_max, watchdog_s, mk_journal,
             batched=True):
    """The crash protocol: close the old incarnation's front door and
    journal handle, drop EVERY in-memory structure, restore from the
    last checkpoint + journal (same admission bound, same residency
    pressure, same window budget, same measured controller floor — a
    restart must not quietly relax the memory, admission or control
    regime). ``mk_journal`` reopens the SAME WAL directory with the
    same rotation/fsync/retire policy — a restart must not quietly
    relax the durability regime either."""
    from cause_tpu.serve import BatchController

    floor_ms = svc.controller.floor_ms
    t_batch_ms = svc.controller.t_batch_ms
    max_ops = svc.queue.max_ops
    svc.close()  # watchdog + the incarnation's live obs subscriber
    svc.queue.close_admission()
    if svc.queue.journal is not None:
        svc.queue.journal.close()
    del svc
    queue = IngestQueue(max_ops=max_ops, journal=mk_journal())
    return SyncService.restore(
        ckpt_dir, queue=queue,
        residency=ResidencyManager(capacity=capacity),
        controller=BatchController(floor_ms=floor_ms,
                                   initial_ms=t_batch_ms),
        d_max=d_max, watchdog_s=watchdog_s, batched=batched)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--capacity", type=int, default=None,
                    help="residency capacity (default tenants//2: the "
                         "zipf tail lives spilled on host)")
    ap.add_argument("--doc", type=int, default=30)
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--rate-mult", type=float, default=2.0,
                    help="offered batch rate as a multiple of the "
                         "MEASURED steady-state wave rate (1x = "
                         "sustainable, 2x/4x = overload)")
    ap.add_argument("--max-ops", type=int, default=256)
    ap.add_argument("--d-max", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--calib-ticks", type=int, default=4)
    ap.add_argument("--chaos", nargs="?", const="crash", default=None,
                    choices=("crash", "disk"),
                    help="arm a seeded fault arm: 'crash' (bare "
                         "--chaos keeps meaning this) arms one mid-"
                         "steady-state serve.tick crash and one mid-"
                         "drain serve.drain crash; 'disk' arms the "
                         "committed --disk-plan (all five disk fault "
                         "modes + a mid-GC crash) and the storage "
                         "gates (exit 7). Either way the harness "
                         "restores from checkpoint + journal and the "
                         "no-loss gates must still hold")
    ap.add_argument("--fsync", default="batch",
                    choices=("none", "batch", "always"),
                    help="WAL fsync policy (PERF.md Round 15 prices "
                         "the three)")
    ap.add_argument("--disk-plan",
                    default=os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "..", "measurements", "disk_plan_r15.json"),
                    help="seeded chaos plan JSON for --chaos disk "
                         "(the COMMITTED plan is the reproducible "
                         "acceptance artifact)")
    ap.add_argument("--rotate-bytes", type=int, default=None,
                    help="WAL segment rotation threshold (default "
                         "8 KiB under --chaos disk so GC cycles "
                         "several times per run, 512 KiB otherwise)")
    ap.add_argument("--batched", default="on", choices=("on", "off"),
                    help="cross-tenant batched ticks (PR 18: one "
                         "fused dispatch per pow2 bucket) vs the "
                         "per-tenant wave path — the A/B axis for "
                         "the dispatch-collapse evidence")
    ap.add_argument("--gate-dispatch", action="store_true",
                    help="gate the dispatch collapse (exit 8): over "
                         "the timed window, wave dispatches per tick "
                         "must scale with the BUCKET count, not the "
                         "touched-tenant count (the 8x-tenant soak "
                         "config's acceptance gate; requires "
                         "--batched on)")
    ap.add_argument("--obs-out", required=True,
                    help="obs JSONL sidecar (required: the committed "
                         "stream IS the shed/lag/crash evidence)")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="gate p99 admitted-op lag (exit 3 past it)")
    ap.add_argument("--state-dir", default=None,
                    help="journal + checkpoint dir (default: a fresh "
                         "tempdir next to --obs-out)")
    args = ap.parse_args()

    # the sidecar IS the run's evidence: the gates compare engine
    # stats against THIS run's events, so a stale file from an
    # earlier run must not pollute the counts
    if os.path.exists(args.obs_out):
        os.unlink(args.obs_out)
    obs.configure(enabled=True, out=args.obs_out)
    obs.set_platform(jax.default_backend())
    sync.quarantine_reset()

    state_dir = args.state_dir or (args.obs_out + ".state")
    ckpt_dir = os.path.join(state_dir, "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    wal_dir = os.path.join(state_dir, "wal")
    retired_dir = os.path.join(state_dir, "wal_retired")
    for d in (wal_dir, retired_dir):
        if os.path.isdir(d):
            shutil.rmtree(d)
    legacy_journal = os.path.join(state_dir, "ingest.jsonl")
    if os.path.exists(legacy_journal):
        os.unlink(legacy_journal)
    rotate_bytes = args.rotate_bytes or (
        8192 if args.chaos == "disk" else 512 * 1024)

    def _mk_journal():
        # the PR-15 segmented WAL: same policy on every incarnation;
        # GC retires sealed segments INTO retired_dir so the oracle
        # can replay the whole admission history after GC
        return WriteAheadLog(wal_dir, rotate_bytes=rotate_bytes,
                             fsync=args.fsync, retire_dir=retired_dir)

    capacity = args.capacity or max(1, args.tenants // 2)
    batched = args.batched == "on"
    queue = IngestQueue(max_ops=args.max_ops, journal=_mk_journal())
    svc = SyncService(queue,
                      residency=ResidencyManager(capacity=capacity),
                      checkpoint_dir=ckpt_dir, d_max=args.d_max,
                      watchdog_s=5.0, batched=batched)
    holder = {"queue": queue}
    retired_queues = []

    pairs = _mk_fleet(args.tenants, args.doc)
    pairs_init = {}
    tenants = []
    for a, b in pairs:
        uuid = svc.add_tenant(a, b)
        pairs_init[uuid] = (a, b)
        tenants.append(_Tenant(uuid, a, b))
    print(f"serve soak: {args.tenants} tenant(s), residency capacity "
          f"{capacity}, max_ops {args.max_ops}, "
          f"batched={args.batched}", flush=True)

    # ---- calibration: the MEASURED steady-state wave rate ----------
    # closed-loop: mint one batch per tenant, tick, repeat — the
    # achieved batch rate includes every real cost (host mint +
    # validate + journal, per-batch apply, the wave, doc growth), so
    # "1x" genuinely means sustainable and 2x/4x genuinely mean
    # overload. The first ticks pay compiles: warm separately first.
    rng = random.Random(args.seed ^ 0x5EED)
    calib_weights = _zipf_weights(len(tenants), 1.2)

    def _flush():
        for _ in range(500):
            if not (queue.depth or queue.deferred):
                return
            svc.tick()

    def _calib_round(k):
        # the calibration load mirrors the open-loop shape (zipf
        # tenant pick, 1-3 op batches, several batches coalescing per
        # tick) so the measured walls price the REAL window sizes,
        # not a best-case one-op wave; each round drains completely,
        # so its wall is its own work and nothing leaks across rounds
        n = 0
        for j in range(3 * len(tenants)):
            t = rng.choices(tenants, weights=calib_weights)[0]
            st = t.sites[rng.randrange(2)]
            ids = [st.mint(f"c{k}.{j}.{i}")
                   for i in range(rng.randrange(1, 4))]
            if obs.enabled():
                _lag.op_created(t.uuid, ids)
            if _offer_pending(queue, t, st).admitted:
                n += 1
        _flush()
        return n

    for k in range(args.calib_ticks):  # warm: compiles, first waves,
        _calib_round(k)                # window-budget growth settles
    calib_s = 2.0
    t0 = time.perf_counter()
    batches = 0
    rounds = 0
    k = args.calib_ticks
    while rounds < 5 or time.perf_counter() - t0 < calib_s:
        batches += _calib_round(k)
        rounds += 1
        k += 1
    calib_elapsed = time.perf_counter() - t0
    steady_per_s = batches / max(1e-3, calib_elapsed)
    offered_per_s = args.rate_mult * steady_per_s
    # CPU-honest controller floor: the measured per-tenant wave wall,
    # not the tunnel's 67 ms dispatch constant (the controller's
    # default) — the inversion target must be computed in this
    # host's own cost units
    floor_ms = 1000.0 * calib_elapsed / rounds / max(1, args.tenants)
    svc.controller.floor_ms = floor_ms
    print(f"serve soak: measured steady-state {steady_per_s:.1f} "
          f"batch/s over {rounds} drained closed-loop round(s) "
          f"(measured floor {floor_ms:.2f} ms/wave); offering "
          f"{args.rate_mult:g}x = {offered_per_s:.1f} batch/s",
          flush=True)

    # flush the calibration backlog completely so the timed window's
    # lag distribution prices ONLY the open-loop run (calibration ops
    # converge — and their lag records land — before t_run_start)
    for _ in range(500):
        if not (queue.depth or queue.deferred):
            break
        svc.tick()
    # scope the measured lag to the run: calibration ops are resolved
    # (queue flushed above), so a lag epoch bump here means every
    # cumulative lag.window histogram from now on prices ONLY the
    # open-loop run
    _lag.reset()
    run_epoch = _lag.current_epoch()
    svc.checkpoint()  # the durable baseline every crash restores past

    if args.chaos == "disk":
        # arm AFTER calibration + the baseline checkpoint so the
        # plan's per-hook invocation indices count from the run's
        # first real append — the committed plan is reproducible
        with open(args.disk_plan) as f:
            disk_plan = json.load(f)
        chaos.configure(plan=disk_plan)
        print(f"serve soak: disk chaos armed from {args.disk_plan} "
              f"(seed {disk_plan.get('seed')}, "
              f"{len(disk_plan.get('faults') or [])} fault spec(s); "
              f"fsync={args.fsync} rotate_bytes={rotate_bytes})",
              flush=True)

    gen = Generator(holder, tenants, offered_per_s, args.seed)
    t_run_start_us = time.time_ns() // 1000
    gen.start()

    # ---- the timed open-loop run -----------------------------------
    svc.start_watchdog()
    t_start = time.perf_counter()
    deadline = t_start + args.seconds
    ticks = 0
    crashes = 0
    mttr_ms = []
    chaos_armed = False
    # --chaos disk: periodic checkpoints drive the retention policy —
    # each one advances the watermark and the WAL GC retires the
    # fully-applied segments; the bounded-disk gate samples across
    # these cycles while baseline_accum carries the would-have-been
    # single-file size (lifetime appended bytes) across restarts
    ckpt_every = max(1.0, args.seconds / 8.0)
    next_ckpt = t_start + ckpt_every
    gc_cycles = 0
    gc_crashes = 0
    rename_survived = 0
    manifest_intact = True
    baseline_accum = 0
    live_bytes_series = []
    baseline_bytes_series = []
    manifest_path = os.path.join(ckpt_dir, MANIFEST_NAME)
    # per-tick dispatch accounting (PR 18): every timed tick's
    # touched-tenant / bucket / costmodel-counted dispatch triple —
    # the dispatch-collapse gate's evidence base
    tick_series = []

    def _note_tick(ts):
        if ts["tenants"]:
            tick_series.append((ts["tenants"], ts["buckets"],
                                ts["wave_dispatches"]))

    # disk-arm extension (flaky-gate fix): the bounded-disk evidence
    # needs >= 3 checkpoint/GC cycles, and on a slow (~1.5-cpu CI)
    # container the timed window may simply not fit them. Rather than
    # a red gate for being slow, keep the open-loop run going past the
    # deadline until the cycles land — bounded by a HARD op-count cap
    # so a wedged GC can never spin forever (past the cap the gate
    # reports an honest skip instead)
    ext_cap_ops = max(4096, args.max_ops * 16)

    def _loop_live():
        if time.perf_counter() < deadline:
            return True
        return (args.chaos == "disk" and gc_cycles < 3
                and gen.admitted < ext_cap_ops)

    while _loop_live():
        if args.chaos == "crash" and not chaos_armed \
                and time.perf_counter() - t_start > args.seconds / 2:
            # arm at the wall-clock midpoint: the NEXT tick crashes
            # (mid-steady-state) and the FIRST drain invocation
            # crashes (mid-drain) — both restored below
            chaos.configure(plan={"seed": args.seed, "faults": [
                {"family": "crash", "site": "serve.tick", "at": [1]},
                {"family": "crash", "site": "serve.drain",
                 "at": [1]}]})
            chaos_armed = True
            print("serve soak: chaos armed at run midpoint",
                  flush=True)
        try:
            _note_tick(svc.tick())
            ticks += 1
        except ServiceCrashed as e:
            print(f"serve soak: CRASH ({e}) — restoring", flush=True)
            t_crash = time.perf_counter()
            retired_queues.append(svc.queue)
            baseline_accum += svc.queue.journal.appended_bytes
            svc = _restart(svc, ckpt_dir, capacity, args.d_max,
                           5.0, _mk_journal, batched=batched)
            holder["queue"] = svc.queue
            svc.start_watchdog()
            # the first post-restore tick closes the MTTR
            _note_tick(svc.tick())
            ticks += 1
            crashes += 1
            mttr_ms.append(round(1000 * (time.perf_counter()
                                         - t_crash), 3))
        if args.chaos == "disk" and time.perf_counter() >= next_ckpt:
            try:
                svc.checkpoint()
                gc_cycles += 1
            except ServiceCrashed as e:
                # the seeded mid-GC crash: the watermark manifest
                # landed, the retired-but-not-yet-moved segments are
                # still on disk — the restore must replay identically
                # and the NEXT cycle's GC finishes the retirement
                print(f"serve soak: CRASH mid-GC ({e}) — restoring",
                      flush=True)
                t_crash = time.perf_counter()
                retired_queues.append(svc.queue)
                baseline_accum += svc.queue.journal.appended_bytes
                svc = _restart(svc, ckpt_dir, capacity, args.d_max,
                               5.0, _mk_journal, batched=batched)
                holder["queue"] = svc.queue
                svc.start_watchdog()
                crashes += 1
                gc_crashes += 1
                mttr_ms.append(round(1000 * (time.perf_counter()
                                             - t_crash), 3))
            except _shared.CausalError as e:
                causes = getattr(e, "info", {}).get("causes", ())
                if "checkpoint-rename" not in causes:
                    raise
                # the injected manifest-rename failure: the PREVIOUS
                # manifest must still parse — the service keeps
                # serving and the next cycle's checkpoint supersedes
                try:
                    with open(manifest_path) as f:
                        m = json.load(f)
                    ok = (isinstance(m, dict)
                          and "~serve_manifest" in m)
                except (OSError, ValueError):
                    ok = False
                manifest_intact = manifest_intact and ok
                rename_survived += 1
                print("serve soak: checkpoint rename failed "
                      f"(previous manifest intact: {ok})", flush=True)
            live_bytes_series.append(svc.queue.journal.dir_bytes())
            baseline_bytes_series.append(
                baseline_accum + svc.queue.journal.appended_bytes)
            # re-space from NOW (not += ckpt_every): a slow restore
            # must not make missed slots fire back-to-back — each
            # bounded-disk sample prices a real interval of appends.
            # Past the deadline (the GC-cycle extension) tighten the
            # cadence: the extension exists only to land cycles
            next_ckpt = time.perf_counter() + (
                ckpt_every if time.perf_counter() < deadline
                else min(ckpt_every, 1.0))
        if svc.queue.depth == 0:
            # T_batch is a coalescing window, not a pure delay: with
            # a backlog waiting the batch is already built — tick on
            time.sleep(svc.controller.t_batch_ms / 1000.0)
    extended_s = round(max(0.0, time.perf_counter() - deadline), 3)
    if extended_s:
        print(f"serve soak: GC-cycle extension ran {extended_s:g}s "
              f"past the timed window ({gc_cycles} cycle(s) landed)",
              flush=True)
    gen.stop_evt.set()
    gen.join(timeout=10.0)
    elapsed = time.perf_counter() - t_start
    if holder.get("gen_errors"):
        print("serve soak: GENERATOR FAILED: "
              + "; ".join(holder["gen_errors"]), flush=True)
        return 2

    # ---- drain (chaos: crashes once mid-drain, restored, re-drained;
    # disk: the drain-time checkpoint may hit the injected manifest-
    # rename failure — the previous manifest is intact by contract
    # and the drain is simply retried, exactly a real operator's move)
    for _ in range(4):
        try:
            svc.drain()
            break
        except ServiceCrashed as e:
            print(f"serve soak: CRASH mid-drain ({e}) — restoring",
                  flush=True)
            t_crash = time.perf_counter()
            retired_queues.append(svc.queue)
            baseline_accum += svc.queue.journal.appended_bytes
            svc = _restart(svc, ckpt_dir, capacity, args.d_max,
                           None, _mk_journal, batched=batched)
            holder["queue"] = svc.queue
            crashes += 1
            mttr_ms.append(round(1000 * (time.perf_counter()
                                         - t_crash), 3))
        except _shared.CausalError as e:
            causes = getattr(e, "info", {}).get("causes", ())
            if "checkpoint-rename" not in causes:
                raise
            try:
                with open(manifest_path) as f:
                    m = json.load(f)
                ok = isinstance(m, dict) and "~serve_manifest" in m
            except (OSError, ValueError):
                ok = False
            manifest_intact = manifest_intact and ok
            rename_survived += 1
            print("serve soak: drain checkpoint rename failed "
                  f"(previous manifest intact: {ok}) — retrying",
                  flush=True)
    else:
        print("serve soak: drain did not complete in 4 attempts",
              flush=True)
        return EXIT_CONVERGENCE
    if args.chaos == "disk":
        # the drain checkpoint is the run's last GC cycle — sample it
        gc_cycles += 1
        live_bytes_series.append(svc.queue.journal.dir_bytes())
        baseline_bytes_series.append(
            baseline_accum + svc.queue.journal.appended_bytes)
    digests = {u: svc.converged_digest(u) for u in pairs_init}
    t_batch_final = round(svc.controller.t_batch_ms, 3)
    control_changes = svc.controller.changes
    svc.stop_watchdog()

    # ---- gates ------------------------------------------------------
    # (1) drain→restore bit-identity
    retired_queues.append(svc.queue)
    svc.queue.journal.close()
    svc2 = SyncService.restore(
        ckpt_dir, residency=ResidencyManager(capacity=capacity),
        d_max=args.d_max)
    restore_ok = all(svc2.converged_digest(u) == digests[u]
                     for u in pairs_init)
    # (2) the pure-oracle journal replay (no admitted op lost)
    obs.flush()
    with chaos.suspended():
        obs.configure(enabled=False)
        oracle, journal_entries = _journal_oracle(pairs_init, wal_dir,
                                                  retired_dir)
        mismatched = [u for u in pairs_init
                      if not _doc_equal(svc2.materialize(u),
                                        oracle[u])]
    # (2b) disk arm: replay-after-GC bit-identity + the final scrub —
    # an explicit end-of-run GC pass at the manifest watermark must
    # not change the replayable suffix, a THIRD restore after it must
    # reproduce every digest, and the live WAL must scrub clean (the
    # faulty segments sealed + retired during the run)
    replay_after_gc_ok = True
    gc_restore_ok = True
    final_live_bytes = None
    scrub_rep = None
    if args.chaos == "disk":
        with chaos.suspended():
            with open(manifest_path) as f:
                final_wm = int(json.load(f).get("gc_watermark") or 0)
            svc2.queue.journal.close()
            jx = _mk_journal()
            pre_gc = list(jx.iter_from(final_wm))
            jx.gc(final_wm)
            post_gc = list(jx.iter_from(final_wm))
            replay_after_gc_ok = pre_gc == post_gc
            svc3 = SyncService.restore(
                ckpt_dir,
                residency=ResidencyManager(capacity=capacity),
                d_max=args.d_max)
            gc_restore_ok = all(svc3.converged_digest(u) == digests[u]
                                for u in pairs_init)
            final_live_bytes = jx.dir_bytes()
            jx.close()
            scrub_rep = scrub_wal(wal_dir, retired=retired_dir)
    # (3) evidence + bounds, over the committed sidecar
    from cause_tpu.obs import lag as lag_mod
    from cause_tpu.obs import ledger
    from cause_tpu.obs.perfetto import load_jsonl

    evs = load_jsonl(args.obs_out)
    shed_events = [e for e in evs if e.get("ev") == "event"
                   and e.get("name") == "serve.shed"]
    stats_total = {"sheds": 0, "shed_ops": 0, "admitted_ops": 0,
                   "admitted_batches": 0, "max_depth": 0}
    by_rung = {"defer": 0, "reject": 0, "drop_oldest": 0,
               "durability": 0}
    for q in retired_queues:
        for k in ("sheds", "shed_ops", "admitted_ops",
                  "admitted_batches"):
            stats_total[k] += q.stats[k]
        stats_total["max_depth"] = max(stats_total["max_depth"],
                                       q.stats["max_depth"])
        for k in by_rung:
            by_rung[k] += q.stats["shed_by_rung"][k]
    # lag epoch-scoped to the run (the calibration epoch's cumulative
    # histograms are a different generation); wave rate over the run
    # window by timestamp
    summary_lag = lag_mod.lag_summary(evs, epoch=run_epoch)
    conv = summary_lag["converged"]
    waves = sum(1 for e in evs if e.get("ev") == "event"
                and e.get("name") == "wave.digest"
                and (e.get("ts_us") or 0) >= t_run_start_us)
    waves_per_s = round(waves / max(1e-3, elapsed), 2)
    chaos_injects = sum(1 for e in evs if e.get("ev") == "event"
                        and e.get("name") == "chaos.inject")

    # ---- dispatch-collapse evidence (PR 18) -------------------------
    # every timed tick's (touched tenants, buckets, costmodel-counted
    # wave dispatches); restores cost extra dispatches (digest-gated
    # re-upload) and are priced separately so the gate below compares
    # the WAVE cost, not the residency churn
    touches_total = sum(t for t, _b, _d in tick_series)
    buckets_total = sum(b for _t, b, _d in tick_series)
    disp_total = sum(d for _t, _b, d in tick_series)
    restores_run = sum(1 for e in evs if e.get("ev") == "event"
                       and e.get("name") == "serve.restore"
                       and (e.get("ts_us") or 0) >= t_run_start_us)
    fallbacks_run = sum((e.get("fields") or {}).get("fallbacks", 0)
                        for e in evs if e.get("ev") == "event"
                        and e.get("name") == "serve.tick"
                        and (e.get("ts_us") or 0) >= t_run_start_us)
    dispatch_summary = {
        "batched": batched,
        "ticks_touched": len(tick_series),
        "tenant_touches": touches_total,
        "buckets": buckets_total,
        "wave_dispatches": disp_total,
        "fallbacks": fallbacks_run,
        "restores": restores_run,
        "per_touch": round(disp_total / max(1, touches_total), 3),
        "per_bucket": round(disp_total / max(1, buckets_total), 3)
        if buckets_total else None,
    }

    # ---- disk-arm detection + bounded-disk evidence -----------------
    # every INJECTED storage fault must be DETECTED with exact
    # evidence on the right ladder: refused appends as durability
    # sheds, bit-rot by the scrubber's CRC walk, fsync/rename
    # failures as serve.disk events, the mid-GC crash survived
    disk_summary = None
    disk_failures = []
    if args.chaos == "disk":
        inj_by_mode = {}
        inj_gc_crashes = 0
        for r in chaos.injected():
            if r.get("family") == "disk":
                m = r.get("mode")
                inj_by_mode[m] = inj_by_mode.get(m, 0) + 1
            elif r.get("family") == "crash" \
                    and r.get("site") == "serve.wal.gc":
                inj_gc_crashes += 1
        shed_reasons = {}
        for e in shed_events:
            f = e.get("fields") or {}
            if f.get("rung") == "durability":
                shed_reasons[f.get("reason")] = \
                    shed_reasons.get(f.get("reason"), 0) + 1
        disk_ops = {}
        for e in evs:
            if e.get("ev") == "event" and e.get("name") == "serve.disk":
                op = (e.get("fields") or {}).get("op")
                disk_ops[op] = disk_ops.get(op, 0) + 1
        retired_rep = (scrub_rep or {}).get("retired") or {}
        crc_found = ((scrub_rep or {}).get("crc_failures", 0)
                     + retired_rep.get("crc_failures", 0))
        torn_found = ((scrub_rep or {}).get("torn", 0)
                      + retired_rep.get("torn", 0))
        checks = {
            "enospc_refused_exactly":
                inj_by_mode.get("enospc", 0) > 0
                and shed_reasons.get("wal-enospc", 0)
                == inj_by_mode["enospc"],
            "torn_refused_exactly":
                inj_by_mode.get("torn", 0) > 0
                and shed_reasons.get("wal-torn", 0)
                == inj_by_mode["torn"]
                and torn_found == inj_by_mode["torn"],
            "bitrot_scrubbed_exactly":
                inj_by_mode.get("bitrot", 0) > 0
                and crc_found == inj_by_mode["bitrot"],
            "fsync_fail_evidenced":
                inj_by_mode.get("fsync", 0) > 0
                and disk_ops.get("fsync", 0) == inj_by_mode["fsync"],
            "rename_fail_evidenced":
                inj_by_mode.get("rename", 0) > 0
                and disk_ops.get("checkpoint", 0)
                == inj_by_mode["rename"],
            "manifest_intact": manifest_intact and rename_survived > 0,
            "gc_crash_survived": gc_crashes >= 1
                and inj_gc_crashes >= 1,
            "replay_after_gc_identical": replay_after_gc_ok
                and gc_restore_ok,
            "live_scrub_clean": bool((scrub_rep or {}).get("clean")),
            # Baseline must grow strictly while the generator runs
            # (appends never starved); the final drain-time sample may
            # tie — generation has already stopped by then. When even
            # the extension could not land 3 GC cycles (hard op cap),
            # the claim is UNTESTED on this host — report the honest
            # skip, never a red gate for being slow.
            "disk_bounded": (
                "skipped: insufficient_gc_cycles"
                if gc_cycles < 3 or len(live_bytes_series) < 3
                else (all(b2 > b1 for b1, b2 in zip(
                          baseline_bytes_series[:-1],
                          baseline_bytes_series[1:-1]))
                      and baseline_bytes_series[-1]
                      >= baseline_bytes_series[-2]
                      and live_bytes_series[-1] * 2
                      < baseline_bytes_series[-1])),
        }
        disk_failures = sorted(k for k, ok in checks.items() if not ok)
        disk_summary = {
            "fsync": args.fsync, "rotate_bytes": rotate_bytes,
            "plan": os.path.relpath(args.disk_plan),
            "injected_by_mode": inj_by_mode,
            "gc_crashes_injected": inj_gc_crashes,
            "durability_sheds_by_reason": shed_reasons,
            "serve_disk_events_by_op": disk_ops,
            "gc_cycles": gc_cycles, "gc_crashes": gc_crashes,
            "extension_s": extended_s,
            "extension_cap_ops": ext_cap_ops,
            "rename_survived": rename_survived,
            "live_bytes_series": live_bytes_series,
            "baseline_bytes_series": baseline_bytes_series,
            "final_live_bytes": final_live_bytes,
            "scrub": {"clean": bool((scrub_rep or {}).get("clean")),
                      "crc_failures": crc_found,
                      "torn": torn_found,
                      "live_segments":
                          len((scrub_rep or {}).get("segments") or []),
                      "retired_segments":
                          len(retired_rep.get("segments") or [])},
            "checks": checks,
        }

    summary = {
        "rate_mult": args.rate_mult,
        "steady_per_s": round(steady_per_s, 2),
        "offered_per_s": round(offered_per_s, 2),
        "offered": gen.offered, "gen_admitted": gen.admitted,
        "gen_refused": gen.refused,
        "admitted_ops": stats_total["admitted_ops"],
        "admitted_batches": stats_total["admitted_batches"],
        "journal_entries": journal_entries,
        "ticks": ticks, "waves_per_s": waves_per_s,
        "max_depth": stats_total["max_depth"],
        "max_ops": args.max_ops,
        "sheds": stats_total["sheds"], "shed_by_rung": by_rung,
        "shed_events": len(shed_events),
        "p50_ms": conv["p50_ms"], "p99_ms": conv["p99_ms"],
        "pending": summary_lag["pending"],
        "t_batch_ms": t_batch_final,
        "control_changes": control_changes,
        "floor_ms": round(floor_ms, 3),
        "crashes": crashes, "mttr_ms": mttr_ms,
        "chaos_injects": chaos_injects,
        "fsync": args.fsync,
        "batched": batched,
        "dispatch": dispatch_summary,
        "restore_bit_identical": bool(restore_ok),
        "oracle_mismatches": mismatched,
    }
    if disk_summary is not None:
        summary["disk"] = disk_summary
    print("serve soak:", json.dumps(summary, indent=1), flush=True)

    if stats_total["max_depth"] > args.max_ops:
        print("serve soak: QUEUE DEPTH BOUND VIOLATED", flush=True)
        return EXIT_DEPTH
    if stats_total["sheds"] != len(shed_events):
        print(f"serve soak: UNEVIDENCED SHEDS (stats "
              f"{stats_total['sheds']} != events {len(shed_events)})",
              flush=True)
        return EXIT_UNEVIDENCED_SHED
    if mismatched or not restore_ok:
        print("serve soak: CONVERGENCE GATE FAILED "
              f"(restore_ok={restore_ok}, mismatched={mismatched})",
              flush=True)
        return EXIT_CONVERGENCE
    if args.chaos == "crash" and crashes < 2:
        print(f"serve soak: chaos armed but only {crashes} crash(es) "
              "fired — the no-loss claim was not exercised",
              flush=True)
        return EXIT_CONVERGENCE
    if args.chaos == "disk" and disk_failures:
        print(f"serve soak: DISK GATES FAILED: {disk_failures}",
              flush=True)
        return EXIT_DISK
    if args.gate_dispatch:
        # the batched tick's whole claim: dispatches scale with the
        # BUCKET count, not the touched-tenant count. Allowances are
        # explicit and evidenced: a fallback's full-width wave is ~3
        # dispatches, a digest-gated restore ~2, and each touched
        # tick gets one dispatch of slack (capacity-growth full
        # re-uploads on a growing document)
        bound = (buckets_total + 3 * fallbacks_run + 2 * restores_run
                 + len(tick_series))
        collapsed = (batched and disp_total <= bound
                     and disp_total < 3 * max(1, touches_total))
        if not collapsed:
            print("serve soak: DISPATCH COLLAPSE GATE FAILED "
                  f"(batched={batched}, dispatches {disp_total} vs "
                  f"bucket-bound {bound}, 3x-touches "
                  f"{3 * touches_total}): {dispatch_summary}",
                  flush=True)
            return EXIT_DISPATCH
        print(f"serve soak: dispatch collapse held — {disp_total} "
              f"dispatch(es) over {touches_total} tenant-touches in "
              f"{len(tick_series)} tick(s) ({buckets_total} bucket "
              f"dispatch(es), {fallbacks_run} fallback(s), "
              f"{restores_run} restore(s))", flush=True)

    try:
        if args.chaos == "disk":
            row_kind = "disk"
            metric = "disk soak live WAL bytes after final GC"
            value = final_live_bytes
        else:
            row_kind = "serve"
            metric = "serve soak p99 admitted-op lag"
            value = conv["p99_ms"]
        row = ledger.ingest_record(
            {
                "platform": jax.default_backend(),
                "metric": metric,
                "value": value,
                "kernel": row_kind,
                "config": f"tenants={args.tenants} cap={capacity} "
                          f"mult={args.rate_mult:g} "
                          f"max_ops={args.max_ops} "
                          f"chaos={args.chaos or 'off'} "
                          f"fsync={args.fsync} "
                          f"batched={args.batched}",
                "smoke": False,
            },
            source=f"serve-soak seed={args.seed} "
                   f"seconds={args.seconds:g}",
            obs_jsonl=args.obs_out,
            kind=row_kind,
            extra={row_kind: {k: v for k, v in summary.items()
                              if k != "oracle_mismatches"}},
        )
        print(f"serve soak: ledger row ({row['platform']}) -> "
              f"{ledger.default_path()}", flush=True)
    except Exception as e:  # noqa: BLE001 - best-effort ledger append
        print(f"serve soak: ledger append skipped "
              f"({type(e).__name__}: {e})", flush=True)

    if args.slo_ms is not None:
        if conv["p99_ms"] is None or conv["p99_ms"] > args.slo_ms:
            print(f"serve soak: LAG GATE BREACH (p99 "
                  f"{conv['p99_ms']} ms > {args.slo_ms:g} ms)",
                  flush=True)
            return EXIT_LAG
    print(f"serve soak: clean — {stats_total['admitted_ops']} op(s) "
          f"admitted, {stats_total['sheds']} shed(s) all evidenced, "
          f"{crashes} crash(es) survived, every tenant bit-identical "
          f"to the journal oracle", flush=True)
    return 0


if __name__ == "__main__":
    rc = main()
    chaos.reset()
    sys.exit(rc)
