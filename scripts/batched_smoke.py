"""Batched-serve smoke: the PR-18 acceptance instrument CI runs on
every push.

Twelve tenants split across two pow2 buckets (delta budgets 16 and
48 -> window caps 32 and 64), one op each, ONE batched tick — then
the same admitted-op schedule through an unbatched service. Gates:

- the batched tick's device dispatch count (costmodel-counted)
  equals the BUCKET count, with zero per-tenant fallbacks;
- every tenant observed at least one agreeing ``wave.digest`` in
  that single tick (the fused dispatch is not skipping anyone);
- per-tenant converged digests are bit-identical between the
  batched and unbatched arms (batching changes WHEN device programs
  run, never what they compute);
- a ``--kind serve`` ledger row lands (value = dispatches per
  batched tick) for ``ledger --check`` to vet.

Exit 0 clean; any gate miss raises (exit 1). Usage::

    CAUSE_TPU_LEDGER=/tmp/scratch.jsonl python scripts/batched_smoke.py
"""

from __future__ import annotations

import _bootstrap  # noqa: F401

import argparse
import os
import shutil
import sys
import tempfile

import jax  # noqa: E402

import cause_tpu as c  # noqa: E402
from cause_tpu import obs, serde, sync  # noqa: E402
from cause_tpu.collections import clist as c_list  # noqa: E402
from cause_tpu.collections.clist import CausalList  # noqa: E402
from cause_tpu.ids import new_site_id  # noqa: E402
from cause_tpu.obs import ledger, load_jsonl  # noqa: E402
from cause_tpu.serve import (IngestJournal, IngestQueue,  # noqa: E402
                             ResidencyManager, SyncService)


def _base(n=8):
    base = CausalList(c_list.weave(
        c.clist(weaver="jax").extend(["w"] * n).ct
    ))
    base.ct.lanes.segments()
    return base


def _pair(base):
    a = CausalList(base.ct.evolve(site_id=new_site_id()))
    b = CausalList(base.ct.evolve(site_id=new_site_id()))
    return a.conj("A"), b.conj("B")


def _delta_items(new, old):
    return serde.encode_node_items(
        sync.delta_nodes(new, sync.version_vector(old)))


def _service(root, capacity, batched):
    os.makedirs(root, exist_ok=True)
    jr = IngestJournal(os.path.join(root, "wal.jsonl"))
    q = IngestQueue(max_ops=4096, journal=jr)
    return SyncService(
        q, residency=ResidencyManager(capacity=capacity),
        checkpoint_dir=os.path.join(root, "ckpt"),
        d_max=64, batched=batched)


def _events(evs, name):
    return [e for e in evs if e.get("ev") == "event"
            and e.get("name") == name]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", type=int, default=12)
    ap.add_argument("--obs-out", default="/tmp/obs_batched_smoke.jsonl")
    args = ap.parse_args()
    n = args.tenants

    if os.path.exists(args.obs_out):
        os.unlink(args.obs_out)
    obs.configure(enabled=True, out=args.obs_out)
    work = tempfile.mkdtemp(prefix="batched_smoke_")
    try:
        svc_b = _service(os.path.join(work, "b"), capacity=n,
                         batched=True)
        tenants = []
        for i in range(n):
            a, b = _pair(_base(8))
            # two delta budgets -> exactly two pow2 window buckets
            svc_b.add_tenant(a, b, d_max=16 if i % 2 == 0 else 48)
            tenants.append({"uuid": str(a.ct.uuid), "a": a, "b": b,
                            "d_max": 16 if i % 2 == 0 else 48})
        schedule = []
        for i, t in enumerate(tenants):
            nl = t["a"].conj(f"op{i}")
            schedule.append((t["uuid"], nl.ct.site_id,
                             _delta_items(nl, t["a"])))
        for uuid, site, items in schedule:
            assert svc_b.queue.offer(uuid, site, items).admitted
        out = svc_b.tick(max_ops=4 * n)
        assert out["tenants"] == n, out
        assert out["buckets"] == 2, out
        # THE smoke gate: one fused dispatch per bucket, nothing more
        assert out["wave_dispatches"] == out["buckets"], out
        dig_b = {t["uuid"]: svc_b.converged_digest(t["uuid"])
                 for t in tenants}

        svc_u = _service(os.path.join(work, "u"), capacity=n,
                         batched=False)
        assert not svc_u.batched
        for t in tenants:
            svc_u.add_tenant(t["a"], t["b"], d_max=t["d_max"])
        for uuid, site, items in schedule:
            assert svc_u.queue.offer(uuid, site, items).admitted
        svc_u.tick(max_ops=4 * n)
        for t in tenants:
            assert svc_u.converged_digest(t["uuid"]) == dig_b[t["uuid"]]
    finally:
        obs.configure(enabled=False)
        shutil.rmtree(work, ignore_errors=True)

    evs = load_jsonl(args.obs_out)
    ticks = [e["fields"] for e in _events(evs, "serve.tick")]
    tick_b = ticks[0]  # the batched arm ticked first
    assert tick_b["buckets"] == 2 and tick_b["fallbacks"] == 0, tick_b
    assert tick_b["wave_dispatches"] == 2, tick_b
    assert tick_b["batch_rows"] >= n, tick_b
    # every tenant agreed inside the batched tick's fused waves: only
    # count digests observed BEFORE the unbatched arm's tick (the
    # stream is append-ordered, so stop at the second serve.tick)
    agreed = set()
    seen_ticks = 0
    for e in evs:
        if e.get("ev") != "event":
            continue
        if e.get("name") == "serve.tick":
            seen_ticks += 1
            if seen_ticks == 2:
                break
        if e.get("name") == "wave.digest" \
                and e["fields"].get("agreed"):
            agreed.add(e["fields"]["uuid"])
    missing = {t["uuid"] for t in tenants} - agreed
    assert not missing, f"tenants without an agreed wave.digest: " \
                        f"{sorted(missing)}"

    row = ledger.ingest_record(
        {
            "platform": jax.default_backend(),
            "metric": "batched tick dispatches per bucket",
            "value": out["wave_dispatches"] / out["buckets"],
            "kernel": "serve",
            "config": f"tenants={n} buckets=2 batched=smoke",
            "smoke": True,
        },
        source="batched-smoke one-tick",
        obs_jsonl=args.obs_out,
        kind="serve",
        extra={"serve": {"tenants": n, "buckets": out["buckets"],
                         "wave_dispatches": out["wave_dispatches"],
                         "fallbacks": tick_b["fallbacks"],
                         "batch_rows": tick_b["batch_rows"],
                         "digest_bit_identical": True}},
    )
    print(f"batched smoke: {n} tenants, {out['buckets']} buckets, "
          f"{out['wave_dispatches']} dispatch(es) in one tick; "
          f"digests bit-identical to unbatched; ledger row "
          f"({row['platform']}) -> {ledger.default_path()}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
