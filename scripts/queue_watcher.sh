#!/bin/bash
# Delegator kept for PERF.md command compatibility: generation 1 of the
# round-3 queue watcher, now one parameterization of tunnel_watcher.sh.
exec bash "$(dirname "$0")/tunnel_watcher.sh" queue --hours 24
