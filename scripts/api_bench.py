"""API-level merge benchmarks.

Two stories, both end-to-end through the public handles (kernel-level
benchmarks bypass the host by generating lanes synthetically —
benchgen; THIS script pays every host cost honestly):

1. default: single ``CausalList.merge`` at 10k nodes per backend, with
   the jax path split into host-union / host-marshal / device-kernel.
2. ``--wave B``: a batched merge wave of B divergent replica pairs
   through ``parallel.merge_wave`` — the north-star path (BASELINE
   config 5) — split into host assembly (cached-lane gathering +
   segment tables + budgets) vs device kernel vs digest sync, plus the
   on-demand cost of materializing one merged pair back to a host
   handle. The lane cache means assembly touches numpy arrays only;
   the per-tree marshal was paid once at build time and maintained
   incrementally by the handles' edits.

Prints one JSON line per measurement.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path for checkout runs)

import argparse
import json
import os
import time

import numpy as np

# process-launch anchor for the claim-deadline arithmetic in
# claimed_platform (the watcher sizes HARVEST_CLAIM_DEADLINE at launch)
_T0 = time.monotonic()


def build_pair(n_base: int, n_div: int, weaver: str):
    import cause_tpu as c
    from cause_tpu.collections.clist import CausalList
    from cause_tpu.ids import new_site_id

    base = c.clist(weaver=weaver).extend(["x"] * n_base)
    a = CausalList(base.ct.evolve(site_id=new_site_id()))
    b = CausalList(base.ct.evolve(site_id=new_site_id()))
    a = a.extend([f"a{i}" for i in range(n_div)])
    b = b.extend([f"b{i}" for i in range(n_div)])
    return a, b


def timed(fn, reps=3):
    fn()  # warm (compiles, caches)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1000)
    return float(np.median(ts))


def claimed_platform() -> str:
    """The bounded backend claim, shared by every path (round-5
    review): claimguard arms around the first blocking backend call so
    a wedged tunnel claim cannot outlive the watcher's deadline; the
    guard disarms before any compile can be in flight. Call ONLY
    after all pure-host minting is done (window economy — the claim
    negotiation is in flight from interpreter start, so host work
    before this call overlaps the wait instead of burning granted
    tunnel seconds). The watcher's HARVEST_CLAIM_DEADLINE was sized at
    process LAUNCH, so the minutes the mint spent before this call are
    subtracted — the wedge guarantee is anchored to launch, not to
    whenever we got around to arming."""
    import claimguard
    import jax

    dl = float(os.environ.get("HARVEST_CLAIM_DEADLINE", "0") or 0)
    if dl > 0:
        elapsed = time.monotonic() - _T0
        os.environ["HARVEST_CLAIM_DEADLINE"] = str(
            max(60.0, dl - elapsed))
    disarm = claimguard.arm("api_bench")
    platform = jax.devices()[0].platform
    disarm()
    return platform


def wave_bench(args):
    import jax

    import cause_tpu as c
    from cause_tpu.collections import clist as c_list
    from cause_tpu.collections.clist import CausalList
    from cause_tpu.ids import new_site_id
    from cause_tpu.parallel import merge_wave
    from cause_tpu.parallel.wave import WaveBuffers, _assemble_rows, _digest_fn
    from cause_tpu.weaver import lanecache
    from cause_tpu.weaver.arrays import next_pow2
    from cause_tpu.benchgen import LANE_KEYS5, v5_token_budget
    import jax.numpy as jnp

    # BENCH_KERNEL routes the wave-family kernel (v5 default, v5w
    # euler walk, v5f fused pipeline) — the SAME knob merge_wave
    # reads, so the device-kernel split and the whole-wave number in
    # one log line always measure the same program; every JSON line
    # below records it
    wave_kernel = os.environ.get("BENCH_KERNEL", "").strip() or "v5"
    if wave_kernel not in ("v5", "v5w", "v5f"):
        raise SystemExit(f"api_bench: BENCH_KERNEL must be "
                         f"v5/v5w/v5f, got {wave_kernel!r}")
    if wave_kernel == "v5f":
        from cause_tpu.weaver.jaxw5f import (
            batched_merge_weave_v5f)

        def batched_merge_weave_v5(*a, u_max, k_max):
            return batched_merge_weave_v5f(*a, u_max=u_max,
                                           k_max=k_max)
    else:
        from cause_tpu.weaver.jaxw5 import (
            batched_merge_weave_v5 as _bm5)
        _euler = "walk" if wave_kernel == "v5w" else "doubling"

        def batched_merge_weave_v5(*a, u_max, k_max):
            return _bm5(*a, u_max=u_max, k_max=k_max, euler=_euler)

    B, n_base, n_div = args.wave, args.n_base, args.n_div

    t0 = time.perf_counter()
    # mint with the PURE weaver: the jax-weaver base weave device_puts
    # its 10k-node chain, i.e. the first mint line would block on the
    # backend claim before claimguard arms and before the overlap the
    # deferred claim exists for (round-5 review; verified by a backend
    # -init spy). The handles evolve to weaver="jax" after the weave —
    # identical wave behavior, zero backend touch during the mint.
    ct = c.clist(weaver="pure", lazy=args.lazy).extend(["x"] * n_base).ct
    if args.lazy:
        # materialize once; non-lazy extend already wove incrementally
        # (a second full fold would be a redundant O(n^2) host pass)
        ct = c_list.weave(ct)
    # warm the base lane view host-side (pure numpy): the jax mint got
    # this as a device-weave side effect; replicas inherit the view
    # through evolve() and extend it incrementally per edit, so the
    # wave measures cached-lane assembly exactly as before
    base = CausalList(ct.evolve(
        weaver="jax",
        lanes=lanecache.build_view(ct.nodes, ct.uuid)))
    pairs = []
    for p in range(B):
        # BASELINE config-5 shape: divergent suffixes with a tombstone
        # every 8th node (tombstones break chain runs, so this is the
        # honest segment/token structure, not a best case)
        def replica(tag):
            r = CausalList(base.ct.evolve(site_id=new_site_id()))
            vals = [f"{tag}{p}.{i}" for i in range(n_div)]
            for start in range(0, n_div, 8):
                r = r.extend(vals[start:start + 8])
                r = r.append(r.tail_id(), c.hide)
            return r

        pairs.append((replica("a"), replica("b")))
    build_s = time.perf_counter() - t0
    # emit the finished setup measurement BEFORE the blocking claim: a
    # wedged claim (guard rc=3) must not discard evidence already won
    print(json.dumps({
        "metric": "wave setup (mint replicas, incl. incremental lane cache)",
        "pairs": B, "nodes_per_tree": n_base + n_div + 1,
        "value": round(build_s, 1), "unit": "s",
    }), flush=True)

    platform = claimed_platform()

    # --- host side: view gathering + batch assembly + budget ---------
    bufs = WaveBuffers()

    def host_assemble():
        views = [(lanecache.view_for(a.ct), lanecache.view_for(b.ct))
                 for a, b in pairs]
        cap = next_pow2(max(max(va.n, vb.n) for va, vb in views))
        lanes = _assemble_rows(views, cap, bufs=bufs)
        return lanes, v5_token_budget(lanes)

    t_host = timed(host_assemble, reps=args.reps)
    lanes, u_max = host_assemble()

    # --- device side: one wave dispatch + scalar sync ----------------
    jlanes = [jnp.asarray(lanes[k]) for k in LANE_KEYS5]

    def kernel_once():
        r, v, _c_, ov = batched_merge_weave_v5(
            *jlanes, u_max=u_max, k_max=u_max
        )
        d = _digest_fn()(jlanes[0], jlanes[1], r, v)
        return int(np.asarray(d[0])), int(np.asarray(ov.sum()))

    t_kernel = timed(kernel_once, reps=args.reps)

    # amortized per-wave cost over a pipelined burst (one terminal
    # sync): the dispatch-floor-resistant number — see PERF.md
    n_burst = args.burst

    def kernel_burst():
        outs = []
        for _ in range(n_burst):
            r, v, _c_, ov = batched_merge_weave_v5(
                *jlanes, u_max=u_max, k_max=u_max
            )
            outs.append(_digest_fn()(jlanes[0], jlanes[1], r, v))
        return [int(np.asarray(d[0])) for d in outs][-1]

    t_burst = timed(kernel_burst, reps=max(1, args.reps - 1)) / n_burst

    # --- whole wave through the public API ---------------------------
    t_wave = timed(lambda: merge_wave(pairs), reps=args.reps)
    res = merge_wave(pairs)
    t_mat = timed(lambda: res.merged(0), reps=args.reps)

    # --- device-resident session: the steady-state loop --------------
    from cause_tpu.parallel.session import FleetSession

    sess = FleetSession(pairs)
    sess.wave()
    w = [0]

    def edit_all():
        w[0] += 1
        return [(x.conj(f"s{w[0]}x"), y.extend([f"s{w[0]}y"]))
                for x, y in sess.pairs]

    sess.update(edit_all())
    sess.wave()  # compile the delta path
    t_edits, t_rounds = [], []
    for _ in range(args.reps + 1):
        t0 = time.perf_counter()
        nxt = edit_all()
        t1 = time.perf_counter()
        sess.update(nxt)
        sess.wave()
        t2 = time.perf_counter()
        t_edits.append((t1 - t0) * 1000)
        t_rounds.append((t2 - t1) * 1000)
    print(json.dumps({
        "metric": "device-resident session round",
        "kernel": "v5",  # the session's resident splice is v5-only
        "pairs": B,
        "edit_all_replicas_ms": round(float(np.median(t_edits[1:])), 1),
        "delta_update_plus_wave_ms": round(
            float(np.median(t_rounds[1:])), 1
        ),
        "unit": "ms",
        "platform": platform,
    }), flush=True)

    _, n_over = kernel_once()
    print(json.dumps({
        "metric": f"merge wave {B} pairs x {n_base + n_div + 1}-node "
                  "CausalLists (API, cached lanes)",
        "host_assembly_ms": round(t_host, 1),
        "device_kernel_ms": round(t_kernel, 1),
        "device_kernel_amortized_ms": round(t_burst, 1),
        "whole_wave_ms": round(t_wave, 1),
        "materialize_one_pair_ms": round(t_mat, 2),
        "host_lt_kernel": bool(t_host < t_kernel),
        "u_max": int(u_max), "overflow_rows": n_over,
        "fallback_pairs": len(res.fallback),
        "kernel": wave_kernel,
        "platform": platform, "unit": "ms",
    }), flush=True)


def map_bench(args):
    """Batched map-forest merge at fleet scale: B replica pairs of one
    CausalMap through mapw.batched_merge_map_weave (VERDICT r2 #4's
    bench row for maps)."""
    import jax

    import cause_tpu as c
    from cause_tpu import K
    from cause_tpu.collections.cmap import CausalMap
    from cause_tpu.ids import new_site_id
    from cause_tpu.weaver import mapw

    B = args.maps
    base = c.cmap()
    for i in range(args.n_keys):
        base = base.append(K(f"k{i}"), f"v{i}")
    pairs = []
    for p in range(B):
        a = CausalMap(base.ct.evolve(site_id=new_site_id()))
        b = CausalMap(base.ct.evolve(site_id=new_site_id()))
        for e in range(args.n_edits):
            a = a.append(K(f"k{(p + e) % args.n_keys}"), f"a{p}.{e}")
            b = b.append(K(f"x{e % 4}"), f"b{p}.{e}")
        pairs.append((a.ct.nodes, b.ct.nodes))

    platform = claimed_platform()

    t_marshal = timed(lambda: mapw.pair_rows(pairs), reps=args.reps)
    lanes, meta = mapw.pair_rows(pairs)

    # device-side digest + one scalar sync (same methodology as the
    # list wave bench: never time a full-batch device->host transfer)
    import jax.numpy as jnp
    from cause_tpu.parallel.wave import _digest_fn

    jhi = jnp.asarray(lanes["hi"])
    jlo = jnp.asarray(lanes["lo"])

    def kernel():
        o, r, v, _c_, ov = mapw.batched_merge_map_weave(lanes)
        hs = jnp.take_along_axis(jhi, o, axis=1)
        ls = jnp.take_along_axis(jlo, o, axis=1)
        d = _digest_fn()(hs, ls, r, v)
        assert not bool(np.asarray(ov.sum()))
        return int(np.asarray(d[0]))

    t_kernel = timed(kernel, reps=args.reps)
    print(json.dumps({
        "metric": f"batched map merge, {B} replica pairs x "
                  f"{args.n_keys} keys + {args.n_edits} edits/side",
        "host_marshal_ms": round(t_marshal, 1),
        "device_kernel_ms": round(t_kernel, 1),
        "capacity": meta["capacity"],
        "platform": platform,
        "unit": "ms",
    }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-base", type=int, default=9_000)
    ap.add_argument("--n-div", type=int, default=1_000)
    ap.add_argument("--wave", type=int, default=0,
                    help="batched wave of this many replica pairs")
    ap.add_argument("--maps", type=int, default=0,
                    help="batched MAP merge of this many replica pairs")
    ap.add_argument("--n-keys", type=int, default=32)
    ap.add_argument("--n-edits", type=int, default=16)
    ap.add_argument("--burst", type=int, default=8,
                    help="pipelined waves per amortized measurement")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--lazy", action="store_true",
                    help="lazy-weave replicas: skip the per-op host "
                         "weave splice in the edit loop")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    # The wave/map paths claim the backend INSIDE their bench fn via
    # claimed_platform(), AFTER the pure-host fleet mint (round-5
    # window economy: the 1024-pair mint is ~79 s of host work that
    # must not spend granted tunnel time — the same marshal-before
    # -claim rule as bench.py/harvest.py).
    if args.maps:
        map_bench(args)
        return
    if args.wave:
        wave_bench(args)
        return

    platform = claimed_platform()
    for weaver in ("pure", "native", "jax"):
        a, b = build_pair(args.n_base, args.n_div, weaver)
        p50 = timed(lambda: a.merge(b))
        print(json.dumps({
            "metric": f"CausalList.merge {args.n_base}+{args.n_div} nodes",
            "weaver": weaver,
            "value": round(p50, 1),
            "unit": "ms",
        }), flush=True)

        if weaver == "jax":
            from cause_tpu.collections import shared as s
            from cause_tpu.weaver import jaxw
            from cause_tpu.weaver.arrays import NodeArrays

            union = s.union_nodes(a.ct, b.ct)
            t_union = timed(lambda: s.union_nodes(a.ct, b.ct))
            t_marshal = timed(lambda: NodeArrays.from_nodes_map(union.nodes))
            na = NodeArrays.from_nodes_map(union.nodes)
            t_kernel = timed(lambda: jaxw.weave_arrays(na))

            def rebuild():
                rank, _ = jaxw.weave_arrays(na)
                order = np.argsort(rank[: na.capacity], kind="stable")
                return [na.nodes[i] for i in order[: na.n]]

            t_rebuild = timed(rebuild)
            print(json.dumps({
                "metric": "jax merge breakdown",
                "host_union_ms": round(t_union, 1),
                "host_marshal_ms": round(t_marshal, 1),
                "device_weave_ms": round(t_kernel, 1),
                "weave_plus_rebuild_ms": round(t_rebuild, 1),
                "platform": platform,
            }), flush=True)


if __name__ == "__main__":
    main()
