"""API-level merge benchmark: the end-to-end cost of
``CausalList.merge`` at 10k nodes, per backend, with the jax path
split into host-marshal vs device-kernel so the marshal overhead is
measured honestly (kernel-level benchmarks bypass it via benchgen).

Prints one JSON line per backend plus the breakdown.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path for checkout runs)

import argparse
import json
import time

import numpy as np


def build_pair(n_base: int, n_div: int, weaver: str):
    import cause_tpu as c
    from cause_tpu.collections.clist import CausalList
    from cause_tpu.ids import new_site_id

    base = c.clist(weaver=weaver).extend(["x"] * n_base)
    a = CausalList(base.ct.evolve(site_id=new_site_id()))
    b = CausalList(base.ct.evolve(site_id=new_site_id()))
    a = a.extend([f"a{i}" for i in range(n_div)])
    b = b.extend([f"b{i}" for i in range(n_div)])
    return a, b


def timed(fn, reps=3):
    fn()  # warm (compiles, caches)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1000)
    return float(np.median(ts))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-base", type=int, default=9_000)
    ap.add_argument("--n-div", type=int, default=1_000)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    platform = None

    for weaver in ("pure", "native", "jax"):
        if weaver == "jax":
            platform = jax.devices()[0].platform
        a, b = build_pair(args.n_base, args.n_div, weaver)
        p50 = timed(lambda: a.merge(b))
        print(json.dumps({
            "metric": f"CausalList.merge {args.n_base}+{args.n_div} nodes",
            "weaver": weaver,
            "value": round(p50, 1),
            "unit": "ms",
        }), flush=True)

        if weaver == "jax":
            from cause_tpu.collections import shared as s
            from cause_tpu.weaver import jaxw
            from cause_tpu.weaver.arrays import NodeArrays

            union = s.union_nodes(a.ct, b.ct)
            t_union = timed(lambda: s.union_nodes(a.ct, b.ct))
            t_marshal = timed(lambda: NodeArrays.from_nodes_map(union.nodes))
            na = NodeArrays.from_nodes_map(union.nodes)
            t_kernel = timed(lambda: jaxw.weave_arrays(na))

            def rebuild():
                rank, _ = jaxw.weave_arrays(na)
                order = np.argsort(rank[: na.capacity], kind="stable")
                return [na.nodes[i] for i in order[: na.n]]

            t_rebuild = timed(rebuild)
            print(json.dumps({
                "metric": "jax merge breakdown",
                "host_union_ms": round(t_union, 1),
                "host_marshal_ms": round(t_marshal, 1),
                "device_weave_ms": round(t_kernel, 1),
                "weave_plus_rebuild_ms": round(t_rebuild, 1),
                "platform": platform,
            }), flush=True)


if __name__ == "__main__":
    main()
