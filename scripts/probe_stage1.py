"""Pin down why the v3 kernel's flags/scans stage costs ~1.7 s at full
size when a lone cumsum at the same shape costs ~24 ms: time each
sub-expression of stages 0-3 in isolation at B=1024, N=20480.

Methodology: each program is jitted, warmed, then timed 3x with the
scalar-fetch sync; the dispatch floor (empty program) is printed first
so marginal costs can be read by subtraction.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path for checkout runs)

import math
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cause_tpu import benchgen
from cause_tpu.benchgen import LANE_KEYS
from cause_tpu.weaver.arrays import I32_MAX
from cause_tpu.weaver.jaxw3 import _shift1

B, NB, ND, CAP = 1024, 9_000, 1_000, 10_240
K = 2251


def timed(name, fn, *args, reps=3):
    out = np.asarray(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = np.asarray(fn(*args))
        ts.append((time.perf_counter() - t0) * 1000.0)
    print(f"{name:52s} {float(np.median(ts)):9.1f} ms")
    return out


def main():
    print(f"platform={jax.devices()[0].platform} B={B} cap={CAP}")
    batch = benchgen.batched_pair_lanes(
        n_replicas=B, n_base=NB, n_div=ND, capacity=CAP, hide_every=8
    )
    dev = [jax.device_put(batch[k]) for k in LANE_KEYS]
    N = dev[0].shape[1]
    hi, lo = dev[0], dev[1]

    @jax.jit
    def empty(h, l):
        return jnp.float32(0) + h[0, 0] + l[0, 0]

    timed("dispatch floor (scalar only)", empty, hi, lo)

    @jax.jit
    def sort_only(h, l):
        order = jnp.vmap if False else None  # noqa
        o = jax.vmap(lambda a, b: jnp.lexsort((b, a)))(h, l)
        return jnp.sum(o.astype(jnp.float32))

    timed("lexsort2 (indices only)", sort_only, hi, lo)

    @jax.jit
    def sort_apply6(*a):
        def row(h, l, ch, cl, vc, va):
            o = jnp.lexsort((l, h))
            return (h[o], l[o], ch[o], cl[o], vc[o], va[o])

        outs = jax.vmap(row)(*a)
        return sum(jnp.sum(x.astype(jnp.float32)) for x in outs)

    timed("lexsort2 + 6 perm gathers", sort_apply6, *dev)

    @jax.jit
    def sort_operands(*a):
        def row(h, l, ch, cl, vc, va):
            return lax.sort((h, l, ch, cl, vc, va.astype(jnp.int32)),
                            num_keys=2)

        outs = jax.vmap(row)(*a)
        return sum(jnp.sum(x.astype(jnp.float32)) for x in outs)

    timed("lax.sort 6 operands (num_keys=2)", sort_operands, *dev)

    @jax.jit
    def one_cumsum(h, l):
        return jnp.sum(jnp.cumsum(h, axis=1).astype(jnp.float32))

    timed("cumsum int32", one_cumsum, hi, lo)

    @jax.jit
    def one_cummax(h, l):
        return jnp.sum(lax.cummax(h, axis=1).astype(jnp.float32))

    timed("cummax int32", one_cummax, hi, lo)

    @jax.jit
    def two_scans(h, l):
        return (jnp.sum(jnp.cumsum(h, axis=1).astype(jnp.float32))
                + jnp.sum(lax.cummax(l, axis=1).astype(jnp.float32)))

    timed("cumsum + cummax", two_scans, hi, lo)

    # stage-1 flags WITHOUT the sort (feed raw lanes as if sorted)
    @jax.jit
    def flags_noscan(*a):
        def row(h, l, ch, cl, vc, va):
            idx = jnp.arange(N, dtype=jnp.int32)
            prev_h, prev_l = _shift1(h, I32_MAX), _shift1(l, I32_MAX)
            dup = (h == prev_h) & (l == prev_l) & (idx > 0)
            keep = va & ~dup
            is_root = keep & (idx == 0)
            special = keep & (vc > 0)
            rel = keep & ~is_root
            adj = rel & (ch == prev_h) & (cl == prev_l)
            return (keep.astype(jnp.float32).sum()
                    + adj.astype(jnp.float32).sum()
                    + special.astype(jnp.float32).sum())

        return jnp.sum(jax.vmap(row)(*a))

    timed("flags only (elementwise, no sort no scan)", flags_noscan, *dev)

    @jax.jit
    def flags_scans(*a):
        def row(h, l, ch, cl, vc, va):
            idx = jnp.arange(N, dtype=jnp.int32)
            prev_h, prev_l = _shift1(h, I32_MAX), _shift1(l, I32_MAX)
            dup = (h == prev_h) & (l == prev_l) & (idx > 0)
            keep = va & ~dup
            cum_keep = jnp.cumsum(keep.astype(jnp.int32))
            kidx = cum_keep - 1
            is_root = keep & (idx == 0)
            special = keep & (vc > 0)
            rel = keep & ~is_root
            sp_pack = lax.cummax(
                jnp.where(keep, idx * 2 + special.astype(jnp.int32), -1)
            )
            sp_prev = _shift1(sp_pack, -1)
            prev_kept = jnp.where(sp_prev >= 0, sp_prev >> 1, -1)
            prev_kept_special = (sp_prev >= 0) & (sp_prev % 2 == 1)
            adj = (rel & (ch == prev_h) & (cl == prev_l)
                   & (prev_kept >= 0))
            host_case = adj & ~special & prev_kept_special
            irregular = rel & (~adj | host_case)
            return (kidx.astype(jnp.float32).sum()
                    + irregular.astype(jnp.float32).sum())

        return jnp.sum(jax.vmap(row)(*a))

    timed("flags + scans (stage1 body, no sort)", flags_scans, *dev)

    # searchsorted K targets into an N-wide nondecreasing array
    cum = jnp.cumsum((dev[5]).astype(jnp.int32), axis=1)
    targets = jnp.arange(1, K + 1, dtype=jnp.int32)

    @jax.jit
    def ss(c):
        def row(cr):
            return jnp.searchsorted(cr, targets, side="left").astype(
                jnp.int32)

        return jnp.sum(jax.vmap(row)(c).astype(jnp.float32))

    timed("searchsorted K into N (jnp)", ss, cum)

    # hand-rolled fori binary search (the kernel's sbody pattern)
    @jax.jit
    def bs(c):
        def row(cr):
            steps = max(1, math.ceil(math.log2(max(2, N)))) + 1

            def sbody(_, carry):
                lo_b, hi_b = carry
                mid = (lo_b + hi_b) // 2
                ms = jnp.clip(mid, 0, N - 1)
                less = cr[ms] < targets
                return (jnp.where(less, mid + 1, lo_b),
                        jnp.where(less, hi_b, mid))

            lo_b, _ = lax.fori_loop(
                0, steps, sbody,
                (jnp.zeros_like(targets), jnp.full_like(targets, N)),
            )
            return lo_b

        return jnp.sum(jax.vmap(row)(c).astype(jnp.float32))

    timed("fori binary search K into N", bs, cum)

    # K-wide gather from an N-wide array
    qidx = jnp.broadcast_to(
        jnp.arange(K, dtype=jnp.int32) * 7 % N, (B, K)).copy()

    @jax.jit
    def kg(h, q):
        def row(hr, qr):
            return hr[qr]

        return jnp.sum(jax.vmap(row)(h, q).astype(jnp.float32))

    timed("ONE K-wide gather from N", kg, hi, qidx)

    # pointer doubling at 2K width, 13 rounds (euler_rank core)
    nxt = jnp.broadcast_to(
        (jnp.arange(2 * K, dtype=jnp.int32) * 5 + 1) % (2 * K),
        (B, 2 * K)).copy()
    w = jnp.ones((B, 2 * K), jnp.int32)

    @jax.jit
    def pd(nx, ww):
        def row(n, v):
            def body(_, c):
                val, x = c
                return val + val[x], x[x]

            val, _ = lax.fori_loop(0, 13, body, (v, n))
            return val

        return jnp.sum(jax.vmap(row)(nx, ww).astype(jnp.float32))

    timed("pointer doubling 13 rounds at 2K", pd, nxt, w)

    # K->N scatter (.at[].set)
    vals = jnp.ones((B, K), jnp.int32)

    @jax.jit
    def sc(q, v):
        def row(qr, vr):
            return jnp.zeros(N, jnp.int32).at[qr].set(vr, mode="drop")

        return jnp.sum(jax.vmap(row)(q, v).astype(jnp.float32))

    timed("ONE K->N scatter", sc, qidx, vals)


if __name__ == "__main__":
    main()
