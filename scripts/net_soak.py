"""Wire-level chaos soak for the network transport (PR 13's
acceptance instrument): N loopback client endpoints replicate into a
``SyncService`` through real sockets (``cause_tpu.net``) under a
seeded fault plan — partitions (refused dials), connection resets,
injected latency, blackholed frames, wire-duplicated frames, payload
reordering, and a mid-soak SERVER crash restored from checkpoint +
journal — and the run gates the transport's contracts
machine-to-machine:

- **bit-identical reconvergence, zero admitted ops lost** (exit 4) —
  after the final drain every tenant's materialized document must
  equal the fault-free single-process oracle (the tenant's pure pair
  merge + a pure replay of the whole write-ahead journal, computed
  with chaos suspended and obs off), every client must have drained
  its outbound queue completely (all minted ops acked, zero client
  sheds), and every minted op id must be present in the converged
  document;
- **every injected fault detected** (exit 5) — wire-duplicate frames
  EXACTLY equal the server's ``dup_frames`` evidence, payload mangles
  land ``sync.reject`` NACKs, resets/blackholes force reconnects,
  partition injections appear as failed dials, and the armed crash
  fires exactly once and restores;
- **evidence is exact** — the committed sidecar's ``net.*`` events
  must agree with the endpoints' own stats (reconnects, NACKs).

A clean run lands a ``--kind net`` ledger row (value = mean partition
MTTR ms; extra = reconnect count, duplicates suppressed, NACK/backoff
histograms, per-frame round-trip overhead, crash MTTR).

Usage::

    python scripts/net_soak.py --obs-out net.jsonl \
        [--clients 4] [--doc 20] [--seconds 8] [--mint-every 0.08] \
        [--max-ops 256] [--d-max 16] [--seed 13] \
        [--chaos measurements/net_plan_r13.json] [--frame-bench 200]

Clients are one thread each (the NetClient contract), minting 1-3 op
batches on their own site at a seeded cadence and pumping the session;
the server tick loop runs in the main thread. The chaos plan arms
AFTER the warm/checkpoint phase so fault schedules are stable against
warm-up variance; the plan's ``crash`` spec (site ``serve.tick``)
fires on the Nth tick and the harness drops the WHOLE server process
-equivalent — replication server, service object, queue — and
restores from checkpoint + journal on the same port, exactly what the
clients' reconnect/backoff + watermark resume exists to heal.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401

import argparse
import json
import os
import random
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import cause_tpu as c  # noqa: E402
from cause_tpu import chaos, obs, serde, sync  # noqa: E402
from cause_tpu.collections import clist as c_list  # noqa: E402
from cause_tpu.collections.clist import CausalList  # noqa: E402
from cause_tpu.ids import new_site_id  # noqa: E402
from cause_tpu.net import (Backoff, NetClient,  # noqa: E402
                           ReplicationServer, transport)
from cause_tpu.serve import (IngestJournal, IngestQueue,  # noqa: E402
                             ResidencyManager, ServiceCrashed,
                             SyncService)

EXIT_CONVERGENCE = 4
EXIT_UNDETECTED = 5
EXIT_JOURNEY = 6


class ClientDriver(threading.Thread):
    """One producer endpoint: mints chained op batches on its own
    site at a seeded cadence, queues them into its NetClient and
    pumps the session. Everything network-shaped degrades inside the
    client; this thread only ever sees queued-or-acked."""

    def __init__(self, idx, port, uuid, seed, mint_every_s,
                 stop_evt):
        super().__init__(name=f"net-soak-c{idx}", daemon=True)
        self.idx = idx
        self.uuid = uuid
        self.site = new_site_id()
        self.rng = random.Random(seed * 7919 + idx)
        self.mint_every_s = mint_every_s
        self.stop_evt = stop_evt
        self.minted = 0
        self.minted_ids = []
        self._last = c.root_id
        self._ts = 10_000 + idx * 1_000_000
        self.errors = []
        self.client = NetClient(
            "127.0.0.1", port, [uuid], client_id=f"c{idx}",
            read_timeout_s=1.0, heartbeat_s=0.5,
            connect_timeout_s=0.5, site=f"net.c{idx}",
            backoff=Backoff(base_ms=20, cap_ms=500, seed=seed + idx))

    def _mint_batch(self):
        n = self.rng.randrange(1, 4)
        out = []
        for _ in range(n):
            self._ts += 1
            nid = (self._ts, self.site, 0)
            out.append((nid, self._last, f"c{self.idx}.{self._ts}"))
            self.minted_ids.append(nid)
            self._last = nid
        self.minted += n
        return out

    def run(self):
        try:
            while not self.stop_evt.is_set():
                if not self.client.queue_ops(self.uuid, self.site,
                                             self._mint_batch()):
                    self.errors.append("client shed minted ops "
                                       "(outbound bound too small)")
                    return
                self.client.pump()
                self.stop_evt.wait(self.mint_every_s)
        except Exception as e:  # noqa: BLE001 - surfaced in main
            self.errors.append(f"{type(e).__name__}: {e}")


def _mk_tenants(svc, n, doc):
    """``n`` DISTINCT documents (a fresh clist per tenant — evolve()
    keeps the doc uuid, and tenants are keyed by it), each a (left,
    right) replica pair at one shared doc size (one compile
    bucket)."""
    uuids, pairs = [], {}
    for i in range(n):
        base = CausalList(c_list.weave(
            c.clist(weaver="jax").extend(
                [f"w{i}.{j}" for j in range(doc)]).ct))
        base.ct.lanes.segments()
        a = CausalList(base.ct.evolve(site_id=new_site_id())).conj(
            f"A{i}")
        b = CausalList(base.ct.evolve(site_id=new_site_id())).conj(
            f"B{i}")
        uuid = svc.add_tenant(a, b)
        uuids.append(uuid)
        pairs[uuid] = (a, b)
    return uuids, pairs


def _pure(h):
    return CausalList(h.ct.evolve(weaver="pure", lanes=None))


def _journal_oracle(pairs_init, journal_path):
    """The fault-free single-process oracle (serve_soak's shape): the
    tenant's pure pair merge + a pure replay of the whole write-ahead
    journal (read back through IngestJournal itself — ONE torn-line/
    format authority, not a reimplementation), chaos suspended + obs
    off."""
    out = {u: _pure(a).merge(_pure(b))
           for u, (a, b) in pairs_init.items()}
    jr = IngestJournal(journal_path)
    entries = sorted(jr.iter_from(0), key=lambda e: int(e["seq"]))
    jr.close()
    for e in entries:
        uuid = str(e.get("uuid"))
        if uuid not in out:
            continue
        sync.validate_node_items(e["items"])
        out[uuid] = sync.apply_delta(
            out[uuid], serde.decode_node_items(e["items"]),
            _count_as_delta=False)
    return out, len(entries)


def _doc_equal(dev_handle, pure_handle) -> bool:
    return (c.causal_to_edn(dev_handle) == c.causal_to_edn(pure_handle)
            and dict(dev_handle.ct.nodes) == dict(pure_handle.ct.nodes)
            and [n[0] for n in dev_handle.get_weave()]
            == [n[0] for n in pure_handle.get_weave()])


def _frame_bench(port, uuid, n_frames):
    """Per-frame overhead on the healthy loopback link: mean/max
    round-trip of a 1-op delta frame (send → validate → watermark →
    offer → journal → ack). Real admitted ops — they ride into the
    oracle like any other."""
    site = new_site_id()
    fs = transport.dial("127.0.0.1", port, site="net.bench")
    transport.send_msg(fs, {"op": "hello", "client": "bench",
                            "uuids": [uuid]})
    transport.recv_msg(fs, timeout_s=5.0)
    last = c.root_id
    walls = []
    for i in range(n_frames):
        nid = (1_000_000 + i, site, 0)
        enc = serde.encode_node_items({nid: (last, f"b{i}")})
        last = nid
        t0 = time.perf_counter()
        transport.send_msg(fs, {"op": "delta", "seq": i + 1,
                                "uuid": uuid, "site": site,
                                "nodes": enc,
                                "crc": sync.payload_checksum(enc)})
        r = transport.recv_msg(fs, timeout_s=5.0)
        walls.append((time.perf_counter() - t0) * 1000.0)
        assert r.get("op") == "ack", r
    transport.send_msg(fs, {"op": "bye"})
    fs.close()
    walls.sort()
    return {"frames": n_frames,
            "mean_ms": round(sum(walls) / len(walls), 4),
            "p50_ms": round(walls[len(walls) // 2], 4),
            "max_ms": round(walls[-1], 4)}


def _restart(svc, srv, ckpt_dir, journal_path, max_ops, d_max,
             capacity, port):
    """The server crash protocol: drop the whole serve-side object
    graph (replication server, service, queue) and restore from the
    last checkpoint + write-ahead journal, re-listening on the SAME
    port — the clients' reconnect ladder does the rest."""
    srv.stop()
    svc.close()
    svc.queue.close_admission()
    if svc.queue.journal is not None:
        svc.queue.journal.close()
    del svc
    queue = IngestQueue(max_ops=max_ops, defer_frac=1.0,
                        journal=IngestJournal(journal_path))
    svc2 = SyncService.restore(
        ckpt_dir, queue=queue,
        residency=ResidencyManager(capacity=capacity), d_max=d_max)
    srv2 = ReplicationServer(svc2, port=port).start()
    return svc2, srv2


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4,
                    help="client endpoints (= tenants, one each)")
    ap.add_argument("--doc", type=int, default=20)
    ap.add_argument("--seconds", type=float, default=8.0)
    ap.add_argument("--mint-every", type=float, default=0.12)
    ap.add_argument("--max-ops", type=int, default=256)
    ap.add_argument("--d-max", type=int, default=32)
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--tick-every", type=float, default=0.03)
    ap.add_argument("--chaos", default=None,
                    help="seeded fault plan JSON (path or inline); "
                         "armed AFTER the warm/checkpoint phase")
    ap.add_argument("--frame-bench", type=int, default=200,
                    help="per-frame overhead bench frames on the "
                         "healthy link (0 disables)")
    ap.add_argument("--obs-out", required=True)
    ap.add_argument("--proc-clients", type=int, default=0,
                    help="additional client endpoints as REAL child "
                         "interpreters (one tenant each), each "
                         "writing its own obs stream to "
                         "<obs-out>.pK — the per-process evidence "
                         "`obs journey` merges; a clean run gates "
                         "every child trace reconstructing complete "
                         "(zero orphan hops) across pids (exit 6)")
    ap.add_argument("--proc-ops", type=int, default=6,
                    help="ops each --proc-clients child mints")
    ap.add_argument("--state-dir", default=None)
    args = ap.parse_args()

    obs.configure(enabled=True, out=args.obs_out)
    obs.set_platform(jax.default_backend())
    sync.quarantine_reset()
    chaos.reset()

    state_dir = args.state_dir or (args.obs_out + ".state")
    ckpt_dir = os.path.join(state_dir, "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    journal_path = os.path.join(state_dir, "ingest.jsonl")
    if os.path.exists(journal_path):
        os.unlink(journal_path)

    capacity = args.clients + args.proc_clients
    queue = IngestQueue(max_ops=args.max_ops, defer_frac=1.0,
                        journal=IngestJournal(journal_path))
    svc = SyncService(queue,
                      residency=ResidencyManager(capacity=capacity),
                      checkpoint_dir=ckpt_dir, d_max=args.d_max)
    uuids, pairs_init = _mk_tenants(svc, args.clients, args.doc)
    proc_uuids = []
    if args.proc_clients:
        # out-of-process endpoints get tenants of their own: their
        # ops ride the SAME oracle/digest gates (appended to uuids),
        # their traces the journey gate below
        proc_uuids, proc_pairs = _mk_tenants(svc, args.proc_clients,
                                             args.doc)
        uuids = uuids + proc_uuids
        pairs_init.update(proc_pairs)
    srv = ReplicationServer(svc).start()
    port = srv.port
    print(f"net soak: {args.clients} client(s)/tenant(s) on "
          f"127.0.0.1:{port}, max_ops {args.max_ops}", flush=True)

    # ---- warm + per-frame overhead on the healthy link -------------
    frame_rt = None
    if args.frame_bench:
        frame_rt = _frame_bench(port, uuids[0], args.frame_bench)
        for _ in range(200):
            if not queue.depth:
                break
            svc.tick()
        print(f"net soak: per-frame round-trip mean "
              f"{frame_rt['mean_ms']} ms (p50 {frame_rt['p50_ms']}, "
              f"max {frame_rt['max_ms']}) over "
              f"{frame_rt['frames']} frames", flush=True)
    svc.checkpoint()  # the durable baseline every crash restores past

    # ---- arm the plan, start the fleet -----------------------------
    plan = None
    if args.chaos:
        raw = args.chaos.strip()
        plan = (json.loads(raw) if raw.startswith("{")
                else json.load(open(raw)))
        chaos.configure(plan=plan)
        print(f"net soak: chaos armed — {len(plan['faults'])} "
              f"fault spec(s), seed {plan.get('seed')}", flush=True)
    stop_evt = threading.Event()
    drivers = [ClientDriver(i, port, uuids[i], args.seed,
                            args.mint_every, stop_evt)
               for i in range(args.clients)]
    for d in drivers:
        d.start()

    # ---- genuinely separate processes: the per-host evidence shape.
    # Each child interpreter (journey_smoke's --child half) dials in
    # over loopback, mints ONE traced batch on its own tenant, pumps
    # until its outbound drains (reconnect ladder included — chaos is
    # armed), writes its OWN obs stream, and hands its trace id back
    # on stdout for the journey gate.
    import subprocess
    proc_streams = [f"{args.obs_out}.p{k + 1}"
                    for k in range(args.proc_clients)]
    for p in proc_streams:
        if os.path.exists(p):
            os.unlink(p)
    procs = [subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "journey_smoke.py"),
         "--child", "--port", str(port), "--uuid", proc_uuids[k],
         "--ops", str(args.proc_ops), "--obs-out", proc_streams[k]],
        stdout=subprocess.PIPE, text=True)
        for k in range(args.proc_clients)]

    # ---- the timed run (main thread = the serve tick loop) ---------
    retired_server_stats = []
    crashes = 0
    crash_mttr_ms = []
    state = {"svc": svc, "srv": srv}

    def tick_protected():
        """One service tick; an armed crash drops the WHOLE serve
        side (server + service + queue) and restores — every ticking
        phase (timed run, client flush, final drain) must survive the
        crash wherever the plan lands it."""
        nonlocal crashes
        try:
            state["svc"].tick()
            return 1
        except ServiceCrashed as e:
            print(f"net soak: SERVER CRASH ({e}) — restoring",
                  flush=True)
            t_crash = time.perf_counter()
            retired_server_stats.append(dict(state["srv"].stats))
            state["svc"], state["srv"] = _restart(
                state["svc"], state["srv"], ckpt_dir, journal_path,
                args.max_ops, args.d_max, capacity, port)
            state["svc"].tick()
            crashes += 1
            crash_mttr_ms.append(
                round(1000 * (time.perf_counter() - t_crash), 3))
            return 2

    t_start = time.perf_counter()
    deadline = t_start + args.seconds
    ticks = 0
    while time.perf_counter() < deadline:
        ticks += tick_protected()
        time.sleep(args.tick_every)
    stop_evt.set()
    for d in drivers:
        d.join(timeout=10.0)
    gen_errors = [e for d in drivers for e in d.errors]
    if gen_errors:
        print("net soak: CLIENT DRIVER FAILED: "
              + "; ".join(gen_errors), flush=True)
        return 2
    proc_handoffs = []
    for k, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=40.0)
        except subprocess.TimeoutExpired:
            p.kill()
            out = ""
        if p.returncode != 0:
            print(f"net soak: PROC CLIENT p{k + 1} FAILED "
                  f"(rc={p.returncode}): {out!r}", flush=True)
            return 2
        proc_handoffs.append(json.loads(out.strip().splitlines()[-1]))

    # ---- drain: every client flushes, the service flushes. ONE tick
    # per iteration so client pumps interleave with the queue drain —
    # a backlogged post-crash queue must not starve the reconnect
    # ladder of pump() calls for whole seconds
    flush_deadline = time.monotonic() + 30.0
    while time.monotonic() < flush_deadline:
        pending = 0
        for d in drivers:
            d.client.pump()
            pending += d.client.outbound_depth
        if state["svc"].queue.depth or state["svc"].queue.deferred:
            tick_protected()
        elif pending == 0:
            break
        else:
            time.sleep(0.01)
    for d in drivers:
        d.client.close()
    for _ in range(200):
        if not state["svc"].queue.depth:
            break
        tick_protected()
    svc, srv = state["svc"], state["srv"]
    digests = {u: svc.converged_digest(u) for u in uuids}
    retired_server_stats.append(dict(srv.stats))
    srv.stop()

    # ---- gates ------------------------------------------------------
    obs.flush()
    with chaos.suspended():
        obs.configure(enabled=False)
        oracle, journal_entries = _journal_oracle(pairs_init,
                                                  journal_path)
        mismatched = [u for u in uuids
                      if not _doc_equal(svc.materialize(u), oracle[u])]
        missing_ops = 0
        for d_ in drivers:
            nodes = svc.materialize(d_.uuid).ct.nodes
            missing_ops += sum(1 for nid in d_.minted_ids
                               if nid not in nodes)

    srv_total = {}
    for st in retired_server_stats:
        for k, v in st.items():
            srv_total[k] = srv_total.get(k, 0) + v
    stuck = [d.idx for d in drivers if d.client.outbound_depth]
    minted = sum(d.minted for d in drivers)
    acked = sum(d.client.stats["acked_ops"] for d in drivers)
    # a crash between the journal append and the ack loses the ACK,
    # not the op: the resend is either watermark-filtered client-side
    # (resumed_skipped) or suppressed server-side and acked as dup
    # (dup_acked) — all three buckets together must account for every
    # minted op, and the doc-presence gate below proves none was lost
    dup_acked = sum(d.client.stats["dup_acked_ops"] for d in drivers)
    resumed = sum(d.client.stats["resumed_skipped_ops"]
                  for d in drivers)
    accounted = acked + dup_acked + resumed
    shed = sum(d.client.stats["shed_ops"] for d in drivers)
    reconnects = sum(d.client.stats["reconnects"] for d in drivers)
    dial_failures = sum(d.client.stats["dial_failures"]
                        for d in drivers)
    nack_hist = {}
    backoff_hist = {}
    mttr_s = []
    for d in drivers:
        mttr_s.extend(d.client.partition_mttr_s)
        for k, v in d.client.stats["nacks"].items():
            nack_hist[k] = nack_hist.get(k, 0) + v
        for k, v in d.client.stats["backoff_hist"].items():
            backoff_hist[k] = backoff_hist.get(k, 0) + v
    partition_mttr_ms = [round(x * 1000, 3) for x in sorted(mttr_s)]
    mttr_mean = (round(sum(partition_mttr_ms)
                       / len(partition_mttr_ms), 3)
                 if partition_mttr_ms else None)

    # planned-vs-detected accounting (explicit `at` schedules only)
    planned = {"reset": 0, "partition": 0, "dup": 0, "blackhole": 0,
               "latency": 0, "payload": 0, "crash": 0}
    if plan:
        for spec in plan["faults"]:
            fam = spec["family"]
            key = spec.get("mode", "reset") if fam == "net" else fam
            planned[key] = planned.get(key, 0) + len(spec.get("at")
                                                    or ())

    from cause_tpu.obs import ledger
    from cause_tpu.obs.perfetto import load_jsonl

    evs = load_jsonl(args.obs_out)

    def count_ev(name):
        return sum(1 for e in evs if e.get("ev") == "event"
                   and e.get("name") == name)

    summary = {
        "clients": args.clients, "seconds": args.seconds,
        "ticks": ticks, "minted_ops": minted, "acked_ops": acked,
        "dup_acked_ops": dup_acked, "resumed_skipped_ops": resumed,
        "client_shed_ops": shed,
        "journal_entries": journal_entries,
        "admitted_ops": srv_total.get("admitted_ops", 0),
        "reconnects": reconnects, "dial_failures": dial_failures,
        "dup_frames": srv_total.get("dup_frames", 0),
        "dup_ops_suppressed": srv_total.get("dup_ops_suppressed", 0),
        "ooo_frames": srv_total.get("ooo_frames", 0),
        "poison_nacks": srv_total.get("poison_nacks", 0),
        "nacks": nack_hist, "backoff_hist": backoff_hist,
        "partition_mttr_ms": partition_mttr_ms,
        "partition_mttr_mean_ms": mttr_mean,
        "crashes": crashes, "crash_mttr_ms": crash_mttr_ms,
        "frame_rt": frame_rt,
        "planned": {k: v for k, v in planned.items() if v},
        "sync_rejects_evidenced": count_ev("sync.reject"),
        "reconnect_events": count_ev("net.reconnect"),
        "oracle_mismatches": mismatched,
        "minted_ops_missing": missing_ops,
        "stuck_clients": stuck,
    }
    print("net soak:", json.dumps(summary, indent=1), flush=True)

    # (1) reconvergence bit-identity + zero loss
    if mismatched or missing_ops or stuck or shed \
            or accounted != minted:
        print("net soak: CONVERGENCE GATE FAILED "
              f"(mismatched={mismatched} missing={missing_ops} "
              f"stuck={stuck} shed={shed} "
              f"accounted={accounted}/{minted})",
              flush=True)
        return EXIT_CONVERGENCE
    # (2) every injected fault family detected; duplicates EXACT
    if plan:
        fails = []
        if srv_total.get("dup_frames", 0) != planned.get("dup", 0):
            fails.append(f"dup frames {srv_total.get('dup_frames')} "
                         f"!= planned {planned.get('dup')}")
        if planned.get("payload") \
                and summary["sync_rejects_evidenced"] \
                < planned["payload"]:
            fails.append("payload mangle undetected")
        if planned.get("reset") and reconnects < planned["reset"]:
            fails.append("resets did not force reconnects")
        if planned.get("blackhole") \
                and reconnects < planned["reset"] \
                + planned["blackhole"]:
            fails.append("blackhole did not force a reconnect")
        if planned.get("partition") \
                and dial_failures < planned["partition"]:
            fails.append("partition refusals unobserved")
        if planned.get("crash") and crashes != planned["crash"]:
            fails.append(f"crashes {crashes} != planned "
                         f"{planned['crash']}")
        if summary["reconnect_events"] != reconnects:
            fails.append("reconnect evidence != client stats")
        if fails:
            print("net soak: DETECTION GATE FAILED: "
                  + "; ".join(fails), flush=True)
            return EXIT_UNDETECTED
    assert digests  # every tenant digest fetched before srv.stop

    # (3) cross-process journeys reconstruct complete: every child
    # interpreter's trace spans both pids with zero orphan hops in
    # the MERGED per-process streams — exactly what `obs journey`
    # gives an operator holding the per-host sidecars
    journey_summary = None
    if args.proc_clients:
        from cause_tpu.obs.journey import JourneyFold
        from cause_tpu.obs.perfetto import load_streams

        jfold = JourneyFold(retain_all=True)
        jfold.feed_many(load_streams([args.obs_out] + proc_streams))
        jrep = jfold.report()
        jfails = []
        for k, hand in enumerate(proc_handoffs):
            if hand["accounted"] != args.proc_ops:
                jfails.append(f"p{k + 1} accounted "
                              f"{hand['accounted']}/{args.proc_ops}")
            j = jfold.journey(hand["trace"])
            if j is None:
                jfails.append(f"p{k + 1} trace {hand['trace']} "
                              f"absent from merged streams")
            elif not j["complete"] or j["orphans"] \
                    or len(j["pids"]) < 2:
                jfails.append(
                    f"p{k + 1} trace {hand['trace']}: "
                    f"complete={j['complete']} "
                    f"orphans={j['orphans']} pids={j['pids']}")
        if jrep["orphan_hops"]:
            jfails.append(f"{jrep['orphan_hops']} orphan hop(s) "
                          f"fleet-wide")
        if not jrep["clock"]["edges"]:
            jfails.append("no clock edge measured")
        if any(j_["orphans"] for j_ in jfold.worst(5)):
            jfails.append("a worst-5 (p99 offender) journey has "
                          "orphan hops")
        if jfails:
            print("net soak: JOURNEY GATE FAILED: "
                  + "; ".join(jfails), flush=True)
            return EXIT_JOURNEY
        journey_summary = {
            "proc_clients": args.proc_clients,
            "streams": 1 + len(proc_streams),
            "traces": jrep["traces"],
            "complete": jrep["complete"],
            "orphan_hops": jrep["orphan_hops"],
            "clock_edges": len(jrep["clock"]["edges"]),
            "total_p99_ms": jrep["total"]["p99_ms"],
            "proc_traces": [h["trace"] for h in proc_handoffs],
        }
        print("net soak: journey gate clean — "
              + json.dumps(journey_summary), flush=True)

    try:
        row = ledger.ingest_record(
            {
                "platform": jax.default_backend(),
                "metric": "net soak partition MTTR (mean)",
                "value": mttr_mean,
                "kernel": "net",
                "config": f"clients={args.clients} doc={args.doc} "
                          f"max_ops={args.max_ops} "
                          f"chaos={int(bool(plan))}",
                "smoke": False,
            },
            source=f"net-soak seed={args.seed} "
                   f"seconds={args.seconds:g}",
            obs_jsonl=args.obs_out,
            kind="net",
            extra={"net": {k: v for k, v in summary.items()
                           if k not in ("oracle_mismatches",
                                        "stuck_clients")},
                   **({"journey": journey_summary}
                      if journey_summary else {})},
        )
        print(f"net soak: ledger row ({row['platform']}) -> "
              f"{ledger.default_path()}", flush=True)
    except Exception as e:  # noqa: BLE001 - best-effort ledger append
        print(f"net soak: ledger append skipped "
              f"({type(e).__name__}: {e})", flush=True)

    print(f"net soak: clean — {minted} op(s) replicated over the "
          f"wire, {reconnects} reconnect(s), "
          f"{srv_total.get('dup_frames', 0)} wire duplicate(s) "
          f"suppressed exactly, {crashes} server crash(es) survived, "
          f"every tenant bit-identical to the journal oracle",
          flush=True)
    return 0


if __name__ == "__main__":
    rc = main()
    chaos.reset()
    sys.exit(rc)
