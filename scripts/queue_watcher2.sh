#!/bin/bash
# Delegator kept for PERF.md command compatibility: generation 2 (also
# waits out a running stage probe) of the round-3 queue watcher.
exec bash "$(dirname "$0")/tunnel_watcher.sh" queue --hours 24 --wait-stages
