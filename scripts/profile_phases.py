"""Phase-level TPU timing of the north-star merge+weave program.

Times each stage of the batched v2 pipeline in isolation (same shapes
and data as bench.py full size unless --smoke) so optimization work
targets the real bottleneck instead of a guess. Each phase is its own
jitted program whose output reduces to one scalar; the device->host
fetch of that scalar is the sync point (block_until_ready does not
block on the axon tunnel).

DEPRECATION NOTE: this script's private timing loop is gone — all
timing routes through the shared obs stage profiler
(``cause_tpu.obs.stages.timed_median``), so with ``CAUSE_TPU_OBS=1``
each phase's warm compile and reps land in the obs JSONL/Perfetto
stream. The v5 stage ladder equivalent is ``python -m cause_tpu.obs
stages``; this script remains for the v2-pipeline phase split only.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path for checkout runs)

import argparse
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cause_tpu import benchgen
from cause_tpu.benchgen import LANE_KEYS
from cause_tpu.obs.stages import timed_median
from cause_tpu.weaver import jaxw


def timed(name, fn, *args, reps=3):
    # the one timing loop lives in cause_tpu.obs.stages; this keeps
    # only the historical stdout format
    out, p50, ts = timed_median(name, fn, *args, reps=reps)
    print(f"{name:42s} {p50:10.1f} ms   (reps: {[round(t,1) for t in ts]})")
    return out, p50


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--reps", type=int, default=3)
    args_ns = ap.parse_args()

    if args_ns.smoke:
        B, n_base, n_div, cap = 8, 800, 100, 1024
    else:
        B, n_base, n_div, cap = 1024, 9_000, 1_000, 10_240

    print(f"platform={jax.devices()[0].platform} B={B} cap={cap}")
    batch = benchgen.batched_pair_lanes(
        n_replicas=B, n_base=n_base, n_div=n_div, capacity=cap, hide_every=8
    )
    k_max = benchgen.pair_run_budget(batch)
    print(f"k_max={k_max}")
    dev = [jax.device_put(batch[k]) for k in LANE_KEYS]
    hi, lo, chi, clo, vc, va = dev
    M = hi.shape[1]
    reps = args_ns.reps

    # ---- phase 1: id lexsort only
    @jax.jit
    def p_sort(hi, lo):
        def row(h, l):
            o = jnp.lexsort((l, h))
            return jnp.sum(o.astype(jnp.float32))
        return jnp.sum(jax.vmap(row)(hi, lo))

    timed("front: id lexsort (2-key, M)", p_sort, hi, lo, reps=reps)

    # ---- phase 2: full front half
    @jax.jit
    def p_front(hi, lo, chi, clo, vc, va):
        def row(h, l, ch, cl, v, m):
            order, (h_s, l_s, ci, v_s, keep, conf) = (
                jaxw._merge_front_half(h, l, ch, cl, v, m))
            return (jnp.sum(ci.astype(jnp.float32))
                    + jnp.sum(order.astype(jnp.float32)))
        return jnp.sum(jax.vmap(row)(hi, lo, chi, clo, vc, va))

    timed("front: full (2 sorts + join)", p_front, *dev, reps=reps)

    # ---- materialize sorted lanes once for the back-half phases
    @jax.jit
    def front_out(hi, lo, chi, clo, vc, va):
        def row(h, l, ch, cl, v, m):
            order, (h_s, l_s, ci, v_s, keep, conf) = (
                jaxw._merge_front_half(h, l, ch, cl, v, m))
            return h_s, l_s, ci, v_s, keep
        return jax.vmap(row)(hi, lo, chi, clo, vc, va)

    h_s, l_s, ci, v_s, keep = [np.asarray(x) for x in front_out(*dev)]
    h_s, l_s, ci, v_s, keep = map(jax.device_put, (h_s, l_s, ci, v_s, keep))

    # ---- phase 3: host jump (while_loop pointer doubling)
    @jax.jit
    def p_host(ci, v_s, keep):
        def row(c, v, k):
            N = c.shape[0]
            idx = jnp.arange(N, dtype=jnp.int32)
            is_root = k & (idx == 0)
            special = k & (v > 0)
            rel = k & ~is_root
            cs = jnp.clip(c, 0, N - 1)
            host = jaxw._host_jump(
                special, cs, rel, max(1, math.ceil(math.log2(N))))
            return jnp.sum(host.astype(jnp.float32))
        return jnp.sum(jax.vmap(row)(ci, v_s, keep))

    timed("back: host jump (while_loop)", p_host, ci, v_s, keep, reps=reps)

    # ---- phase 4: v2 full linearize
    @jax.jit
    def p_lin2(h_s, l_s, ci, v_s, keep):
        def row(h, l, c, v, k):
            rank, vis, ovf = jaxw.linearize_v2(h, l, c, v, k, k_max)
            return (jnp.sum(rank.astype(jnp.float32))
                    + jnp.sum(vis.astype(jnp.float32))
                    + ovf.astype(jnp.float32))
        return jnp.sum(jax.vmap(row)(h_s, l_s, ci, v_s, keep))

    timed("back: linearize_v2 (full)", p_lin2, h_s, l_s, ci, v_s, keep,
          reps=reps)

    # ---- phase 5: v2 contraction only (no euler, no visibility)
    @jax.jit
    def p_contract(h_s, l_s, ci, v_s, keep):
        def row(h, l, c, v, k):
            N = h.shape[0]
            idx = jnp.arange(N, dtype=jnp.int32)
            is_root = k & (idx == 0)
            special = k & (v > 0)
            rel = k & ~is_root
            cs = jnp.clip(c, 0, N - 1)
            host = jaxw._host_jump(
                special, cs, rel, max(1, math.ceil(math.log2(N))))
            parent_t = jnp.where(special, cs, host)
            parent = jnp.where(rel, parent_t, -1)
            kidx = jnp.cumsum(k.astype(jnp.int32)) - 1
            has_parent = parent >= 0
            pc = jnp.clip(parent, 0, N - 1)
            child_count = (
                jnp.zeros(N + 1, jnp.int32)
                .at[jnp.where(has_parent, pc, N)]
                .add(1)[:N]
            )
            only_child = has_parent & (child_count[pc] == 1)
            glued = only_child & (kidx[pc] == kidx - 1)
            run_start = k & ~glued
            run_id = jnp.cumsum(run_start.astype(jnp.int32)) - 1
            return jnp.sum(run_id.astype(jnp.float32))
        return jnp.sum(jax.vmap(row)(h_s, l_s, ci, v_s, keep))

    timed("back: contraction only", p_contract, h_s, l_s, ci, v_s, keep,
          reps=reps)

    # ---- phase 6: visibility only
    @jax.jit
    def p_vis(ci, v_s, keep):
        def row(c, v, k):
            N = c.shape[0]
            idx = jnp.arange(N, dtype=jnp.int32)
            rank = idx  # stand-in rank with the right shape/dtype
            node_at = jaxw._scatter_by_rank(rank, k, N)
            succ = node_at[jnp.clip(rank, 0, N) + 1]
            ss = jnp.clip(succ, 0, N - 1)
            hide = ((succ >= 0) & ((v[ss] == 2) | (v[ss] == 3))
                    & (c[ss] == idx))
            return jnp.sum(hide.astype(jnp.float32))
        return jnp.sum(jax.vmap(row)(ci, v_s, keep))

    timed("back: visibility scatter+gather", p_vis, ci, v_s, keep, reps=reps)

    # ---- whole program for reference
    from cause_tpu.benchgen import merge_wave_scalar

    def whole():
        return merge_wave_scalar(*dev, k_max=k_max)

    timed("WHOLE v2 program", whole, reps=reps)

    def whole_v1():
        return merge_wave_scalar(*dev, k_max=0)

    timed("WHOLE v1 program", whole_v1, reps=reps)


if __name__ == "__main__":
    main()
