#!/bin/bash
# Round-3 endgame sequencer: wait for the soak + slow suite to drain,
# then take clean quiet-box measurements for PERF.md.
set -u
cd "$(dirname "$0")/.."

# 1. wait for the soak and the full slow suite (background pytest)
while pgrep -f "soak.py --minutes" > /dev/null 2>&1; do sleep 60; done
while pgrep -f "pytest tests/ -q -m slow" > /dev/null 2>&1; do sleep 60; done
echo "endgame: [$(date -u +%H:%M:%S)] box quiet; measuring" >&2

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

# 2. host benchmark table (configs 1-4 + lazy row), quiet box
python -m cause_tpu.benchmarks > measurements/hostbench_quiet_r3.log 2>&1

# 3. end-to-end API wave at full scale with lazy replicas + pstore
python -u scripts/api_bench.py --wave 1024 --lazy --cpu \
  > measurements/api_wave1024_lazy_quiet_r3.log 2>&1

# 4. pairwise API merge timings (pure/native/jax)
python -u scripts/api_bench.py --cpu \
  > measurements/api_pairwise_quiet_r3.log 2>&1

echo "endgame: [$(date -u +%H:%M:%S)] done" >&2
