"""Obs-off byte-identity pin for the wire + journal formats (PR 19).

The distributed-tracing layer adds trace context to net frames and
journal records, but ONLY when obs is enabled. The standing obs-off
invariance contract says a disabled process's bytes are untouchable —
and the wire format is the riskiest seam, so this script pins it
machine-to-machine:

- **wire**: a real ``NetClient`` talks to a real ``ReplicationServer``
  through a byte-recording loopback proxy with obs OFF; both
  directions' raw frame bytes (hello/welcome, delta/ack, ping/pong,
  delta/nack, bye) are captured end-to-end — every byte the endpoints
  actually construct, not a re-serialization;
- **journal**: fixed batches appended to an ``IngestJournal`` file and
  a ``WriteAheadLog`` segment with pinned timestamps; the on-disk
  bytes are captured verbatim.

``--out`` writes the capture JSON (run once, pre-change, and commit
it); ``--check`` re-runs the identical scenario against the current
code and exits non-zero on the first differing byte. The committed
capture in ``measurements/obs_off_pin_r19.json`` was generated at the
pre-PR-19 tree, so ``--check`` passing IS the obs-off invariance
evidence.

Stdlib + cause_tpu host modules only (no jax: the stub service serves
admission, never a wave).
"""

from __future__ import annotations

import _bootstrap  # noqa: F401

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading

from cause_tpu import chaos, obs
from cause_tpu import root_id
from cause_tpu.net import NetClient, ReplicationServer
from cause_tpu.serve import IngestJournal, IngestQueue
from cause_tpu.serve.wal import WriteAheadLog

PIN_PATH_DEFAULT = "measurements/obs_off_pin_r19.json"
_TENANT = "pin-tenant"
_SITE = "s1"


class _StubService:
    """The duck-typed surface ReplicationServer fronts: a queue and a
    tenant registry. No jax, no waves — admission is host work."""

    def __init__(self):
        self.queue = IngestQueue(max_ops=64, defer_frac=1.0)
        self.tenants = {_TENANT: {"applied_seq": 0}}


class _RecordingProxy:
    """A loopback TCP proxy that records both directions' raw bytes —
    the capture sees exactly what the endpoints put on the wire."""

    def __init__(self, upstream_port: int):
        self.c2s = bytearray()
        self.s2c = bytearray()
        self._up_port = upstream_port
        self._lsock = socket.create_server(("127.0.0.1", 0))
        self._lsock.settimeout(10.0)
        self.port = self._lsock.getsockname()[1]
        self._threads = []
        self._accept = threading.Thread(target=self._run, daemon=True)
        self._accept.start()

    def _run(self):
        try:
            conn, _ = self._lsock.accept()
        except OSError:
            return
        up = socket.create_connection(("127.0.0.1", self._up_port))
        for src, dst, buf in ((conn, up, self.c2s),
                              (up, conn, self.s2c)):
            t = threading.Thread(target=self._shuttle,
                                 args=(src, dst, buf), daemon=True)
            t.start()
            self._threads.append(t)

    @staticmethod
    def _shuttle(src, dst, buf):
        try:
            while True:
                chunk = src.recv(65536)
                if not chunk:
                    break
                buf.extend(chunk)
                dst.sendall(chunk)
        except OSError:
            pass
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def close(self):
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)


def capture_wire() -> dict:
    """One scripted client/server session with obs OFF, byte-recorded:
    hello/welcome, a 2-op delta/ack, ping/pong, an unknown-tenant
    delta/nack, bye. Every input is pinned (fixed tenant/site/node
    ids), so the bytes are deterministic run to run."""
    assert not obs.enabled(), "the pin is an obs-OFF capture"
    svc = _StubService()
    srv = ReplicationServer(svc).start()
    proxy = _RecordingProxy(srv.port)
    try:
        cl = NetClient("127.0.0.1", proxy.port, [_TENANT],
                       client_id="pin", heartbeat_s=3600.0,
                       read_timeout_s=5.0, connect_timeout_s=5.0)
        cl.pump()  # connect: hello -> welcome
        assert cl.connected, "pin client failed to connect"
        ops = [((1001, _SITE, 0), root_id, "a"),
               ((1002, _SITE, 0), (1001, _SITE, 0), "b")]
        assert cl.queue_ops(_TENANT, _SITE, ops)
        cl.pump()  # delta -> ack
        assert cl.stats["acked_ops"] == 2, cl.stats
        cl._heartbeat()  # ping -> pong (deterministic seq)
        assert cl.queue_ops("nope", _SITE,
                            [((2001, _SITE, 0), root_id, "x")])
        cl.pump()  # delta -> nack (unknown-tenant)
        assert cl.stats["nacks"].get("unknown-tenant") == 1, cl.stats
        cl.close()  # bye
    finally:
        proxy.close()
        srv.stop()
    return {"c2s": bytes(proxy.c2s).hex(),
            "s2c": bytes(proxy.s2c).hex()}


_JOURNAL_BATCHES = [
    (_TENANT, _SITE,
     [[[1001, _SITE, 0], ["r", "", 0], "a"],
      [[1002, _SITE, 0], [1001, _SITE, 0], "b"]],
     1_700_000_000_000_000),
    (_TENANT, "s2",
     [[[1003, "s2", 0], ["r", "", 0], "c"]],
     1_700_000_000_500_000),
]


def capture_journal() -> dict:
    """Fixed batches with pinned timestamps appended to both journal
    implementations, on-disk bytes captured verbatim."""
    assert not obs.enabled()
    tmp = tempfile.mkdtemp(prefix="obs_off_pin_")
    try:
        jp = os.path.join(tmp, "ingest.jsonl")
        jr = IngestJournal(jp)
        for uuid, site, items, ts in _JOURNAL_BATCHES:
            jr.append(uuid, site, items, ts_us=ts)
        jr.close()
        with open(jp, "rb") as f:
            journal_bytes = f.read()
        wd = os.path.join(tmp, "wal")
        os.makedirs(wd)
        wal = WriteAheadLog(wd)
        for uuid, site, items, ts in _JOURNAL_BATCHES:
            wal.append(uuid, site, items, ts_us=ts)
        wal.close()
        seg = os.path.join(wd, "wal-00000001.seg")
        with open(seg, "rb") as f:
            wal_bytes = f.read()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {"ingest_journal": journal_bytes.hex(),
            "wal_segment": wal_bytes.hex()}


def capture() -> dict:
    obs.configure(enabled=False, reset=True)
    obs.configure(enabled=False)
    chaos.reset()
    return {"pin": "obs-off byte identity (PR 19)",
            "wire": capture_wire(), "journal": capture_journal()}


def check(pin_path: str) -> int:
    with open(pin_path) as f:
        want = json.load(f)
    got = capture()
    fails = []
    for section in ("wire", "journal"):
        for key, w in want[section].items():
            g = got[section].get(key)
            if g != w:
                fails.append(f"{section}.{key}: "
                             f"{len(w) // 2}B pinned != "
                             f"{(len(g) or 0) // 2}B current")
    if fails:
        print("obs-off pin: BYTES CHANGED — " + "; ".join(fails))
        for section in ("wire", "journal"):
            for key, w in want[section].items():
                g = got[section].get(key) or ""
                if g != w:
                    wb, gb = bytes.fromhex(w), bytes.fromhex(g)
                    i = next((k for k in range(min(len(wb), len(gb)))
                              if wb[k] != gb[k]),
                             min(len(wb), len(gb)))
                    print(f"  {section}.{key} first diff at byte {i}:")
                    print(f"    pinned : ...{wb[max(0, i - 20):i + 40]!r}")
                    print(f"    current: ...{gb[max(0, i - 20):i + 40]!r}")
        return 1
    print("obs-off pin: clean — wire frames and journal bytes "
          "byte-identical to the pre-PR capture")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="write a fresh capture to this path")
    ap.add_argument("--check", default=None, nargs="?",
                    const=PIN_PATH_DEFAULT,
                    help="re-capture and compare against a pinned "
                         f"capture (default {PIN_PATH_DEFAULT})")
    a = ap.parse_args(argv)
    if a.out:
        cap = capture()
        with open(a.out, "w") as f:
            json.dump(cap, f, indent=1)
        print(f"obs-off pin: capture written to {a.out}")
        return 0
    if a.check:
        return check(a.check)
    ap.error("need --out or --check")
    return 2


if __name__ == "__main__":
    sys.exit(main())
