#!/bin/bash
# Round-5 endgame sequencer: stop background load, take clean quiet
# -box CPU measurements for PERF.md's round-5 bars. Run it ~2h before
# the driver's round-end bench (the watcher keeps its own deadline and
# is NOT touched here — a TPU window during the endgame pauses these
# CPU numbers' "quiet" claim, which step 0 records).
set -u
cd "$(dirname "$0")/.."
LOGDIR=measurements
note() { echo "endgame: [$(date -u +%H:%M:%S)] $*" >&2; }

# 0. record whether a TPU claimant is measuring right now (the quiet
# -box claim below is honest only if not)
pgrep -f "scripts/harvest.py|scripts/api_bench.py --wave 1024" \
  > /dev/null 2>&1 && note "WARNING: a TPU claimant is active; CPU \
numbers may be under load" || note "box quiet of claimants"

# 1. stop the session soak gracefully (SIGTERM; it prints its total)
pkill -TERM -f "soak.py --minutes" 2>/dev/null && sleep 5
while pgrep -f "soak.py --minutes" > /dev/null 2>&1; do sleep 10; done
note "soak drained"

export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

# 2. north-star CPU bar (the number the chip must beat)
BENCH_FORCE_CPU=1 python bench.py \
  > "$LOGDIR/bench_cpu_quiet_r5.log" 2>&1
note "bench done"

# 3. end-to-end API wave at full scale, lazy replicas (round-5 code:
# pure-host mint + claim-after-mint)
python -u scripts/api_bench.py --wave 1024 --lazy --cpu \
  > "$LOGDIR/api_wave_cpu_quiet_r5.log" 2>&1
note "api wave done"

# 4. pairwise API merge (pure/native/jax) + host benchmark table
python -u scripts/api_bench.py --cpu \
  > "$LOGDIR/api_pairwise_quiet_r5.log" 2>&1
python -m cause_tpu.benchmarks > "$LOGDIR/hostbench_quiet_r5.log" 2>&1
note "host benches done"

# 5. map-fleet CLI row (config 6, both kernel routes)
python -m cause_tpu.benchmarks -c 6 \
  > "$LOGDIR/mapfleet_quiet_r5.log" 2>&1
note "done"
