"""Native host weaver: ctypes bindings over the C++ linearizer.

The runtime around the TPU compute path is native where it is hot: full
reweaves and merges on the host go through ``weaver.cpp``'s O(n)
preorder construction instead of the O(n^2) sequential replay. The
shared library is built lazily with g++ on first use and cached next to
the source (keyed by source mtime); ``available()`` reports whether the
toolchain produced one, and every caller falls back to the pure weaver
when it did not.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings
from typing import Optional

import numpy as np

__all__ = ["available", "weave_list_ranks", "weave_map_ranks", "lib"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "weaver.cpp")
# read-only installs can point the build cache elsewhere
_CACHE_DIR = os.environ.get("CAUSE_TPU_NATIVE_CACHE", _HERE)
_SO = os.path.join(_CACHE_DIR, "_ct_weaver.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    """Compile weaver.cpp to a shared library (cached by mtime). The
    compile goes to a per-pid temp file and is renamed into place so
    concurrent first-use across processes never loads a torn .so."""
    if not (os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        os.makedirs(_CACHE_DIR, exist_ok=True)
        tmp = f"{_SO}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _SO)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    lib = ctypes.CDLL(_SO)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.ct_weave_list.restype = ctypes.c_int32
    lib.ct_weave_list.argtypes = [ctypes.c_int32, i32p, i32p, i32p]
    lib.ct_weave_map.restype = ctypes.c_int32
    lib.ct_weave_map.argtypes = [ctypes.c_int32, ctypes.c_int32, i32p, i32p,
                                 i32p, i32p, i32p]
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when the build failed."""
    global _lib, _build_failed
    if _lib is None and not _build_failed:
        with _lock:
            if _lib is None and not _build_failed:
                try:
                    # causelint: disable-next-line=LCK003 -- one-time lazy cc build under the init lock IS the design: double-checked init, every later caller takes the fast path above the lock
                    _lib = _build()
                except (OSError, subprocess.CalledProcessError) as e:
                    _build_failed = True
                    detail = getattr(e, "stderr", "") or str(e)
                    warnings.warn(
                        "cause_tpu native weaver build failed; "
                        'weaver="native" degrades to the pure host path '
                        "(set CAUSE_TPU_NATIVE_CACHE to a writable dir "
                        f"if the install is read-only): {detail.strip()[:400]}",
                        RuntimeWarning,
                        stacklevel=3,
                    )
    return _lib


def available() -> bool:
    return lib() is not None


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def weave_list_ranks(cause_idx, vclass):
    """Weave rank for one list tree's lanes (ascending id order, lane 0
    = root). Raises RuntimeError when the library is missing or the
    lanes are malformed."""
    L = lib()
    if L is None:
        raise RuntimeError("native weaver unavailable")
    cause_idx = _i32(cause_idx)
    vclass = _i32(vclass)
    n = cause_idx.shape[0]
    rank = np.empty(n, np.int32)
    rc = L.ct_weave_list(n, _ptr(cause_idx), _ptr(vclass), _ptr(rank))
    if rc != 0:
        raise RuntimeError(f"ct_weave_list failed with code {rc}")
    return rank


def weave_map_ranks(cause_idx, key_rank, vclass, n_keys: int):
    """(rank, key_out) for one map tree's lanes: a forest preorder where
    each key's lanes are contiguous in that key's weave order."""
    L = lib()
    if L is None:
        raise RuntimeError("native weaver unavailable")
    cause_idx = _i32(cause_idx)
    key_rank = _i32(key_rank)
    vclass = _i32(vclass)
    n = cause_idx.shape[0]
    rank = np.empty(n, np.int32)
    key_out = np.empty(n, np.int32)
    rc = L.ct_weave_map(
        n, n_keys, _ptr(cause_idx), _ptr(key_rank), _ptr(vclass),
        _ptr(rank), _ptr(key_out),
    )
    if rc != 0:
        raise RuntimeError(f"ct_weave_map failed with code {rc}")
    return rank, key_out
