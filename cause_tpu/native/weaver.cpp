// Native host weaver: O(n) causal-tree linearization.
//
// The third weave backend ("native", next to "pure" and "jax"): a C++
// implementation of the same derived-tree construction the JAX kernel
// uses (see cause_tpu/weaver/jaxw.py), for host-side full reweaves and
// merges where the O(n^2) sequential replay (reference:
// src/causal/collections/list.cljc:20-34) is too slow and a TPU
// round-trip is not worth it.
//
// Contract (shared with the device kernel, fuzz-verified against the
// pure weaver):
//   - lanes arrive in ascending id order, lane 0 is the root sentinel,
//     so the lane index IS the id rank: sibling "descending id" order
//     equals descending lane index;
//   - a special node's parent is its cause; a non-special's parent is
//     its host — the first non-special on its cause chain;
//   - children order under a parent: specials first, then descending
//     id; among specials also descending id;
//   - the weave is the preorder DFS of that tree.
//
// Map trees are a forest of per-key mini-weaves (reference:
// src/causal/collections/map.cljc:21-45): key-caused lanes hang off a
// per-key virtual root and the DFS emits each key's weave as one
// contiguous run; id-caused lanes resolve their key through the parent
// chain.
//
// All arrays are int32 and caller-allocated; the entry points return 0
// on success. No exceptions, no allocation failures other than
// std::bad_alloc aborting.

#include <cstdint>
#include <vector>

namespace {

// Build child buckets (specials-first, descending lane) and run a
// preorder DFS from the given roots. parent[i] < i for every non-root
// lane (causes precede effects in id order). rank_out gets the weave
// position of each lane; roots themselves are emitted too.
void preorder(int32_t n, const int32_t* parent, const uint8_t* special,
              const std::vector<int32_t>& roots, int32_t* rank_out) {
  // counting sort children by parent, ascending lane
  std::vector<int32_t> head_special(n, -1), head_normal(n, -1);
  std::vector<int32_t> next_lane(n, -1);
  // iterate descending lane so singly-linked lists come out ascending;
  // DFS pushes ascending onto a stack, popping descending — the
  // sibling order we need — with specials popped before normals.
  for (int32_t i = 0; i < n; ++i) {
    int32_t p = parent[i];
    if (p < 0 || p >= n) continue;
    if (special[i]) {
      next_lane[i] = head_special[p];
      head_special[p] = i;
    } else {
      next_lane[i] = head_normal[p];
      head_normal[p] = i;
    }
  }
  // head_* lists are now descending-lane? No: built by pushing lanes in
  // ascending order, each prepended, so heads hold the LARGEST lane and
  // lists run descending — exactly sibling order. DFS with an explicit
  // stack: push normals first, then specials, both in reverse sibling
  // order, so specials pop first and siblings pop descending.
  std::vector<int32_t> stack;
  stack.reserve(64);
  int32_t pos = 0;
  std::vector<int32_t> tmp;
  for (int32_t r : roots) {
    stack.push_back(r);
    while (!stack.empty()) {
      int32_t v = stack.back();
      stack.pop_back();
      rank_out[v] = pos++;
      // children in reverse sibling order: normals ascending, then
      // specials ascending (so that popping yields specials desc first)
      tmp.clear();
      for (int32_t c = head_normal[v]; c >= 0; c = next_lane[c]) tmp.push_back(c);
      for (int32_t j = (int32_t)tmp.size() - 1; j >= 0; --j) stack.push_back(tmp[j]);
      tmp.clear();
      for (int32_t c = head_special[v]; c >= 0; c = next_lane[c]) tmp.push_back(c);
      for (int32_t j = (int32_t)tmp.size() - 1; j >= 0; --j) stack.push_back(tmp[j]);
    }
  }
}

}  // namespace

extern "C" {

// List weave. Lanes 0..n-1 in ascending id order, lane 0 = root
// sentinel (cause_idx[0] < 0). vclass: 0 normal, 1 hide, 2 h.hide,
// 3 h.show. Outputs rank_out[n] (weave position); rendering/visibility
// stays host-side on the weave list (hide?, list.cljc:48-55).
int32_t ct_weave_list(int32_t n, const int32_t* cause_idx,
                      const int32_t* vclass, int32_t* rank_out) {
  if (n <= 0) return 1;
  std::vector<uint8_t> special(n);
  std::vector<int32_t> parent(n);
  std::vector<int32_t> host(n);  // host[x] = first non-special at-or-above x
  for (int32_t i = 0; i < n; ++i) special[i] = vclass[i] > 0 ? 1 : 0;
  host[0] = 0;
  parent[0] = -1;
  for (int32_t i = 1; i < n; ++i) {
    int32_t c = cause_idx[i];
    if (c < 0 || c >= i) return 2;  // causes must precede effects
    host[i] = special[i] ? host[c] : i;
    parent[i] = special[i] ? c : host[c];
  }
  preorder(n, parent.data(), special.data(), {0}, rank_out);
  return 0;
}

// Map weave. key_rank[i] >= 0 for key-caused lanes (the key's interned
// ordinal), -1 for id-caused lanes (cause_idx[i] then names the target
// lane). n_keys = number of distinct keys. Outputs rank_out[n] — a
// forest preorder in which each key's lanes are one contiguous run, in
// that key's weave order (the per-key s/weave-node order of
// map.cljc:21-45) — and key_out[n], each lane's resolved key ordinal.
//
// Every key's mini-weave is an ordinary list weave whose root is a
// per-key virtual lane (the ROOT sentinel of map.cljc:80): key-caused
// lanes are caused by their key's root; id-caused lanes by the target.
int32_t ct_weave_map(int32_t n, int32_t n_keys, const int32_t* cause_idx,
                     const int32_t* key_rank, const int32_t* vclass,
                     int32_t* rank_out, int32_t* key_out) {
  if (n < 0 || n_keys < 0) return 1;
  if (n == 0) return 0;
  // lane n+k is the virtual root of key k (non-special, hosts itself)
  int32_t m = n + n_keys;
  std::vector<uint8_t> special(m, 0);
  std::vector<int32_t> parent(m, -1);
  std::vector<int32_t> host(m);  // host[x] = first non-special at-or-above x
  for (int32_t i = 0; i < n; ++i) special[i] = vclass[i] > 0 ? 1 : 0;
  for (int32_t k = 0; k < n_keys; ++k) host[n + k] = n + k;
  for (int32_t i = 0; i < n; ++i) {
    int32_t c;  // the cause lane inside the forest
    if (key_rank[i] >= 0) {
      if (key_rank[i] >= n_keys) return 3;
      key_out[i] = key_rank[i];
      c = n + key_rank[i];
    } else {
      c = cause_idx[i];
      if (c < 0 || c >= i) return 2;  // causes must precede effects
      key_out[i] = key_out[c];
    }
    host[i] = special[i] ? host[c] : i;
    parent[i] = special[i] ? c : host[c];
  }
  std::vector<int32_t> roots;
  roots.reserve(n_keys);
  for (int32_t k = 0; k < n_keys; ++k) roots.push_back(n + k);
  std::vector<int32_t> rank_all(m);
  preorder(m, parent.data(), special.data(), roots, rank_all.data());
  // compress out the virtual roots: ranks renumbered in global order
  std::vector<int32_t> at(m, -1);
  for (int32_t i = 0; i < m; ++i) at[rank_all[i]] = i;
  int32_t pos = 0;
  for (int32_t r = 0; r < m; ++r) {
    int32_t lane = at[r];
    if (lane >= 0 && lane < n) rank_out[lane] = pos++;
  }
  return 0;
}

}  // extern "C"
