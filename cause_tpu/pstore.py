"""Amortized-O(1) persistent stores for the hot append paths.

The causal tree is a frozen value: every op returns a new tree, and the
reference gets cheap copies from Clojure's persistent maps/vectors
(shared.cljc:104-119 — ``assoc``/``conj`` are structural sharing).
Python's dict/list made each insert O(n) (a 10k-node tree paid ~200 us
copying ``nodes`` and ~150 us copying its own yarn per conj). These two
classes restore the reference's cost model:

- ``OverlayMap``: an immutable Mapping of (base dict, small extra
  dict). ``assoc`` copies only the extra (bounded ~sqrt(n)), flattening
  into a new base when it grows past the bound — amortized O(sqrt(n))
  per insert instead of O(n).
- ``AppendVec``: an immutable Sequence of frozen blocks + a small
  tail. ``appended`` copies only the tail (bounded by BLOCK) —
  amortized O(1) per append.

Both interoperate with their plain counterparts (dict/list) — mixed
comparisons work via the reflected ``__eq__`` — so the rest of the
codebase keeps producing plain structures wherever it already does.
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence
from itertools import chain

__all__ = ["OverlayMap", "AppendVec", "assoc_items", "yarn_appended"]

# below this store size a plain dict copy is cheaper than the overlay
# bookkeeping; yarns convert to AppendVec past the same scale
_SMALL = 2048


class OverlayMap(Mapping):
    """Immutable mapping = base dict + small extra dict (disjoint
    keys; ``assoc`` flattens on overlap, so lookups never shadow).

    The base is aliased, not copied: the constructor's caller promises
    the base dict is frozen from here on (see assoc_items)."""

    __slots__ = ("_base", "_extra")

    def __init__(self, base: dict, extra: dict):
        self._base = base
        self._extra = extra

    def __getitem__(self, k):
        e = self._extra
        if k in e:
            return e[k]
        return self._base[k]

    def __contains__(self, k):
        return k in self._extra or k in self._base

    def __iter__(self):
        return chain(self._base, self._extra)

    def __len__(self):
        return len(self._base) + len(self._extra)

    def get(self, k, default=None):
        e = self._extra
        if k in e:
            return e[k]
        return self._base.get(k, default)

    def assoc(self, items: dict) -> "Mapping":
        """This mapping plus ``items`` (new object; self unchanged)."""
        base, extra = self._base, self._extra
        if any(k in self for k in items):
            # overwrite: flatten so later lookups stay unambiguous
            out = dict(base)
            out.update(extra)
            out.update(items)
            return out
        new_extra = {**extra, **items}
        # keep the copied-every-assoc part ~sqrt(total): amortized
        # sqrt(n) per op; flattening is rare (every ~sqrt(n) ops)
        if len(new_extra) * len(new_extra) >= max(_SMALL, len(base)):
            out = dict(base)
            out.update(new_extra)
            return out
        return OverlayMap(base, new_extra)

    def __eq__(self, other):
        if not isinstance(other, Mapping):
            return NotImplemented
        if len(other) != len(self):
            return False
        for k, v in self.items():
            if k not in other or other[k] != v:
                return False
        return True

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    __hash__ = None  # mutable-adjacent: match dict's unhashability

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"OverlayMap({len(self._base)}+{len(self._extra)})"


class AppendVec(Sequence):
    """Immutable sequence = tuple of frozen blocks + small tail tuple;
    ``appended`` shares every block (amortized O(1))."""

    __slots__ = ("_blocks", "_tail", "_len")

    BLOCK = 128

    def __init__(self, blocks=(), tail=(), length=None):
        self._blocks = blocks
        self._tail = tail
        self._len = (sum(len(b) for b in blocks) + len(tail)
                     if length is None else length)

    @staticmethod
    def from_list(xs) -> "AppendVec":
        xs = tuple(xs)
        B = AppendVec.BLOCK
        blocks = tuple(xs[i:i + B] for i in range(0, len(xs) - len(xs) % B, B))
        tail = xs[len(xs) - len(xs) % B:]
        return AppendVec(blocks, tail, len(xs))

    def appended(self, x) -> "AppendVec":
        tail = self._tail + (x,)
        if len(tail) >= self.BLOCK:
            return AppendVec(self._blocks + (tail,), (), self._len + 1)
        return AppendVec(self._blocks, tail, self._len + 1)

    def __len__(self):
        return self._len

    def __iter__(self):
        for b in self._blocks:
            yield from b
        yield from self._tail

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._len)
            if step != 1:
                return list(self)[i]
            # walk only the covered blocks: a suffix slice (the sync
            # delta path, sync.py:91) stays O(len(slice)), not O(n)
            out = []
            B = self.BLOCK
            nb = len(self._blocks)
            for b in range(max(0, start // B), nb):
                lo = b * B
                if lo >= stop:
                    break
                blk = self._blocks[b]
                out.extend(blk[max(0, start - lo):
                               max(0, min(B, stop - lo))])
            tail_lo = nb * B
            if stop > tail_lo:
                out.extend(self._tail[max(0, start - tail_lo):
                                      stop - tail_lo])
            return out
        n = self._len
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        if i >= n - len(self._tail):
            return self._tail[i - (n - len(self._tail))]
        b, off = divmod(i, self.BLOCK)
        return self._blocks[b][off]

    def __eq__(self, other):
        if isinstance(other, AppendVec):
            return (self._len == other._len
                    and all(a == b for a, b in zip(self, other)))
        if isinstance(other, (list, tuple)):
            return (self._len == len(other)
                    and all(a == b for a, b in zip(self, other)))
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    __hash__ = None

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"AppendVec({list(self)!r})"


def assoc_items(store: Mapping, items: dict) -> Mapping:
    """``store`` plus ``items``, picking the cheapest representation:
    plain-dict copy while small, OverlayMap structural sharing once the
    copy would dominate the op.

    ALIASING INVARIANT: past the small-store threshold the caller's
    ``store`` is wrapped as the OverlayMap base WITHOUT copying — it
    must never be mutated in place afterwards or every derived tree
    silently corrupts. All nodes stores in this codebase are treated
    as frozen (union_nodes_many copies first); new callers must keep
    that contract."""
    if isinstance(store, OverlayMap):
        return store.assoc(items)
    if len(store) < _SMALL or any(k in store for k in items):
        # small store, or an overwrite (assoc_nodes is historically
        # overwrite-tolerant): plain copy keeps keys unambiguous
        out = dict(store)
        out.update(items)
        return out
    return OverlayMap(store, dict(items))


def yarn_appended(yarn, n):
    """``yarn`` with ``n`` appended (new object), upgrading big lists
    to AppendVec so the per-append copy stays bounded."""
    if isinstance(yarn, AppendVec):
        return yarn.appended(n)
    if len(yarn) >= _SMALL:
        return AppendVec.from_list(yarn).appended(n)
    return list(yarn) + [n]
