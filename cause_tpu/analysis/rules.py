"""The causelint rule families, each grounded in a shipped incident.

- **TID** — trace-identity soundness. The CAUSE_TPU_* strategy
  switches are read at trace time, so they are program identity:
  every name must be registered (TRACE_SWITCHES or KNOWN_ENV_KNOBS),
  never restated as a literal outside switches.py, and every host-side
  cache of a traced program must fold the switch snapshot into its key
  (the round-4/5 stale-program incidents).
- **JPH** — jit-purity hazards. Host effects inside jit-reachable
  code run at trace time only (or break retracing): env reads, clock
  reads, print, open, ``.item()``, mutation of module-level state.
- **OBS** — obs-off invariance. ``cause_tpu/obs`` must read zero
  TRACE_SWITCHES env vars on any path the disabled mode reaches, and
  traced code may only touch the guarded no-op instrument factories.
- **LCA** — lane-cache aliasing. LaneArena columns are shared by
  every view of a tree; in-place stores outside the arena-owning
  ``lanecache`` module corrupt sibling views silently.

Every rule is a function ``(ctx, module) -> yields Finding`` registered
in :data:`REGISTRY`. Rules receive the cross-module
:class:`~cause_tpu.analysis.callgraph.Program` via ``ctx`` so the
jit-reachability answer is shared, not recomputed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import FrozenSet, Iterator, List, NamedTuple, Optional

from .callgraph import FuncInfo, ModuleInfo, Program, dotted_parts

# imported from the one authority — the module rule this linter
# enforces applies to the linter too
from ..switches import KNOWN_ENV_KNOBS, TRACE_SWITCHES

SWITCH_HELPERS = frozenset({"resolve", "raw_key", "raw_switch_key"})
_ENV_READ_ATTRS = frozenset({"get", "pop", "setdefault", "__getitem__"})
_CACHE_DECOS = frozenset({"lru_cache", "cache"})
_OBS_GUARDED = frozenset({"span", "counter", "gauge", "event"})
_OBS_UNGUARDED = frozenset(
    {"flush", "configure", "reset", "counters_snapshot", "events",
     "export_jsonl", "set_platform", "load_jsonl"}
)
ARENA_COLS = frozenset(
    {"ts", "site", "tx", "cause_idx", "vclass", "cause_hi", "cause_lo"}
)
# the arena-owning module: its committed-mutation sites (extend_view's
# in-place append, sync_ranks' rank upgrade) are the whitelist the LCA
# family is defined around
_ARENA_OWNER = "lanecache"


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    @property
    def family(self) -> str:
        return self.rule.rstrip("0123456789")


@dataclass
class RuleSpec:
    rule_id: str
    help: str
    check: object  # callable(ctx, module) -> Iterator[Finding]


REGISTRY: dict = {}

# Bumped whenever rule logic or the rule set changes; the incremental
# cache (core.cached_run) keys on it so a rule-set change invalidates
# every cached verdict even when no analyzed file changed.
RULESET_VERSION = 5  # PR 20: SHP001 gates the telemetry-ship layer


def rule(rule_id: str, help_text: str):
    def deco(fn):
        REGISTRY[rule_id] = RuleSpec(rule_id, help_text, fn)
        return fn
    return deco


class Context:
    """Shared per-run state handed to every rule."""

    def __init__(self, program: Program):
        self.program = program
        self.reachable = program.reachable()

    def reachable_funcs(self, module: ModuleInfo) -> List[FuncInfo]:
        return [f for fid, f in module.funcs.items()
                if fid in self.reachable]


# --------------------------------------------------------------- utils

def _finding(rule_id: str, module: ModuleInfo, node: ast.AST,
             message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    snippet = (module.lines[line - 1].strip()
               if 0 < line <= len(module.lines) else "")
    return Finding(rule_id, module.path, line,
                   getattr(node, "col_offset", 0), message, snippet)


def _env_read_key(node: ast.Call) -> Optional[ast.expr]:
    """The key expression of an ``os.environ.get/pop/...`` or
    ``os.getenv`` call, else None."""
    parts = dotted_parts(node.func)
    if parts is None:
        return None
    if parts[-1] == "getenv" or (
            len(parts) >= 2 and parts[-2] == "environ"
            and parts[-1] in _ENV_READ_ATTRS):
        return node.args[0] if node.args else None
    return None


def _environ_subscript(node: ast.AST) -> Optional[ast.expr]:
    """``os.environ[KEY]`` (read or write target) -> KEY, else None."""
    if isinstance(node, ast.Subscript):
        parts = dotted_parts(node.value)
        if parts is not None and parts[-1] == "environ":
            return node.slice
    return None


def _iter_env_keys(tree_nodes) -> Iterator[ast.expr]:
    """Every env-var key expression (call-style and subscript-style)
    in an AST node stream."""
    for n in tree_nodes:
        if isinstance(n, ast.Call):
            key = _env_read_key(n)
            if key is not None:
                yield key
        key = _environ_subscript(n)
        if key is not None:
            yield key


def _literal(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_switches_module(module: ModuleInfo) -> bool:
    return module.segments[-1] == "switches"


def _in_obs_package(module: ModuleInfo) -> bool:
    return "obs" in module.segments[:-1] or module.segments[-1] == "obs"


def _docstring_lines(module: ModuleInfo) -> set:
    """Line spans of docstring constants (skipped by literal rules)."""
    out = set()
    if module.tree is None:
        return out
    for n in ast.walk(module.tree):
        if isinstance(n, (ast.Module, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef)):
            body = getattr(n, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant) and isinstance(
                    body[0].value.value, str):
                c = body[0].value
                out.update(range(c.lineno, (c.end_lineno or c.lineno) + 1))
    return out


# ----------------------------------------------------------------- TID

@rule("TID001",
      "trace-reachable read of a CAUSE_TPU_* env var that is not a "
      "registered TRACE_SWITCHES member")
def check_tid001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if _is_switches_module(module):
        return
    registered = set(TRACE_SWITCHES) | set(KNOWN_ENV_KNOBS)
    for info in ctx.reachable_funcs(module):
        for key in _iter_env_keys(info.body_nodes()):
            name = _literal(key)
            if (name and name.startswith("CAUSE_TPU_")
                    and name not in registered):
                yield _finding(
                    "TID001", module, key,
                    f"jit-reachable code reads {name!r}, which is in "
                    "neither TRACE_SWITCHES nor KNOWN_ENV_KNOBS — an "
                    "unregistered trace-time config axis never reaches "
                    "program-cache keys (import the registry in "
                    "cause_tpu/switches.py, never invent names)")
    # helper misuse is a hazard anywhere: resolve()/raw_key() on an
    # unknown name silently returns "" forever
    if module.tree is None:
        return
    for n in ast.walk(module.tree):
        if isinstance(n, ast.Call):
            parts = dotted_parts(n.func)
            if (parts is not None and parts[-1] in ("resolve", "raw_key")
                    and n.args):
                name = _literal(n.args[0])
                if (name and name.startswith("CAUSE_TPU_")
                        and name not in TRACE_SWITCHES):
                    yield _finding(
                        "TID001", module, n,
                        f"switch helper called with {name!r}, which is "
                        "not a TRACE_SWITCHES member — the read can "
                        "never be part of program identity")


@rule("TID002",
      "TRACE_SWITCHES name restated as a string literal outside "
      "switches.py (module rule: import, never restate)")
def check_tid002(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if _is_switches_module(module) or module.tree is None:
        return
    doc_lines = _docstring_lines(module)
    # literals passed straight to the switch helpers are the sanctioned
    # read pattern, not a restatement
    helper_args = set()
    for n in ast.walk(module.tree):
        if isinstance(n, ast.Call):
            parts = dotted_parts(n.func)
            if parts is not None and parts[-1] in SWITCH_HELPERS:
                for a in n.args:
                    helper_args.add(id(a))
    for n in ast.walk(module.tree):
        if not isinstance(n, ast.Constant) or not isinstance(n.value, str):
            continue
        if n.lineno in doc_lines or id(n) in helper_args:
            continue
        head = n.value.split("=", 1)[0]
        if head in TRACE_SWITCHES:
            yield _finding(
                "TID002", module, n,
                f"switch name {head!r} restated as a literal — a copy "
                "that drifts from switches.py silently serves/keys a "
                "different program config; import TRACE_SWITCHES / "
                "BESTSTREAM_FLIPS instead (or suppress with a reason "
                "for deliberate A/B flips)")


@rule("TID003",
      "host-side cache of a traced program whose key omits the switch "
      "snapshot (stale-program hazard)")
def check_tid003(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    for fid, info in module.funcs.items():
        node = info.node
        if isinstance(node, ast.Lambda):
            continue
        if not any(
            (dotted_parts(d) or ["?"])[-1] in _CACHE_DECOS
            or (isinstance(d, ast.Call)
                and (dotted_parts(d.func) or ["?"])[-1] in _CACHE_DECOS)
            for d in node.decorator_list
        ):
            continue
        # trace roots lexically inside this cached factory
        inner = [f for f in ctx.program.roots
                 if f.startswith(fid + ".")] + (
            [fid] if fid in ctx.program.roots else [])
        if not inner:
            continue
        traced = ctx.program.reachable_from(inner)
        reads_switches = False
        for tfid in traced:
            tinfo = ctx.program.funcs[tfid]
            for parts, _ln in tinfo.calls:
                if parts[-1] in SWITCH_HELPERS:
                    reads_switches = True
            for key in _iter_env_keys(tinfo.body_nodes()):
                name = _literal(key)
                if name in TRACE_SWITCHES:
                    reads_switches = True
        if not reads_switches:
            continue
        params = {a.arg for a in (
            list(node.args.posonlyargs) + list(node.args.args)
            + list(node.args.kwonlyargs))}
        if "switches" not in params:
            yield _finding(
                "TID003", module, node,
                f"{info.qualname} caches a traced program that reads "
                "TRACE_SWITCHES at trace time, but its cache key has "
                "no `switches` parameter — after a switch flip the "
                "cache serves the program traced under the OLD config "
                "(fold switches.raw_switch_key() into the key)")


# ----------------------------------------------------------------- JPH

_JPH_EXEMPT_LAST_SEG = frozenset({"switches"})


def _jph_applies(module: ModuleInfo) -> bool:
    # switches.py's resolve/raw_key ARE the sanctioned trace-time env
    # readers; the obs package's guard discipline is the OBS family's
    # job (its factories run host-side at trace time by design)
    return (module.segments[-1] not in _JPH_EXEMPT_LAST_SEG
            and not _in_obs_package(module))


@rule("JPH001",
      "direct os.environ access inside jit-reachable code (route "
      "trace-time config through switches.resolve/raw_key)")
def check_jph001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _jph_applies(module):
        return
    for info in ctx.reachable_funcs(module):
        for key in _iter_env_keys(info.body_nodes()):
            name = _literal(key)
            yield _finding(
                "JPH001", module, key,
                "jit-reachable code reads the environment directly"
                + (f" ({name!r})" if name else "")
                + " — the value binds at trace time and never joins "
                "program identity; use switches.resolve()/raw_key() "
                "(registered switches) or hoist the read to host code")


@rule("JPH002",
      "clock read (time.*) inside jit-reachable code")
def check_jph002(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _jph_applies(module):
        return
    for info in ctx.reachable_funcs(module):
        for n in info.body_nodes():
            if isinstance(n, ast.Call):
                parts = dotted_parts(n.func)
                if (parts is not None and len(parts) >= 2
                        and parts[-2] == "time"):
                    yield _finding(
                        "JPH002", module, n,
                        f"time.{parts[-1]}() inside jit-reachable code "
                        "runs once at trace time, not per step — hoist "
                        "to the host caller (obs spans time host-side)")


@rule("JPH003", "print() inside jit-reachable code")
def check_jph003(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _jph_applies(module):
        return
    for info in ctx.reachable_funcs(module):
        for n in info.body_nodes():
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id == "print"):
                yield _finding(
                    "JPH003", module, n,
                    "print() inside jit-reachable code fires at trace "
                    "time only (silent after the first call) — use "
                    "jax.debug.print or host-side obs events")


@rule("JPH004", "open() inside jit-reachable code")
def check_jph004(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _jph_applies(module):
        return
    for info in ctx.reachable_funcs(module):
        for n in info.body_nodes():
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id == "open"):
                yield _finding(
                    "JPH004", module, n,
                    "open() inside jit-reachable code is a host file "
                    "effect at trace time — hoist it to the caller")


@rule("JPH005",
      ".item()/float()-on-parameter inside jit-reachable code "
      "(forces a device sync / fails under trace)")
def check_jph005(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _jph_applies(module):
        return
    for info in ctx.reachable_funcs(module):
        params = set()
        if not isinstance(info.node, ast.Lambda):
            args = info.node.args
            params = {a.arg for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs))}
        for n in info.body_nodes():
            if not isinstance(n, ast.Call):
                continue
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "item" and not n.args):
                yield _finding(
                    "JPH005", module, n,
                    ".item() on a traced value aborts tracing (or "
                    "blocks on device sync) — keep reductions in the "
                    "program and fetch on the host")
            elif (isinstance(n.func, ast.Name) and n.func.id == "float"
                    and n.args and isinstance(n.args[0], ast.Name)
                    and n.args[0].id in params):
                yield _finding(
                    "JPH005", module, n,
                    "float() on a traced argument aborts tracing — "
                    "use .astype()/jnp casts inside the program")


@rule("JPH006",
      "mutation of module-level state inside jit-reachable code "
      "(trace-time side effect; silently stale on cache hits)")
def check_jph006(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _jph_applies(module):
        return
    module_level = set(module.top_funcs)
    if module.tree is not None:
        for n in module.tree.body:
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        module_level.add(t.id)
            elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                if isinstance(n.target, ast.Name):
                    module_level.add(n.target.id)
    mutators = {"append", "add", "update", "setdefault", "pop",
                "clear", "extend", "insert", "popitem"}
    for info in ctx.reachable_funcs(module):
        declared_global = set()
        for n in info.body_nodes():
            if isinstance(n, ast.Global):
                declared_global.update(n.names)
        for n in info.body_nodes():
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            for t in targets:
                base = t
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if (isinstance(base, ast.Name) and base is not t
                        and base.id in module_level):
                    yield _finding(
                        "JPH006", module, t,
                        f"jit-reachable code mutates module-level "
                        f"{base.id!r} — runs at trace time only, so "
                        "cached executions silently skip it")
                elif isinstance(t, ast.Name) and t.id in declared_global:
                    yield _finding(
                        "JPH006", module, t,
                        f"jit-reachable code rebinds global {t.id!r} "
                        "at trace time only")
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in mutators
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in module_level):
                yield _finding(
                    "JPH006", module, n,
                    f"jit-reachable code calls .{n.func.attr}() on "
                    f"module-level {n.func.value.id!r} — a trace-time "
                    "side effect cached executions skip")


# ----------------------------------------------------------------- OBS

@rule("OBS001",
      "cause_tpu/obs reads a TRACE_SWITCHES env var (obs-off "
      "invariance: disabled mode must add zero identity-adjacent "
      "env reads)")
def check_obs001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _in_obs_package(module) or module.tree is None:
        return
    for key in _iter_env_keys(ast.walk(module.tree)):
        name = _literal(key)
        if name is None:
            yield _finding(
                "OBS001", module, key,
                "obs reads an env var through a non-literal key — "
                "causelint cannot prove it is not a TRACE_SWITCHES "
                "member; read via a literal, or suppress with a "
                "reason at the one sanctioned enabled-span snapshot")
        elif name in TRACE_SWITCHES:
            yield _finding(
                "OBS001", module, key,
                f"obs reads trace switch {name!r} — the obs-off "
                "contract is ZERO TRACE_SWITCHES reads (program "
                "identity must not depend on whether obs is on)")


@rule("OBS002",
      "jit-reachable code calls an unguarded obs API (only the no-op "
      "factories span/counter/gauge/event may sit on traced paths)")
def check_obs002(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if _in_obs_package(module):
        return
    for info in ctx.reachable_funcs(module):
        for parts, lineno in info.calls:
            if parts[-1] not in _OBS_UNGUARDED:
                continue
            target = ctx.program.resolve_call(info, parts)
            if target is not None:
                tmod = target.split("::", 1)[0]
                is_obs = "obs" in tmod.split(".")
            else:
                # unresolved: trust the spelling — obs.flush(),
                # _obs_flush(), aliased obs module attributes
                is_obs = (len(parts) >= 2 and "obs" in parts[:-1]) or \
                    parts[0].startswith("_obs")
            if not is_obs:
                continue
            node = ast.Constant(value="")
            node.lineno, node.col_offset = lineno, 0
            yield _finding(
                "OBS002", module, node,
                f"obs.{parts[-1]}() inside jit-reachable code does "
                "unconditional work even with obs disabled — hot "
                "paths route through span()/counter()/gauge()/"
                "event(), which collapse to shared no-ops")


def _is_enabled_name(name: str) -> bool:
    """The sanctioned guard in any of the repo's import spellings:
    ``obs.enabled()``, ``devprof.enabled()``, or the aliased
    ``from ..obs import enabled as _obs_enabled`` style lanecache
    uses — matching only the literal ``enabled`` would flag
    correctly-guarded code the moment an aliasing module becomes
    jit-reachable."""
    return name == "enabled" or name.endswith("_enabled")


def _mentions_enabled(test: ast.expr) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Call):
            parts = dotted_parts(n.func)
            if parts and _is_enabled_name(parts[-1]):
                return True
    return False


def _is_not_enabled_exit(s: ast.stmt) -> bool:
    """``if not ...enabled(): return/raise/continue/break`` — the
    early-return guard style; everything after it in the same
    statement list only runs with obs on."""
    return (isinstance(s, ast.If) and not s.orelse
            and isinstance(s.test, ast.UnaryOp)
            and isinstance(s.test.op, ast.Not)
            and _mentions_enabled(s.test.operand)
            and bool(s.body)
            and isinstance(s.body[-1], (ast.Return, ast.Raise,
                                        ast.Continue, ast.Break)))


def _calls_with_guards(info: FuncInfo):
    """(Call node, guarded) pairs over one scope's own statements,
    where ``guarded`` means the call sits inside the body of an
    ``if ...enabled()...:`` test, or after an
    ``if not ...enabled(): return`` early exit in the same statement
    list. Nested function/lambda bodies are their own scopes (they
    get their own FuncInfo)."""

    def walk_stmts(stmts, guarded):
        for s in stmts:
            yield from walk(s, guarded)
            if _is_not_enabled_exit(s):
                guarded = True

    def walk(n, guarded):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return
        if isinstance(n, ast.Call):
            yield n, guarded
        if isinstance(n, ast.If):
            # polarity matters: `if enabled():` guards the BODY,
            # `if not enabled():` guards the ELSE branch — marking
            # both bodies guarded whenever the test mentions enabled()
            # would sanction obs-off-only code and flag the correctly
            # guarded else of a negated test
            if (isinstance(n.test, ast.UnaryOp)
                    and isinstance(n.test.op, ast.Not)
                    and _mentions_enabled(n.test.operand)):
                body_g, else_g = guarded, True
            elif _mentions_enabled(n.test):
                body_g, else_g = True, guarded
            else:
                body_g = else_g = guarded
            yield from walk(n.test, guarded)
            yield from walk_stmts(n.body, body_g)
            yield from walk_stmts(n.orelse, else_g)
            return
        for name, value in ast.iter_fields(n):
            if name in ("body", "orelse", "finalbody") \
                    and isinstance(value, list):
                yield from walk_stmts(value, guarded)
                continue
            for c in (value if isinstance(value, list) else [value]):
                if isinstance(c, ast.AST):
                    yield from walk(c, guarded)

    if isinstance(info.node.body, list):
        yield from walk_stmts(info.node.body, False)
    else:
        yield from walk(info.node.body, False)


# --------------------------------------------- guarded-API rule table
#
# OBS003-007, CHS001, SRV001, NET001 and DSK001 all share one shape:
# a subsystem whose APIs do real host work the moment obs (or chaos)
# is on, matched by distinctive bare names plus module qualifiers,
# excused by the sanctioned ...enabled() guard spellings, and scoped
# away from the subsystem's own package. Only the table rows differ —
# the per-PR copy-paste of the checker body was the dominant growth
# cost of this file, so the rows are data now. Rule ids, help texts,
# messages, fixtures and suppressions are unchanged.


class _GuardSpec(NamedTuple):
    rule_id: str
    help: str
    apis: FrozenSet[str]       # distinctive bare names
    quals: FrozenSet[str]      # module-qualifier spellings
    skip: object               # module -> bool, extra exclusions
    guard_desc: str            # "an obs.enabled()" / chaos variant
    work_desc: str             # why the call is real work
    prefix: Optional[str] = None   # message head; None -> dotted path
    sanctioned: FrozenSet[str] = frozenset()


_GUARD_RULES = (
    _GuardSpec(
        "OBS003",
        "devprof API reached from jit-reachable code without an "
        "obs.enabled() guard (device-program telemetry samples live "
        "arrays and AOT-compiles the moment obs is on)",
        frozenset({"profile_program", "program_cost",
                   "sample_device_memory", "arena_footprint"}),
        frozenset({"devprof", "_devprof"}),
        lambda module: False,
        "an obs.enabled()",
        "unlike the no-op span/counter factories, devprof does real "
        "work when obs is on",
        prefix="devprof"),
    _GuardSpec(
        "OBS004",
        "semantic-event/fleet API reached from jit-reachable code "
        "without an obs.enabled() guard (the CRDT-semantic layer "
        "assembles real field dicts and walks weaves/version vectors "
        "the moment obs is on)",
        frozenset({"sync_applied", "sync_full_bag", "sync_rejected",
                   "sync_quarantined", "sync_readmitted",
                   "observe_wave", "session_overflow",
                   "token_headroom", "gc_compacted",
                   "lazy_materialized", "fleet_report"}),
        frozenset({"semantic", "_semantic", "_sem"}),
        lambda module: False,
        "an obs.enabled()",
        "unlike the no-op span/counter factories, the semantic layer "
        "builds event payloads (staleness bookkeeping, weave scans) "
        "when obs is on",
        prefix="semantic"),
    _GuardSpec(
        "OBS005",
        "costmodel API reached from jit-reachable code without an "
        "obs.enabled() guard (the wave cost model takes locks and "
        "assembles dispatch/divergence records the moment obs is on)",
        frozenset({"record_dispatch", "register_program",
                   "note_delta_ops", "note_full_bag", "wave_begin",
                   "wave_abandon", "wave_cost", "costmodel_digest",
                   "cost_vs_divergence", "gap_report"}),
        frozenset({"costmodel", "_costmodel", "_cm"}),
        lambda module: False,
        "an obs.enabled()",
        "unlike the no-op span/counter factories, the cost model "
        "takes registry locks and builds per-wave dispatch records "
        "when obs is on",
        prefix="costmodel"),
    _GuardSpec(
        "OBS006",
        "convergence-lag API reached from jit-reachable code without "
        "an obs.enabled() guard (the lag tracer takes registry locks, "
        "stamps wall clocks and assembles per-op records the moment "
        "obs is on)",
        frozenset({"op_created", "ops_applied", "wave_observed",
                   "level_observed", "pending_ops", "lag_summary",
                   "set_slo"}),
        frozenset({"lag", "_lag"}),
        lambda module: False,
        "an obs.enabled()",
        "unlike the no-op span/counter factories, the lag tracer "
        "reads monotonic clocks and mutates the bounded op registry "
        "when obs is on",
        prefix="lag"),
    # distinctive bare names only: generic verbs (attach/feed/poll/
    # snapshot) are matched through the ``live`` module qualifier, or
    # they would flag every unrelated object with a feed()
    _GuardSpec(
        "OBS007",
        "live-telemetry API reached from jit-reachable code without "
        "an obs.enabled() guard (the live layer folds records, takes "
        "monitor locks and evaluates alert rules the moment obs is "
        "on)",
        frozenset({"LiveMonitor", "LiveFold", "LiveAttachment",
                   "emit_snapshot", "default_rules", "parse_rule"}),
        frozenset({"live", "_live"}),
        lambda module: False,
        "an obs.enabled()",
        "unlike the no-op span/counter factories, the live monitor "
        "drains subscriber queues, folds records and evaluates alert "
        "rules when obs is on",
        prefix="live"),
    # distinctive bare names only: ``hop``/``bind_ops``/``trace_of``
    # are unambiguous; a generic spelling like ``reset`` matches
    # through the ``xtrace`` module qualifier instead
    _GuardSpec(
        "XTR001",
        "cross-process tracing API reached from jit-reachable code "
        "without an obs.enabled() guard (the xtrace layer takes the "
        "span-registry lock, mints span ids and assembles hop/clock "
        "event payloads the moment obs is on)",
        frozenset({"hop", "new_trace", "bind_ops", "trace_of",
                   "traces_of", "wire_context", "continue_from",
                   "clock_sample", "reply_stamp", "last_span"}),
        frozenset({"xtrace", "_xtrace"}),
        lambda module: False,
        "an obs.enabled()",
        "unlike the no-op span/counter factories, the tracer takes "
        "the registry lock, mints span ids and builds hop records "
        "when obs is on",
        prefix="xtrace"),
    # ``run_dispatch``/``is_transient`` are SANCTIONED unguarded —
    # run_dispatch IS the dispatch path (its idle cost is one
    # chaos.enabled() read and a try frame)
    _GuardSpec(
        "CHS001",
        "chaos/recovery API reached from jit-reachable code without a "
        "chaos.enabled()/obs.enabled() guard (fault hooks draw RNG "
        "and take the engine lock; recovery telemetry assembles event "
        "payloads the moment obs is on)",
        frozenset({"mangle_items", "dispatch_fault", "budget_exhaust",
                   "should_crash", "stall_point", "chaos_report",
                   "restore_recorded"}),
        frozenset({"chaos", "_chaos", "recovery", "_recovery"}),
        lambda module: ("chaos" in module.segments
                        or module.segments[-1] == "recovery"),
        "a chaos.enabled()/obs.enabled()",
        "fault hooks advance seeded RNG streams under the engine lock "
        "and recovery telemetry builds event payloads when enabled",
        sanctioned=frozenset({"run_dispatch", "is_transient",
                              "suspended"})),
    # distinctive bare names per subsystem; generic verbs (offer/
    # drain, pump/dial, append/gc) are matched through the module
    # qualifiers instead, or they would flag every unrelated queue,
    # socket helper and list.append in the tree. These layers are
    # HOST work by definition (locks, sockets, fsyncs) — reaching
    # them from jit-reachable code unguarded is a structural smell,
    # not just an overhead one.
    _GuardSpec(
        "SRV001",
        "sync-service API reached from jit-reachable code without an "
        "obs.enabled() guard (the serve layer takes admission-queue "
        "locks, appends to the write-ahead journal and packs/restores "
        "checkpoint-grade state — host lifecycle work that must "
        "never sit on a traced path)",
        frozenset({"IngestQueue", "IngestJournal", "BatchController",
                   "ResidencyManager", "SyncService",
                   # PR 18: the cross-tenant batch scheduler marshals
                   # heterogeneous window packs and walks per-tenant
                   # frontiers on the host before its one fused
                   # dispatch — same never-on-a-traced-path contract
                   "BatchScheduler", "wave_fleet"}),
        frozenset({"serve", "_serve"}),
        lambda module: "serve" in module.segments,
        "an obs.enabled()",
        "the serve layer takes queue locks, journals admissions and "
        "spills/restores checkpoint packs"),
    _GuardSpec(
        "NET001",
        "network-transport API reached from jit-reachable code "
        "without an obs.enabled() guard (the net layer blocks on "
        "sockets, sleeps out reconnect backoff and takes connection "
        "locks — host transport work that must never sit on a traced "
        "path)",
        frozenset({"NetClient", "ReplicationServer", "FrameStream",
                   "Backoff", "loopback_pair"}),
        frozenset({"net", "_net", "transport", "_transport"}),
        lambda module: "net" in module.segments,
        "an obs.enabled()",
        "the net layer blocks on socket IO, sleeps out backoff "
        "ladders and mutates connection state"),
    _GuardSpec(
        "DSK001",
        "WAL/scrubber API reached from jit-reachable code without an "
        "obs.enabled() guard (the durable-storage layer fsyncs file "
        "descriptors, rotates/retires segment files and walks "
        "segment directories re-checking CRCs — host storage work "
        "that must never sit on a traced path)",
        frozenset({"WriteAheadLog", "open_journal", "scrub_wal",
                   "scrub_checkpoints", "bench_fsync"}),
        frozenset({"wal", "_wal", "scrub", "_scrub"}),
        lambda module: "serve" in module.segments,
        "an obs.enabled()",
        "the durable-storage layer fsyncs descriptors, rotates and "
        "retires segment files and re-checks CRCs over whole "
        "directories"),
    # PR 20: the telemetry-shipping layer — the exporter spawns a
    # pump thread and dials sockets, the collector binds listeners
    # and appends to a WAL; both are obs-off no-ops ONLY through
    # attach_exporter's subscribe gate, so reaching the classes
    # directly from jit-reachable code must carry the guard
    _GuardSpec(
        "SHP001",
        "telemetry-shipping API reached from jit-reachable code "
        "without an obs.enabled() guard (the ship layer spawns pump "
        "threads, dials collector sockets and buffers records; the "
        "collector binds listeners and appends WAL segments — host "
        "plumbing that must never sit on a traced path)",
        frozenset({"ShipExporter", "CollectorServer",
                   "attach_exporter"}),
        frozenset({"ship", "_ship", "collector", "_collector"}),
        lambda module: False,
        "an obs.enabled()",
        "the shipping layer spawns threads, dials sockets and "
        "persists segments when obs is on",
        prefix="ship"),
)


def _check_guarded_api(spec: _GuardSpec, ctx: Context,
                       module: ModuleInfo) -> Iterator[Finding]:
    if _in_obs_package(module) or spec.skip(module):
        return
    for info in ctx.reachable_funcs(module):
        for call, guarded in _calls_with_guards(info):
            parts = dotted_parts(call.func)
            if parts is None:
                continue
            if _is_enabled_name(parts[-1]) \
                    or parts[-1] in spec.sanctioned:
                # ...enabled() IS the sanctioned guard — flagging it
                # would gate the exact pattern the docs prescribe
                continue
            hit = (parts[-1] in spec.apis
                   or any(q in spec.quals for q in parts[:-1]))
            if hit and not guarded:
                head = (f"{spec.prefix}.{parts[-1]}" if spec.prefix
                        else ".".join(parts))
                yield _finding(
                    spec.rule_id, module, call,
                    f"{head}() on a jit-reachable path without "
                    f"{spec.guard_desc} guard — {spec.work_desc}; "
                    "gate the call (or hoist it off the traced path)")


def _register_guard_rules() -> None:
    for spec in _GUARD_RULES:
        def check(ctx, module, _spec=spec):
            return _check_guarded_api(_spec, ctx, module)
        rule(spec.rule_id, spec.help)(check)


_register_guard_rules()


# ----------------------------------------------------------------- LCA

@rule("LCA001",
      "in-place store into a LaneArena column outside the arena-owning "
      "lanecache module (aliased views share those arrays)")
def check_lca001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if module.segments[-1] == _ARENA_OWNER or module.tree is None:
        return
    for info in module.funcs.values():
        # names bound from <expr>.arena in this scope (plus parameters
        # conventionally named `arena`)
        aliases = set()
        if not isinstance(info.node, ast.Lambda):
            aliases = {a.arg for a in info.node.args.args
                       if a.arg == "arena"}
        for n in info.body_nodes():
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], (ast.Name, ast.Tuple)):
                tgts = (n.targets[0].elts
                        if isinstance(n.targets[0], ast.Tuple)
                        else [n.targets[0]])
                vals = (n.value.elts
                        if isinstance(n.value, ast.Tuple)
                        and isinstance(n.targets[0], ast.Tuple)
                        and len(n.value.elts) == len(tgts)
                        else [n.value] * len(tgts))
                for t, v in zip(tgts, vals):
                    if (isinstance(t, ast.Name)
                            and isinstance(v, ast.Attribute)
                            and v.attr == "arena"):
                        aliases.add(t.id)
        for n in info.body_nodes():
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, ast.AugAssign):
                targets = [n.target]
            for t in targets:
                if not (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr in ARENA_COLS):
                    continue
                base = t.value.value
                is_arena = (
                    (isinstance(base, ast.Name) and base.id in aliases)
                    or (isinstance(base, ast.Attribute)
                        and base.attr == "arena")
                )
                if is_arena:
                    yield _finding(
                        "LCA001", module, t,
                        f"in-place store into arena column "
                        f"'{t.value.attr}' outside weaver/lanecache — "
                        "every LaneView over this arena aliases that "
                        "array, so sibling tree versions see the "
                        "mutation; copy via _copy_arena/build_view or "
                        "add the site to lanecache's committed-append "
                        "path")


def all_rule_ids() -> List[str]:
    return sorted(REGISTRY)
