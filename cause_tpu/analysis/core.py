"""causelint driver: file collection, suppressions, rule execution.

Wiring only — the interesting logic lives in ``callgraph`` (the
jit-reachability answer) and ``rules`` (the TID/JPH/OBS/LCA families).
Stdlib-only end to end: the CI lint gate runs this before jax (or even
numpy) is installed.

Suppression syntax, per line::

    something_flagged()   # causelint: disable=TID002 -- reason
    # causelint: disable-next-line=JPH001,JPH002 -- reason
    the_flagged_line()

Rule tokens may be full ids (``TID002``), family prefixes (``TID``),
or ``all``. The ``-- reason`` tail is free text; write one — a
suppression is a recorded decision, not an escape hatch.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import subprocess
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .callgraph import ModuleInfo, build_program
from .rules import Context, Finding, REGISTRY, RULESET_VERSION

# importing these populates REGISTRY with the LCK/DUR/EVD families
from . import concurrency as _concurrency  # noqa: F401  (registration)
from . import protocol as _protocol        # noqa: F401  (registration)

_SUPPRESS_RE = re.compile(
    r"#\s*causelint:\s*disable(?P<next>-next-line)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_*,\s]+?)"
    r"(?:\s*--\s*(?P<reason>.*))?$"
)


@dataclass
class Suppression:
    line: int          # the line the suppression APPLIES to
    tokens: Set[str]
    reason: str
    used: bool = False


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files: int = 0
    root: str = "."

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def parse_suppressions(lines: List[str]) -> Dict[int, List[Suppression]]:
    """Suppressions from REAL comments only: the source is tokenized so
    a ``# causelint: disable=...`` example inside a docstring (this
    module has one) never registers as a live suppression. Files that
    fail to tokenize fall back to raw-line matching — they already get
    a GEN001 parse finding, so no rule finding needs suppressing."""
    try:
        comments = [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(
                io.StringIO("\n".join(lines) + "\n").readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        comments = list(enumerate(lines, start=1))
    out: Dict[int, List[Suppression]] = {}
    for i, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        tokens = {t.strip() for t in m.group("rules").split(",")
                  if t.strip()}
        target = i + 1 if m.group("next") else i
        out.setdefault(target, []).append(
            Suppression(target, tokens, (m.group("reason") or "").strip())
        )
    return out


def _matches(tokens: Set[str], rule_id: str) -> bool:
    return any(t in ("all", "*") or t == rule_id
               or (t.isalpha() and rule_id.startswith(t))
               for t in tokens)


def collect_files(paths: List[str]) -> List[str]:
    """Every .py file under the given paths (sorted, deduped);
    __pycache__ and hidden directories are skipped."""
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if not d.startswith(".")
                               and d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    seen: Set[str] = set()
    uniq = []
    for p in sorted(out):
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            uniq.append(p)
    return uniq


def _default_root(files: List[str]) -> str:
    """cwd when every analyzed file lives under it (the normal
    from-the-repo invocation), else the files' common ancestor.
    Module names — and with them the package-scoped rules (OBS001's
    obs scope, DUR002/EVD001's serve/net scopes) — derive from paths
    relative to this root; falling back to bare stems for
    outside-the-root files would silently disable those rules when
    the CLI is invoked from elsewhere with absolute paths."""
    cwd = os.getcwd()
    ab = [os.path.abspath(f) for f in files]
    prefix = cwd.rstrip(os.sep) + os.sep
    if not ab or all(f.startswith(prefix) for f in ab):
        return cwd
    return os.path.commonpath([os.path.dirname(f) for f in ab])


def fingerprint(f: Finding, root: str) -> str:
    """Line-number-independent identity of a finding, for baselines:
    unrelated edits above a frozen finding must not unfreeze it."""
    rel = os.path.relpath(os.path.abspath(f.path), os.path.abspath(root))
    h = hashlib.sha1(
        f"{f.rule}|{rel}|{f.snippet.strip()}".encode()
    ).hexdigest()
    return h[:20]


def run(paths: List[str], root: Optional[str] = None,
        rule_ids: Optional[List[str]] = None) -> AnalysisResult:
    """Analyze ``paths`` and return every unsuppressed finding.
    ``rule_ids=None`` runs every rule; an explicit empty list runs
    none (GEN findings — parse errors, unused suppressions — are the
    driver's own and always emitted on full runs)."""
    files = collect_files(paths)
    root = root or _default_root(files)
    program = build_program(files, root)
    ctx = Context(program)
    full_run = rule_ids is None
    selected = [REGISTRY[r]
                for r in (sorted(REGISTRY) if full_run else rule_ids)
                if r in REGISTRY]
    result = AnalysisResult(files=len(files), root=root)
    for module in program.modules:
        result.findings.extend(_check_module(ctx, module, selected))
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # suppressions apply last, so families can be filtered uniformly
    by_path = {m.path: parse_suppressions(m.lines)
               for m in program.modules}
    kept: List[Finding] = []
    for f in result.findings:
        hit = next(
            (s for s in by_path.get(f.path, {}).get(f.line, ())
             if _matches(s.tokens, f.rule)), None)
        if hit is not None:
            hit.used = True
            result.suppressed.append(f)
        else:
            kept.append(f)
    # a suppression nothing matched is a stale recorded decision —
    # report it so the ratchet cannot leak. Full runs only: with a
    # rule subset selected, "unused" would just mean "rule not run".
    if full_run:
        lines_of = {m.path: m.lines for m in program.modules}
        for path, supps in by_path.items():
            for slist in supps.values():
                for s in slist:
                    if not s.used:
                        lines = lines_of.get(path, [])
                        snippet = (lines[s.line - 1].strip()
                                   if 0 < s.line <= len(lines) else "")
                        kept.append(Finding(
                            "GEN002", path, s.line, 0,
                            "suppression matched no finding "
                            f"({', '.join(sorted(s.tokens))}) — the "
                            "code it guarded is gone or the rule id "
                            "is wrong; delete it", snippet))
        kept.sort(key=lambda f: (f.path, f.line, f.rule))
    result.findings = kept
    return result


# --------------------------------------------------- incremental runs

def _hash_files(files: List[str], root: str) -> Dict[str, str]:
    """relpath -> content sha1 for every analyzed file (the cache
    key, alongside the rule-set version)."""
    out: Dict[str, str] = {}
    aroot = os.path.abspath(root)
    for p in files:
        rel = os.path.relpath(os.path.abspath(p), aroot)
        try:
            with open(p, "rb") as f:
                out[rel] = hashlib.sha1(f.read()).hexdigest()
        except OSError:
            out[rel] = ""
    return out


def _finding_to_list(f: Finding) -> list:
    return [f.rule, f.path, f.line, f.col, f.message, f.snippet]


def cached_run(paths: List[str], root: Optional[str] = None,
               rule_ids: Optional[List[str]] = None,
               cache_path: Optional[str] = None) -> AnalysisResult:
    """``run()`` behind a content-hash memo: when every analyzed
    file's sha1 and the rule-set version match the cache, the previous
    verdict replays without parsing a single file (the warm CI path).
    ANY change re-runs the WHOLE analysis — the call graph is
    cross-module, so per-file verdict reuse would be unsound (a
    signature change in one file creates findings in another). A
    ``RULESET_VERSION`` bump invalidates every cached verdict even
    when no analyzed file changed."""
    if cache_path is None:
        return run(paths, root=root, rule_ids=rule_ids)
    files = collect_files(paths)
    root = root or _default_root(files)
    hashes = _hash_files(files, root)
    key_rules = sorted(rule_ids) if rule_ids is not None else None
    try:
        with open(cache_path) as f:
            cached = json.load(f)
    except (OSError, ValueError):
        cached = None
    if (isinstance(cached, dict)
            and cached.get("ruleset") == RULESET_VERSION
            and cached.get("rules") == key_rules
            and cached.get("hashes") == hashes):
        res = AnalysisResult(files=len(files), root=root)
        res.findings = [Finding(*v) for v in cached["findings"]]
        res.suppressed = [Finding(*v) for v in cached["suppressed"]]
        return res
    res = run(paths, root=root, rule_ids=rule_ids)
    payload = {
        "ruleset": RULESET_VERSION,
        "rules": key_rules,
        "hashes": hashes,
        "findings": [_finding_to_list(f) for f in res.findings],
        "suppressed": [_finding_to_list(f) for f in res.suppressed],
    }
    tmp = f"{cache_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, cache_path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return res


def changed_files(paths: List[str], ref: str,
                  root: Optional[str] = None) -> Optional[List[str]]:
    """The subset of ``collect_files(paths)`` that differs from git
    ``ref`` (tracked diffs plus untracked files). None when git is
    unavailable or ``ref`` does not resolve — callers fall back to a
    full run rather than silently analyzing nothing."""
    root = root or os.getcwd()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "-z", ref, "--"],
            capture_output=True, text=True, cwd=root, timeout=60)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "-z"],
            capture_output=True, text=True, cwd=root, timeout=60)
    except (OSError, subprocess.SubprocessError):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    changed = {p for out in (diff.stdout, untracked.stdout)
               for p in out.split("\0") if p}
    aroot = os.path.abspath(root)
    out = []
    for p in collect_files(paths):
        rel = os.path.relpath(os.path.abspath(p), aroot)
        if rel in changed:
            out.append(p)
    return out


def _check_module(ctx: Context, module: ModuleInfo,
                  selected) -> List[Finding]:
    findings: List[Finding] = []
    if module.parse_error is not None:
        e = module.parse_error
        findings.append(Finding(
            "GEN001", module.path, getattr(e, "lineno", 1) or 1, 0,
            f"file does not parse: {e.msg if hasattr(e, 'msg') else e}",
            ""))
        return findings
    for spec in selected:
        findings.extend(spec.check(ctx, module))
    return findings


def list_rules() -> List[tuple]:
    """(rule_id, help) pairs, plus the GEN family the driver owns."""
    out = [(rid, REGISTRY[rid].help) for rid in sorted(REGISTRY)]
    out.append(("GEN001", "file does not parse (syntax error)"))
    out.append(("GEN002",
                "suppression comment matched no finding (stale "
                "recorded decision; full runs only)"))
    return sorted(out)


# re-export for consumers that only import core
__all__ = [
    "AnalysisResult",
    "Finding",
    "cached_run",
    "changed_files",
    "collect_files",
    "fingerprint",
    "list_rules",
    "parse_suppressions",
    "run",
]
