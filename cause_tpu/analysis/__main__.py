"""``python -m cause_tpu.analysis [paths...]`` — the causelint CLI.

Exit codes: 0 = clean (after suppressions and baseline), 1 = findings,
2 = usage error. Stdlib-only: the CI lint job runs this from a bare
checkout, before jax/numpy are installed.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import core, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.analysis",
        description=("causelint: trace-identity (TID), jit-purity "
                     "(JPH), obs-off invariance (OBS), lane-cache "
                     "aliasing (LCA), concurrency (LCK), durability "
                     "(DUR) and refusal-evidence (EVD) static "
                     "analysis"),
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: "
                         "cause_tpu/ scripts/ bench.py where present)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--rules",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="freeze findings recorded in FILE (see "
                         "--write-baseline); only NEW findings gate")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="record current findings into FILE and exit 0")
    ap.add_argument("--cache", metavar="FILE",
                    help="memoize the verdict keyed on file sha1s + "
                         "rule-set version; a warm hit replays the "
                         "result without parsing anything")
    ap.add_argument("--changed", metavar="GIT_REF",
                    help="report only findings in files that differ "
                         "from GIT_REF (tracked diffs + untracked); "
                         "exits 0 fast when nothing changed. The "
                         "whole program is still analyzed (the call "
                         "graph is cross-module) — combine with "
                         "--cache to make that cheap")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, help_text in core.list_rules():
            print(f"{rid}  {help_text}")
        return 0

    paths = args.paths
    if not paths:
        paths = [p for p in ("cause_tpu", "scripts", "bench.py")
                 if os.path.exists(p)]
        if not paths:
            print("causelint: no paths given and no default layout "
                  "found", file=sys.stderr)
            return 2
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"causelint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids
                   if r not in dict(core.list_rules())]
        if unknown:
            print(f"causelint: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        # GEN rules are the driver's own and cannot be toggled; an
        # explicitly emptied selection still reports parse errors
        rule_ids = [r for r in rule_ids if not r.startswith("GEN")]

    # --changed narrows the REPORT, not the analysis: the call graph
    # is cross-module (a helper in an unchanged file can prove a
    # changed file's refusal path emits evidence), so analyzing only
    # the diff would both miss and invent findings. The whole program
    # is still analyzed — the cache makes that cheap — and findings
    # are then filtered to files that differ from the ref.
    changed_set = None
    if args.changed:
        subset = core.changed_files(paths, args.changed)
        if subset is None:
            print(f"causelint: cannot diff against {args.changed!r} "
                  "(not a git checkout, or the ref does not resolve); "
                  "running the full analysis", file=sys.stderr)
        elif not subset:
            print(f"causelint: no analyzed files changed vs "
                  f"{args.changed}")
            return 0
        else:
            changed_set = {os.path.abspath(p) for p in subset}

    result = core.cached_run(paths, rule_ids=rule_ids,
                             cache_path=args.cache)
    if changed_set is not None:
        result.findings = [f for f in result.findings
                           if os.path.abspath(f.path) in changed_set]
        result.suppressed = [f for f in result.suppressed
                             if os.path.abspath(f.path) in changed_set]

    if args.write_baseline:
        n = report.write_baseline(args.write_baseline, result)
        print(f"causelint: froze {n} finding(s) into "
              f"{args.write_baseline}")
        return 0

    baseline_filtered = 0
    if args.baseline:
        baseline_filtered = report.apply_baseline(
            result, report.load_baseline(args.baseline))

    if args.format == "json":
        import json

        print(json.dumps(report.to_json(result, baseline_filtered),
                         indent=2, sort_keys=True))
    else:
        print(report.render_text(result, baseline_filtered))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
