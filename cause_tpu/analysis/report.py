"""Reporters (text / JSON) and the findings baseline.

The baseline freezes accepted pre-existing findings so the CI gate
blocks only NEW ones: fingerprints are line-number independent
(rule + relative path + stripped source line), so edits elsewhere in a
file never unfreeze a frozen finding, while touching the flagged line
itself re-opens it — the right default for a ratchet.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Set

from .core import AnalysisResult, Finding, fingerprint

SCHEMA_VERSION = 1


def _fingerprints(result: AnalysisResult) -> Dict[int, str]:
    """id(finding) -> fingerprint, with an occurrence index folded in
    for duplicates: two identical flagged lines in one file must NOT
    share a fingerprint, or freezing the first would silently baseline
    every future copy. Occurrences are numbered in line order, so the
    (line-number independent) base hash still survives unrelated edits
    while a NEW duplicate gets a new, unfrozen fingerprint."""
    seen: Dict[str, int] = {}
    out: Dict[int, str] = {}
    for f in sorted(result.findings, key=lambda f: (f.path, f.line,
                                                    f.rule, f.col)):
        base = fingerprint(f, result.root)
        n = seen.get(base, 0)
        seen[base] = n + 1
        out[id(f)] = base if n == 0 else f"{base}#{n}"
    return out


def to_json(result: AnalysisResult,
            baseline_filtered: int = 0) -> dict:
    counts: dict = {}
    for f in result.findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    fps = _fingerprints(result)
    return {
        "version": SCHEMA_VERSION,
        "tool": "causelint",
        "files": result.files,
        "total": len(result.findings),
        "suppressed": len(result.suppressed),
        "baseline_filtered": baseline_filtered,
        "counts": counts,
        "findings": [
            {
                "rule": f.rule,
                "family": f.family,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "snippet": f.snippet,
                "fingerprint": fps[id(f)],
            }
            for f in result.findings
        ],
    }


def render_text(result: AnalysisResult,
                baseline_filtered: int = 0) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    tail = (f"causelint: {len(result.findings)} finding(s) in "
            f"{result.files} file(s)")
    extras = []
    if result.suppressed:
        extras.append(f"{len(result.suppressed)} suppressed")
    if baseline_filtered:
        extras.append(f"{baseline_filtered} baselined")
    if extras:
        tail += " (" + ", ".join(extras) + ")"
    lines.append(tail)
    return "\n".join(lines)


def load_baseline(path: str) -> Set[str]:
    """Fingerprints frozen by an earlier ``--write-baseline`` run. A
    missing file is an empty baseline (first run bootstraps)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return set()
    fps = data.get("fingerprints", []) if isinstance(data, dict) else []
    return {str(x) for x in fps}


def write_baseline(path: str, result: AnalysisResult) -> int:
    fps = sorted(set(_fingerprints(result).values()))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": SCHEMA_VERSION, "tool": "causelint",
                   "fingerprints": fps}, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(fps)


def apply_baseline(result: AnalysisResult,
                   baseline: Optional[Set[str]]) -> int:
    """Drop findings whose fingerprint is frozen; returns the count."""
    if not baseline:
        return 0
    fps = _fingerprints(result)
    kept: List[Finding] = []
    dropped = 0
    for f in result.findings:
        if fps[id(f)] in baseline:
            dropped += 1
        else:
            kept.append(f)
    result.findings = kept
    return dropped
