"""The LCK family: lock discipline for the serve/net/WAL substrate.

Rounds 11-15 grew genuinely concurrent host code (serve tick/watchdog
threads, per-connection net handler threads, the WAL's fsync/rotate/GC
protocol) and every review pass found the same bug classes by hand.
This module mechanizes the reviewer:

- **LCK001** — guarded-by violations. For every class that owns a
  ``threading.Lock``/``RLock``/``Condition`` attribute, infer the
  guarded-by set of each ``self.*`` attribute from where it is
  *written*: a write inside a ``with self._lock:`` region (or inside a
  ``*_locked``-suffixed method, the repo's caller-holds-the-lock
  convention) marks the attribute lock-guarded. Any lock-free read or
  write of a guarded attribute in another thread-reachable method is a
  finding (PR 12's boundary-reject stats; PR 13's non-atomic
  filter->offer->advance).
- **LCK002** — lock-order cycles. Build the lock-acquisition order
  graph across the call graph (an edge A->B when B is acquired, lexically
  or through calls, while A is held) and flag every edge on a cycle
  plus reacquisition of a non-reentrant ``Lock``.
- **LCK003** — blocking calls while holding a lock: ``fsync``,
  ``recv``, ``sleep``, ``join``, socket ``connect``/``accept``,
  ``select`` and the ``subprocess`` family, directly or through
  resolved helpers. Calls into ``*_locked`` helpers are the class's
  *declared* under-lock protocol and are not followed.
- **LCK004** — commit-step reentrancy: a function that seals/rotates/
  commits state reachable from itself through an error path (the exact
  PR-15 double-seal shape: ``_fsync_locked`` failure handling calling
  back into ``_rotate_locked``).

Thread-reachability is seeded from ``threading.Thread(target=...)``
spawns (watchdog closures, socket handler spawns) and callback
registration surfaces (``on_*=``/``callback=`` keywords, the
LiveMonitor ``attach(on_alert=[...])`` surface), then closed over the
call graph. The resolver extends the callgraph's name resolution with
attribute types recovered from ``__init__`` (direct construction,
annotated parameters, annotated return types), so
``handler -> self.queue.offer`` edges resolve cross-class.

Known approximations (documented in README "Static analysis"): the
model is flow-insensitive; a class is only checked once some method of
it is thread-reachable or it spawns threads itself; ``acquire()`` /
``release()`` pairs outside ``with`` are invisible; cross-object lock
identity is per-class, so two instances sharing a lock object are not
distinguished. Stdlib-only, like the rest of causelint.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .callgraph import FuncInfo, ModuleInfo, Program, dotted_parts
from .rules import Context, Finding, _finding, rule

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})
# attribute method calls that mutate the receiver object in place
_MUTATORS = frozenset(
    {"append", "add", "update", "setdefault", "pop", "clear", "extend",
     "insert", "popitem", "remove", "discard"}
)
# blocking terminal names (LCK003); `join` and `connect` carry extra
# shape checks so str.join / os.path.join / sqlite3.connect never flag
_BLOCKING_BARE = frozenset(
    {"fsync", "fdatasync", "recv", "recv_into", "recvfrom", "accept",
     "sleep", "select"}
)
_SUBPROCESS_CALLS = frozenset(
    {"run", "call", "check_call", "check_output", "Popen"}
)
# commit-protocol verbs for the reentrancy rule: only cycles touching
# one of these are flagged, so ordinary recursion stays quiet
_COMMIT_VERBS = ("rotate", "seal", "commit", "fsync", "checkpoint",
                 "flush", "close", "gc", "retire")
# guard marker for attributes whose only write sites are *_locked
# convention methods (guarded, but by an unnamed lock)
_CONVENTION = "<*_locked convention>"

_CRASH_SEAMS = frozenset({"should_crash", "stall_point"})


def _last_name(qualname: str) -> str:
    return qualname.split(".")[-1].split("<")[0] or qualname


def _is_locked_name(qualname: str) -> bool:
    return qualname.split(".")[-1].endswith("_locked")


def _is_dunder(qualname: str) -> bool:
    n = qualname.split(".")[-1]
    return n.startswith("__") and n.endswith("__")


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X`` (one level only), else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _chain_self_attr(node: ast.AST) -> Optional[str]:
    """The first attribute off ``self`` in an access chain:
    ``self.X``, ``self.X[i]``, ``self.X.y[i]`` all -> ``X``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        attr = _self_attr(node)
        if attr is not None:
            return attr
        node = node.value
    return None


def _ann_name(node: Optional[ast.AST]) -> Optional[str]:
    """Terminal class name of an annotation (``IngestQueue``,
    ``serve.IngestQueue``, ``"IngestQueue"``, ``Optional[X]`` -> X)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].strip("'\" ]")
    if isinstance(node, ast.Subscript):
        return _ann_name(node.slice)
    parts = dotted_parts(node)
    return parts[-1] if parts else None


class _ClassModel:
    __slots__ = ("name", "module", "lock_attrs", "methods",
                 "spawns_thread", "attr_types")

    def __init__(self, name: str, module: ModuleInfo):
        self.name = name
        self.module = module
        self.lock_attrs: Dict[str, str] = {}     # attr -> Lock/RLock/...
        self.methods: List[FuncInfo] = []        # incl. nested closures
        self.spawns_thread = False
        self.attr_types: Dict[str, str] = {}     # attr -> class name


def _lock_factory_kind(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        parts = dotted_parts(value.func)
        if parts and parts[-1] in _LOCK_FACTORIES:
            if len(parts) == 1 or parts[-2] in ("threading", "th"):
                return parts[-1]
    return None


class _EventWalker:
    """Walks one function body tracking the lexically held lock set.

    Yields tuples:
      ("call",  node, held, in_err)
      ("read",  attr, node, held)
      ("write", attr, node, held)
      ("acquire", lock_id, node, held_before)
    Nested function/lambda bodies are their own scopes and are skipped
    (a closure defined under a lock does not *run* under it).
    """

    def __init__(self, info: FuncInfo, class_locks: Dict[str, str],
                 module_locks: Dict[str, str], module_name: str):
        self.info = info
        self.class_locks = class_locks
        self.module_locks = module_locks
        self.module_name = module_name
        self.events: List[tuple] = []

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.class_locks:
            return f"{self.info.class_name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.module_name}.{expr.id}"
        return None

    def run(self) -> List[tuple]:
        body = (self.info.node.body
                if isinstance(self.info.node.body, list)
                else [ast.Expr(value=self.info.node.body)])
        self._stmts(body, frozenset(), False)
        return self.events

    def _stmts(self, stmts, held: FrozenSet[str], in_err: bool) -> None:
        for s in stmts:
            self._node(s, held, in_err)

    def _node(self, n, held: FrozenSet[str], in_err: bool) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                lid = self._lock_id(item.context_expr)
                self._node(item.context_expr, held, in_err)
                if lid is not None:
                    self.events.append(
                        ("acquire", lid, item.context_expr, held))
                    held = held | {lid}
            self._stmts(n.body, held, in_err)
            return
        if isinstance(n, ast.Try):
            self._stmts(n.body, held, in_err)
            for h in n.handlers:
                self._stmts(h.body, held, True)
            self._stmts(n.orelse, held, in_err)
            self._stmts(n.finalbody, held, True)
            return
        if isinstance(n, ast.Call):
            self.events.append(("call", n, held, in_err))
            # the callee chain: self.meth() is dispatch, not a state
            # read; self.X.append() mutates X; deeper chains read X
            func = n.func
            if isinstance(func, ast.Attribute):
                recv = func.value
                attr = _chain_self_attr(recv)
                if attr is not None:
                    kind = ("write" if func.attr in _MUTATORS
                            else "read")
                    self.events.append((kind, attr, func, held))
                    # still walk subscript indices inside the receiver
                    self._children(recv, held, in_err, skip_attrs=True)
                elif _self_attr(func) is None:
                    self._node(recv, held, in_err)
            else:
                self._node(func, held, in_err)
            for a in n.args:
                self._node(a, held, in_err)
            for kw in n.keywords:
                self._node(kw.value, held, in_err)
            return
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                attr = _chain_self_attr(t)
                if attr is not None:
                    self.events.append(("write", attr, t, held))
                    self._children(t, held, in_err, skip_attrs=True)
                else:
                    self._node(t, held, in_err)
            value = getattr(n, "value", None)
            if value is not None:
                self._node(value, held, in_err)
            return
        if isinstance(n, ast.Attribute):
            attr = _self_attr(n)
            if attr is not None:
                kind = ("write" if isinstance(n.ctx, (ast.Store, ast.Del))
                        else "read")
                self.events.append((kind, attr, n, held))
                return
            self._node(n.value, held, in_err)
            return
        self._children(n, held, in_err)

    def _children(self, n, held, in_err, skip_attrs: bool = False):
        for name, value in ast.iter_fields(n):
            if skip_attrs and name in ("value",):
                continue
            for c in (value if isinstance(value, list) else [value]):
                if isinstance(c, ast.AST):
                    self._node(c, held, in_err)


def _blocking_op(call: ast.Call) -> Optional[str]:
    """The blocking-operation label of a call, or None."""
    parts = dotted_parts(call.func)
    if parts is None:
        return None
    last = parts[-1]
    quals = parts[:-1]
    if "subprocess" in parts and (last in _SUBPROCESS_CALLS
                                  or parts[0] == "subprocess"):
        return f"subprocess.{last}"
    if last in _BLOCKING_BARE:
        if last == "sleep" and quals and quals[-1] not in ("time",):
            # anything.sleep() beyond time.sleep is rare; still count
            pass
        return last
    if last == "join":
        # Thread.join blocks; str.join / os.path.join never do. A
        # thread join has no positional args (or a numeric timeout).
        if "path" in parts or parts[0] == "os":
            return None
        if not call.args and not call.keywords:
            return "join"
        if (len(call.args) == 1 and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))):
            return "join"
        if any(kw.arg == "timeout" for kw in call.keywords):
            return "join"
        return None
    if last == "connect" and "sqlite3" not in parts:
        return "connect"
    return None


class ConcurrencyModel:
    """Whole-program lock/thread facts, built once per analysis run."""

    def __init__(self, program: Program):
        self.program = program
        self.classes: Dict[str, Dict[str, _ClassModel]] = {}
        self.module_locks: Dict[str, Dict[str, str]] = {}
        self.class_index: Dict[str, ModuleInfo] = {}
        self.lock_kinds: Dict[str, str] = {}      # lock id -> kind
        self.events: Dict[str, List[tuple]] = {}  # fid -> event list
        self.thread_entries: Set[str] = set()
        self.thread_reachable: Set[str] = set()
        self.crash_sites: Dict[str, List[tuple]] = {}  # module -> sites
        self._build()

    # ------------------------------------------------------ structure
    def _build(self) -> None:
        for m in self.program.modules:
            if m.tree is None:
                continue
            self._index_module(m)
        for m in self.program.modules:
            if m.tree is None:
                continue
            self._type_attrs(m)
        self._walk_all()
        self._seed_threads()
        self.thread_reachable = self._closure(sorted(self.thread_entries))

    def _index_module(self, m: ModuleInfo) -> None:
        locks: Dict[str, str] = {}
        for n in m.tree.body:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                kind = _lock_factory_kind(n.value)
                if kind:
                    locks[n.targets[0].id] = kind
                    self.lock_kinds[f"{m.name}.{n.targets[0].id}"] = kind
        self.module_locks[m.name] = locks
        classes: Dict[str, _ClassModel] = {}
        for info in m.funcs.values():
            if info.class_name is None:
                continue
            cm = classes.get(info.class_name)
            if cm is None:
                cm = classes[info.class_name] = _ClassModel(
                    info.class_name, m)
                self.class_index.setdefault(info.class_name, m)
            cm.methods.append(info)
        # lock attributes: `self.X = threading.Lock()` anywhere
        for cm in classes.values():
            for info in cm.methods:
                for n in info.body_nodes():
                    if isinstance(n, ast.Assign):
                        attr = (_self_attr(n.targets[0])
                                if len(n.targets) == 1 else None)
                        kind = _lock_factory_kind(n.value)
                        if attr and kind:
                            cm.lock_attrs[attr] = kind
                            self.lock_kinds[f"{cm.name}.{attr}"] = kind
                    if isinstance(n, ast.Call):
                        parts = dotted_parts(n.func)
                        if parts and parts[-1] == "Thread":
                            cm.spawns_thread = True
        self.classes[m.name] = classes

    def _type_attrs(self, m: ModuleInfo) -> None:
        """attr -> class-name map per class, from ``__init__`` shapes:
        direct construction, annotated parameters, and calls whose
        resolved target has an annotated return type."""
        for cm in self.classes[m.name].values():
            for info in cm.methods:
                params: Dict[str, str] = {}
                if not isinstance(info.node, ast.Lambda):
                    for a in (list(info.node.args.args)
                              + list(info.node.args.kwonlyargs)):
                        t = _ann_name(a.annotation)
                        if t and t in self.class_index:
                            params[a.arg] = t
                for n in info.body_nodes():
                    attr, value = None, None
                    if isinstance(n, ast.Assign) and len(n.targets) == 1:
                        attr, value = _self_attr(n.targets[0]), n.value
                    elif isinstance(n, ast.AnnAssign):
                        attr = _self_attr(n.target)
                        t = _ann_name(n.annotation)
                        if attr and t and t in self.class_index:
                            cm.attr_types.setdefault(attr, t)
                        value = n.value
                    if attr is None or value is None:
                        continue
                    if isinstance(value, ast.Name) \
                            and value.id in params:
                        cm.attr_types.setdefault(attr, params[value.id])
                    elif isinstance(value, ast.Call):
                        parts = dotted_parts(value.func)
                        if parts is None:
                            continue
                        if parts[-1] in self.class_index:
                            cm.attr_types.setdefault(attr, parts[-1])
                        else:
                            fid = self.program.resolve_call(info, parts)
                            fn = (self.program.funcs.get(fid)
                                  if fid else None)
                            rt = (_ann_name(getattr(fn.node, "returns",
                                                    None))
                                  if fn is not None and not isinstance(
                                      fn.node, ast.Lambda) else None)
                            if rt and rt in self.class_index:
                                cm.attr_types.setdefault(attr, rt)

    def _walk_all(self) -> None:
        for m in self.program.modules:
            if m.tree is None:
                continue
            crash: List[tuple] = []
            for fid, info in m.funcs.items():
                class_locks = {}
                if info.class_name:
                    cm = self.classes[m.name].get(info.class_name)
                    if cm is not None:
                        class_locks = cm.lock_attrs
                ev = _EventWalker(info, class_locks,
                                  self.module_locks[m.name],
                                  m.name).run()
                self.events[fid] = ev
                for kind, *rest in ev:
                    if kind != "call":
                        continue
                    node, held, _err = rest
                    parts = dotted_parts(node.func)
                    if parts and parts[-1] in _CRASH_SEAMS and held:
                        crash.append((node, frozenset(held), info))
            self.crash_sites[m.name] = crash

    # -------------------------------------------------------- threads
    def resolve(self, info: FuncInfo,
                parts: List[str]) -> Optional[str]:
        """callgraph resolution plus typed-attribute dispatch:
        ``self.queue.offer`` resolves through the attr-type map.

        Deep ``self.X.y`` chains deliberately do NOT fall back to the
        callgraph's ``Class.y`` guess (fine for reachability over-
        approximation, wrong for lock analysis: ``self._fh.close()``
        is not ``Class.close``) — they resolve through the typed
        attribute map or not at all."""
        if (len(parts) >= 3 and parts[0] == "self"
                and info.class_name is not None):
            cm = self.classes.get(info.module.name, {}).get(
                info.class_name)
            target_cls = (cm.attr_types.get(parts[1])
                          if cm is not None else None)
            if target_cls is not None:
                tmod = self.class_index.get(target_cls)
                if tmod is not None:
                    return tmod.top_funcs.get(
                        f"{target_cls}.{parts[-1]}")
            return None
        return self.program.resolve_call(info, parts)

    def _resolve_callback(self, info: FuncInfo,
                          value: ast.AST) -> Iterator[str]:
        values = (value.elts if isinstance(value, (ast.List, ast.Tuple))
                  else [value])
        for v in values:
            parts = dotted_parts(v)
            if parts is None:
                continue
            fid = self.resolve(info, parts)
            if fid is None and len(parts) >= 3 and parts[0] == "self":
                fid = self.resolve(info, parts)
            if fid is not None:
                yield fid

    def _seed_threads(self) -> None:
        for m in self.program.modules:
            for fid, info in m.funcs.items():
                for n in info.body_nodes():
                    if not isinstance(n, ast.Call):
                        continue
                    parts = dotted_parts(n.func)
                    is_thread = parts is not None and \
                        parts[-1] == "Thread"
                    for kw in n.keywords:
                        if kw.arg is None:
                            continue
                        if (is_thread and kw.arg == "target") or \
                                kw.arg.startswith("on_") or \
                                kw.arg in ("callback", "callbacks"):
                            self.thread_entries.update(
                                self._resolve_callback(info, kw.value))

    def _closure(self, seeds: List[str]) -> Set[str]:
        seen: Set[str] = set()
        queue = [f for f in seeds if f in self.program.funcs]
        while queue:
            fid = queue.pop()
            if fid in seen:
                continue
            seen.add(fid)
            info = self.program.funcs[fid]
            for parts, _ln in info.calls:
                t = self.resolve(info, parts)
                if t is not None and t not in seen:
                    queue.append(t)
        return seen

    # ---------------------------------------------------- derived sets
    def class_is_threaded(self, cm: _ClassModel) -> bool:
        return cm.spawns_thread or any(
            f.fid in self.thread_reachable for f in cm.methods)

    def may_block(self) -> Dict[str, Set[str]]:
        """fid -> blocking-op labels it may perform, transitively.
        Propagation never crosses a ``*_locked`` callee boundary: those
        helpers are the class's declared under-lock protocol."""
        blocks: Dict[str, Set[str]] = {}
        for fid, ev in self.events.items():
            ops = {op for kind, *rest in ev if kind == "call"
                   for op in [_blocking_op(rest[0])] if op}
            if ops:
                blocks[fid] = ops
        changed = True
        while changed:
            changed = False
            for fid, info in self.program.funcs.items():
                for parts, _ln in info.calls:
                    t = self.resolve(info, parts)
                    if t is None or t == fid or t not in blocks:
                        continue
                    if _is_locked_name(
                            self.program.funcs[t].qualname):
                        continue
                    cur = blocks.setdefault(fid, set())
                    if not blocks[t] <= cur:
                        cur.update(blocks[t])
                        changed = True
        return blocks

    def may_acquire(self) -> Dict[str, Set[str]]:
        """fid -> lock ids it may acquire, transitively."""
        acq: Dict[str, Set[str]] = {}
        for fid, ev in self.events.items():
            lids = {rest[0] for kind, *rest in ev if kind == "acquire"}
            if lids:
                acq[fid] = lids
        changed = True
        while changed:
            changed = False
            for fid, info in self.program.funcs.items():
                for parts, _ln in info.calls:
                    t = self.resolve(info, parts)
                    if t is None or t == fid or t not in acq:
                        continue
                    cur = acq.setdefault(fid, set())
                    if not acq[t] <= cur:
                        cur.update(acq[t])
                        changed = True
        return acq


def model_for(ctx: Context) -> ConcurrencyModel:
    model = getattr(ctx, "_concurrency_model", None)
    if model is None:
        model = ConcurrencyModel(ctx.program)
        ctx._concurrency_model = model
    return model


def _lock_desc(lids) -> str:
    names = sorted(lids)
    return " + ".join(n if n != _CONVENTION
                      else "the class lock (held by *_locked convention)"
                      for n in names)


# ---------------------------------------------------------------- LCK001

@rule("LCK001",
      "lock-free access to a lock-guarded attribute in a "
      "thread-reachable method (guarded-by inference from `with "
      "self._lock:` regions and the *_locked naming convention)")
def check_lck001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    model = model_for(ctx)
    for cm in model.classes.get(module.name, {}).values():
        if not cm.lock_attrs or not model.class_is_threaded(cm):
            continue
        guarded: Dict[str, Set[str]] = {}
        writers: Dict[str, str] = {}
        accesses: List[tuple] = []
        for info in cm.methods:
            locked_conv = _is_locked_name(info.qualname)
            dunder = _is_dunder(info.qualname)
            for kind, *rest in model.events.get(info.fid, ()):
                if kind not in ("read", "write"):
                    continue
                attr, node, held = rest
                if attr in cm.lock_attrs:
                    continue
                if kind == "write":
                    if held:
                        guarded.setdefault(attr, set()).update(held)
                        writers.setdefault(attr, info.qualname)
                    elif locked_conv:
                        guarded.setdefault(attr, set()).add(_CONVENTION)
                        writers.setdefault(attr, info.qualname)
                if dunder or locked_conv:
                    continue
                accesses.append((attr, kind, node, held, info))
        seen_lines: Set[tuple] = set()
        for attr, kind, node, held, info in accesses:
            guards = guarded.get(attr)
            if not guards or held:
                continue
            key = (attr, getattr(node, "lineno", 0))
            if key in seen_lines:
                continue
            seen_lines.add(key)
            verb = "written" if kind == "write" else "read"
            yield _finding(
                "LCK001", module, node,
                f"self.{attr} is written under {_lock_desc(guards)} "
                f"(e.g. in {writers[attr]}) but {verb} lock-free in "
                f"{info.qualname}, which threads reach — take the "
                "lock, or move the access into a *_locked helper "
                "(the PR-12 boundary-stats shape)")


# ---------------------------------------------------------------- LCK002

def _lock_edges(model: ConcurrencyModel):
    """(A, B) -> (module_name, node, via) acquisition-order edges."""
    acq = model.may_acquire()
    edges: Dict[Tuple[str, str], tuple] = {}
    for m in model.program.modules:
        for fid, info in m.funcs.items():
            for kind, *rest in model.events.get(fid, ()):
                if kind == "acquire":
                    lid, node, held = rest
                    for h in held:
                        edges.setdefault((h, lid), (m.name, node, None))
                    if not held:
                        continue
                elif kind == "call":
                    node, held, _err = rest
                    if not held:
                        continue
                    parts = dotted_parts(node.func)
                    t = model.resolve(info, parts) if parts else None
                    if t is None:
                        continue
                    via = model.program.funcs[t].qualname
                    for lid in acq.get(t, ()):
                        for h in held:
                            edges.setdefault((h, lid),
                                             (m.name, node, via))
    return edges


def _cyclic_nodes(edges) -> Set[str]:
    """Lock ids that sit on a cycle of >= 2 distinct locks."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
    cyc: Set[str] = set()
    for start in graph:
        # DFS: can we come back to start?
        stack, seen = [start], set()
        while stack:
            n = stack.pop()
            for nxt in graph.get(n, ()):
                if nxt == start:
                    cyc.add(start)
                    stack = []
                    break
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
    return cyc


@rule("LCK002",
      "lock-acquisition order cycle across the call graph (deadlock "
      "potential), or reacquisition of a non-reentrant Lock")
def check_lck002(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    model = model_for(ctx)
    edges = getattr(ctx, "_lck002_edges", None)
    if edges is None:
        edges = ctx._lck002_edges = _lock_edges(model)
    cyc = getattr(ctx, "_lck002_cyc", None)
    if cyc is None:
        cyc = ctx._lck002_cyc = _cyclic_nodes(edges)
    for (a, b), (mod_name, node, via) in edges.items():
        if mod_name != module.name:
            continue
        if a == b:
            if model.lock_kinds.get(a) == "Lock":
                yield _finding(
                    "LCK002", module, node,
                    f"reacquisition of non-reentrant lock {a} on a "
                    "path that already holds it — self-deadlock; use "
                    "an RLock or split a *_locked helper"
                    + (f" (via {via}())" if via else ""))
            continue
        if a in cyc and b in cyc:
            yield _finding(
                "LCK002", module, node,
                f"acquiring {b} while holding {a}"
                + (f" (via {via}())" if via else "")
                + " completes a lock-order cycle — two threads "
                "interleaving the opposite orders deadlock; pick one "
                "global order and document it")


# ---------------------------------------------------------------- LCK003

@rule("LCK003",
      "blocking call (fsync/recv/sleep/join/connect/accept/select/"
      "subprocess) while holding a lock, directly or through resolved "
      "helpers")
def check_lck003(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    model = model_for(ctx)
    blocks = getattr(ctx, "_lck003_blocks", None)
    if blocks is None:
        blocks = ctx._lck003_blocks = model.may_block()
    for fid, info in module.funcs.items():
        for kind, *rest in model.events.get(fid, ()):
            if kind != "call":
                continue
            node, held, _err = rest
            if not held:
                continue
            op = _blocking_op(node)
            parts = dotted_parts(node.func)
            if op is not None:
                yield _finding(
                    "LCK003", module, node,
                    f"{'.'.join(parts)}() blocks on {op} while "
                    f"holding {_lock_desc(held)} — every thread "
                    "contending for the lock stalls behind the IO; "
                    "move the blocking call outside the region (or "
                    "suppress with the design reason)")
                continue
            t = model.resolve(info, parts) if parts else None
            if t is None or t not in blocks:
                continue
            callee = model.program.funcs[t]
            if _is_locked_name(callee.qualname):
                # declared under-lock protocol (caller holds by design)
                continue
            ops = "/".join(sorted(blocks[t]))
            yield _finding(
                "LCK003", module, node,
                f"call into {callee.qualname}() while holding "
                f"{_lock_desc(held)} — it blocks on {ops}; move the "
                "call outside the lock-held region (or suppress with "
                "the design reason)")


# ---------------------------------------------------------------- LCK004

def _error_edges(model: ConcurrencyModel):
    """Resolved call edges, each tagged with whether the call site sits
    on an error path (except handler / finally body)."""
    edges: Dict[Tuple[str, str], bool] = {}
    for fid, info in model.program.funcs.items():
        for kind, *rest in model.events.get(fid, ()):
            if kind != "call":
                continue
            node, _held, in_err = rest
            parts = dotted_parts(node.func)
            t = model.resolve(info, parts) if parts else None
            if t is None:
                continue
            key = (fid, t)
            edges[key] = edges.get(key, False) or in_err
    return edges


def _sccs(edges) -> List[Set[str]]:
    """Tarjan SCCs (iterative) over the edge dict's node set."""
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]
    for root in graph:
        if root in index:
            continue
        work = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            succs = graph[node]
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recurse:
                continue
            if low[node] == index[node]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


@rule("LCK004",
      "commit-step reentrancy: a function that seals/rotates/commits "
      "state is reachable from itself through an error path (the "
      "PR-15 double-seal shape)")
def check_lck004(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    model = model_for(ctx)
    cycles = getattr(ctx, "_lck004_cycles", None)
    if cycles is None:
        edges = _error_edges(model)
        cycles = []
        for comp in _sccs(edges):
            if len(comp) < 2 and not any(
                    (f, f) in edges for f in comp):
                continue
            in_err = any(err for (a, b), err in edges.items()
                         if a in comp and b in comp)
            if not in_err:
                continue
            verbs = [f for f in comp if any(
                v in _last_name(
                    model.program.funcs[f].qualname).lower()
                for v in _COMMIT_VERBS)]
            if verbs:
                cycles.append((comp, sorted(verbs)))
        ctx._lck004_cycles = cycles
    for comp, verbs in cycles:
        for fid in verbs:
            info = model.program.funcs[fid]
            if info.module.name != module.name:
                continue
            path = " -> ".join(sorted(
                model.program.funcs[f].qualname for f in comp))
            yield _finding(
                "LCK004", module, info.node,
                f"{info.qualname} commits/seals/rotates state and is "
                f"reachable from itself through an error path "
                f"({path}) — reentry applies the commit step twice "
                "(the PR-15 double-seal shape); break the cycle by "
                "letting the caller decide the retry")
