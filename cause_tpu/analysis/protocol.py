"""The DUR (commit protocol) and EVD (evidence contract) families.

The WAL/checkpoint substrate (PR 15) commits state with one idiom:
write a tmp file, ``flush`` + ``os.fsync`` the fd, ``os.replace`` onto
the final name, then ``fsync_dir`` the parent so the rename itself is
durable. The admission path (PR 12/13) has a twin invariant: the
journal append *dominates* the ack (journal-before-ack), or a crash
between the two loses an acknowledged batch. And the serve/net
boundary has a convention the reviews kept re-stating by hand: every
refusal is evidence — a nack/shed/raise that emits no obs event is
invisible to the evidence ledger. These rules mechanize all three:

- **DUR001** — an ``os.replace``/``os.rename`` whose source file was
  opened for writing in the same function, with no ``os.fsync`` before
  the rename: the rename can land while the data is still in the page
  cache, committing a torn file (the PR-15 review bug).
- **DUR002** — same shape, but missing the ``fsync_dir`` directory
  sync after the rename (scoped to ``serve`` modules, where the
  ``wal.fsync_dir`` idiom applies — the rename is not durable until
  the directory entry is).
- **DUR003** — journal-before-ack: a function that appends to a
  journal/WAL must not return an admission ack lexically before the
  append (crash window loses an acked batch, the PR-13 double-journal
  arc's invariant).
- **DUR004** — chaos crash seams (``should_crash``/``stall_point``)
  inside a lock-held region: a seam that fires while a lock is held
  models a crash no real process exhibits (locks die with the
  process), and a *stall* seam holding a lock serializes every other
  thread behind the fault injector.
- **EVD001** — a serve/net boundary refusal (``raise CausalError`` or
  an explicit nack/``Admission(False)`` return) on a path that emits
  no obs event/counter, directly or through a resolved helper (the
  "every refusal is evidence" invariant).

All flow-insensitive per-function (lexical order stands in for
dominance — the repo's commit helpers are small and straight-line),
stdlib-only, and riding the shared suppression/baseline machinery.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from .callgraph import FuncInfo, ModuleInfo, dotted_parts
from .concurrency import _lock_desc, model_for
from .rules import Context, Finding, _finding, rule

_WRITE_MODES = ("w", "a", "x", "+")
_ACK_OPS = frozenset({"ack", "admit", "welcome"})
_EVIDENCE_CALLS = frozenset({"event", "counter", "gauge", "span"})
_OBS_QUALS = frozenset({"obs", "_obs"})


def _in_serve_or_net(module: ModuleInfo) -> bool:
    segs = module.segments
    return "serve" in segs or "net" in segs


def _src_key(node: ast.AST) -> Optional[str]:
    """Identity of a file-path expression for matching an open() target
    against a rename source: a bare name or a self attribute."""
    if isinstance(node, ast.Name):
        return f"n:{node.id}"
    if isinstance(node, ast.Attribute):
        parts = dotted_parts(node)
        if parts is not None:
            return "a:" + ".".join(parts)
    return None


def _opened_for_write(info: FuncInfo) -> Dict[str, int]:
    """path-key -> first line where the function opens it writable."""
    out: Dict[str, int] = {}
    for n in info.body_nodes():
        if not isinstance(n, ast.Call):
            continue
        parts = dotted_parts(n.func)
        if parts is None or parts[-1] != "open" or not n.args:
            continue
        if parts[-1] == "open" and len(parts) > 1 \
                and parts[-2] not in ("io", "os"):
            continue  # foo.open() on an unknown object
        mode = None
        if len(n.args) >= 2 and isinstance(n.args[1], ast.Constant):
            mode = n.args[1].value
        for kw in n.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not isinstance(mode, str) \
                or not any(c in mode for c in _WRITE_MODES):
            continue
        key = _src_key(n.args[0])
        if key is not None:
            out.setdefault(key, n.lineno)
    return out


def _renames(info: FuncInfo):
    """(call node, src-key) for every os.replace/os.rename."""
    for n in info.body_nodes():
        if not isinstance(n, ast.Call):
            continue
        parts = dotted_parts(n.func)
        if (parts is not None and len(parts) >= 2
                and parts[-2] == "os"
                and parts[-1] in ("replace", "rename")
                and len(n.args) >= 2):
            yield n, _src_key(n.args[0])


def _call_lines(info: FuncInfo, pred) -> List[int]:
    return sorted(n.lineno for n in info.body_nodes()
                  if isinstance(n, ast.Call)
                  and pred(dotted_parts(n.func) or []))


# ---------------------------------------------------------------- DUR001

@rule("DUR001",
      "os.replace/os.rename of a file written in-function with no "
      "os.fsync on the tmp fd before the rename (torn-commit hazard; "
      "the PR-15 review bug)")
def check_dur001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    for info in module.funcs.values():
        writes = _opened_for_write(info)
        if not writes:
            continue
        fsyncs = _call_lines(
            info, lambda p: p and p[-1] in ("fsync", "fdatasync"))
        for call, src in _renames(info):
            if src is None or src not in writes:
                continue
            if not any(ln < call.lineno for ln in fsyncs):
                yield _finding(
                    "DUR001", module, call,
                    "os.replace() commits a file this function wrote "
                    "without an os.fsync on the tmp fd first — after "
                    "a crash the rename can be durable while the data "
                    "is not, publishing a torn file; fsync the file "
                    "object before renaming (see wal._write_manifest_"
                    "locked for the idiom)")


# ---------------------------------------------------------------- DUR002

@rule("DUR002",
      "os.replace/os.rename of a file written in-function with no "
      "fsync_dir on the parent directory afterwards (serve modules: "
      "the rename is not durable until the directory entry is)")
def check_dur002(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if "serve" not in module.segments:
        return
    for info in module.funcs.values():
        writes = _opened_for_write(info)
        if not writes:
            continue
        dir_syncs = _call_lines(
            info, lambda p: bool(p) and p[-1].endswith("fsync_dir"))
        for call, src in _renames(info):
            if src is None or src not in writes:
                continue
            if not any(ln > call.lineno for ln in dir_syncs):
                yield _finding(
                    "DUR002", module, call,
                    "os.replace() commits a file but the parent "
                    "directory is never fsynced afterwards — the "
                    "rename itself can be lost on crash; call "
                    "wal.fsync_dir(dirname) after the rename")


# ---------------------------------------------------------------- DUR003

def _is_ack_return(n: ast.Return) -> bool:
    v = n.value
    if isinstance(v, ast.Call):
        parts = dotted_parts(v.func)
        if parts is not None and parts[-1] == "Admission":
            if v.args and isinstance(v.args[0], ast.Constant):
                return v.args[0].value is True
            for kw in v.keywords:
                if kw.arg == "admitted" \
                        and isinstance(kw.value, ast.Constant):
                    return kw.value.value is True
    if isinstance(v, ast.Dict):
        for k, val in zip(v.keys, v.values):
            if (isinstance(k, ast.Constant) and k.value == "op"
                    and isinstance(val, ast.Constant)
                    and val.value in _ACK_OPS):
                return True
    return False


@rule("DUR003",
      "admission ack returned lexically before the journal/WAL append "
      "in the same function (journal-before-ack: a crash between ack "
      "and append loses an acknowledged batch)")
def check_dur003(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    for info in module.funcs.values():
        appends = _call_lines(
            info, lambda p: len(p) >= 2 and p[-1] == "append"
            and any(q in ("journal", "_journal", "wal", "_wal")
                    for q in p[:-1]))
        if not appends:
            continue
        first_append = min(appends)
        for n in info.body_nodes():
            if isinstance(n, ast.Return) and _is_ack_return(n) \
                    and n.lineno < first_append:
                yield _finding(
                    "DUR003", module, n,
                    "admission acked before the journal append that "
                    "records it — a crash in between loses an "
                    "acknowledged batch; append to the journal first, "
                    "ack after (journal-before-ack)")


# ---------------------------------------------------------------- DUR004

@rule("DUR004",
      "chaos crash seam (should_crash/stall_point) inside a lock-held "
      "region — a simulated crash-with-lock-held models no real "
      "failure, and a stall seam serializes threads behind the "
      "injector")
def check_dur004(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    model = model_for(ctx)
    for node, held, info in model.crash_sites.get(module.name, ()):
        parts = dotted_parts(node.func)
        yield _finding(
            "DUR004", module, node,
            f"{'.'.join(parts)}() fires while holding "
            f"{_lock_desc(held)} — crash seams belong between "
            "lock-held regions so the simulated failure matches a "
            "real process death (locks die with the process; stalls "
            "must not serialize other threads)")


# ---------------------------------------------------------------- EVD001

def _is_evidence_call(parts: List[str]) -> bool:
    if not parts or parts[-1] not in _EVIDENCE_CALLS:
        return False
    return (any(q in _OBS_QUALS for q in parts[:-1])
            or parts[0].startswith("_obs"))


def _emits_evidence(ctx: Context) -> Set[str]:
    """fids that call obs.event/counter/gauge/span, transitively."""
    model = model_for(ctx)
    emits: Set[str] = set()
    for fid, info in ctx.program.funcs.items():
        for parts, _ln in info.calls:
            if _is_evidence_call(parts):
                emits.add(fid)
                break
    changed = True
    while changed:
        changed = False
        for fid, info in ctx.program.funcs.items():
            if fid in emits:
                continue
            for parts, _ln in info.calls:
                t = model.resolve(info, parts)
                if t is not None and t in emits:
                    emits.add(fid)
                    changed = True
                    break
    return emits


def _is_refusal(n: ast.stmt):
    """A refusal statement: raise CausalError(...) or a nack /
    Admission(False) return. Returns a description or None."""
    if isinstance(n, ast.Raise) and isinstance(n.exc, ast.Call):
        parts = dotted_parts(n.exc.func)
        if parts is not None and parts[-1].endswith("CausalError"):
            return "raise CausalError"
    if isinstance(n, ast.Return):
        v = n.value
        if isinstance(v, ast.Call):
            parts = dotted_parts(v.func)
            if parts is not None and parts[-1] == "Admission":
                refused = False
                if v.args and isinstance(v.args[0], ast.Constant):
                    refused = v.args[0].value is False
                for kw in v.keywords:
                    if kw.arg == "admitted" \
                            and isinstance(kw.value, ast.Constant):
                        refused = kw.value.value is False
                if refused:
                    return "refusing Admission(False)"
        if isinstance(v, ast.Dict):
            for k, val in zip(v.keys, v.values):
                if (isinstance(k, ast.Constant) and k.value == "op"
                        and isinstance(val, ast.Constant)
                        and val.value == "nack"):
                    return "nack return"
    return None


class _RefusalWalker:
    """Walks a function body in lexical order tracking whether an
    evidence emission (direct obs call or resolved helper that emits)
    has occurred on the path so far. Lenient at joins: evidence in any
    branch counts for what follows — the rule hunts refusal paths with
    NO evidence anywhere upstream, not exact dominance."""

    def __init__(self, ctx: Context, info: FuncInfo, emits: Set[str]):
        self.ctx = ctx
        self.model = model_for(ctx)
        self.info = info
        self.emits = emits
        self.findings: List[ast.stmt] = []
        self.descs: List[str] = []

    def _stmt_has_evidence(self, n: ast.AST) -> bool:
        for c in ast.walk(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(c, ast.Call):
                parts = dotted_parts(c.func)
                if parts is None:
                    continue
                if _is_evidence_call(parts):
                    return True
                t = self.model.resolve(self.info, parts)
                if t is not None and t in self.emits:
                    return True
        return False

    def walk(self) -> None:
        body = self.info.node.body
        if isinstance(body, list):
            self._stmts(body, False)

    def _stmts(self, stmts, flag: bool) -> bool:
        for s in stmts:
            flag = self._stmt(s, flag)
        return flag

    def _stmt(self, s, flag: bool) -> bool:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return flag
        desc = _is_refusal(s)
        if desc is not None:
            if not flag and not self._stmt_has_evidence(s):
                self.findings.append(s)
                self.descs.append(desc)
            return flag
        if isinstance(s, ast.If):
            pre = flag or self._stmt_has_evidence(s.test)
            b = self._stmts(s.body, pre)
            e = self._stmts(s.orelse, pre)
            return b or e
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            b = self._stmts(s.body, flag)
            e = self._stmts(s.orelse, b)
            return e
        if isinstance(s, (ast.With, ast.AsyncWith)):
            pre = flag or any(self._stmt_has_evidence(i.context_expr)
                              for i in s.items)
            return self._stmts(s.body, pre)
        if isinstance(s, ast.Try):
            b = self._stmts(s.body, flag)
            h = flag
            for handler in s.handlers:
                h = self._stmts(handler.body, b) or h
            o = self._stmts(s.orelse, b)
            return self._stmts(s.finalbody, b or h or o)
        return flag or self._stmt_has_evidence(s)


@rule("EVD001",
      "serve/net boundary refusal (raise CausalError, nack, "
      "Admission(False)) on a path that emits no obs event — every "
      "refusal is evidence, or operators debug blind")
def check_evd001(ctx: Context, module: ModuleInfo) -> Iterator[Finding]:
    if not _in_serve_or_net(module):
        return
    emits = getattr(ctx, "_evd_emits", None)
    if emits is None:
        emits = ctx._evd_emits = _emits_evidence(ctx)
    for info in module.funcs.values():
        if _is_dunder_name(info.qualname):
            continue
        w = _RefusalWalker(ctx, info, emits)
        w.walk()
        for node, desc in zip(w.findings, w.descs):
            yield _finding(
                "EVD001", module, node,
                f"{desc} on a serve/net boundary path with no obs "
                "event/counter emitted on the path — refusals that "
                "leave no evidence are undebuggable in production; "
                "emit an obs event under `if obs.enabled():` before "
                "refusing (or suppress with the reason the path is "
                "pre-stream)")


def _is_dunder_name(qualname: str) -> bool:
    n = qualname.split(".")[-1]
    return n.startswith("__") and n.endswith("__")
