"""Module indexing and the jit-reachability call graph.

causelint's rules need one non-local fact: *is this function reachable
from traced code?* A host helper may read the clock or the environment
freely; the same read inside anything `jax.jit`/`vmap`/`shard_map`/
`pallas_call` ultimately traces is a program-identity or purity hazard.
This module computes that fact with stdlib ``ast`` only:

- every scanned file becomes a :class:`ModuleInfo` (dotted name derived
  from its path, functions/lambdas as :class:`FuncInfo` nodes with
  lexical parents, per-scope import aliases, and the raw call list of
  each body);
- **seeding**: any function handed to a tracing wrapper — a
  ``@jax.jit`` / ``@partial(jax.jit, ...)`` / ``@partial(shard_map,
  ...)`` decorator, a ``jax.jit(f)`` / ``jax.vmap(f)`` /
  ``pallas_call(kernel, ...)`` call (nested wrappers recurse:
  ``jax.jit(jax.vmap(f))`` seeds ``f``), or a lambda in any of those
  positions — is a trace root;
- **reachability**: BFS over name-resolved call edges. Resolution is
  lexical (own nested defs, enclosing functions, module scope) then
  import-based (aliases resolved against the scanned module set, so
  ``mesh.step -> vmap lambda -> merge_weave_kernel_v3 ->
  bitonic.sort_pairs -> switches.resolve`` is a real path). Unresolved
  calls (methods on unknown objects, builtins) drop silently — the
  graph is lint-grade, deliberately best-effort, and biased toward
  under-approximation so rules stay low-noise.

No jax import anywhere (the CI lint job runs before jax is installed).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

# A call whose callee's terminal name is one of these receives
# functions that will be traced: its function-valued arguments (and
# decorated defs) seed the reachability BFS.
TRACE_WRAPPERS = frozenset(
    {"jit", "vmap", "pmap", "shard_map", "pallas_call", "grad",
     "value_and_grad", "checkpoint", "remat"}
)
# partial(...) forwards its function arguments; recurse through it when
# hunting wrapped callables inside decorators.
_FORWARDERS = frozenset({"partial"})


def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None for non-name expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class FuncInfo:
    """One function-like scope (def, async def, or lambda)."""

    __slots__ = ("fid", "node", "module", "parent", "qualname",
                 "local_funcs", "imports", "calls", "class_name")

    def __init__(self, fid: str, node: ast.AST, module: "ModuleInfo",
                 parent: Optional["FuncInfo"], qualname: str,
                 class_name: Optional[str]):
        self.fid = fid
        self.node = node
        self.module = module
        self.parent = parent
        self.qualname = qualname
        self.class_name = class_name      # enclosing class, if a method
        self.local_funcs: Dict[str, str] = {}   # name -> fid
        self.imports: Dict[str, str] = {}       # alias -> dotted target
        # (parts, lineno) per call whose callee is a name chain
        self.calls: List[Tuple[List[str], int]] = []

    def body_nodes(self):
        """This scope's own statements, excluding nested function/
        lambda bodies (those are their own FuncInfo)."""
        roots = (self.node.body if isinstance(self.node.body, list)
                 else [self.node.body])
        stack = list(roots)
        while stack:
            n = stack.pop()
            yield n
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                stack.append(child)


class ModuleInfo:
    """One scanned file: AST, source, scopes, suppressions."""

    __slots__ = ("name", "path", "tree", "source", "lines", "funcs",
                 "top_funcs", "imports", "parse_error", "_pending_roots")

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self.tree: Optional[ast.Module] = None
        self.source = ""
        self.lines: List[str] = []
        self.funcs: Dict[str, FuncInfo] = {}
        self.top_funcs: Dict[str, str] = {}   # module-level name -> fid
        self.imports: Dict[str, str] = {}     # module-level aliases
        self.parse_error: Optional[SyntaxError] = None
        self._pending_roots: tuple = ((), ())

    @property
    def segments(self) -> Tuple[str, ...]:
        return tuple(self.name.split("."))


def module_name_for(path: str, root: str) -> str:
    """Dotted module name of ``path`` relative to the scan root
    (``a/b/c.py`` -> ``a.b.c``; package ``__init__`` collapses onto the
    package name). Paths outside the root fall back to the stem."""
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:  # pragma: no cover - windows cross-drive
        rel = os.path.basename(path)
    if rel.startswith(".."):
        rel = os.path.basename(path)
    name = rel[:-3] if rel.endswith(".py") else rel
    name = name.replace(os.sep, ".").replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def _resolve_relative(module: ModuleInfo, level: int,
                      target: Optional[str]) -> str:
    """``from ..x import y`` inside package ``a.b.c`` -> ``a.x``."""
    parts = list(module.segments[:-1])  # the module's package
    for _ in range(level - 1):
        if parts:
            parts.pop()
    if target:
        parts.extend(target.split("."))
    return ".".join(parts)


class _Indexer(ast.NodeVisitor):
    """Builds FuncInfo scopes with lexical parents and call lists."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.scope: Optional[FuncInfo] = None
        self.class_stack: List[str] = []
        self.roots: List[str] = []   # fids seeded by trace wrappers
        # (scope, parts) seeds that need the cross-module index —
        # resolved by build_program once every file is indexed
        self.named_roots: List[Tuple[Optional[FuncInfo], List[str]]] = []

    # ------------------------------------------------------- imports
    def _record_import(self, alias: str, target: str) -> None:
        table = (self.scope.imports if self.scope is not None
                 else self.module.imports)
        table[alias] = target

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self._record_import(a.asname or a.name.split(".")[0],
                                a.name if a.asname else
                                a.name.split(".")[0])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = (node.module or "")
        if node.level:
            base = _resolve_relative(self.module, node.level, node.module)
        for a in node.names:
            if a.name == "*":
                continue
            self._record_import(a.asname or a.name,
                                f"{base}.{a.name}" if base else a.name)
        self.generic_visit(node)

    # -------------------------------------------------------- scopes
    def _enter(self, node, display: str) -> FuncInfo:
        qual = (f"{self.scope.qualname}.{display}" if self.scope
                else ".".join(self.class_stack + [display]))
        fid = f"{self.module.name}::{qual}"
        info = FuncInfo(fid, node, self.module, self.scope, qual,
                        self.class_stack[-1] if self.class_stack else None)
        self.module.funcs[fid] = info
        if self.scope is not None:
            self.scope.local_funcs[display] = fid
        elif not self.class_stack:
            self.module.top_funcs[display] = fid
        else:
            # methods are addressable as Class.method at module level
            self.module.top_funcs[qual] = fid
        return info

    def _visit_func(self, node, display: str) -> None:
        info = self._enter(node, display)
        if not isinstance(node, ast.Lambda):
            for dec in node.decorator_list:
                if self._is_trace_wrapper(dec):
                    self.roots.append(info.fid)
        outer, self.scope = self.scope, info
        self.generic_visit(node)
        self.scope = outer

    def visit_FunctionDef(self, node):  # noqa: N802
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self._visit_func(node, node.name)

    def visit_Lambda(self, node):  # noqa: N802
        self._visit_func(node, f"<lambda@{node.lineno}>")

    def visit_ClassDef(self, node):  # noqa: N802
        if self.scope is None:
            self.class_stack.append(node.name)
            self.generic_visit(node)
            self.class_stack.pop()
        else:
            self.generic_visit(node)

    # --------------------------------------------------------- calls
    def _is_trace_wrapper(self, node: ast.AST) -> bool:
        """Whether this decorator/callee expression ends in a tracing
        wrapper, directly (``jax.jit``) or through a forwarder call
        (``partial(jax.jit, ...)`` / ``partial(shard_map, ...)``)."""
        parts = dotted_parts(node)
        if parts is not None:
            return parts[-1].lstrip("_") in TRACE_WRAPPERS
        if isinstance(node, ast.Call):
            cparts = dotted_parts(node.func)
            if cparts is not None and (
                    cparts[-1].lstrip("_") in TRACE_WRAPPERS
                    or cparts[-1] in _FORWARDERS):
                if cparts[-1] in _FORWARDERS:
                    return any(self._is_trace_wrapper(a)
                               for a in node.args)
                return True
        return False

    def _seed_from_args(self, call: ast.Call) -> None:
        """``jax.jit(f)`` / ``vmap(lambda: ...)`` — function-valued
        arguments of a tracing wrapper become roots; nested wrapper
        calls recurse."""
        for arg in call.args:
            if isinstance(arg, ast.Lambda):
                # the lambda's FuncInfo is minted when generic_visit
                # reaches it; compute its fid the same way
                qual = (f"{self.scope.qualname}.<lambda@{arg.lineno}>"
                        if self.scope else f"<lambda@{arg.lineno}>")
                self.roots.append(f"{self.module.name}::{qual}")
            elif isinstance(arg, ast.Call):
                cparts = dotted_parts(arg.func)
                if cparts is not None and (
                        cparts[-1].lstrip("_") in TRACE_WRAPPERS
                        or cparts[-1] in _FORWARDERS):
                    self._seed_from_args(arg)
            else:
                parts = dotted_parts(arg)
                if parts is not None:
                    fid = resolve_name(self.scope, self.module, parts)
                    if fid is not None:
                        self.roots.append(fid)
                    else:
                        # imported function handed to a wrapper:
                        # resolvable only once every module is indexed
                        self.named_roots.append((self.scope, parts))

    def visit_Assign(self, node: ast.Assign) -> None:  # noqa: N802
        # function aliases: ``_compressed = merge_weave_kernel_v2`` and
        # ``batched = functools.partial(fn, ...)`` create call-graph
        # edges exactly like imports do, so record them in the same
        # per-scope alias table (value resolved lazily at BFS time)
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            value = node.value
            if isinstance(value, ast.Call):
                cparts = dotted_parts(value.func)
                if (cparts is not None and cparts[-1] in _FORWARDERS
                        and value.args):
                    value = value.args[0]
            parts = dotted_parts(value)
            if parts is not None:
                dotted = self._alias_target(parts)
                if dotted is not None:
                    self._record_import(node.targets[0].id, dotted)
        self.generic_visit(node)

    def _alias_target(self, parts: List[str]) -> Optional[str]:
        """Dotted global name an aliased value will resolve to, or
        None when the head is unknown (plain data assignments)."""
        head = parts[0]
        s = self.scope
        while s is not None:
            if head in s.local_funcs and len(parts) == 1:
                # nested defs are addressed by fid, not dotted name;
                # keep the qualname path so the index lookup works
                return None
            if head in s.imports:
                return ".".join([s.imports[head]] + parts[1:])
            s = s.parent
        if head in self.module.top_funcs and len(parts) == 1:
            return f"{self.module.name}.{head}"
        if head in self.module.imports:
            return ".".join([self.module.imports[head]] + parts[1:])
        return None

    def visit_Call(self, node: ast.Call) -> None:
        parts = dotted_parts(node.func)
        if parts is not None:
            if self.scope is not None:
                self.scope.calls.append((parts, node.lineno))
            if parts[-1].lstrip("_") in TRACE_WRAPPERS:
                self._seed_from_args(node)
        self.generic_visit(node)


def resolve_name(scope: Optional[FuncInfo], module: ModuleInfo,
                 parts: List[str],
                 index: Optional[Dict[str, str]] = None) -> Optional[str]:
    """Resolve a dotted name to a FuncInfo fid: lexical scopes first
    (nested defs), then per-scope and module imports, then module-level
    defs, then the cross-module index of every scanned file."""
    head = parts[0]
    s = scope
    while s is not None:
        if head in s.local_funcs and len(parts) == 1:
            return s.local_funcs[head]
        if head in s.imports:
            return _resolve_dotted(
                ".".join([s.imports[head]] + parts[1:]), index)
        s = s.parent
    if head in module.top_funcs and len(parts) == 1:
        return module.top_funcs[head]
    if len(parts) == 2 and f"{head}.{parts[1]}" in module.top_funcs:
        return module.top_funcs[f"{head}.{parts[1]}"]
    if head == "self" and scope is not None and scope.class_name:
        meth = f"{scope.class_name}.{parts[-1]}"
        if meth in module.top_funcs:
            return module.top_funcs[meth]
    if head in module.imports:
        return _resolve_dotted(
            ".".join([module.imports[head]] + parts[1:]), index)
    return None


def _resolve_dotted(dotted: str,
                    index: Optional[Dict[str, str]]) -> Optional[str]:
    return None if index is None else index.get(dotted)


class Program:
    """The scanned module set plus the jit-reachability answer."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        # dotted global name ("pkg.mod.fn" / "pkg.mod.Cls.meth") -> fid
        self.index: Dict[str, str] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.roots: List[str] = []
        for m in modules:
            self.funcs.update(m.funcs)
            for name, fid in m.top_funcs.items():
                self.index[f"{m.name}.{name}"] = fid
        self._reachable: Optional[Set[str]] = None

    def add_roots(self, fids: List[str]) -> None:
        self.roots.extend(f for f in fids if f in self.funcs)

    def resolve_call(self, info: FuncInfo,
                     parts: List[str]) -> Optional[str]:
        return resolve_name(info, info.module, parts, self.index)

    def reachable(self) -> Set[str]:
        """fids reachable from any trace root (roots included)."""
        if self._reachable is None:
            seen: Set[str] = set()
            queue = [f for f in self.roots if f in self.funcs]
            while queue:
                fid = queue.pop()
                if fid in seen:
                    continue
                seen.add(fid)
                info = self.funcs[fid]
                for parts, _ln in info.calls:
                    target = self.resolve_call(info, parts)
                    if target is not None and target not in seen:
                        queue.append(target)
            self._reachable = seen
        return self._reachable

    def reachable_from(self, fids: List[str]) -> Set[str]:
        """Closure over the call graph from an explicit seed list
        (used by rule TID003 to scope a cached program's trace)."""
        seen: Set[str] = set()
        queue = [f for f in fids if f in self.funcs]
        while queue:
            fid = queue.pop()
            if fid in seen:
                continue
            seen.add(fid)
            info = self.funcs[fid]
            for parts, _ln in info.calls:
                target = self.resolve_call(info, parts)
                if target is not None and target not in seen:
                    queue.append(target)
        return seen


def index_module(path: str, root: str) -> ModuleInfo:
    """Parse and index one file. Parse failures are recorded on the
    ModuleInfo (the driver turns them into findings), never raised."""
    mod = ModuleInfo(module_name_for(path, root), path)
    try:
        with open(path, encoding="utf-8") as f:
            mod.source = f.read()
        mod.lines = mod.source.splitlines()
        mod.tree = ast.parse(mod.source, filename=path)
    except SyntaxError as e:
        mod.parse_error = e
        return mod
    except (OSError, UnicodeDecodeError) as e:
        mod.parse_error = SyntaxError(str(e))
        return mod
    indexer = _Indexer(mod)
    indexer.visit(mod.tree)
    mod._pending_roots = (indexer.roots, indexer.named_roots)
    return mod


def build_program(paths: List[str], root: str) -> Program:
    """Index every file and wire the cross-module call graph."""
    modules = [index_module(p, root) for p in paths]
    prog = Program(modules)
    for m in modules:
        fids, named = m._pending_roots if m._pending_roots else ([], [])
        prog.add_roots(fids)
        for scope, parts in named:
            fid = resolve_name(scope, m, parts, prog.index)
            if fid is not None:
                prog.add_roots([fid])
    return prog
