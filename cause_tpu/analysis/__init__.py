"""causelint — trace-identity and jit-purity static analysis.

The framework's correctness leans on conventions nothing used to
enforce mechanically: trace-time switches are *imported, never
restated* and appear in every program-cache key; obs-off code paths
read zero TRACE_SWITCHES env vars; jit-reachable code is free of host
effects; lane-cache arena views are never mutated in place outside
their owner. Each convention is the fossil of a real fixed bug (stale
sharded programs across switch flips, uncertified static flips,
blocking tunnel claims from cache lookups) — this package turns them
into CI-gated rules. v2 extends the catalog to the concurrent host
substrate: lock discipline (LCK — guarded-by inference, lock-order
cycles, blocking under a lock, commit-step reentrancy), durable
commit protocol (DUR — fsync-before-rename, dir-fsync,
journal-before-ack, crash seams under locks) and the refusal-evidence
contract (EVD). See ``rules`` for the TID/JPH/OBS/LCA catalog and the
parameterized guard-rule table, ``concurrency``/``protocol`` for the
LCK/DUR/EVD families, ``callgraph`` for the jit-reachability
machinery, and ``__main__`` for the CLI
(``python -m cause_tpu.analysis``, with ``--cache``/``--changed``
incremental modes).

Deliberately dependency-light: stdlib ``ast`` plus
``cause_tpu.switches`` (itself import-free) — no jax, no numpy, so
the lint gate runs before the test matrix installs anything.
"""

from .core import (AnalysisResult, Finding, cached_run, changed_files,
                   list_rules, run)
from .report import load_baseline, to_json, write_baseline

__all__ = [
    "AnalysisResult",
    "Finding",
    "cached_run",
    "changed_files",
    "list_rules",
    "load_baseline",
    "run",
    "to_json",
    "write_baseline",
]
