"""causelint — trace-identity and jit-purity static analysis.

The framework's correctness leans on conventions nothing used to
enforce mechanically: trace-time switches are *imported, never
restated* and appear in every program-cache key; obs-off code paths
read zero TRACE_SWITCHES env vars; jit-reachable code is free of host
effects; lane-cache arena views are never mutated in place outside
their owner. Each convention is the fossil of a real fixed bug (stale
sharded programs across switch flips, uncertified static flips,
blocking tunnel claims from cache lookups) — this package turns them
into CI-gated rules. See ``rules`` for the TID/JPH/OBS/LCA catalog,
``callgraph`` for the jit-reachability machinery, and ``__main__``
for the CLI (``python -m cause_tpu.analysis``).

Deliberately dependency-light: stdlib ``ast`` plus
``cause_tpu.switches`` (itself import-free) — no jax, no numpy, so
the lint gate runs before the test matrix installs anything.
"""

from .core import AnalysisResult, Finding, list_rules, run
from .report import load_baseline, to_json, write_baseline

__all__ = [
    "AnalysisResult",
    "Finding",
    "list_rules",
    "load_baseline",
    "run",
    "to_json",
    "write_baseline",
]
