"""CausalSet — an observed-remove set CRDT on the causal tree.

A reference roadmap wish ("∪ Implement CausalSet",
/root/reference/README.md:250) the reference never built; cause_tpu
provides it on the existing machinery: the tree IS a list tree (chain
of add-nodes under the weave tail, tombstones as hide specials), so
every backend — pure scan, native C++, and the batched TPU kernels —
accelerates it with zero new kernel code.

Semantics (classic OR-set): ``add`` appends a node carrying the
element; ``discard`` tombstones every *observed* add-node of the
element. A concurrent add at another site is unobserved by the remover,
so it survives the merge — add wins, the standard OR-set resolution.
Rendered value: the distinct visible elements.
"""

from __future__ import annotations

from typing import Optional

from ..ids import HIDE
from . import clist as c_list
from . import shared as s
from .handle import ListTreeHandle
from .shared import CausalTree

__all__ = ["SET_TYPE", "CausalSet", "new_causal_set", "new_causal_tree"]

SET_TYPE = "set"


def new_causal_tree(weaver: str = "pure") -> CausalTree:
    """A set tree is a list tree with its own type tag."""
    return c_list.new_causal_tree(weaver).evolve(type=SET_TYPE)


def visible_nodes_by_value(ct: CausalTree) -> dict:
    """{element -> [visible nodes carrying it]} in weave order.
    ``add`` fail-fasts on unhashable elements, but nodes can also
    arrive through insert/merge/serde from a replica that did not —
    surface those as CausalError here, not a bare TypeError."""
    out: dict = {}
    for node in c_list.causal_list_to_list(ct):
        try:
            out.setdefault(node[2], []).append(node)
        except TypeError:
            raise s.CausalError(
                "set elements must be hashable",
                {"id": node[0], "type": type(node[2]).__name__},
            ) from None
    return out


def causal_set_to_edn(ct: CausalTree, opts: Optional[dict] = None) -> set:
    return {
        s.causal_to_edn(v, opts) for v in visible_nodes_by_value(ct)
    }


class CausalSet(ListTreeHandle):
    """Immutable CausalSet handle. ``len``/iteration cover the distinct
    visible elements; all mutating-looking methods return a new set.
    The shared protocol surface (metadata, insert/append/weft, merge
    dispatch) lives on ``ListTreeHandle``."""

    __slots__ = ("ct",)

    _fresh = staticmethod(new_causal_tree)

    # -- CausalTo --
    def causal_to_edn(self, opts: Optional[dict] = None) -> set:
        return causal_set_to_edn(self.ct, opts)

    # -- set interop --
    def add(self, value) -> "CausalSet":
        """Add an element. ALWAYS mints a fresh add-node, even when the
        element is already visible — the node is the OR-set's unique
        tag, and it is what lets this add survive a concurrent remove
        (a remove only covers the adds it observed). Skipping
        already-present values (the LWW map's assoc stance) would
        silently drop that protection."""
        try:
            hash(value)
        except TypeError:
            raise s.CausalError(
                "set elements must be hashable",
                {"type": type(value).__name__},
            ) from None
        return CausalSet(c_list.conj_(self.ct, value))

    def discard(self, value) -> "CausalSet":
        """Tombstone every *observed* add of the element (OR-set
        remove); a no-op when absent. Concurrent unobserved adds
        survive a later merge — add wins."""
        nodes = visible_nodes_by_value(self.ct).get(value, [])
        ct = self.ct
        for node in nodes:
            ct = s.append(c_list.weave, ct, node[0], HIDE)
        return CausalSet(ct) if nodes else self

    def empty(self) -> "CausalSet":
        return CausalSet(
            new_causal_tree(self.ct.weaver).evolve(
                site_id=self.ct.site_id, uuid=self.ct.uuid
            )
        )

    def __contains__(self, value) -> bool:
        return value in visible_nodes_by_value(self.ct)

    def __len__(self) -> int:
        return len(visible_nodes_by_value(self.ct))

    def __iter__(self):
        return iter(visible_nodes_by_value(self.ct))

    def __repr__(self) -> str:
        return f"#causal/set {causal_set_to_edn(self.ct)!r}"

    def __str__(self) -> str:
        return str(causal_set_to_edn(self.ct))


def new_causal_set(*items, weaver: str = "pure") -> CausalSet:
    cs = CausalSet(new_causal_tree(weaver))
    for v in items:
        cs = cs.add(v)
    return cs
