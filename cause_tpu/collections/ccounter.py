"""CausalCounter — a convergent counter CRDT on the causal tree.

A reference roadmap wish ("∆ Implement CausalCounter",
/root/reference/README.md:249) the reference never built. The tree is
a list tree whose node values are numeric deltas; the rendered value
is the sum of visible deltas. Addition commutes, so any merge order
converges; a delta can be undone by tombstoning its node (the same
id-caused hide the other collections use), giving the counter undo
semantics no ordinary PN-counter has.
"""

from __future__ import annotations

from numbers import Number
from typing import Optional

from ..ids import HIDE
from . import clist as c_list
from . import shared as s
from .handle import ListTreeHandle
from .shared import CausalTree

__all__ = [
    "COUNTER_TYPE", "CausalCounter", "new_causal_counter",
    "new_causal_tree",
]

COUNTER_TYPE = "counter"


def new_causal_tree(weaver: str = "pure") -> CausalTree:
    """A counter tree is a list tree with its own type tag."""
    return c_list.new_causal_tree(weaver).evolve(type=COUNTER_TYPE)


def counter_value(ct: CausalTree):
    return sum(
        n[2] for n in c_list.causal_list_to_list(ct)
        if isinstance(n[2], Number)
    )


def _check_delta(n) -> None:
    if not isinstance(n, Number) or isinstance(n, bool):
        raise s.CausalError(
            "Counter deltas must be numbers.",
            {"causes": {"not-a-number"}, "value": n},
        )


class CausalCounter(ListTreeHandle):
    """Immutable CausalCounter handle; mutating-looking methods return
    a new counter. The shared protocol surface (metadata,
    insert/append/weft, merge dispatch) lives on ``ListTreeHandle``."""

    __slots__ = ("ct",)

    _fresh = staticmethod(new_causal_tree)

    # -- CausalTo --
    def causal_to_edn(self, opts: Optional[dict] = None):
        return counter_value(self.ct)

    # -- counter interop --
    def increment(self, n=1) -> "CausalCounter":
        """Record a delta (any number, so decrement = increment(-n))."""
        _check_delta(n)
        return CausalCounter(c_list.conj_(self.ct, n))

    def decrement(self, n=1) -> "CausalCounter":
        _check_delta(n)  # before negating: -True is int 1
        return self.increment(-n)

    def undo_delta(self, node_id) -> "CausalCounter":
        """Tombstone one recorded delta by node id."""
        return self.append(node_id, HIDE)

    def value(self):
        return counter_value(self.ct)

    def deltas(self):
        """The visible delta nodes in weave order (for blame/undo)."""
        return [
            n for n in c_list.causal_list_to_list(self.ct)
            if isinstance(n[2], Number)
        ]

    def __int__(self) -> int:
        return int(counter_value(self.ct))

    def __repr__(self) -> str:
        return f"#causal/counter {counter_value(self.ct)!r}"

    def __str__(self) -> str:
        return str(counter_value(self.ct))


def new_causal_counter(start=0, weaver: str = "pure") -> CausalCounter:
    cc = CausalCounter(new_causal_tree(weaver))
    if start:
        cc = cc.increment(start)
    return cc
