"""CausalCounter — a convergent counter CRDT on the causal tree.

A reference roadmap wish ("∆ Implement CausalCounter",
/root/reference/README.md:249) the reference never built. The tree is
a list tree whose node values are numeric deltas; the rendered value
is the sum of visible deltas. Addition commutes, so any merge order
converges; a delta can be undone by tombstoning its node (the same
id-caused hide the other collections use), giving the counter undo
semantics no ordinary PN-counter has.
"""

from __future__ import annotations

from numbers import Number
from typing import Optional

from ..ids import HIDE
from . import clist as c_list
from . import shared as s
from .shared import CausalTree

__all__ = [
    "COUNTER_TYPE", "CausalCounter", "new_causal_counter",
    "new_causal_tree",
]

COUNTER_TYPE = "counter"


def new_causal_tree(weaver: str = "pure") -> CausalTree:
    """A counter tree is a list tree with its own type tag."""
    return c_list.new_causal_tree(weaver).evolve(type=COUNTER_TYPE)


def counter_value(ct: CausalTree):
    return sum(
        n[2] for n in c_list.causal_list_to_list(ct)
        if isinstance(n[2], Number)
    )


def _check_delta(n) -> None:
    if not isinstance(n, Number) or isinstance(n, bool):
        raise s.CausalError(
            "Counter deltas must be numbers.",
            {"causes": {"not-a-number"}, "value": n},
        )


class CausalCounter:
    """Immutable CausalCounter handle; mutating-looking methods return
    a new counter."""

    __slots__ = ("ct",)

    def __init__(self, ct: CausalTree):
        object.__setattr__(self, "ct", ct)

    def __setattr__(self, *a):
        raise AttributeError("CausalCounter is immutable")

    # -- CausalMeta --
    def get_uuid(self) -> str:
        return self.ct.uuid

    def get_ts(self) -> int:
        return self.ct.lamport_ts

    def get_site_id(self) -> str:
        return self.ct.site_id

    # -- CausalTree protocol --
    def get_weave(self):
        return self.ct.weave

    def get_nodes(self):
        return self.ct.nodes

    def insert(self, node, more_nodes=None) -> "CausalCounter":
        return CausalCounter(
            s.insert(c_list.weave, self.ct, node, more_nodes)
        )

    def append(self, cause, value) -> "CausalCounter":
        return CausalCounter(s.append(c_list.weave, self.ct, cause, value))

    def weft(self, ids_to_cut_yarns) -> "CausalCounter":
        return CausalCounter(
            s.weft(c_list.weave,
                   lambda: new_causal_tree(self.ct.weaver),
                   self.ct, ids_to_cut_yarns)
        )

    def merge(self, other: "CausalCounter") -> "CausalCounter":
        if self.ct.weaver == "jax":
            from ..weaver import jaxw

            return CausalCounter(jaxw.merge_list_trees(self.ct, other.ct))
        if self.ct.weaver == "native":
            from ..weaver import nativew

            return CausalCounter(nativew.merge_trees(self.ct, other.ct))
        return CausalCounter(s.merge_trees(c_list.weave, self.ct, other.ct))

    def merge_many(self, others) -> "CausalCounter":
        if self.ct.weaver == "jax":
            from ..weaver import jaxw

            return CausalCounter(
                jaxw.merge_many_list_trees(
                    [self.ct] + [o.ct for o in others]
                )
            )
        ct = s.union_nodes_many([self.ct] + [o.ct for o in others])
        return CausalCounter(c_list.weave(ct))

    # -- CausalTo --
    def causal_to_edn(self, opts: Optional[dict] = None):
        return counter_value(self.ct)

    # -- counter interop --
    def increment(self, n=1) -> "CausalCounter":
        """Record a delta (any number, so decrement = increment(-n))."""
        _check_delta(n)
        return CausalCounter(c_list.conj_(self.ct, n))

    def decrement(self, n=1) -> "CausalCounter":
        _check_delta(n)  # before negating: -True is int 1
        return self.increment(-n)

    def undo_delta(self, node_id) -> "CausalCounter":
        """Tombstone one recorded delta by node id."""
        return self.append(node_id, HIDE)

    def value(self):
        return counter_value(self.ct)

    def deltas(self):
        """The visible delta nodes in weave order (for blame/undo)."""
        return [
            n for n in c_list.causal_list_to_list(self.ct)
            if isinstance(n[2], Number)
        ]

    def __int__(self) -> int:
        return int(counter_value(self.ct))

    def __eq__(self, other) -> bool:
        return isinstance(other, CausalCounter) and self.ct == other.ct

    def __hash__(self) -> int:
        return hash((self.ct.uuid, self.ct.lamport_ts, self.ct.site_id,
                     tuple(sorted(self.ct.nodes))))

    def __repr__(self) -> str:
        return f"#causal/counter {counter_value(self.ct)!r}"

    def __str__(self) -> str:
        return str(counter_value(self.ct))

    # -- IObj/IMeta analogue --
    def with_meta(self, m) -> "CausalCounter":
        return CausalCounter(self.ct.evolve(meta=m))

    def meta(self):
        return self.ct.meta


def new_causal_counter(start=0, weaver: str = "pure") -> CausalCounter:
    cc = CausalCounter(new_causal_tree(weaver))
    if start:
        cc = cc.increment(start)
    return cc
