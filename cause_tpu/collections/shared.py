"""Causal-tree core: tree shape, insert/append, yarn cache, weft, merge.

The cause_tpu equivalent of the reference's generic CRDT core
(reference: src/causal/collections/shared.cljc). A causal tree holds:

- ``nodes`` — canonical append-only store ``{id: (cause, value)}``
  (shared.cljc:9,62);
- ``yarns`` — CACHE: per-site, time-sorted list of nodes
  (shared.cljc:10,64-65), kept so weft (time travel) is fast;
- ``weave`` — CACHE: the linearized output order; a list of nodes for
  list trees (shared.cljc:67) or a ``{key: list-weave}`` dict for map
  trees (shared.cljc:68).

Caches are disposable: ``refresh_caches`` rebuilds yarns, lamport-ts and
the weave from ``nodes`` alone (shared.cljc:259-266) — a tree can always
be reconstituted from a bag of nodes.

All operations are functional: they return a new ``CausalTree`` value and
never mutate their input (copy-on-write per call, mirroring the
reference's persistent maps). The host-side structures stay O(n)-per-op
like the reference; bulk/batched work belongs to the device weaver.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs
from .. import pstore
from .. import util as u
from ..ids import (
    ROOT_ID,
    is_key,
    is_special,
    new_site_id,
    new_uid,
    node_from_kv,
    get_tx,
)
from ..weaver import pure

__all__ = [
    "CausalTree",
    "CausalError",
    "assoc_nodes",
    "spin",
    "insert",
    "ensure_weave",
    "append",
    "refresh_ts",
    "yarns_to_nodes",
    "refresh_caches",
    "weft",
    "check_mergeable",
    "union_nodes",
    "union_nodes_many",
    "merge_trees",
    "causal_to_edn",
]

LIST_TYPE = "list"
MAP_TYPE = "map"


class CausalError(Exception):
    """Validation failure in a causal operation. Carries an info dict like
    the reference's ``ex-info`` (e.g. shared.cljc:163-181)."""

    def __init__(self, message: str, info: Optional[dict] = None):
        super().__init__(message)
        self.info = info or {}


@dataclass(frozen=True)
class CausalTree:
    """One causal tree (shared.cljc:72-73). Treat as immutable; all ops
    return a new tree. ``weaver`` selects the weave backend: "pure"
    (host scan, default) or "jax" (device kernel for full rebuilds and
    merges) — the framework's one real flag."""

    type: str
    lamport_ts: int
    uuid: str
    site_id: str
    nodes: Dict[tuple, tuple]
    yarns: Dict[str, list]
    # CACHE, excluded from equality: ``nodes`` (with ``yarns``) fully
    # determines the weave — ``ensure_weave`` rebuilds it from them —
    # and under ``lazy_weave`` a stale tree (weave=None) must still
    # compare equal to its materialized twin at the raw-dataclass
    # level, not only through ListTreeHandle.__eq__.
    weave: Any = field(compare=False)
    weaver: str = "pure"
    # IObj/IMeta analogue (list.cljc:97-101, map.cljc:159-163): an
    # arbitrary attachment that never affects equality and is not
    # serialized — Clojure metadata semantics.
    meta: Any = field(default=None, compare=False)
    # Lazy weave mode (list trees, opt-in): inserts skip the O(n) host
    # weave splice entirely; ``weave=None`` marks the cache stale and
    # any reader materializes it once via ``ensure_weave`` (a full
    # rebuild — device-routed under weaver="jax"). ``weave_tail`` is
    # the one incremental fact kept alive while stale: the id of the
    # current last weave node, valid only for the append-at-tail chain
    # (``conj``'s cause), invalidated by any other insert. No
    # reference analogue — the reference always weaves eagerly
    # (shared.cljc:12); this is the TPU-fleet editing mode where the
    # device wave, not the host, owns linearization.
    lazy_weave: bool = field(default=False, compare=False)
    weave_tail: Any = field(default=None, compare=False, repr=False)
    # CACHE: marshalled device lanes (weaver.lanecache.LaneView), the
    # fourth disposable cache next to yarns/weave — maintained on the
    # append fast path, attached by the device weaver after rebuilds,
    # and cleared by ``evolve`` whenever ``nodes`` changes without an
    # explicit replacement (so it can never go stale).
    lanes: Any = field(default=None, compare=False, repr=False)

    def evolve(self, **kw) -> "CausalTree":
        if "nodes" in kw and "lanes" not in kw:
            kw["lanes"] = None
        return replace(self, **kw)


WeaveFn = Callable[..., CausalTree]


def assoc_nodes(ct: CausalTree, nodes) -> CausalTree:
    """Add node triples to the canonical ``nodes`` store
    (shared.cljc:104-110). Structural sharing past the small-store
    threshold (pstore.assoc_items) keeps this amortized-sublinear, the
    reference's persistent-map cost model."""
    return ct.evolve(nodes=pstore.assoc_items(
        ct.nodes, {n[0]: (n[1], n[2]) for n in nodes}
    ))


def _spin_one(yarns: Dict[str, list], n) -> None:
    """Place one node into its site's time-sorted yarn, mutating the
    (freshly copied) yarns dict (shared.cljc:112-119)."""
    site = n[0][1]
    yarn = yarns.get(site)
    if yarn is None:
        yarns[site] = [n]
    elif yarn[-1][0] < n[0]:
        yarns[site] = pstore.yarn_appended(yarn, n)
    else:
        # expensive sorted splice; avoided on the append fast path above
        yarns[site] = u.insert_sorted(yarn, n)


def spin(ct: CausalTree, node=None, more_nodes=None) -> CausalTree:
    """Maintain the yarn cache (shared.cljc:121-149).

    With no node, rebuild every yarn from the canonical store in sorted
    id order. With a node (and optional same-tx run), place just those.
    The reference intends a bulk fast path for sequential list
    transactions (shared.cljc:137-143) but its guard never fires; we spin
    one node at a time, which is the behavior it actually exhibits (the
    per-site append fast path keeps the common case O(1)).
    """
    yarns = dict(ct.yarns)
    if node is None:
        # bulk rebuild: sorted ids grouped by site in one pass — the
        # incremental path's copy-on-append would be O(n^2) here
        yarns = {}
        for nid, (cause, value) in sorted(ct.nodes.items()):
            yarns.setdefault(nid[1], []).append((nid, cause, value))
    else:
        _spin_one(yarns, node)
        if more_nodes:
            for n in more_nodes:
                _spin_one(yarns, n)
    return ct.evolve(yarns=yarns)


def insert(weave_fn: WeaveFn, ct: CausalTree, node, more_nodes_in_tx=None) -> CausalTree:
    """Insert an arbitrary node from any site and any point in time
    (shared.cljc:151-184). Validations:

    - all nodes in one call must belong to the same transaction;
    - re-inserting an identical node is an idempotent no-op; inserting a
      *different* body under an existing id raises (append-only store);
    - an id-valued cause must already exist in the tree;
    - the local lamport-ts fast-forwards to the node's ts if greater.
    """
    nodes = [node]
    if more_nodes_in_tx:
        nodes.extend(more_nodes_in_tx)
    txs = {get_tx(n) for n in nodes}
    if len(txs) > 1:
        raise CausalError("All nodes must belong to the same tx.", {"txs": txs})
    # every node of the run gets the same scrutiny as a single insert —
    # a run must not be a validation bypass (append-only bodies, causes
    # resolving in the tree or earlier in the run)
    dup = 0
    for nd in nodes:
        existing = ct.nodes.get(nd[0])
        if existing is not None:
            if existing != (nd[1], nd[2]):
                raise CausalError(
                    "This node is already in the tree and can't be changed.",
                    {"causes": {"append-only", "edits-not-allowed"},
                     "existing_node": (nd[0],) + existing},
                )
            dup += 1
    if dup == len(nodes):
        return ct  # idempotency!
    if dup:
        raise CausalError(
            "A same-tx run must be all-new or an exact replay.",
            {"causes": {"append-only", "partial-tx-run"}},
        )
    seen = set()
    for nd in nodes:
        if not is_key(nd[1]) and nd[1] not in ct.nodes and nd[1] not in seen:
            raise CausalError(
                "The cause of this node is not in the tree.",
                {"causes": {"cause-must-exist"}},
            )
        seen.add(nd[0])
    if obs.enabled():
        # convergence-lag provenance: every local mutation funnels
        # through here (conj/cons/extend/insert all land on this
        # validated path), so this is the one host-side stamp point —
        # site and lamport ride in the node id, the monotonic clock is
        # captured inside op_created, all outside any trace
        op_ids = [nd[0] for nd in nodes]
        obs.lag.op_created(ct.uuid, op_ids)
        # distributed-trace mint (PR 19): the same funnel is where a
        # locally-created batch gets its causal identity; ops already
        # bound (a replayed run) keep their original trace
        tr = obs.xtrace.new_trace()
        obs.xtrace.hop("mint", tr, parent="", source="funnel",
                       uuid=str(ct.uuid), ops=len(nodes))
        obs.xtrace.bind_ops(tr, op_ids)
    # a non-chaining same-tx run is the one input whose INCREMENTAL
    # weave (contiguous splice at the run head's cause — the
    # runs-stick-together rule) differs from a from-scratch rebuild
    # (each node at its own cause). Lazy deferral implies rebuild
    # semantics, so such a run must weave eagerly: materialize first,
    # then take the normal splice path below.
    lazy = ct.lazy_weave and ct.type == LIST_TYPE
    chained = all(
        nodes[i + 1][1] == nodes[i][0] for i in range(len(nodes) - 1)
    )
    if lazy and not chained:
        ensure_weave(weave_fn, ct)
        lazy = False
    # one fused evolve (dataclass replace is a measurable share of the
    # per-op cost): nodes, yarns, clock, lanes, and the lazy staleness
    # all land in a single copy
    kw = {"nodes": pstore.assoc_items(
        ct.nodes, {n[0]: (n[1], n[2]) for n in nodes}
    )}
    yarns = dict(ct.yarns)
    _spin_one(yarns, node)
    if more_nodes_in_tx:
        for n in more_nodes_in_tx:
            _spin_one(yarns, n)
    kw["yarns"] = yarns
    if node[0][0] > ct.lamport_ts:
        kw["lamport_ts"] = node[0][0]
    if ct.lanes is not None and ct.type == LIST_TYPE:
        from ..weaver import lanecache

        kw["lanes"] = lanecache.extend_view(ct.lanes, nodes)
    if lazy:
        # skip the weave splice; keep only the tail hint alive. The
        # run chains (checked above), so if its first cause is the
        # current last weave node the whole run lands at the end and
        # its last node becomes the new tail — for local conj, pastes,
        # AND foreign appends alike. Anything else may displace the
        # last element in ways only a weave scan can see: the hint
        # dies and the next tail read pays one materialization.
        prev_tail = (ct.weave[-1][0] if ct.weave is not None
                     else ct.weave_tail)
        kw["weave"] = None
        kw["weave_tail"] = (
            nodes[-1][0]
            if prev_tail is not None and nodes[0][1] == prev_tail
            else None
        )
        return ct.evolve(**kw)
    return weave_fn(ct.evolve(**kw), node, more_nodes_in_tx)


def ensure_weave(weave_fn: WeaveFn, ct: CausalTree) -> CausalTree:
    """Materialize a lazy tree's weave in place (no-op when fresh).

    The weave is a pure function of ``nodes``, so back-filling the
    frozen dataclass's cache field is referentially transparent — the
    same discipline as the lanes cache. Returns ``ct`` itself, now
    woven."""
    if ct.weave is not None:
        return ct
    fresh = weave_fn(ct)  # full rebuild; device-routed under "jax"
    object.__setattr__(ct, "weave", fresh.weave)
    object.__setattr__(ct, "weave_tail", None)
    if fresh.lanes is not None:
        object.__setattr__(ct, "lanes", fresh.lanes)
    if obs.enabled():
        # semantic layer: each paid materialization records weave
        # length vs live values + tombstone ratio — the read-side cost
        # the lazy fleet-editing mode defers, and the quantity GC
        # exists to reclaim
        obs.semantic.lazy_materialized(ct)
    return ct


def append(weave_fn: WeaveFn, ct: CausalTree, cause, value) -> CausalTree:
    """Mint a node at the next local lamport-ts and insert it
    (shared.cljc:186-192)."""
    ct2 = ct.evolve(lamport_ts=ct.lamport_ts + 1)
    n = ((ct2.lamport_ts, ct2.site_id, 0), cause, value)
    return insert(weave_fn, ct2, n)


def refresh_ts(ct: CausalTree) -> CausalTree:
    """Set lamport-ts to the max ts in the (up-to-date, sorted) yarns
    (shared.cljc:243-249)."""
    ts = 0
    for yarn in ct.yarns.values():
        if yarn:
            ts = max(ts, yarn[-1][0][0])
    return ct.evolve(lamport_ts=ts)


def yarns_to_nodes(ct: CausalTree) -> CausalTree:
    """Rebuild the canonical store from the yarns (shared.cljc:251-257)."""
    store = {}
    for yarn in ct.yarns.values():
        for n in yarn:
            store[n[0]] = (n[1], n[2])
    return ct.evolve(nodes=store)


def refresh_caches(weave_fn: WeaveFn, ct: CausalTree) -> CausalTree:
    """Rebuild yarns, lamport-ts and the weave from ``nodes`` alone
    (shared.cljc:259-266). The idempotency oracle of the test suite:
    an incrementally-maintained tree must equal its refreshed self."""
    ct = spin(ct)
    ct = refresh_ts(ct)
    return weave_fn(ct)


def weft(weave_fn: WeaveFn, new_causal_tree_fn: Callable[[], CausalTree],
         ct: CausalTree, ids_to_cut_yarns) -> CausalTree:
    """Time travel: cut each named site's yarn at an id and rebuild the
    sub-tree at that previous point in time (shared.cljc:268-293).
    Combinations of ids that do not preserve causality are invalid and
    yield gibberish trees, exactly as in the reference."""
    filtered = [i for i in ids_to_cut_yarns if tuple(i) != ROOT_ID]
    new_ct = new_causal_tree_fn()
    yarns = dict(new_ct.yarns)
    for nid in filtered:
        nid = tuple(nid)
        src_yarn = ct.yarns.get(nid[1], [])
        cut = []
        for n in src_yarn:
            if n[0] == nid:
                break
            cut.append(n)
        cut.append(node_from_kv((nid, ct.nodes[nid])))
        yarns[nid[1]] = cut
    new_ct = new_ct.evolve(
        yarns=yarns,
        site_id=ct.site_id,
        lamport_ts=max((i[0] for i in filtered), default=0),
        weaver=ct.weaver,
        lazy_weave=ct.lazy_weave,
    )
    new_ct = yarns_to_nodes(new_ct)
    return weave_fn(new_ct)


def check_mergeable(ct1: CausalTree, ct2: CausalTree) -> None:
    """Merge guards shared by the pure and device merge paths: type and
    uuid must match (shared.cljc:303-311)."""
    if ct1.type != ct2.type:
        raise CausalError(
            "Causal type missmatch. Merge not allowed.",
            {"causes": {"type-missmatch"}, "types": [ct1.type, ct2.type]},
        )
    if ct1.uuid != ct2.uuid:
        raise CausalError(
            "Causal UUID missmatch. Merge not allowed.",
            {"causes": {"uuid-missmatch"}, "uuids": [ct1.uuid, ct2.uuid]},
        )


def check_no_conflicting_bodies(nodes: dict, other: dict) -> None:
    """The append-only union validation every merge path shares: a
    duplicate id whose body differs raises, reporting the body already
    in ``nodes`` (the merge target's side). C-speed on the common case
    via the set-algebra membership test."""
    common = nodes.keys() & other.keys()
    for nid in common:
        if nodes[nid] != other[nid]:
            raise CausalError(
                "This node is already in the tree and can't be changed.",
                {"causes": {"append-only", "edits-not-allowed"},
                 "existing_node": (nid,) + nodes[nid]},
            )


def union_nodes(ct1: CausalTree, ct2: CausalTree) -> CausalTree:
    """The host half of every accelerated merge: guard, union the node
    stores (append-only conflict check, as in ``insert``), fast-forward
    the lamport clock, and respin the yarns. The caller reweaves with
    its backend. Shared by the jax and native merge paths."""
    return union_nodes_many((ct1, ct2))


def union_nodes_many(cts) -> CausalTree:
    """N-way ``union_nodes``: one guard+union pass over a whole fleet of
    replicas, one respin. The weave being a pure function of the node
    set makes this equal to any fold of pairwise merges — including the
    validations: foreign nodes new to the union must have their
    id-shaped cause somewhere in it (insert's cause-must-exist check,
    shared.cljc:175-178; duplicates skip validation there too)."""
    cts = list(cts)
    if not cts:
        raise CausalError("Nothing to merge.", {"causes": {"empty-fleet"}})
    first = cts[0]
    nodes = dict(first.nodes)
    max_new_ts = first.lamport_ts
    added = []
    for ct in cts[1:]:
        check_mergeable(first, ct)
        other = ct.nodes
        # set-algebra split (C speed) instead of a per-node branch
        common = nodes.keys() & other.keys()
        for nid in common:
            if nodes[nid] != other[nid]:
                raise CausalError(
                    "This node is already in the tree and can't be changed.",
                    {"causes": {"append-only", "edits-not-allowed"},
                     "existing_node": (nid,) + nodes[nid]},
                )
        new_ids = other.keys() - nodes.keys()
        nodes.update((nid, other[nid]) for nid in new_ids)
        added.extend(new_ids)
    if added:
        ts_high = max(nid[0] for nid in added)
        if ts_high > max_new_ts:
            max_new_ts = ts_high
    for nid in added:
        cause = nodes[nid][0]
        if not is_key(cause) and cause not in nodes:
            raise CausalError(
                "The cause of this node is not in the tree.",
                {"causes": {"cause-must-exist"}, "node": (nid,) + nodes[nid]},
            )
    ct = first.evolve(nodes=nodes, lamport_ts=max_new_ts)
    return spin(ct)


def merge_trees(weave_fn: WeaveFn, ct1: CausalTree, ct2: CausalTree) -> CausalTree:
    """Merge two causal trees into one (shared.cljc:300-314).

    Same guards as the reference (type and uuid must match). Unlike the
    reference's arbitrary-order reduce-insert (which is O(n*m) and can
    trip the cause-must-exist check on unlucky iteration orders), we
    insert ct2's novel nodes in sorted id order — causes always sort
    before their effects, so the reduce is deterministic; the resulting
    tree is identical because a weave is a pure function of the node set.
    With ``weaver="jax"`` the merge is instead union + one batched
    device reweave (see cause_tpu.weaver.jaxw), the north-star path.
    """
    check_mergeable(ct1, ct2)
    for nid in sorted(ct2.nodes):
        ct1 = insert(weave_fn, ct1, node_from_kv((nid, ct2.nodes[nid])))
    return ct1


def causal_to_edn(value, opts: Optional[dict] = None):
    """Materialize a causal value to plain data; non-causal values pass
    through (shared.cljc:320-328). Polymorphic over anything exposing a
    ``causal_to_edn(opts)`` method (the CausalTo protocol,
    protocols.cljc:33-35) — collections, bases, and refs."""
    opts = opts or {}
    m = getattr(value, "causal_to_edn", None)
    if m is not None:
        return m(opts)
    return value
