"""CausalMap — a map CRDT: LWW-register per key with per-key mini-weaves.

Port of reference src/causal/collections/map.cljc. Each key owns a small
list-weave rooted at the sentinel; plain key-caused writes weave at the
root in recency order (newest first), so the first visible node is the
last-writer-wins value; id-caused nodes (hide/show of one specific
write) weave under that write, enabling undo by id (map.cljc:21-45).
"""

from __future__ import annotations

from typing import Optional

from ..ids import (
    HIDE,
    H_HIDE,
    ROOT_ID,
    ROOT_NODE,
    is_id,
    is_special,
    new_site_id,
    new_uid,
    node_from_kv,
)
from ..weaver import pure
from . import shared as s
from .shared import CausalTree

__all__ = [
    "new_causal_tree",
    "weave",
    "BLANK",
    "active_node",
    "CausalMap",
    "new_causal_map",
]

# sentinel returned by active_node when a key's value is hidden
BLANK = object()


_MISSING = object()


def _update_in(container, path, f, args):
    """The one ``update_in`` recursion, over CausalMap-likes (anything
    with ``get``/``assoc``) and plain dicts. A missing or
    non-associative intermediate raises a CausalError naming the
    offending segment. Mirrors ``get_in``'s presence semantics: a
    dict key explicitly holding None is present (just not associative);
    a CausalMap register holding None is indistinguishable from absent."""
    k = path[0]
    is_cmap = hasattr(container, "assoc")
    if len(path) == 1:
        new_v = f(container.get(k), *args)
        return container.assoc(k, new_v) if is_cmap else {**container, k: new_v}
    inner = container.get(k) if is_cmap else container.get(k, _MISSING)
    missing = inner is None if is_cmap else inner is _MISSING
    if missing:
        raise s.CausalError(
            "update_in: missing intermediate key.",
            {"causes": {"missing-path-segment"}, "key": k,
             "path": list(path)},
        )
    if not hasattr(inner, "assoc") and not isinstance(inner, dict):
        raise s.CausalError(
            "update_in: intermediate value is not associative.",
            {"causes": {"not-associative"}, "key": k,
             "value_type": type(inner).__name__},
        )
    new_inner = _update_in(inner, path[1:], f, args)
    return (container.assoc(k, new_inner) if is_cmap
            else {**container, k: new_inner})


def new_causal_tree(weaver: str = "pure") -> CausalTree:
    """A fresh map tree; the weave is a dict of key -> list-weave
    (map.cljc:12-19)."""
    return CausalTree(
        type=s.MAP_TYPE,
        lamport_ts=0,
        uuid=new_uid(),
        site_id=new_site_id(),
        nodes={},
        yarns={},
        weave={},
        weaver=weaver,
    )


def weave(ct: CausalTree, node=None, more_nodes=None) -> CausalTree:
    """The map weave function (map.cljc:21-45).

    An id-caused node resolves to its cause's key and weaves under the
    cause inside that key's weave; a key-caused node weaves at the root
    of its key's weave (so plain writes order by recency). Full rebuild
    folds all nodes in sorted id order.
    """
    if node is None:
        if ct.weaver == "native":
            from ..weaver import nativew

            return nativew.refresh_map_weave(ct)
        if ct.weaver == "jax":
            from ..weaver import jaxw

            return jaxw.refresh_map_weave(ct)
        ct = ct.evolve(weave={})
        for nid in sorted(ct.nodes):
            ct = weave(ct, node_from_kv((nid, ct.nodes[nid])))
        return ct
    nid, cause, v = node
    cause_is_id = is_id(cause)
    if cause_is_id:
        key = ct.nodes.get(cause, (None, None))[0]
        cause_in_weave = cause
    else:
        key = cause
        cause_in_weave = ROOT_ID  # non-id causes weave to the root
    if nid not in ct.nodes:
        return ct
    key_weave = ct.weave.get(key) or [ROOT_NODE]
    key_weave = pure.weave_node(key_weave, (nid, cause_in_weave, v))
    new_weave = dict(ct.weave)
    new_weave[key] = key_weave
    ct = ct.evolve(weave=new_weave)
    if more_nodes:
        return weave(ct, more_nodes[0], list(more_nodes[1:]) or None)
    return ct


def active_node(k, weave_for_key):
    """The active node for one key's weave, or BLANK when hidden
    (map.cljc:47-59). First visible non-root, non-special node whose
    successor is not a hide — i.e. the LWW winner."""
    if not weave_for_key:
        return BLANK
    first_v = weave_for_key[1][2] if len(weave_for_key) > 1 else None
    if first_v is HIDE or first_v is H_HIDE:
        return BLANK
    n_w = len(weave_for_key)
    for i, n in enumerate(weave_for_key):
        nid, _, v = n
        nr_v = weave_for_key[i + 1][2] if i + 1 < n_w else None
        if nid == ROOT_ID:
            continue
        if is_special(v):
            continue
        if nr_v is HIDE or nr_v is H_HIDE:
            continue
        return (nid, k, v)
    return BLANK


def get_(ct: CausalTree, k):
    """Current value at key, or None (map.cljc:61-66)."""
    node = active_node(k, ct.weave.get(k))
    if node is BLANK:
        return None
    return node[2]


def count_(ct: CausalTree) -> int:
    """Number of keys with a visible value (map.cljc:68-73)."""
    return sum(
        1 for k, w in ct.weave.items() if active_node(k, w) is not BLANK
    )


def assoc_(ct: CausalTree, k, v, *kvs) -> CausalTree:
    """Set a key (skips writing an equal value twice, map.cljc:75-81)."""
    if v != get_(ct, k):
        ct = s.append(weave, ct, k, v)
    if kvs:
        return assoc_(ct, *kvs)
    return ct


def dissoc_(ct: CausalTree, k, *ks) -> CausalTree:
    """Hide a key (only keys with a truthy current value, matching the
    reference's nil/false-punning guard, map.cljc:83-89)."""
    cur = get_(ct, k)
    if cur is not None and cur is not False:
        ct = s.append(weave, ct, k, HIDE)
    if ks:
        return dissoc_(ct, *ks)
    return ct


def empty_(ct: CausalTree) -> CausalTree:
    """A fresh tree preserving identity (map.cljc:91-92)."""
    return new_causal_tree(ct.weaver).evolve(site_id=ct.site_id, uuid=ct.uuid)


def causal_map_to_edn(ct: CausalTree, opts: Optional[dict] = None) -> dict:
    """Materialize the current state as a plain dict (map.cljc:94-103)."""
    out = {}
    for k, w in ct.weave.items():
        node = active_node(k, w)
        if node is not BLANK:
            out[node[1]] = s.causal_to_edn(node[2], opts)
    return out


def causal_map_to_list(ct: CausalTree) -> list:
    """The active nodes, newest key first — the reference's reduce-kv
    conj onto a list reverses weave order (map.cljc:105-109)."""
    out = []
    for k, w in ct.weave.items():
        node = active_node(k, w)
        if node is not BLANK:
            out.append(node)
    out.reverse()
    return out


class CausalMap:
    """Immutable CausalMap handle (map.cljc:111-260).

    ``len`` counts visible keys; iteration yields the active *nodes*
    (newest first); ``cm[k]`` / ``cm.get(k)`` return current values.
    """

    __slots__ = ("ct",)

    def __init__(self, ct: CausalTree):
        object.__setattr__(self, "ct", ct)

    def __setattr__(self, *a):
        raise AttributeError("CausalMap is immutable")

    # -- CausalMeta --
    def get_uuid(self) -> str:
        return self.ct.uuid

    def get_ts(self) -> int:
        return self.ct.lamport_ts

    def get_site_id(self) -> str:
        return self.ct.site_id

    # -- CausalTree protocol --
    def get_weave(self):
        return self.ct.weave

    def get_nodes(self):
        return self.ct.nodes

    def insert(self, node, more_nodes=None) -> "CausalMap":
        return CausalMap(s.insert(weave, self.ct, node, more_nodes))

    def append(self, cause, value) -> "CausalMap":
        return CausalMap(s.append(weave, self.ct, cause, value))

    def weft(self, ids_to_cut_yarns) -> "CausalMap":
        return CausalMap(
            s.weft(weave, lambda: new_causal_tree(self.ct.weaver), self.ct,
                   ids_to_cut_yarns)
        )

    def merge(self, other: "CausalMap") -> "CausalMap":
        if self.ct.weaver == "jax":
            from ..weaver import jaxw

            return CausalMap(jaxw.merge_map_trees(self.ct, other.ct))
        if self.ct.weaver == "native":
            from ..weaver import nativew

            return CausalMap(nativew.merge_trees(self.ct, other.ct))
        return CausalMap(s.merge_trees(weave, self.ct, other.ct))

    def merge_many(self, others) -> "CausalMap":
        """Converge a whole fleet in one pass: N-way node union + one
        full reweave (equals any fold of pairwise merges)."""
        ct = s.union_nodes_many([self.ct] + [o.ct for o in others])
        return CausalMap(weave(ct))

    # -- CausalTo --
    def causal_to_edn(self, opts: Optional[dict] = None) -> dict:
        return causal_map_to_edn(self.ct, opts)

    # -- Python container interop (map.cljc:111-216) --
    def assoc(self, k, v, *kvs) -> "CausalMap":
        return CausalMap(assoc_(self.ct, k, v, *kvs))

    def dissoc(self, k, *ks) -> "CausalMap":
        return CausalMap(dissoc_(self.ct, k, *ks))

    def conj(self, mapping) -> "CausalMap":
        kvs = []
        for k, v in dict(mapping).items():
            kvs.extend((k, v))
        return CausalMap(assoc_(self.ct, *kvs)) if kvs else self

    def empty(self) -> "CausalMap":
        return CausalMap(empty_(self.ct))

    def get(self, k, not_found=None):
        v = get_(self.ct, k)
        return not_found if v is None else v

    def __getitem__(self, k):
        return get_(self.ct, k)

    def __contains__(self, k) -> bool:
        return get_(self.ct, k) is not None

    def __len__(self) -> int:
        return count_(self.ct)

    def __iter__(self):
        return iter(causal_map_to_list(self.ct))

    def keys(self):
        return causal_map_to_edn(self.ct).keys()

    def values(self):
        return causal_map_to_edn(self.ct).values()

    def items(self):
        return causal_map_to_edn(self.ct).items()

    _MISSING = _MISSING

    def get_in(self, path, not_found=None):
        """Walk ``path`` through nested gettable values — CausalMaps,
        plain dicts, and sequences indexed by int (Clojure ``get-in``
        over associative values; exercised at map_test.cljc:56-61).
        A plain-dict key explicitly holding None is *present* (returned
        as None); a CausalMap register holding None is indistinguishable
        from an absent key — the ``get``/``active_node`` contract."""
        cur = self
        for k in path:
            if isinstance(cur, dict):
                cur = cur.get(k, CausalMap._MISSING)
                if cur is CausalMap._MISSING:
                    return not_found
            elif hasattr(cur, "get"):
                cur = cur.get(k)
                if cur is None:
                    return not_found
            elif (isinstance(cur, (list, tuple)) and isinstance(k, int)
                  and 0 <= k < len(cur)):
                cur = cur[k]
            else:
                return not_found
        return cur

    def update(self, k, f, *args) -> "CausalMap":
        """Assoc ``f(current, *args)`` at ``k`` (Clojure ``update``)."""
        return self.assoc(k, f(self.get(k), *args))

    def update_in(self, path, f, *args) -> "CausalMap":
        """Apply ``f`` at a nested path (Clojure ``update-in``).
        Intermediates may be CausalMaps or plain dicts; a missing
        intermediate raises a CausalError naming the absent segment
        (rather than Clojure's silent nil->map auto-create, which would
        mint an un-caused collection inside a CRDT)."""
        path = list(path)
        if not path:
            raise ValueError("update_in: empty path")
        return _update_in(self, path, f, args)

    def reduce_kv(self, f, init):
        """Fold ``f(acc, k, v)`` over the rendered map — the IKVReduce
        analogue, which the reference also defines over the
        materialized EDN (map.cljc:141-143)."""
        acc = init
        for k, v in causal_map_to_edn(self.ct).items():
            acc = f(acc, k, v)
        return acc

    # -- IObj/IMeta analogue (map.cljc:159-163) --
    def with_meta(self, m) -> "CausalMap":
        return CausalMap(self.ct.evolve(meta=m))

    def meta(self):
        return self.ct.meta

    def __eq__(self, other) -> bool:
        return isinstance(other, CausalMap) and self.ct == other.ct

    def __hash__(self) -> int:
        return hash((self.ct.uuid, self.ct.lamport_ts, self.ct.site_id,
                     tuple(sorted(self.ct.nodes))))

    def __repr__(self) -> str:
        return f"#causal/map {causal_map_to_edn(self.ct)!r}"

    def __str__(self) -> str:
        return str(causal_map_to_edn(self.ct))


def new_causal_map(*kvs, weaver: str = "pure", **kwargs) -> CausalMap:
    """Create a new causal map from alternating keys and values and/or
    keyword arguments (map.cljc:256-260)."""
    cm = CausalMap(new_causal_tree(weaver))
    pairs = list(kvs)
    for k, v in kwargs.items():
        pairs.extend((k, v))
    if pairs:
        cm = cm.assoc(*pairs)
    return cm
