"""Causal collection types: the shared causal-tree core plus the
CausalList and CausalMap types (reference: src/causal/collections/)
and the CausalSet / CausalCounter types the reference's roadmap
wished for (README.md:249-250)."""

from . import shared  # noqa: F401
