"""Causal collection types: the shared causal-tree core plus the
CausalList and CausalMap types (reference: src/causal/collections/)."""

from . import shared  # noqa: F401
