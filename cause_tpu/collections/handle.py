"""Shared handle machinery for list-shaped causal collections.

``CausalList``, ``CausalSet``, and ``CausalCounter`` are all handles
over the same list-tree core (reference: the deftype protocol surface,
list.cljc:74-178) — same metadata accessors, same insert/append/weft
plumbing, and the same three-way pure/native/jax merge dispatch. That
dispatch is exactly the code that must never diverge between
collection types (a backend added to one and not the others would
silently change merge complexity), so it lives here once and each
concrete class contributes only its rendering and its type-specific
interop.
"""

from __future__ import annotations

from . import shared as _s

__all__ = ["ListTreeHandle"]


class ListTreeHandle:
    """Mixin for immutable handles over a list-shaped causal tree.

    Concrete classes define ``__slots__ = ("ct",)``, a ``_fresh``
    staticmethod returning an empty tree of their type (same weaver),
    and their own rendering/interop. Every method here returns
    ``type(self)(...)`` so subclasses stay closed under the shared
    operations.
    """

    __slots__ = ()

    def __init__(self, ct):
        object.__setattr__(self, "ct", ct)

    def __setattr__(self, *a):
        raise AttributeError(f"{type(self).__name__} is immutable")

    @staticmethod
    def _fresh(weaver: str):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- CausalMeta (protocols.cljc:3-10) --
    def get_uuid(self) -> str:
        return self.ct.uuid

    def get_ts(self) -> int:
        return self.ct.lamport_ts

    def get_site_id(self) -> str:
        return self.ct.site_id

    @staticmethod
    def _weave_fn():
        # lazy: clist imports this module while defining CausalList
        from . import clist as _c_list

        return _c_list.weave

    # -- CausalTree protocol (protocols.cljc:12-31) --
    def get_weave(self):
        return _s.ensure_weave(self._weave_fn(), self.ct).weave

    def get_nodes(self):
        return self.ct.nodes

    def insert(self, node, more_nodes=None):
        return type(self)(
            _s.insert(self._weave_fn(), self.ct, node, more_nodes)
        )

    def append(self, cause, value):
        return type(self)(_s.append(self._weave_fn(), self.ct, cause, value))

    def weft(self, ids_to_cut_yarns):
        return type(self)(
            _s.weft(self._weave_fn(),
                    lambda: self._fresh(self.ct.weaver),
                    self.ct, ids_to_cut_yarns)
        )

    def merge(self, other):
        if self.ct.weaver == "jax":
            from ..weaver import jaxw

            return type(self)(jaxw.merge_list_trees(self.ct, other.ct))
        if self.ct.weaver == "native":
            from ..weaver import nativew

            return type(self)(nativew.merge_trees(self.ct, other.ct))
        return type(self)(_s.merge_trees(self._weave_fn(), self.ct, other.ct))

    def merge_many(self, others):
        """Converge a whole fleet in one pass: N-way node union + one
        full reweave (the weave is a pure function of the node set, so
        this equals any fold of pairwise merges). No reference
        analogue — the reference folds pairwise (shared.cljc:300-314).
        Under ``weaver="jax"`` the union, validations and reweave are
        all set-algebra/vectorized/device work — no per-node Python
        loop."""
        if self.ct.weaver == "jax":
            from ..weaver import jaxw

            return type(self)(
                jaxw.merge_many_list_trees(
                    [self.ct] + [o.ct for o in others]
                )
            )
        ct = _s.union_nodes_many([self.ct] + [o.ct for o in others])
        return type(self)(self._weave_fn()(ct))

    # -- IObj/IMeta analogue (list.cljc:97-101) --
    def with_meta(self, m):
        return type(self)(self.ct.evolve(meta=m))

    def meta(self):
        return self.ct.meta

    def __eq__(self, other) -> bool:
        if not isinstance(other, type(self)):
            return False
        a, b = self.ct, other.ct
        # cheap fields first, so a trivially-unequal compare (membership
        # tests, different uuids) never pays a stale-weave
        # materialization
        if (a.type, a.lamport_ts, a.uuid, a.site_id, a.weaver,
                a.nodes, a.yarns) != (
                b.type, b.lamport_ts, b.uuid, b.site_id, b.weaver,
                b.nodes, b.yarns):
            return False
        # everything canonical matches; a lazy handle equals its eager
        # twin, so materialize any stale weave before the final compare
        for ct_ in (a, b):
            if ct_.weave is None:
                _s.ensure_weave(self._weave_fn(), ct_)
        return a.weave == b.weave

    def __hash__(self) -> int:
        return hash((self.ct.uuid, self.ct.lamport_ts, self.ct.site_id,
                     tuple(sorted(self.ct.nodes))))
