"""CausalList — a sequence CRDT (RGA-style causal tree).

Port of reference src/causal/collections/list.cljc: causes are
predecessor ids, the weave is a flat list of nodes, and rendering skips
specials, tombstoned nodes and the root. Python container protocols
mirror the reference's Clojure interop: ``len`` counts *active values*
(list.cljc:76-77) while iteration yields the visible *nodes* themselves
(list.cljc:94-95) — "seq returns nodes, count counts values".
"""

from __future__ import annotations

from typing import Optional

from ..ids import (
    HIDE,
    H_HIDE,
    ROOT_ID,
    ROOT_NODE,
    is_special,
    new_site_id,
    new_uid,
    node_from_kv,
)
from ..weaver import pure
from . import shared as s
from .handle import ListTreeHandle
from .shared import CausalTree

__all__ = [
    "new_causal_tree",
    "weave",
    "extend_",
    "hide_q",
    "causal_list_to_edn",
    "causal_list_to_list",
    "CausalList",
    "new_causal_list",
]


def new_causal_tree(weaver: str = "pure", lazy: bool = False) -> CausalTree:
    """A fresh list tree seeded with the root sentinel in all three
    stores (list.cljc:11-18). ``lazy`` defers the weave cache to first
    read (shared.ensure_weave) — the fleet-editing mode."""
    return CausalTree(
        type=s.LIST_TYPE,
        lamport_ts=0,
        uuid=new_uid(),
        site_id=new_site_id(),
        nodes={ROOT_ID: (None, None)},
        yarns={"0": [ROOT_NODE]},
        weave=[ROOT_NODE],
        weaver=weaver,
        lazy_weave=lazy,
    )


def weave(ct: CausalTree, node=None, more_consecutive_nodes_in_same_tx=None) -> CausalTree:
    """The list weave function (list.cljc:20-34).

    Full rebuild (no node): fold every node, in sorted id order, through
    the sequential weave — O(n^2) on the host, or one batched device
    linearization when the tree's weaver is "jax". Incremental (node
    given): O(n) single scan; a run of same-tx nodes is spliced in the
    same pass.
    """
    if node is None:
        if ct.weaver == "jax":
            from ..weaver import jaxw

            return jaxw.refresh_list_weave(ct)
        if ct.weaver == "native":
            from ..weaver import nativew

            return nativew.refresh_list_weave(ct)
        w = []
        for nid in sorted(ct.nodes):
            w = pure.weave_node(w, node_from_kv((nid, ct.nodes[nid])))
        return ct.evolve(weave=w)
    if node[0] not in ct.nodes:
        return ct
    return ct.evolve(
        weave=pure.weave_node(ct.weave, node, more_consecutive_nodes_in_same_tx)
    )


def _tail_id(ct: CausalTree):
    """Id of the last weave node — from the lazy tail hint when it is
    alive (no weave needed), else from the (materialized) weave."""
    if ct.weave is None and ct.weave_tail is not None:
        return ct.weave_tail
    return s.ensure_weave(weave, ct).weave[-1][0]


def conj_(ct: CausalTree, *values) -> CausalTree:
    """Append value(s) after the last node of the current weave
    (list.cljc:36-40)."""
    for v in values:
        ct = s.append(weave, ct, _tail_id(ct), v)
    return ct


def cons_(v, ct: CausalTree) -> CausalTree:
    """Insert a value at the front (cause = root, list.cljc:42-43)."""
    return s.append(weave, ct, ROOT_ID, v)


# one transaction holds 2^13 nodes (tx-indices 0..8191, PackSpec.tx_bits);
# longer pastes split into several transactions
MAX_TX_RUN = 1 << 13


def extend_(ct: CausalTree, values) -> CausalTree:
    """Append many values as contiguous transaction runs: one lamport
    tick per run, tx-index ordering within it, one O(n+m) weave splice
    (the paste path — reference README.md:50,229, list.cljc:23-25 —
    where per-value conj would cost O(n*m))."""
    values = list(values)
    while values:
        chunk, values = values[:MAX_TX_RUN], values[MAX_TX_RUN:]
        cause = _tail_id(ct)
        ct = ct.evolve(lamport_ts=ct.lamport_ts + 1)
        nodes = []
        for i, v in enumerate(chunk):
            nid = (ct.lamport_ts, ct.site_id, i)
            nodes.append((nid, cause, v))
            cause = nid
        ct = s.insert(weave, ct, nodes[0], nodes[1:] or None)
    return ct


def empty_(ct: CausalTree) -> CausalTree:
    """A fresh tree preserving identity (site-id, uuid, weaver, lazy
    mode) (list.cljc:45-46)."""
    return new_causal_tree(ct.weaver, lazy=ct.lazy_weave).evolve(
        site_id=ct.site_id, uuid=ct.uuid)


def hide_q(node, next_node_in_weave) -> bool:
    """Is this node hidden when the weave is rendered? (list.cljc:48-55)
    Hidden iff it is a special, or the next weave node is a hide/h.hide
    targeting it, or it is the root."""
    if is_special(node[2]):
        return True
    nr = next_node_in_weave
    if nr is not None and (nr[2] is HIDE or nr[2] is H_HIDE) and node[0] == nr[1]:
        return True
    return node == ROOT_NODE


def causal_list_to_edn(ct: CausalTree, opts: Optional[dict] = None) -> list:
    """Materialize the current state as a plain list (list.cljc:57-66):
    pairwise scan over the weave keeping visible values."""
    w = s.ensure_weave(weave, ct).weave
    out = []
    for i, n in enumerate(w):
        nr = w[i + 1] if i + 1 < len(w) else None
        if not hide_q(n, nr):
            out.append(s.causal_to_edn(n[2], opts))
    return out


def causal_list_to_list(ct: CausalTree) -> list:
    """The visible *nodes* in weave order (list.cljc:68-72)."""
    w = s.ensure_weave(weave, ct).weave
    out = []
    for i, n in enumerate(w):
        nr = w[i + 1] if i + 1 < len(w) else None
        if not hide_q(n, nr):
            out.append(n)
    return out


class CausalList(ListTreeHandle):
    """Immutable CausalList handle (list.cljc:74-178).

    ``len`` counts active values; iteration yields visible nodes.
    All mutating-looking methods return a new CausalList. The shared
    protocol surface (metadata, insert/append/weft, pure/native/jax
    merge dispatch) lives on ``ListTreeHandle``.
    """

    __slots__ = ("ct",)

    _fresh = staticmethod(new_causal_tree)

    # -- CausalTo (protocols.cljc:33-35) --
    def causal_to_edn(self, opts: Optional[dict] = None) -> list:
        return causal_list_to_edn(self.ct, opts)

    def tail_id(self):
        """Id of the last weave node — what ``conj`` will cause. On a
        lazy tree with a live tail hint this is O(1), no weave needed."""
        return _tail_id(self.ct)

    # -- Python container interop (mirrors list.cljc:74-135) --
    def conj(self, *values) -> "CausalList":
        return CausalList(conj_(self.ct, *values))

    def cons(self, value) -> "CausalList":
        return CausalList(cons_(value, self.ct))

    def extend(self, values) -> "CausalList":
        """Append many values as one transaction run per 8k chunk —
        O(n+m) instead of conj's O(n*m)."""
        return CausalList(extend_(self.ct, values))

    def empty(self) -> "CausalList":
        return CausalList(empty_(self.ct))

    def __len__(self) -> int:
        return len(causal_list_to_edn(self.ct))

    def __iter__(self):
        return iter(causal_list_to_list(self.ct))

    def __getitem__(self, i):
        """Visible node(s) by weave position — the indexed view of the
        same sequence iteration yields (nodes, not values; the
        reference's seq/nth contract, list.cljc:94-95). Negative
        indices and slices follow Python list semantics.

        Each indexed access materializes the visible-node list (O(n));
        for bulk access iterate once (``list(cl)``) or render once
        (``causal_to_edn``) instead of indexing in a loop."""
        return causal_list_to_list(self.ct)[i]

    def nth(self, i, *default):
        """Node at position ``i``, or ``default`` when out of range
        (Clojure ``nth``'s 3-arity — negative indices are out of range,
        as in Clojure; use ``cl[i]`` for Python negative indexing)."""
        nodes = causal_list_to_list(self.ct)
        if 0 <= i < len(nodes):
            return nodes[i]
        if default:
            return default[0]
        raise IndexError(f"nth: index {i} out of range for {len(nodes)}")

    def get(self, i, not_found=None):
        """Rendered *value* at position ``i`` (``get`` on a Clojure
        sequential: the materialized element, not the node)."""
        vals = causal_list_to_edn(self.ct)
        if isinstance(i, int) and -len(vals) <= i < len(vals):
            return vals[i]
        return not_found

    def __repr__(self) -> str:
        return f"#causal/list {causal_list_to_edn(self.ct)!r}"

    def __str__(self) -> str:
        return str(causal_list_to_list(self.ct))


def new_causal_list(*items, weaver: str = "pure",
                    lazy: bool = False) -> CausalList:
    """Create a new causal list containing the items (list.cljc:175-178).
    ``lazy=True`` defers weave maintenance to first read — the editing
    mode for device-backed fleet replicas (shared.CausalTree.lazy_weave)."""
    cl = CausalList(new_causal_tree(weaver, lazy=lazy))
    if items:
        cl = cl.conj(*items)
    return cl
