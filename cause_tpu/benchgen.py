"""Synthetic divergent-replica generators for benchmarks and dry runs.

Builds the *lane-level* inputs of the batched merge kernel
(``weaver.jaxw.merge_weave_kernel``) directly as numpy arrays — the
north-star benchmark merges 1024 replica pairs of 10k-node CausalLists
(BASELINE.json config 5), and minting 20M nodes through the host CRDT
API would measure Python, not the TPU. The generated lanes are exactly
what ``NodeArrays`` would produce for real trees of the same shape
(fuzz-verified in tests/test_benchgen.py):

- a shared **base chain**: an append-only run of ``n_base`` nodes from
  one site (ids ``(i, base_site, 0)`` causing their predecessor — what
  ``clist.conj`` mints, reference: list.cljc:36-40);
- per replica pair, two **divergent suffixes** of ``n_div`` nodes from
  two fresh sites, each continuing the chain from the base tail, with
  every ``hide_every``-th suffix node a ``hide`` tombstone targeting
  its predecessor (reference tombstone semantics, list.cljc:48-55).

Site-id strings never exist here: sites are materialized directly as
order-preserving ranks (root "0" < base < suffix-A < suffix-B), the
same contract ``SiteInterner`` enforces for real trees.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .weaver.arrays import (
    DEFAULT_PACK,
    I32_MAX,
    PackSpec,
    VCLASS_HIDE,
)

__all__ = [
    "chain_tree_lanes",
    "divergent_pair_lanes",
    "batched_pair_lanes",
    "merge_wave_scalar",
    "LANE_KEYS",
]

LANE_KEYS = ("hi", "lo", "chi", "clo", "vc", "valid")

def pair_run_budget(n_div: int) -> int:
    """Chain-contracted run count bound for one ``divergent_pair_lanes``
    merge. The base chain compresses to one run, but the two suffixes
    interleave in id order (same ts range, different sites), so no
    suffix node is kept-lane-adjacent to its cause and every suffix
    node is its own run: runs ~= 2*n_div + small constants. Measured:
    201 runs for n_div=100."""
    return 2 * n_div + 64


_scalar_programs: Dict = {}


def merge_wave_scalar(*args, k_max: int = 0):
    """The shared timed program of the merge benchmarks (bench.py and
    the CLI's config 5): the full batched merge+weave reduced to one
    checksum scalar, because on the axon-tunneled TPU
    ``jax.block_until_ready`` does not actually block and a 4-byte
    device->host transfer is the only reliable sync point.

    ``k_max`` > 0 selects the chain-compressed kernel with that run
    budget and returns a length-2 device array ``[checksum,
    n_overflowed_rows]`` (one transfer fetches both); the default 0
    runs the uncompressed kernel and returns just the checksum.
    """
    program = _scalar_programs.get(k_max)
    if program is None:
        import jax
        import jax.numpy as jnp

        from .weaver.jaxw import batched_merge_weave_v2, merge_weave_kernel

        def _checksum(order, rank, visible, conflict):
            return (
                jnp.sum(rank.astype(jnp.float32))
                + jnp.sum(order.astype(jnp.float32))
                + jnp.sum(visible.astype(jnp.float32))
                + jnp.sum(conflict.astype(jnp.float32))
            )

        if k_max > 0:
            @jax.jit
            def program(*a):
                order, rank, visible, conflict, overflow = (
                    batched_merge_weave_v2(*a, k_max=k_max)
                )
                return jnp.stack([
                    _checksum(order, rank, visible, conflict),
                    jnp.sum(overflow.astype(jnp.float32)),
                ])
        else:
            @jax.jit
            def program(*a):
                return _checksum(*jax.vmap(merge_weave_kernel)(*a))

        _scalar_programs[k_max] = program
    return program(*args)

# synthetic site ranks (order-preserving: "0" sorts first, suffix sites
# are minted after and sort above the base site by construction)
SITE_ROOT = 0
SITE_BASE = 1
SITE_A = 2
SITE_B = 3


def chain_tree_lanes(
    n_base: int,
    n_div: int,
    suffix_site: int,
    capacity: int,
    hide_every: int = 0,
    spec: PackSpec = DEFAULT_PACK,
) -> Dict[str, np.ndarray]:
    """Lanes for ONE tree: root + base chain + one divergent suffix.

    Lanes come out in sorted id order (ts is strictly increasing along
    the chain), root at lane 0 — the ``NodeArrays.from_nodes_map``
    layout. Returns hi/lo (id lanes), chi/clo (cause id lanes), vc,
    valid, each of length ``capacity``.
    """
    n = 1 + n_base + n_div
    if capacity < n:
        raise ValueError(f"capacity {capacity} < node count {n}")
    ts = np.zeros(n, np.int64)
    site = np.zeros(n, np.int64)
    vc = np.zeros(n, np.int32)

    # base chain: ts 1..n_base, all from SITE_BASE
    ts[1 : 1 + n_base] = np.arange(1, n_base + 1)
    site[1 : 1 + n_base] = SITE_BASE
    # divergent suffix: ts n_base+1 .., from suffix_site
    ts[1 + n_base :] = np.arange(n_base + 1, n_base + n_div + 1)
    site[1 + n_base :] = suffix_site

    # causes: chain — node i caused by node i-1 (root causes itself as
    # a placeholder; its cause lanes are (-1,-1) below)
    cts = np.concatenate([[0], ts[:-1]])
    csite = np.concatenate([[0], site[:-1]])

    if hide_every > 0:
        # every k-th suffix node is a hide targeting its predecessor
        j = np.arange(1, n_div + 1)
        is_hide = (j % hide_every) == 0
        vc[1 + n_base :][is_hide] = VCLASS_HIDE

    tx = np.zeros(n, np.int64)
    hi = np.full(capacity, I32_MAX, np.int32)
    lo = np.full(capacity, I32_MAX, np.int32)
    chi = np.full(capacity, -1, np.int32)
    clo = np.full(capacity, -1, np.int32)
    vcl = np.zeros(capacity, np.int32)
    valid = np.zeros(capacity, bool)

    hi[:n] = ts.astype(np.int32)
    lo[:n] = (site.astype(np.int32) << spec.tx_bits) | tx.astype(np.int32)[:n]
    chi[1:n] = cts[1:].astype(np.int32)
    clo[1:n] = (csite[1:].astype(np.int32) << spec.tx_bits)
    vcl[:n] = vc
    valid[:n] = True
    return {"hi": hi, "lo": lo, "chi": chi, "clo": clo, "vc": vcl, "valid": valid}


def divergent_pair_lanes(
    n_base: int,
    n_div: int,
    capacity: int,
    hide_every: int = 0,
) -> Dict[str, np.ndarray]:
    """Concatenated lanes ([2*capacity]) of one divergent replica pair —
    the per-replica input of ``merge_weave_kernel``."""
    a = chain_tree_lanes(n_base, n_div, SITE_A, capacity, hide_every)
    b = chain_tree_lanes(n_base, n_div, SITE_B, capacity, hide_every)
    return {k: np.concatenate([a[k], b[k]]) for k in a}


def batched_pair_lanes(
    n_replicas: int,
    n_base: int,
    n_div: int,
    capacity: int,
    hide_every: int = 0,
) -> Dict[str, np.ndarray]:
    """The [B, 2*capacity] batch for ``batched_merge_weave`` /
    ``sharded_merge_weave``: ``n_replicas`` divergent pairs. Rows are
    identical in structure (XLA's work per row does not depend on lane
    values), so the batch is a broadcast — cheap to build at B=1024."""
    row = divergent_pair_lanes(n_base, n_div, capacity, hide_every)
    return {
        k: np.broadcast_to(v, (n_replicas,) + v.shape).copy() for k, v in row.items()
    }
