"""Synthetic divergent-replica generators for benchmarks and dry runs.

Builds the *lane-level* inputs of the batched merge kernel
(``weaver.jaxw.merge_weave_kernel``) directly as numpy arrays — the
north-star benchmark merges 1024 replica pairs of 10k-node CausalLists
(BASELINE.json config 5), and minting 20M nodes through the host CRDT
API would measure Python, not the TPU. The generated lanes are exactly
what ``NodeArrays`` would produce for real trees of the same shape
(fuzz-verified in tests/test_benchgen.py):

- a shared **base chain**: an append-only run of ``n_base`` nodes from
  one site (ids ``(i, base_site, 0)`` causing their predecessor — what
  ``clist.conj`` mints, reference: list.cljc:36-40);
- per replica pair, two **divergent suffixes** of ``n_div`` nodes from
  two fresh sites, each continuing the chain from the base tail, with
  every ``hide_every``-th suffix node a ``hide`` tombstone targeting
  its predecessor (reference tombstone semantics, list.cljc:48-55).

Site-id strings never exist here: sites are materialized directly as
order-preserving ranks (root "0" < base < suffix-A < suffix-B), the
same contract ``SiteInterner`` enforces for real trees.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .weaver.arrays import (
    DEFAULT_PACK,
    I32_MAX,
    PackSpec,
    VCLASS_HIDE,
    next_pow2,
)

__all__ = [
    "chain_tree_lanes",
    "divergent_pair_lanes",
    "batched_pair_lanes",
    "delta_sweep_inputs",
    "fleet_lanes",
    "tree_fleet_handles",
    "estimate_pair_runs",
    "pair_run_budget",
    "merge_wave_scalar",
    "time_dispatch",
    "enable_compile_cache",
    "v5_inputs",
    "batched_v5_inputs",
    "v5_token_budget",
    "estimate_tokens",
    "LANE_KEYS",
    "LANE_KEYS4",
    "LANE_KEYS5",
]

LANE_KEYS = ("hi", "lo", "chi", "clo", "vc", "valid")
# the v4 kernel's lanes: cause ids are replaced by ``cci``, the cause's
# index in the concatenated pre-sort lane array (known at marshal time)
LANE_KEYS4 = ("hi", "lo", "cci", "vc", "valid")
# the v5 segment-union kernel: v4's node lanes + per-lane segment ids
# + the marshal-extracted segment tables (derived from
# segments.SEG_LANE_KEYS so the two can never drift)
from .weaver.segments import SEG_LANE_KEYS as _SEG_LANE_KEYS

LANE_KEYS5 = LANE_KEYS4 + ("seg",) + _SEG_LANE_KEYS

def _union_lanes_np(hi, lo, chi, clo, vc, valid):
    """Numpy twin of the merge kernel's front half (id lexsort, dup
    drop, sort-join cause resolution) — host-side, so run budgets can
    be derived from the real post-union lane structure before any
    device dispatch."""
    order = np.lexsort((lo, hi))
    hi_s, lo_s = hi[order], lo[order]
    dup = np.concatenate(
        [[False], (hi_s[1:] == hi_s[:-1]) & (lo_s[1:] == lo_s[:-1])]
    )
    keep = valid[order] & ~dup
    vc_s = vc[order]
    chi_s, clo_s = chi[order], clo[order]
    kept_idx = np.flatnonzero(keep)
    key = (
        (hi_s[kept_idx].astype(np.int64) << 32)
        | (lo_s[kept_idx].astype(np.int64) & 0xFFFFFFFF)
    )
    q = (
        (chi_s.astype(np.int64) << 32)
        | (clo_s.astype(np.int64) & 0xFFFFFFFF)
    )
    pos = np.searchsorted(key, q)
    pos_c = np.clip(pos, 0, max(0, len(key) - 1))
    found = (len(key) > 0) & (key[pos_c] == q)
    cause_idx = np.where(found, kept_idx[pos_c], -1).astype(np.int32)
    return cause_idx, vc_s, keep


def estimate_pair_runs(row: Dict[str, np.ndarray]) -> int:
    """Chain-contracted run count of one replica-pair merge, computed
    host-side: emulate the union front half in numpy, then run the same
    ``estimate_runs`` the API dispatch uses."""
    from .weaver.jaxw import estimate_runs

    cause_idx, vc_s, keep = _union_lanes_np(
        row["hi"], row["lo"], row["chi"], row["clo"], row["vc"], row["valid"]
    )
    return estimate_runs(cause_idx, vc_s, keep)


def pair_run_budget(batch: Dict[str, np.ndarray], sample_rows: int = 4) -> int:
    """Run budget for the compressed (v2) kernel, *derived* from the
    generated lanes instead of a shape-specific formula: the host run
    estimator on sampled rows (all of them for a single row dict), plus
    headroom for unsampled rows — the kernel's overflow flag still
    backstops an underestimate."""
    hi = batch["hi"]
    if hi.ndim == 1:
        rows = [batch]
    else:
        B = hi.shape[0]
        picks = sorted({0, B // 3, (2 * B) // 3, B - 1})[:sample_rows]
        rows = [{k: batch[k][i] for k in LANE_KEYS} for i in picks]
    worst = max(estimate_pair_runs(r) for r in rows)
    return int(worst + max(64, worst // 8))


def _default_cache_dir() -> str:
    """Per-user cache location: a fixed world-shared /tmp path collides
    across users and is pre-creatable by any local user; key it by uid
    (and honor XDG/home when available)."""
    import os as _os
    import tempfile as _tempfile

    home = _os.path.expanduser("~")
    if home and home != "~" and _os.access(home, _os.W_OK):
        return _os.path.join(home, ".cache", "cause_tpu",
                             "jax_comp_cache")
    uid = _os.getuid() if hasattr(_os, "getuid") else "u"
    return _os.path.join(_tempfile.gettempdir(),
                         f"jax_comp_cache_{uid}")


def enable_compile_cache(path: Optional[str] = None) -> None:
    """Point JAX's persistent compilation cache at a shared directory so
    the tens-of-seconds XLA compiles of the full-size kernels are paid
    once across bench.py, the probe scripts, and repeat invocations.

    TPU-class backends only: XLA:CPU AOT reloads are pinned to the
    compile machine's CPU features (reloading warns about SIGILL risk),
    and CPU compiles here are seconds, not minutes. Safe no-op on jax
    builds without the knob. NOTE: consults the default backend, so
    call it where backend initialization is already acceptable."""
    import os as _os

    import jax as _jax

    try:
        if _jax.default_backend() == "cpu":
            return
        _jax.config.update(
            "jax_compilation_cache_dir",
            _os.environ.get("JAX_COMPILATION_CACHE_DIR",
                            path or _default_cache_dir()),
        )
        _jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 5.0
        )
    except Exception:  # pragma: no cover - older jax
        pass


def time_dispatch(dispatch, reps: int, burst_n: int = 8,
                  begin=None, end=None):
    """bench.py's timing methodology as ONE helper, so every new
    measurement arm is methodology-identical by construction instead
    of a hand-copied loop: ``reps`` timed single dispatches (each
    synced by fetching the dispatch's return — the only reliable sync
    on the axon tunnel), then amortized ``burst_n``-wave bursts with
    ONE terminal sync — ``reps`` of them while the single p50 is
    under a second, one otherwise (at that point the dispatch floor
    is noise and repeated bursts only burn window time). ``begin``/
    ``end`` bracket each timed single (the cost-model wave window);
    bursts are deliberately un-bracketed — a burst is not one wave.
    Returns ``(singles_ms, bursts_ms)``."""
    import time as _time

    singles = []
    for _ in range(reps):
        if begin is not None:
            begin()
        t0 = _time.perf_counter()
        np.asarray(dispatch())
        ms = (_time.perf_counter() - t0) * 1000.0
        singles.append(ms)
        if end is not None:
            end()
    bursts = []
    burst_reps = (reps if float(np.median(singles)) < 1000.0 else 1)
    for _ in range(burst_reps):
        t0 = _time.perf_counter()
        out = None
        for _ in range(burst_n):
            out = dispatch()
        np.asarray(out)
        bursts.append((_time.perf_counter() - t0) * 1000.0 / burst_n)
    return singles, bursts


_scalar_programs: Dict = {}


def merge_wave_scalar(*args, k_max: int = 0, kernel: str = "v2",
                      u_max: int = 0):
    """The shared timed program of the merge benchmarks (bench.py and
    the CLI's config 5): the full batched merge+weave reduced to one
    checksum scalar, because on the axon-tunneled TPU
    ``jax.block_until_ready`` does not actually block and a 4-byte
    device->host transfer is the only reliable sync point.

    ``k_max`` > 0 selects a compressed kernel — ``kernel`` picks which
    ("v2" chain-compressed, "v3" sparse-irregular, "v4"
    marshal-resolved causes, "v4w" = v4 with the sequential Pallas
    euler walk, "v5" segment-union with token budget ``u_max``,
    "v5w" = v5 with the Pallas euler walk, "v5f" = v5 with the whole
    token pipeline fused into Pallas kernels — jaxw5f) — with
    that run budget, returning a length-2 device array ``[checksum,
    n_overflowed_rows]`` (one transfer fetches both); ``k_max=0`` runs
    the uncompressed v1 kernel and returns just the checksum. For the
    v5 family the checksum is an EXACT order-independent avalanche
    digest of (rank, visibility, lane, conflict): equal integers
    across strategy configs iff the weaves are bit-identical, so the
    same scalar program doubles as the on-chip correctness gate
    (v1-v4 keep the float sum). v1-v3
    take the ``LANE_KEYS`` lanes, v4/v4w the ``LANE_KEYS4`` lanes, v5
    the ``LANE_KEYS5`` lanes.
    """
    # the CAUSE_TPU_* streaming switches are read at TRACE TIME inside
    # the kernels (via switches.resolve), so they are part of program
    # identity. The cache key uses the RAW env values, not resolve():
    # resolve() consults jax.default_backend() once TPU_DEFAULTS is
    # populated, and this lookup runs on host paths (bench.py's parent,
    # the wave assembly) that must stay backend-init-free — triggering
    # the blocking tunnel claim from a cache lookup was ADVICE r4 #2.
    # switches.raw_key: raw env values (never resolve() — that would
    # trigger backend init from this host path), with the safe
    # "xla"-onto-unset collapse for non-defaulted switches; the
    # mapping lives in switches.py next to resolve() so key and
    # trace-time resolution cannot drift.
    from .obs import costmodel as _costmodel
    from .obs import counter as _obs_counter, span as _obs_span
    from .switches import raw_switch_key

    key = (k_max, kernel if k_max > 0 else "v1", u_max,
           raw_switch_key())

    def _prog_id():
        # the ONE spelling of this program's costmodel identity: the
        # dispatch record (below) and the devprof cost registration
        # (miss branch) must agree byte-for-byte or the wave.cost
        # devprof join silently misses
        return (f"scalar:{key[1]}:k{int(k_max)}:u{int(u_max)}"
                f":s{hash(key[3]) & 0xFFFFFFFF:08x}")

    if _costmodel.enabled():
        # dispatch accounting (obs.costmodel): every call here is ONE
        # device program invocation under this switch-aware identity,
        # hit or miss — the wave cost model counts invocations and
        # distinct identities per wave window. Never feeds back into
        # ``key``: the identity contract stays one-way, like the
        # hit/miss counters below.
        _costmodel.record_dispatch(_prog_id(), site="benchgen")
    program = _scalar_programs.get(key)
    if program is None:
        # program-cache provenance: every miss is a fresh trace (and on
        # TPU a fresh XLA compile) keyed by the raw switch snapshot —
        # the counters make silent re-trace storms visible in any obs
        # trace (obs never feeds back into ``key``: the identity
        # contract is one-way)
        _obs_counter("program_cache.miss").inc()
        import functools

        import jax
        import jax.numpy as jnp

        from .weaver.jaxw import batched_merge_weave_v2, merge_weave_kernel

        def _checksum(order, rank, visible, conflict):
            return (
                jnp.sum(rank.astype(jnp.float32))
                + jnp.sum(order.astype(jnp.float32))
                + jnp.sum(visible.astype(jnp.float32))
                + jnp.sum(conflict.astype(jnp.float32))
            )

        if k_max > 0 and kernel in ("v5", "v5w", "v5f"):
            if kernel == "v5f":
                from .weaver.jaxw5f import batched_merge_weave_v5f

                def batched(*a):
                    return batched_merge_weave_v5f(
                        *a, u_max=u_max, k_max=k_max)
            else:
                from .weaver.jaxw5 import batched_merge_weave_v5

                _euler = "walk" if kernel == "v5w" else "doubling"

                def batched(*a):
                    return batched_merge_weave_v5(
                        *a, u_max=u_max, k_max=k_max, euler=_euler)

            @jax.jit
            def program(*a):
                # The v5-family scalar is an EXACT avalanche digest
                # (mesh.replica_digest-style mixing), not a float sum:
                # uint32 wraparound arithmetic is order-independent, so
                # the same weave under ANY strategy config yields the
                # SAME integer — one compiled program per config serves
                # both timing and the on-chip correctness gate
                # (harvest's verify items and bench.py's alt-config
                # gate compare these scalars; two windows were lost to
                # the separate digest program's fresh compile). A
                # linear float sum was observed cancelling
                # compensating errors — the mixing prevents that.
                rank, visible, conflict, overflow = batched(*a)
                lane = jax.lax.broadcasted_iota(
                    jnp.uint32, rank.shape, 1)
                x = (rank.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
                     + visible.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
                     + lane * jnp.uint32(0xC2B2AE35)
                     + jnp.uint32(1))
                x = x ^ (x >> 16)
                x = x * jnp.uint32(0x85EBCA6B)
                x = x ^ (x >> 13)
                x = x * jnp.uint32(0xC2B2AE35)
                x = x ^ (x >> 16)
                row = (jnp.sum(x, axis=1)
                       ^ (conflict.astype(jnp.uint32)
                          * jnp.uint32(0x27D4EB2F)))
                # fold the ROW INDEX into the mix before the modular
                # cross-row sum (ADVICE r5 #4): the plain sum was
                # permutation-invariant across rows, so compensating
                # per-row errors (row i off by +d, row j by -d, or two
                # rows swapped) cancelled. Rotating each row digest by
                # row & 31 breaks that symmetry while keeping the
                # checksum exact and config-independent; (32-r)&31
                # keeps the r==0 shift in-range.
                rix = jax.lax.broadcasted_iota(
                    jnp.uint32, row.shape, 0) & jnp.uint32(31)
                row = (row << rix) | (
                    row >> ((jnp.uint32(32) - rix) & jnp.uint32(31)))
                digest = jax.lax.bitcast_convert_type(
                    jnp.sum(row), jnp.int32)
                return jnp.stack([
                    digest,
                    jnp.sum(overflow.astype(jnp.int32)),
                ])
        elif k_max > 0:
            if kernel in ("v4", "v4w"):
                from .weaver.jaxw4 import batched_merge_weave_v4

                batched = functools.partial(
                    batched_merge_weave_v4,
                    euler="walk" if kernel == "v4w" else "doubling",
                )
            elif kernel == "v3":
                from .weaver.jaxw3 import batched_merge_weave_v3

                batched = batched_merge_weave_v3
            else:
                batched = batched_merge_weave_v2

            @jax.jit
            def program(*a):
                order, rank, visible, conflict, overflow = (
                    batched(*a, k_max=k_max)
                )
                return jnp.stack([
                    _checksum(order, rank, visible, conflict),
                    jnp.sum(overflow.astype(jnp.float32)),
                ])
        else:
            @jax.jit
            def program(*a):
                return _checksum(*jax.vmap(merge_weave_kernel)(*a))

        with _obs_span("program.build", kernel=key[1],
                       k_max=int(k_max), u_max=int(u_max)):
            from .obs import devprof as _devprof

            if _devprof.enabled():
                # one-per-compiled-program device cost capture: route
                # THIS first compile through the AOT path so the
                # executable's cost_analysis lands as a devprof event
                # keyed like the cache key (no second compile; obs-off
                # never reaches here and the cache stores the plain
                # jit program exactly as before)
                prof = _devprof.profile_program(
                    program, args, kernel=key[1], k_max=int(k_max),
                    u_max=int(u_max))
                if prof is not None:
                    program = prof
                    # price this program identity for the wave cost
                    # model: wave.cost events attach the flops/bytes
                    # of the programs a wave actually ran
                    _costmodel.register_program(_prog_id(), prof.cost)
            _scalar_programs[key] = program
            return program(*args)
    _obs_counter("program_cache.hit").inc()
    return program(*args)

def v5_inputs(row: Dict[str, np.ndarray], capacity: int,
              s_max: int = 0) -> Dict[str, np.ndarray]:
    """Build the v5 segment-union kernel's inputs from a concatenated
    multi-tree lane row (``capacity`` lanes per tree): segment each
    tree host-side and assemble the concat segment tables. ``s_max`` 0
    sizes the tables exactly (padded to a multiple of 8)."""
    from .weaver.segments import concat_segments, tree_segments

    n_trees = row["hi"].shape[0] // capacity
    per_tree = []
    for t in range(n_trees):
        sl = slice(t * capacity, (t + 1) * capacity)
        n = int(row["valid"][sl].sum())
        cci = row["cci"][sl]
        local_cci = np.where(cci >= 0, cci - t * capacity, -1).astype(
            np.int32
        )
        segs = tree_segments(
            row["hi"][sl], row["lo"][sl], local_cci, row["vc"][sl], n
        )
        per_tree.append((segs, n))
    total = sum(s["sg_len"].shape[0] for s, _ in per_tree)
    if not s_max:
        s_max = total + (-total) % 8
    out = dict(row)
    out.update(concat_segments(per_tree, capacity, s_max))
    return out


def batched_v5_inputs(batch: Dict[str, np.ndarray],
                      capacity: int) -> Dict[str, np.ndarray]:
    """Per-row ``v5_inputs`` over a [B, n_trees*capacity] batch, with a
    shared segment-table size (rows marshal once; shorter tables pad
    with all-invalid tails to the widest row)."""
    from .weaver.segments import SEG_LANE_KEYS

    B = batch["hi"].shape[0]
    rows = [
        v5_inputs({k: batch[k][i] for k in LANE_KEYS4}, capacity)
        for i in range(B)
    ]
    s_max = max(r["sg_len"].shape[0] for r in rows)
    for r in rows:
        pad = s_max - r["sg_len"].shape[0]
        if pad:
            for k in SEG_LANE_KEYS:
                r[k] = np.concatenate(
                    [r[k], np.zeros(pad, r[k].dtype)]
                )
    return {k: np.stack([r[k] for r in rows]) for k in LANE_KEYS5}


def v5_token_budget(v5batch: Dict[str, np.ndarray],
                    sample_rows: int = 4) -> int:
    """Token budget for the v5 kernel, sampled like ``pair_run_budget``
    (the overflow flag backstops unsampled-row drift)."""
    B = v5batch["hi"].shape[0] if v5batch["hi"].ndim > 1 else 1
    if v5batch["hi"].ndim == 1:
        rows = [v5batch]
    else:
        picks = sorted({0, B // 3, (2 * B) // 3, B - 1})[:sample_rows]
        rows = [{k: v5batch[k][i] for k in LANE_KEYS5} for i in picks]
    worst = max(estimate_tokens(r) for r in rows)
    return int(worst + max(64, worst // 8))


def estimate_tokens(v5row: Dict[str, np.ndarray]) -> int:
    """Host-side token count for one v5 row (numpy twin of the
    kernel's explode/dedupe rules E1/E2) — sizes ``u_max`` before
    dispatch; the kernel's overflow flag backstops drift."""
    va = v5row["sg_valid"]
    mh, ml = v5row["sg_min_hi"][va], v5row["sg_min_lo"][va]
    Mh, Ml = v5row["sg_max_hi"][va], v5row["sg_max_lo"][va]
    ln = v5row["sg_len"][va]
    dense = v5row["sg_dense"][va]
    tsp = v5row["sg_tail_special"][va]
    vsum = v5row["sg_vsum"][va]
    lane0 = v5row["sg_lane0"][va]
    S = ln.shape[0]
    if S == 0:
        return 8
    mins = (mh.astype(np.int64) << 32) | (ml.astype(np.int64) & 0xFFFFFFFF)
    maxs = (Mh.astype(np.int64) << 32) | (Ml.astype(np.int64) & 0xFFFFFFFF)
    order = np.lexsort((ml, mh))
    mins, maxs = mins[order], maxs[order]
    ln, dense, tsp, lane0 = (ln[order], dense[order], tsp[order],
                             lane0[order])
    vsum = vsum[order]
    ncap = len(v5row["cci"])
    hvc = v5row["vc"][np.clip(lane0, 0, ncap - 1)]
    cl0 = v5row["cci"][np.clip(lane0, 0, ncap - 1)]
    cid0 = np.where(
        cl0 >= 0,
        (v5row["hi"][np.clip(cl0, 0, ncap - 1)].astype(np.int64) << 32)
        | (v5row["lo"][np.clip(cl0, 0, ncap - 1)].astype(np.int64)
           & 0xFFFFFFFF),
        -1,
    )
    same = np.zeros(S, bool)
    same[1:] = ((mins[1:] == mins[:-1]) & (maxs[1:] == maxs[:-1])
                & (ln[1:] == ln[:-1]) & dense[1:] & dense[:-1]
                & (hvc[1:] == hvc[:-1]) & (cid0[1:] == cid0[:-1])
                & (tsp[1:] == tsp[:-1]) & (vsum[1:] == vsum[:-1]))
    grp = np.cumsum(~same) - 1
    g_min = mins[np.concatenate([[True], ~same[1:]])]
    g_max = maxs[np.concatenate([[True], ~same[1:]])]
    pm = np.maximum.accumulate(g_max)
    pm_excl = np.concatenate([[np.iinfo(np.int64).min], pm[:-1]])
    nxt_min = np.concatenate([g_min[1:], [np.iinfo(np.int64).max]])
    ov = (mins <= pm_excl[grp]) | (nxt_min[grp] <= maxs)
    # E2 stabs from every segment head's cause (cid0 packs them above)
    has = cl0 >= 0
    cid = cid0
    pg = np.searchsorted(g_min, cid, side="right") - 1
    pgc = np.clip(pg, 0, len(g_min) - 1)
    rep = np.flatnonzero(np.concatenate([[True], ~same[1:]]))
    stab = (
        has & (pg >= 0)
        & (g_min[pgc] <= cid)
        & ((cid < g_max[pgc])
           | ((cid == g_max[pgc]) & tsp[rep[pgc]] & (ln[rep[pgc]] > 1)))
    )
    stabbed = np.zeros(len(g_min), bool)
    stabbed[pgc[stab]] = True
    explode = ov | stabbed[grp]
    twin_drop = same & ~explode
    n_tok = int(np.where(explode, ln,
                         np.where(twin_drop, 0, 1)).sum())
    return max(8, n_tok)


# synthetic site ranks (order-preserving: "0" sorts first, suffix sites
# are minted after and sort above the base site by construction)
SITE_ROOT = 0
SITE_BASE = 1
SITE_A = 2
SITE_B = 3


def chain_tree_lanes(
    n_base: int,
    n_div: int,
    suffix_site: int,
    capacity: int,
    hide_every: int = 0,
    spec: PackSpec = DEFAULT_PACK,
) -> Dict[str, np.ndarray]:
    """Lanes for ONE tree: root + base chain + one divergent suffix.

    Lanes come out in sorted id order (ts is strictly increasing along
    the chain), root at lane 0 — the ``NodeArrays.from_nodes_map``
    layout. Returns hi/lo (id lanes), chi/clo (cause id lanes), vc,
    valid, each of length ``capacity``.
    """
    n = 1 + n_base + n_div
    if capacity < n:
        raise ValueError(f"capacity {capacity} < node count {n}")
    ts = np.zeros(n, np.int64)
    site = np.zeros(n, np.int64)
    vc = np.zeros(n, np.int32)

    # base chain: ts 1..n_base, all from SITE_BASE
    ts[1 : 1 + n_base] = np.arange(1, n_base + 1)
    site[1 : 1 + n_base] = SITE_BASE
    # divergent suffix: ts n_base+1 .., from suffix_site
    ts[1 + n_base :] = np.arange(n_base + 1, n_base + n_div + 1)
    site[1 + n_base :] = suffix_site

    # causes: chain — node i caused by node i-1 (root causes itself as
    # a placeholder; its cause lanes are (-1,-1) below)
    cts = np.concatenate([[0], ts[:-1]])
    csite = np.concatenate([[0], site[:-1]])

    if hide_every > 0:
        # every k-th suffix node is a hide targeting its predecessor
        j = np.arange(1, n_div + 1)
        is_hide = (j % hide_every) == 0
        vc[1 + n_base :][is_hide] = VCLASS_HIDE

    tx = np.zeros(n, np.int64)
    hi = np.full(capacity, I32_MAX, np.int32)
    lo = np.full(capacity, I32_MAX, np.int32)
    chi = np.full(capacity, -1, np.int32)
    clo = np.full(capacity, -1, np.int32)
    cci = np.full(capacity, -1, np.int32)
    vcl = np.zeros(capacity, np.int32)
    valid = np.zeros(capacity, bool)

    hi[:n] = ts.astype(np.int32)
    lo[:n] = (site.astype(np.int32) << spec.tx_bits) | tx.astype(np.int32)[:n]
    chi[1:n] = cts[1:].astype(np.int32)
    clo[1:n] = (csite[1:].astype(np.int32) << spec.tx_bits)
    cci[1:n] = np.arange(n - 1, dtype=np.int32)  # chain: cause = lane i-1
    vcl[:n] = vc
    valid[:n] = True
    return {"hi": hi, "lo": lo, "chi": chi, "clo": clo, "cci": cci,
            "vc": vcl, "valid": valid}


def divergent_pair_lanes(
    n_base: int,
    n_div: int,
    capacity: int,
    hide_every: int = 0,
    spec: PackSpec = DEFAULT_PACK,
) -> Dict[str, np.ndarray]:
    """Concatenated lanes ([2*capacity]) of one divergent replica pair —
    the per-replica input of ``merge_weave_kernel``."""
    a = chain_tree_lanes(n_base, n_div, SITE_A, capacity, hide_every, spec)
    b = chain_tree_lanes(n_base, n_div, SITE_B, capacity, hide_every, spec)
    out = {k: np.concatenate([a[k], b[k]]) for k in a}
    # cci is a concat index: the second tree's causes shift by capacity
    out["cci"][capacity:] = np.where(
        b["cci"] >= 0, b["cci"] + capacity, -1
    )
    return out


def fleet_lanes(
    n_replicas: int,
    n_base: int,
    n_div: int,
    capacity: int,
    hide_every: int = 0,
    spec: PackSpec = DEFAULT_PACK,
) -> Dict[str, np.ndarray]:
    """Flattened ``[n_replicas * capacity]`` lanes of a whole fleet: K
    divergent replicas of one shared base chain, each with its own
    suffix site and tombstone phase. Feed straight into
    ``merge_weave_kernel`` — its sort-dedupe union front half is K-ary
    for free — to converge the entire fleet into ONE tree on device
    (the north star's "1024 replicas into one" reading)."""
    n_sites = SITE_A + n_replicas
    if n_sites > (1 << spec.site_bits):
        raise OverflowError(f"{n_sites} sites exceed {spec.site_bits} bits")
    rows = []
    for r in range(n_replicas):
        row = chain_tree_lanes(
            n_base, n_div, SITE_A + r, capacity,
            hide_every=0, spec=spec,
        )
        if hide_every > 0 and n_div > 0:
            j = np.arange(1, n_div + 1)
            is_hide = ((j + r) % hide_every) == 0
            row["vc"][1 + n_base:1 + n_base + n_div][is_hide] = VCLASS_HIDE
        row["cci"] = np.where(
            row["cci"] >= 0, row["cci"] + r * capacity, -1
        ).astype(np.int32)
        rows.append(row)
    return {k: np.concatenate([row[k] for row in rows]) for k in rows[0]}


def delta_sweep_inputs(
    n_replicas: int,
    n_base: int,
    n_div: int,
    capacity: int,
    hide_every: int = 0,
    spec: PackSpec = DEFAULT_PACK,
    include_full: bool = True,
) -> dict:
    """Paired full-weave / delta-weave inputs for the divergence sweep
    (BENCH_DIV_SWEEP) and the harvest delta items: the same synthetic
    workload expressed both as the document-width batch the full v5
    kernel dispatches and as the delta-native WINDOW batch
    (``weaver.jaxwd.batched_delta_weave``'s inputs) plus the frozen
    prefix state a resident session would hold.

    The workload is ``batched_pair_lanes`` restricted to the delta
    domain: the first divergent node on each side is never a tombstone
    (a tombstone whose cause is the shared base tail — the anchor —
    would flip a frozen resident lane's visibility, which is exactly
    the case the session falls back to a full wave for; see
    ``parallel.wave.delta_domain_ok``). Everything else — per-row
    suffix sites, per-row tombstone phases deeper in the suffix — is
    the headline generator's shape, so the A/B compares the same
    steady-state editing pattern.

    Returns a dict: ``full`` (``LANE_KEYS5`` arrays, [B, 2*capacity]),
    ``window`` (``LANE_KEYS5`` arrays, [B, 2*wcap] with
    ``wcap = next_pow2(1 + n_div)``), ``r0`` ([B] int32 anchor ranks =
    ``n_base``), ``prefix_digest`` ([B] uint32 — the resident prefix's
    frozen avalanche sum, host-computed with the ``mesh.mix32_np``
    twin), ``wcap`` and ``starts``/``counts`` ([B, 2] — the splice
    program's coordinates). Digest identity — full-kernel digest ==
    ``prefix_digest`` + window contribution — is the delta gate both
    consumers check on-device.

    ``include_full=False`` skips the full-width v5 marshal (the
    per-row segment extraction is the expensive half at 1024x10k):
    timing-only delta consumers (harvest's bench_delta items) need
    just the window arm.
    """
    batch = batched_pair_lanes(
        n_replicas=n_replicas, n_base=n_base, n_div=n_div,
        capacity=capacity, hide_every=hide_every, spec=spec,
    )
    # delta-domain restriction: no tombstone on the first suffix node
    # of either side (its cause is the anchor)
    if n_div > 0:
        batch["vc"][:, 1 + n_base] = 0
        batch["vc"][:, capacity + 1 + n_base] = 0
    full = batched_v5_inputs(batch, capacity) if include_full else None

    wcap = next_pow2(max(8, 1 + n_div))
    B = n_replicas
    n_w = 2 * wcap
    window = {
        "hi": np.full((B, n_w), I32_MAX, np.int32),
        "lo": np.full((B, n_w), I32_MAX, np.int32),
        "cci": np.full((B, n_w), -1, np.int32),
        "vc": np.zeros((B, n_w), np.int32),
        "valid": np.zeros((B, n_w), bool),
    }
    sfx = {0: slice(1 + n_base, 1 + n_base + n_div),
           1: slice(capacity + 1 + n_base,
                    capacity + 1 + n_base + n_div)}
    anchor_hi = np.int32(n_base)
    anchor_lo = np.int32(SITE_BASE << spec.tx_bits)
    for t in range(2):
        off = t * wcap
        window["hi"][:, off] = anchor_hi
        window["lo"][:, off] = anchor_lo
        window["valid"][:, off] = True
        if n_div:
            w = 1 + n_div
            window["hi"][:, off + 1:off + w] = batch["hi"][:, sfx[t]]
            window["lo"][:, off + 1:off + w] = batch["lo"][:, sfx[t]]
            window["vc"][:, off + 1:off + w] = batch["vc"][:, sfx[t]]
            window["valid"][:, off + 1:off + w] = True
            # suffix causes are a pure chain off the anchor: window
            # lane j's cause is lane j-1 (the anchor at j=1)
            window["cci"][:, off + 1:off + w] = off + np.arange(
                n_div, dtype=np.int32)
    window = batched_v5_inputs(
        {k: window[k] for k in LANE_KEYS4}, wcap)

    # the frozen prefix: root + base chain, ranks 0..n_base (the weave
    # IS the chain), root invisible, chain visible — identical for
    # every row, so one host sum serves the whole batch
    from .parallel.mesh import mix32_np

    p_hi = np.arange(n_base + 1, dtype=np.int32)
    p_lo = np.full(n_base + 1, np.int32(SITE_BASE << spec.tx_bits))
    p_lo[0] = 0  # the root's site rank is 0
    p_rank = np.arange(n_base + 1, dtype=np.int32)
    p_vis = np.ones(n_base + 1, bool)
    p_vis[0] = False
    pdig = np.uint32(
        mix32_np(p_hi, p_lo, p_rank, p_vis).sum(dtype=np.uint64)
        & np.uint64(0xFFFFFFFF))
    starts = np.full((B, 2), n_base + 1, np.int32)
    counts = np.full((B, 2), n_div, np.int32)
    return {
        "full": full,
        "window": window,
        "wcap": int(wcap),
        "r0": np.full(B, n_base, np.int32),
        "prefix_digest": np.full(B, pdig, np.uint32),
        "starts": starts,
        "counts": counts,
    }


def tree_fleet_handles(n_replicas: int, n_base: int, n_div: int,
                       hide_every: int = 0) -> list:
    """``n_replicas`` REAL divergent replica handles of one shared
    ``n_base``-node CausalList, each extended by its own
    ``n_div``-op suffix (every ``hide_every``-th suffix op a ``hide``
    tombstone targeting its predecessor) — the merge-tree benchmarks'
    and smokes' fleet, as host handles rather than raw lanes, because
    the tree's A/B baseline (the flat pairwise fold) NEEDS handles to
    materialize through.

    Deliberately jax-free: the base weave is computed by the PURE host
    weaver and the trees then evolve to ``weaver="jax"`` (the two
    weavers are semantics-identical — the pure weaver is the oracle),
    so harvest/bench marshal this fleet BEFORE the backend claim
    without spending granted tunnel time or initializing a possibly
    wedged backend. The first suffix op of every replica is a plain
    value (a tombstone there would target the shared base tail — the
    anchor — which is exactly the delta-domain violation the tree
    falls back to full width for)."""
    import cause_tpu as c
    from .collections import clist as c_list
    from .collections.clist import CausalList
    from .ids import new_site_id

    base = c.clist().extend([f"w{i}" for i in range(n_base)])
    base = CausalList(c_list.weave(base.ct))
    base = CausalList(base.ct.evolve(weaver="jax"))
    replicas = []
    for r in range(n_replicas):
        vals: list = []
        for i in range(n_div):
            vals.append(f"r{r}.{i}")
            if hide_every and i and (i + r) % hide_every == 0:
                vals.append(c.hide)
        h = CausalList(base.ct.evolve(site_id=new_site_id()))
        replicas.append(h.extend(vals[:n_div]) if not hide_every
                        else h.extend(vals))
    return replicas


def batched_pair_lanes(
    n_replicas: int,
    n_base: int,
    n_div: int,
    capacity: int,
    hide_every: int = 0,
    spec: PackSpec = DEFAULT_PACK,
) -> Dict[str, np.ndarray]:
    """The [B, 2*capacity] batch for ``batched_merge_weave`` /
    ``sharded_merge_weave``: ``n_replicas`` genuinely *distinct*
    divergent pairs. Every row shares the base chain but gets its own
    pair of suffix sites (row r: ranks ``SITE_A+2r`` / ``SITE_A+2r+1``)
    and its own tombstone phase, so no two rows converge to the same
    weave — per-row digests must differ (asserted by the driver
    dryrun). Built as one broadcast plus vectorized per-row lane
    rewrites, so B=1024 stays cheap."""
    row = divergent_pair_lanes(n_base, n_div, capacity, hide_every, spec)
    out = {
        k: np.broadcast_to(v, (n_replicas,) + v.shape).copy() for k, v in row.items()
    }
    if n_replicas <= 1 or n_div == 0:
        return out

    r = np.arange(n_replicas, dtype=np.int32)
    site_a = (SITE_A + 2 * r)[:, None].astype(np.int32)
    site_b = site_a + 1
    # max rank used is SITE_A + 2*n_replicas - 1; generator lanes have
    # tx=0, so even a max-rank lo can't collide with the I32_MAX sentinel
    n_sites = SITE_A + 2 * n_replicas
    if n_sites > (1 << spec.site_bits):
        raise OverflowError(f"{n_sites} sites exceed {spec.site_bits} bits")

    # suffix id lanes (tx = 0 throughout the generator)
    sfx_a = slice(1 + n_base, 1 + n_base + n_div)
    sfx_b = slice(capacity + 1 + n_base, capacity + 1 + n_base + n_div)
    out["lo"][:, sfx_a] = site_a << spec.tx_bits
    out["lo"][:, sfx_b] = site_b << spec.tx_bits
    # within-suffix chain causes (every suffix node but the first, whose
    # cause is the base tail and keeps the base site)
    csfx_a = slice(2 + n_base, 1 + n_base + n_div)
    csfx_b = slice(capacity + 2 + n_base, capacity + 1 + n_base + n_div)
    out["clo"][:, csfx_a] = site_a << spec.tx_bits
    out["clo"][:, csfx_b] = site_b << spec.tx_bits

    if hide_every > 0:
        # per-row tombstone phase; sides get different phases too
        j = np.arange(1, n_div + 1)
        hide_a = ((j[None, :] + r[:, None]) % hide_every) == 0
        hide_b = ((j[None, :] + r[:, None] + 1) % hide_every) == 0
        out["vc"][:, sfx_a] = np.where(hide_a, VCLASS_HIDE, 0).astype(np.int32)
        out["vc"][:, sfx_b] = np.where(hide_b, VCLASS_HIDE, 0).astype(np.int32)
    return out
