"""Seeded, deterministic fault injection for the sync/wave substrate.

Network-accelerated replication systems treat fault injection as table
stakes (arXiv:1605.05619): you do not find out how a fleet degrades by
waiting for the tunnel to corrupt a frame. This engine injects the
substrate's real failure modes ON PURPOSE, from a seeded plan, so every
recovery path in the repo is exercised deterministically and evidenced
in the obs stream:

- **payload** faults mangle a sync delta's on-wire node triples
  (``corrupt`` / ``truncate`` / ``duplicate`` / ``reorder`` / ``drop``)
  — caught by ``sync.py``'s validate-before-apply boundary
  (``sync.reject`` events, repeat offenders quarantined);
- **dispatch** faults fail a device dispatch (``raise``: a transient
  :class:`InjectedDispatchError` the recovery ladder retries;
  ``exhaust``: a window-budget exhaustion that forces the delta path
  back to full width) — caught by ``parallel/recovery.py``;
- **crash** faults tell a harness to kill and restart a replica
  process-equivalent (drop the ``FleetSession``, restore from its
  serde checkpoint, losing all in-memory state) — ``scripts/soak.py
  --chaos`` acts on :func:`should_crash`;
- **stall** faults sleep inside a wave to trip the PR-10
  ``absence:run.heartbeat`` live-alert rule (the wedge detector);
- **net** faults (PR 13) disrupt the replication transport at the
  wire level: ``partition`` refuses connect attempts (the dial-side
  hook), ``reset`` closes an established connection mid-protocol,
  ``latency`` sleeps before a frame send, ``blackhole`` silently
  drops an outbound frame (the peer waits out its read deadline),
  and ``dup`` sends one frame twice (same seq — the server's
  wire-duplicate detector must count and re-ack it) — all caught by
  ``cause_tpu/net``'s reconnect/backoff + watermark-resume machinery;
- **disk** faults (PR 15) misbehave at the durable-storage seams:
  ``torn`` writes a prefix of a WAL record and fails the append (a
  crash mid-write — the op is never acknowledged, the tear is found
  by the next scan), ``bitrot`` flips one byte of an acked record's
  durable copy (the per-record CRC32 trailer is the detector),
  ``enospc`` refuses the write outright (admission must shed on the
  durability rung, never ack), ``fsync`` fails a flush-to-media call
  (the WAL rotates to a fresh segment with evidence), and ``rename``
  fails the atomic manifest/GC rename (the previous manifest must
  stay intact) — all caught by ``cause_tpu/serve/wal.py`` and the
  checkpoint path, scrubbed by ``python -m cause_tpu.serve scrub``;
- **ship** faults (PR 20) disrupt the TELEMETRY link only — the
  obs-shipping plane between a :class:`~cause_tpu.obs.ship.ShipExporter`
  and the collector: ``partition`` refuses exporter dials, ``drop``
  silently discards an outbound obs frame, ``dup`` sends one obs
  frame twice (same (origin, seq) — the collector's watermark dedup
  must absorb it), ``reorder`` holds a frame back one send so the
  next frame overtakes it — all absorbed by the exporter's
  reconnect/watermark-resume machinery and the collector's per-origin
  dedup. The data plane NEVER sees these: ship faults prove the soak
  stays bit-identical while telemetry degrades.

Determinism: every fault spec keeps its own per-site invocation
counter and its own ``random.Random((plan seed, spec index))`` stream,
so the same plan over the same call sequence injects the same faults
at the same points — the repro contract (seed, plan) -> identical
fault schedule.

Off-invariance contract (the obs contract, verbatim): with
``CAUSE_TPU_CHAOS`` unset (or ``0``), :func:`enabled` is False, every
hook returns its input immediately, no state is kept, no plan file is
read, no records are minted anywhere, and program-cache keys are
byte-identical (pinned in tests/test_chaos.py). Enable with
``CAUSE_TPU_CHAOS=<plan.json path>`` (or an inline JSON object), or
programmatically with :func:`configure` for tests.

Stdlib-only, importable without jax/numpy (the obs rule).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional

__all__ = [
    "FAMILIES",
    "InjectedDispatchError",
    "enabled",
    "configure",
    "reset",
    "suspended",
    "mangle_items",
    "dispatch_fault",
    "budget_exhaust",
    "should_crash",
    "stall_point",
    "net_partition",
    "net_reset",
    "net_latency_ms",
    "net_blackhole",
    "net_dup",
    "disk_torn",
    "disk_bitrot",
    "disk_enospc",
    "disk_fsync_fail",
    "disk_rename_fail",
    "ship_partition",
    "ship_drop",
    "ship_dup",
    "ship_reorder",
    "injected",
    "chaos_report",
]

FAMILIES = ("payload", "dispatch", "crash", "stall", "net", "disk",
            "ship")
PAYLOAD_MODES = ("corrupt", "truncate", "duplicate", "reorder", "drop")
NET_MODES = ("partition", "reset", "latency", "blackhole", "dup")
DISK_MODES = ("torn", "bitrot", "enospc", "fsync", "rename")
SHIP_MODES = ("partition", "drop", "dup", "reorder")
# the value planted by payload corruption: tests and the chaos soak
# gate grep converged documents for it — an admitted corruption is a
# validation hole, not a flake
CORRUPT_MARKER = "⚡chaos-corrupt⚡"
_TRUTHY = ("1", "true", "yes")
_LOG_MAX = 4096          # injected-fault log bound (drops counted)
_STALL_CAP_S = 5.0       # no plan may wedge a run for real


class InjectedDispatchError(RuntimeError):
    """A chaos-injected transient device-dispatch failure. The
    recovery ladder classifies it as transient and retries with
    backoff; nothing else in the repo raises it."""


class _Fault:
    """One armed fault spec (see the plan schema in scripts/soak.py):
    family/site/mode plus a firing schedule — explicit invocation
    indices (``at``), a seeded probability (``prob``), and an optional
    total-fire cap (``times``)."""

    __slots__ = ("family", "site", "mode", "at", "prob", "times",
                 "ms", "seq", "fired", "rng")

    def __init__(self, spec: dict, seed: int, index: int):
        self.family = str(spec.get("family", ""))
        if self.family not in FAMILIES:
            raise ValueError(f"unknown chaos family: {self.family!r}")
        self.site = str(spec.get("site", "*"))
        self.mode = str(spec.get("mode", ""))
        if self.family == "payload":
            self.mode = self.mode or "corrupt"
            if self.mode not in PAYLOAD_MODES:
                raise ValueError(
                    f"unknown payload mode: {self.mode!r}")
        elif self.family == "dispatch":
            self.mode = self.mode or "raise"
            if self.mode not in ("raise", "exhaust"):
                raise ValueError(
                    f"unknown dispatch mode: {self.mode!r}")
        elif self.family == "net":
            self.mode = self.mode or "reset"
            if self.mode not in NET_MODES:
                raise ValueError(f"unknown net mode: {self.mode!r}")
        elif self.family == "disk":
            self.mode = self.mode or "torn"
            if self.mode not in DISK_MODES:
                raise ValueError(f"unknown disk mode: {self.mode!r}")
        elif self.family == "ship":
            self.mode = self.mode or "drop"
            if self.mode not in SHIP_MODES:
                raise ValueError(f"unknown ship mode: {self.mode!r}")
        self.at = frozenset(int(x) for x in (spec.get("at") or ()))
        self.prob = float(spec.get("prob") or 0.0)
        self.times = int(spec.get("times") or 0)
        self.ms = float(spec.get("ms") or 0.0)
        self.seq = 0
        self.fired = 0
        # one independent deterministic stream per spec: firing of
        # spec i never perturbs spec j's schedule. Stable int seed on
        # purpose (str hash() is process-salted; tuple seeding is
        # deprecated) — (plan seed, spec index, family) all mix in.
        self.rng = random.Random(
            int(seed) * 1_000_003 + int(index) * 7_919
            + zlib.crc32(self.family.encode()))

    def matches(self, site: str) -> bool:
        return self.site == "*" or self.site == site \
            or site.startswith(self.site + ".")

    def decide(self) -> bool:
        """One invocation at a matching site: advance the per-spec
        counter and report whether this invocation WOULD inject.
        ``fired`` is charged by the caller for the winning spec only —
        a spec that hits but loses the invocation to an earlier spec
        must not consume its ``times`` cap on a fault it never
        injected. Called under the engine lock."""
        self.seq += 1
        if self.times and self.fired >= self.times:
            return False
        hit = self.seq in self.at
        if not hit and self.prob:
            # drawn EVERY invocation so the stream stays aligned with
            # the invocation counter regardless of earlier outcomes
            hit = self.rng.random() < self.prob
        return hit


class _State:
    __slots__ = ("enabled", "faults", "log", "dropped", "lock",
                 "suspend_depth", "seed")

    def __init__(self, enabled_: bool, plan: Optional[dict]):
        self.enabled = bool(enabled_) and plan is not None
        self.seed = int((plan or {}).get("seed", 0))
        self.faults: List[_Fault] = [
            _Fault(spec, self.seed, i)
            for i, spec in enumerate((plan or {}).get("faults") or ())
        ]
        self.log: List[dict] = []
        self.dropped = 0
        self.lock = threading.Lock()
        self.suspend_depth = 0


_STATE: Optional[_State] = None
_STATE_LOCK = threading.Lock()


def _load_plan(raw: str) -> dict:
    raw = raw.strip()
    if raw.startswith("{"):
        return json.loads(raw)
    with open(raw) as f:
        return json.load(f)


def _resolve_state() -> _State:
    global _STATE
    st = _STATE
    if st is None:
        with _STATE_LOCK:
            st = _STATE
            if st is None:
                raw = os.environ.get("CAUSE_TPU_CHAOS", "").strip()
                if not raw or raw.lower() in ("0", "false", "no"):
                    st = _State(False, None)
                else:
                    # a broken plan fails loudly: silently running
                    # without the faults you asked for is the one
                    # outcome a chaos harness must never have
                    st = _State(True, _load_plan(raw))
                _STATE = st
    return st


def configure(plan: Optional[dict] = None,
              enabled: Optional[bool] = None,
              reset: bool = False) -> None:
    """Arm (or disarm) the engine programmatically — the soak harness
    and tests. ``reset=True`` drops all engine state and re-reads the
    environment on next use."""
    global _STATE
    with _STATE_LOCK:
        if reset:
            _STATE = None
            if plan is None and enabled is None:
                return
        if plan is not None:
            _STATE = _State(True if enabled is None else enabled, plan)
            return
    st = _resolve_state()
    if enabled is not None:
        st.enabled = bool(enabled) and bool(st.faults)


def reset() -> None:
    """Drop all chaos state; re-read ``CAUSE_TPU_CHAOS`` on next use."""
    configure(reset=True)


def enabled() -> bool:
    st = _resolve_state()
    return st.enabled and st.suspend_depth == 0


class suspended:
    """Context manager: chaos is inert inside the block WITHOUT
    consuming any fault-spec invocation counters — the soak's
    fault-free oracle replays the same ops through the same call
    sites and must not perturb (or suffer) the fault schedule."""

    def __enter__(self):
        st = _resolve_state()
        with st.lock:
            st.suspend_depth += 1
        return self

    def __exit__(self, *exc):
        st = _resolve_state()
        with st.lock:
            st.suspend_depth = max(0, st.suspend_depth - 1)
        return False


def _decide(site: str, family: str,
            mode: Optional[str] = None) -> Optional[_Fault]:
    st = _resolve_state()
    if not (st.enabled and st.suspend_depth == 0):
        return None
    with st.lock:
        hit = None
        for f in st.faults:
            if f.family != family or not f.matches(site):
                continue
            if mode is not None and f.mode != mode:
                # mode-specific hooks never advance (or consume) a
                # different mode's schedule: raise-specs tick only at
                # dispatch_fault, exhaust-specs only at budget_exhaust
                continue
            # every matching spec advances (determinism: counters
            # depend on the call sequence, not on other specs'
            # outcomes); the first hit wins the invocation
            if f.decide() and hit is None:
                hit = f
        if hit is not None:
            hit.fired += 1
        return hit


def _record(f: _Fault, site: str, **details) -> None:
    st = _resolve_state()
    rec = {"family": f.family, "site": site, "mode": f.mode,
           "seq": f.seq, "ts_us": time.time_ns() // 1000}
    rec.update(details)
    with st.lock:
        if len(st.log) >= _LOG_MAX:
            st.dropped += 1
        else:
            st.log.append(rec)
    # evidence in the ledgered stream — through obs, so chaos-without
    # -obs still injects (detection evidence is the recovery side's
    # job) and obs-off keeps its zero-records contract
    from .. import obs

    if obs.enabled():
        obs.counter(f"chaos.injected.{f.family}").inc()
        obs.event("chaos.inject", **rec)


# ------------------------------------------------------------- hooks


def mangle_items(items: list, site: str = "sync.delta") -> list:
    """Maybe-mangled copy of an encoded node-triple payload (the
    ``serde.encode_node_items`` wire form). Returns ``items``
    unchanged (same object) when no payload fault fires; empty
    payloads never consume a firing (there is nothing to corrupt)."""
    if not items:
        return items
    f = _decide(site, "payload")
    if f is None:
        return items
    out = [list(it) for it in items]
    idx = f.rng.randrange(len(out))
    mode = f.mode
    if mode == "corrupt":
        out[idx][2] = CORRUPT_MARKER
    elif mode == "truncate":
        out[idx] = out[idx][:2]
    elif mode == "duplicate":
        dup = [out[idx][0], out[idx][1], CORRUPT_MARKER]
        out.insert(idx + 1, dup)
    elif mode == "reorder":
        if len(out) >= 2:
            out[0], out[-1] = out[-1], out[0]
        else:
            out[idx][2] = CORRUPT_MARKER
            mode = "corrupt"
    elif mode == "drop":
        del out[idx]
    _record(f, site, nodes=len(items), index=idx, applied=mode)
    return out


def dispatch_fault(site: str) -> None:
    """A ``dispatch``-family fault in ``raise`` mode: raise the
    transient :class:`InjectedDispatchError` (the recovery ladder's
    retry input). ``exhaust``-mode specs are read by
    :func:`budget_exhaust` instead and never fire here."""
    f = _decide(f"{site}.dispatch", "dispatch", mode="raise")
    if f is None:
        return
    _record(f, site)
    raise InjectedDispatchError(
        f"chaos: injected dispatch failure at {site} "
        f"(seq {f.seq})")


def budget_exhaust(site: str) -> bool:
    """A ``dispatch``-family fault in ``exhaust`` mode: report a
    window-budget exhaustion (the caller drops its delta frontier and
    runs the full-width ladder rung)."""
    f = _decide(f"{site}.budget", "dispatch", mode="exhaust")
    if f is None:
        return False
    _record(f, site)
    return True


def should_crash(site: str) -> bool:
    """Whether a ``crash`` fault fires at this point — the HARNESS
    acts on it (drop the session, restore from checkpoint); the
    engine only schedules and records."""
    f = _decide(site, "crash")
    if f is None:
        return False
    _record(f, site)
    return True


def stall_point(site: str) -> float:
    """Sleep a ``stall`` fault's ``ms`` (capped) inside a wave —
    enough to trip the live ``absence:run.heartbeat`` rule in a
    watching monitor. Returns the seconds actually slept (0.0 when
    nothing fired)."""
    f = _decide(site, "stall")
    if f is None:
        return 0.0
    dur = min(max(f.ms, 0.0) / 1000.0, _STALL_CAP_S)
    _record(f, site, stall_ms=round(dur * 1000.0, 3))
    if dur:
        time.sleep(dur)
    return dur


# ------------------------------------------------------- net (PR 13)
#
# Wire-level fault hooks for the replication transport. Each hook is
# mode-filtered (a ``latency`` spec never advances at the ``reset``
# hook and vice versa — the same rule the dispatch family follows),
# so one plan can schedule independent partition/reset/latency/
# blackhole/dup streams against the same site with per-spec
# determinism. Site convention: the transport calls the dial-side
# hook at ``<site>.connect`` and the frame-send hooks at
# ``<site>.send``, so a spec's ``site`` of ``net.client`` matches
# both via the prefix rule.


def net_partition(site: str) -> bool:
    """Whether a ``partition``-mode net fault refuses this connect
    attempt (the dial raises its connection-refused path; the caller's
    backoff ladder owns the retry). One invocation per dial."""
    f = _decide(f"{site}.connect", "net", mode="partition")
    if f is None:
        return False
    _record(f, site)
    return True


def net_reset(site: str) -> bool:
    """Whether a ``reset``-mode net fault kills the connection at this
    frame send (the transport closes the socket; the peer sees EOF
    mid-protocol)."""
    f = _decide(f"{site}.send", "net", mode="reset")
    if f is None:
        return False
    _record(f, site)
    return True


def net_latency_ms(site: str) -> float:
    """Milliseconds of injected latency before this frame send (the
    spec's ``ms``, capped like stalls so no plan wedges a run for
    real); 0.0 when nothing fired."""
    f = _decide(f"{site}.send", "net", mode="latency")
    if f is None:
        return 0.0
    dur_ms = min(max(f.ms, 0.0), _STALL_CAP_S * 1000.0)
    _record(f, site, latency_ms=round(dur_ms, 3))
    return dur_ms


def net_blackhole(site: str) -> bool:
    """Whether a ``blackhole``-mode net fault silently drops this
    outbound frame (the send "succeeds", nothing crosses the wire —
    the peer's read deadline is the only detector)."""
    f = _decide(f"{site}.send", "net", mode="blackhole")
    if f is None:
        return False
    _record(f, site)
    return True


def net_dup(site: str) -> bool:
    """Whether a ``dup``-mode net fault sends this frame twice (same
    seq on the wire — the receiver's wire-duplicate detector must
    count it and re-ack idempotently)."""
    f = _decide(f"{site}.send", "net", mode="dup")
    if f is None:
        return False
    _record(f, site)
    return True


# ------------------------------------------------------ disk (PR 15)
#
# Durable-storage fault hooks for the WAL/checkpoint write seams.
# Mode-filtered like the net family (a ``torn`` spec never advances at
# the fsync hook and vice versa), so one plan schedules independent
# torn/bitrot/enospc/fsync/rename streams with per-spec determinism.
# Site convention: the WAL calls the record-write hooks at
# ``<site>.write``, the flush-to-media hook at ``<site>.fsync`` and
# the atomic-rename hooks at ``<site>.rename``, so a spec's ``site``
# of ``serve.wal`` (or ``serve.checkpoint``) matches via the prefix
# rule. The hooks only SCHEDULE; the storage layer owns the actual
# misbehavior (write the torn prefix, flip the byte, raise ENOSPC) —
# same split as ``should_crash``.


def disk_torn(site: str) -> bool:
    """Whether a ``torn``-mode disk fault tears this record write (the
    WAL writes a prefix of the line and fails the append — a crash
    mid-write; the op is never acknowledged and the next scan counts
    the tear)."""
    f = _decide(f"{site}.write", "disk", mode="torn")
    if f is None:
        return False
    _record(f, site)
    return True


def disk_bitrot(site: str, nbytes: int, **details) -> Optional[int]:
    """The byte index a ``bitrot``-mode disk fault flips in this
    record's durable copy (None when nothing fired). The caller's
    ``details`` ride the injection log — the soak's oracle reads the
    intact ground truth back from there, since the whole point of the
    fault is that the on-disk copy no longer has it."""
    f = _decide(f"{site}.write", "disk", mode="bitrot")
    if f is None or nbytes <= 0:
        return None
    idx = f.rng.randrange(int(nbytes))
    _record(f, site, index=idx, nbytes=int(nbytes), **details)
    return idx


def disk_enospc(site: str) -> bool:
    """Whether an ``enospc``-mode disk fault refuses this write (the
    WAL raises its unappendable error; admission must refuse with the
    durability shed rung — an unappendable journal never acks)."""
    f = _decide(f"{site}.write", "disk", mode="enospc")
    if f is None:
        return False
    _record(f, site)
    return True


def disk_fsync_fail(site: str) -> bool:
    """Whether a ``fsync``-mode disk fault fails this flush-to-media
    call (the WAL rotates to a fresh segment with evidence — a file
    descriptor that failed fsync has undefined durable state)."""
    f = _decide(f"{site}.fsync", "disk", mode="fsync")
    if f is None:
        return False
    _record(f, site)
    return True


def disk_rename_fail(site: str) -> bool:
    """Whether a ``rename``-mode disk fault fails this atomic
    manifest/GC rename (the caller must keep the previous manifest
    intact and surface the failure loudly)."""
    f = _decide(f"{site}.rename", "disk", mode="rename")
    if f is None:
        return False
    _record(f, site)
    return True


# ------------------------------------------------------ ship (PR 20)
#
# Telemetry-link fault hooks for the obs shipping plane. Mode-filtered
# like the net/disk families (a ``drop`` spec never advances at the
# dup hook and vice versa), so one plan schedules independent
# partition/drop/dup/reorder streams against the telemetry link with
# per-spec determinism. Site convention mirrors the net family: the
# exporter calls the dial-side hook at ``<site>.connect`` and the
# frame-send hooks at ``<site>.send``, so a spec's ``site`` of
# ``obs.ship`` matches both via the prefix rule. These hooks fire
# ONLY inside the shipping layer — the data-plane transport never
# calls them, which is exactly what lets a ship-chaos soak gate on
# bit-identical data-plane output while the telemetry plane burns.


def ship_partition(site: str) -> bool:
    """Whether a ``partition``-mode ship fault refuses this exporter
    dial (the exporter's seeded backoff ladder owns the retry; records
    keep accumulating in the bounded buffer, oldest dropped with
    evidence). One invocation per dial."""
    f = _decide(f"{site}.connect", "ship", mode="partition")
    if f is None:
        return False
    _record(f, site)
    return True


def ship_drop(site: str) -> bool:
    """Whether a ``drop``-mode ship fault silently discards this
    outbound obs frame (the send "succeeds" locally, nothing crosses
    the wire — the collector's watermark gap plus the exporter's
    unacked resend window are the detectors)."""
    f = _decide(f"{site}.send", "ship", mode="drop")
    if f is None:
        return False
    _record(f, site)
    return True


def ship_dup(site: str) -> bool:
    """Whether a ``dup``-mode ship fault sends this obs frame twice
    (same (origin, seq) on the wire — the collector's per-origin
    watermark dedup must absorb it without a duplicate record)."""
    f = _decide(f"{site}.send", "ship", mode="dup")
    if f is None:
        return False
    _record(f, site)
    return True


def ship_reorder(site: str) -> bool:
    """Whether a ``reorder``-mode ship fault holds this obs frame back
    one send, letting the next frame overtake it (the collector sees
    seqs arrive out of order and must either buffer or refuse-and-let-
    resume repair — never persist out of watermark order)."""
    f = _decide(f"{site}.send", "ship", mode="reorder")
    if f is None:
        return False
    _record(f, site)
    return True


# ------------------------------------------------------------ report


def injected() -> List[dict]:
    """A copy of the injected-fault log (bounded; ``chaos_report``
    counts drops)."""
    st = _resolve_state()
    with st.lock:
        return [dict(r) for r in st.log]


def chaos_report() -> dict:
    """The engine's own accounting: total injections, by family, by
    site/mode — the soak gate compares this against the DETECTED side
    (sync.reject, recovery events) so an injected-but-undetected
    fault fails loudly."""
    st = _resolve_state()
    with st.lock:
        log = [dict(r) for r in st.log]
        dropped = st.dropped
    by_family: Dict[str, int] = {}
    by_site: Dict[str, int] = {}
    for r in log:
        by_family[r["family"]] = by_family.get(r["family"], 0) + 1
        key = f"{r['site']}:{r['mode']}" if r.get("mode") else r["site"]
        by_site[key] = by_site.get(key, 0) + 1
    return {"injected": len(log), "dropped": dropped,
            "by_family": by_family, "by_site": by_site, "log": log}
