"""cause_tpu — a TPU-native causal-tree CRDT framework.

The flat public API, mirroring the reference facade
(reference: src/causal/core.cljc:15-53). Everything a user needs lives
here: the CausalBase database, CausalList / CausalMap collection types,
node construction, insert/append/weft/merge, materialization, and the
special values.

The one framework flag is the weave backend: pass ``weaver="jax"`` to
``base`` / ``clist`` / ``cmap`` to run full reweaves and merges as
batched XLA programs on TPU; the pure host weaver is the default and
the semantics oracle.
"""

from __future__ import annotations

from .cbase import (
    CausalBase,
    Ref,
    is_ref,
    new_causal_base,
    uuid_to_ref,
)
from .collections.ccounter import CausalCounter, new_causal_counter
from .collections.clist import CausalList, new_causal_list
from .collections.cmap import CausalMap, new_causal_map
from .collections.cset import CausalSet, new_causal_set
from .collections.shared import CausalError, CausalTree
from .ids import (
    H_HIDE,
    H_SHOW,
    HIDE,
    K,
    Keyword,
    ROOT_ID,
    SPECIALS,
    is_special,
    new_site_id,
    new_uid,
    node,
)

__version__ = "0.3.0"

# Special values have special effects on causal collections.
# NOTE: specials do not compose — applying hide to a hide is not a show
# (reference: core.cljc:13-14).
hide = HIDE
h_hide = H_HIDE
h_show = H_SHOW

# The id of the first node in every causal list; insert at the front by
# using root_id as the cause (core.cljc:16-18).
root_id = ROOT_ID

# Causal base. This is what you want 99% of the time (core.cljc:21-28).
base = new_causal_base


def transact(causal_base, tx):
    """Apply one or many changes at the current logical time
    (protocols.cljc:38-39)."""
    return causal_base.transact(tx)


def undo(causal_base):
    """Undo a transaction by the local site-id (protocols.cljc:43-44)."""
    return causal_base.undo()


def redo(causal_base):
    """Redo a transaction by the local site-id (protocols.cljc:45-46)."""
    return causal_base.redo()


def get_collection(causal_base, ref_or_uuid=None):
    """The collection for a ref/uuid, or the root collection
    (protocols.cljc:40-42)."""
    return causal_base.get_collection(ref_or_uuid)


def set_site_id(causal_base, site_id):
    """Set the local site-id (protocols.cljc:47-48)."""
    return causal_base.set_site_id(site_id)


# Causal meta attributes (core.cljc:33-35).
def get_uuid(causal):
    return causal.get_uuid()


def get_ts(causal):
    return causal.get_ts()


def get_site_id(causal):
    return causal.get_site_id()


# Causal collection types are convergent and EDN-like (core.cljc:41-42).
clist = new_causal_list
cmap = new_causal_map
cset = new_causal_set
ccounter = new_causal_counter


# Causal collection functions (core.cljc:45-50).
def insert(causal, node, more_nodes_in_tx=None):
    """Insert a node in the causal collection (protocols.cljc:20-21)."""
    return causal.insert(node, more_nodes_in_tx)


def append(causal, cause, value):
    """Create and insert a node at the current lamport timestamp
    (protocols.cljc:22-24)."""
    return causal.append(cause, value)


def weft(causal, ids_to_cut_yarns):
    """Cut each yarn at an id and rebuild the collection at a previous
    point in time (protocols.cljc:25-27)."""
    return causal.weft(ids_to_cut_yarns)


def merge(causal1, causal2):
    """Merge two causal collections of the same type and uuid
    (protocols.cljc:28-31)."""
    return causal1.merge(causal2)


def merge_all(causal, *more, tree=True):
    """Converge a whole fleet of replicas into one collection.

    Default shape (>= 4 device-weaver list replicas): the merge
    reduction tree (``cause_tpu.parallel.tree``) — ceil(log2(n))
    batched device rounds, level 0 full width, later levels riding
    the delta-native window path, with per-level convergence digests
    in the flight recorder. Bit-identical to folding ``merge`` in any
    order (the weave is a pure function of the node set; pinned in
    tests/test_merge_tree.py).

    ``tree=False`` — or any fleet outside the tree domain (maps,
    pure/native weavers, < 4 replicas, PackSpec overflow) — takes the
    flat path: the N-way node union + ONE reweave (``merge_many``),
    itself equal to the sequential pairwise fold."""
    # the weaver guard runs BEFORE the parallel import: pure/native
    # users must never pay a jax import (let alone backend init) for a
    # call that lands on merge_many anyway — the attribute check is
    # free, the package import is not
    if tree and len(more) >= 3 \
            and getattr(getattr(causal, "ct", None), "weaver", "") == "jax":
        from .parallel.tree import merge_all_tree

        routed = merge_all_tree([causal, *more])
        if routed is not None:
            return routed
    return causal.merge_many(more)


def get_weave(causal):
    """The woven cache of nodes (protocols.cljc:14-15)."""
    return causal.get_weave()


def content_digest(causal) -> int:
    """Canonical convergence digest of a collection's node bag:
    order-free, process-free, interner-free — two replicas anywhere
    (different hosts, different site-rank interners, different insert
    orders) digest equal iff their node sets are equal. Per-node
    blake2b over the canonical serde encoding, combined by a
    permutation-invariant sum. The device-side
    ``parallel.mesh.replica_digest`` is the fast intra-process twin;
    this one is the cross-host check (sync fleets compare it after
    anti-entropy rounds). No reference analogue (convergence there is
    checked by comparing whole trees)."""
    import hashlib
    import json as _json

    from . import serde as _serde

    total = 0
    # encode_node_items already emits JSON-able tagged data (the wire
    # and checkpoint encoding) — hash exactly those bytes, one
    # json.dumps each, no second to_data pass
    for item in _serde.encode_node_items(causal.get_nodes()):
        blob = _json.dumps(item, allow_nan=False).encode()
        h = hashlib.blake2b(blob, digest_size=8).digest()
        total = (total + int.from_bytes(h, "big")) & (2**64 - 1)
    return total


def blame(causal):
    """Who wrote what, when: the visible content annotated with each
    element's author site and lamport time. Every node carries complete
    history information — "time = lamport-ts, who = site-id"
    (reference: README.md:48) — so blame is a projection of the weave,
    not extra bookkeeping.

    Lists (and sets/counters, which share the list tree) yield
    ``[(value, site_id, lamport_ts), ...]`` in weave order; maps yield
    ``{key: (value, site_id, lamport_ts)}`` for each live key (the LWW
    winner's author); bases yield ``{keyword_path_key: ...}`` per
    collection uuid."""
    from .cbase import CausalBase as _CB
    from .collections.clist import causal_list_to_list
    from .collections.cmap import BLANK, CausalMap as _CM, active_node

    if isinstance(causal, _CB):
        return {
            uuid: blame(coll)
            for uuid, coll in causal.cb.collections.items()
        }
    if isinstance(causal, _CM):
        out = {}
        for key, key_weave in causal.ct.weave.items():
            node = active_node(key, key_weave)
            if node is not BLANK:
                nid = node[0]
                out[key] = (node[2], nid[1], nid[0])
        return out
    return [
        (value, nid[1], nid[0])
        for nid, _cause, value in causal_list_to_list(causal.ct)
    ]


def get_nodes(causal):
    """The canonical {id: (cause, value)} store (protocols.cljc:16-17)."""
    return causal.get_nodes()


# Causal conversion (core.cljc:53).
from .collections.shared import causal_to_edn  # noqa: E402

# Serialization: tagged JSON round-trip + bag-of-nodes reconstitution
# (the reference's print/reader + refresh-caches checkpoint story).
from .serde import dumps, loads  # noqa: E402
from .gc import (compact, compact_stats,  # noqa: E402
                 stability_frontier)
from .sync import (  # noqa: E402
    sync_base_pair,
    sync_pair,
    sync_stream,
    version_vector,
)

# Fleet-scale device APIs, lazily re-exported (PEP 562) so importing
# cause_tpu never drags jax/mesh machinery into pure-host users.
_FLEET_EXPORTS = {
    "merge_wave": "cause_tpu.parallel",
    "merge_tree": "cause_tpu.parallel",
    "FleetSession": "cause_tpu.parallel",
    "WaveResult": "cause_tpu.parallel",
    "WaveBuffers": "cause_tpu.parallel",
    "merge_map_wave": "cause_tpu.weaver.mapw",
}


def __getattr__(name):
    mod = _FLEET_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module 'cause_tpu' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)

__all__ = [
    "CausalBase",
    "CausalError",
    "CausalList",
    "CausalMap",
    "CausalTree",
    "K",
    "Keyword",
    "Ref",
    "HIDE",
    "H_HIDE",
    "H_SHOW",
    "SPECIALS",
    "ROOT_ID",
    "hide",
    "h_hide",
    "h_show",
    "root_id",
    "base",
    "transact",
    "undo",
    "redo",
    "is_ref",
    "uuid_to_ref",
    "get_collection",
    "set_site_id",
    "get_uuid",
    "get_ts",
    "get_site_id",
    "node",
    "clist",
    "cmap",
    "cset",
    "ccounter",
    "CausalSet",
    "CausalCounter",
    "new_causal_list",
    "new_causal_map",
    "new_causal_set",
    "new_causal_counter",
    "new_causal_base",
    "insert",
    "append",
    "weft",
    "merge",
    "merge_all",
    "blame",
    "compact",
    "compact_stats",
    "stability_frontier",
    "content_digest",
    "get_weave",
    "get_nodes",
    "causal_to_edn",
    "dumps",
    "loads",
    "sync_base_pair",
    "sync_pair",
    "sync_stream",
    "version_vector",
    "merge_wave",
    "merge_tree",
    "merge_map_wave",
    "FleetSession",
    "is_special",
    "new_uid",
    "new_site_id",
]
