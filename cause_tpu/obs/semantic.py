"""CRDT-semantic events: what the fleet *means*, not how long it took.

PR 1 gave the repo spans and counters (how long a wave took), PR 4
added device cost (what a wave cost the chip). This module is the
third layer — the *semantic* health of the replicated state itself,
the signal causally-consistent-replication systems treat as primary
(arXiv:1703.05424's staleness/divergence metrics; SafarDB-style
continuously-checked convergence digests, arXiv:2603.08003):

- **sync events** — every anti-entropy delta application (node count,
  incremental vs union path) and every full-bag fallback with its
  reason, so a fleet operator can see what fraction of rounds degrade
  to O(doc) resends;
- **wave digest agreement** — each merge wave / session wave emits one
  ``wave.digest`` event: how many pairs computed device digests, how
  many distinct values, whether the fleet agreed, plus a staleness
  histogram;
- **divergence monitors** — a per-pair staleness count (waves since
  the pair last matched the fleet's modal convergence digest) kept
  per document across waves, surfaced as ``fleet.staleness.max`` /
  ``.mean`` gauges; and when a wave's digests disagree, exactly one
  ``divergence`` event carrying first-differing-site provenance
  (which site's history the odd replica pair disagrees about first);
- **GC evidence** — ``gc.compact`` events and counters for nodes
  examined / reclaimed / safety-valve declines, so compaction stops
  throwing its evidence away;
- **collection health** — lazy-weave materializations with weave
  length vs live-value count and the tombstone ratio, the read-side
  cost signal the lazy fleet-editing mode exists to manage.

Contract (same as the rest of ``cause_tpu.obs``): stdlib-only at
import time, importable without jax/numpy; with ``CAUSE_TPU_OBS``
unset every entry point returns immediately — no records, no state,
no ``TRACE_SWITCHES`` env reads, byte-identical program-cache keys
(pinned by tests/test_fleet_obs.py). On jit-reachable paths, call
sites must sit behind ``obs.enabled()`` guards — causelint rule
OBS004 gates that (these functions assemble real field dicts, unlike
the no-op span/counter factories).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import core

__all__ = [
    "SEMANTIC_EVENT_PREFIXES",
    "enabled",
    "reset",
    "sync_applied",
    "sync_full_bag",
    "sync_rejected",
    "sync_quarantined",
    "sync_readmitted",
    "observe_wave",
    "observe_tree_level",
    "session_overflow",
    "token_headroom",
    "gc_compacted",
    "lazy_materialized",
    "first_differing_site",
]

# Instant events whose name matches one of these prefixes are the
# semantic vocabulary: the Perfetto exporter routes them onto named
# instant-event tracks (one track per family) instead of burying them
# in the per-thread span track.
SEMANTIC_EVENT_PREFIXES = (
    "sync.",
    "wave.digest",
    "wave.cost",
    "divergence",
    "gc.",
    "collection.",
    "fleet.",
    "tree.",
    "op.",
    "lag.",
    # PR 10: the live-telemetry read side's own vocabulary
    # (``live.snapshot`` periodic rollups, ``live.alert`` rule
    # firings) and the run heartbeats (``run.heartbeat`` from wave
    # dispatch, sync rounds, harvest ladder items, soak rounds) —
    # each renders as its own named Perfetto track, so a wedge
    # investigation reads alert/heartbeat swim-lanes directly above
    # the spans that stalled
    "live.",
    "run.",
    # PR 11: the chaos/recovery pairing — every injected fault
    # (``chaos.inject``) and every recovery-ladder transition
    # (``recovery.step``/``retry``/``restore``) on its own named
    # track, so a chaos soak reads as inject -> detect -> recover
    # swim-lanes over the wave spans they disrupted
    "chaos.",
    "recovery.",
    # PR 12/13: the serve-loop and network-transport vocabularies —
    # admission sheds/ticks, and the wire's connect/reconnect/
    # heartbeat/nack/duplicate evidence — each on its own named
    # track, so a partition investigation reads disconnect ->
    # backoff -> reconnect -> resumed-suffix swim-lanes over the
    # serve ticks they starved
    "serve.",
    "net.",
)


def enabled() -> bool:
    """Whether semantic events record anything (== ``obs.enabled()``)."""
    return core.enabled()


# ------------------------------------------------------------------ sync


def sync_applied(n_nodes: int, path: str, uuid: str = "") -> None:
    """One anti-entropy delta landed: ``path`` is ``"incremental"``
    (pure-weaver small-delta replay) or ``"union"`` (one-pass union +
    reweave), matching ``sync.apply_delta``'s dispatch."""
    if not core.enabled():
        return
    core.counter("sync.delta_rounds").inc()
    core.counter("sync.delta_nodes").inc(int(n_nodes))
    core.event("sync.delta_apply", nodes=int(n_nodes), path=path,
               **({"uuid": uuid} if uuid else {}))


def sync_full_bag(reason: str, uuid: str = "") -> None:
    """The prefix-gap fallback fired: the whole bag of nodes is being
    exchanged instead of a delta. ``reason`` is ``"cause-must-exist"``
    (our merge rejected the peer's delta), ``"peer-resync"`` (the
    peer rejected ours and asked for the bag),
    ``"payload-reject"`` (validate-before-apply refused the delta) or
    ``"quarantined"`` (the peer is serving its re-admission resync)."""
    if not core.enabled():
        return
    core.counter("sync.full_bag").inc()
    core.event("sync.full_bag", reason=reason,
               **({"uuid": uuid} if uuid else {}))


def sync_rejected(why: str, uuid: str = "", peer: str = "") -> None:
    """Validate-before-apply refused a sync payload at the ingest
    boundary (PR 11): the document is untouched, the round degrades
    to a full-bag resync, and this is the DETECTION evidence the
    chaos soak gates injected payload faults against."""
    if not core.enabled():
        return
    core.counter("sync.reject").inc()
    core.event("sync.reject", why=why,
               **{k: v for k, v in (("uuid", uuid), ("peer", peer))
                  if v})


def sync_quarantined(peer: str, uuid: str = "", rejects: int = 0) -> None:
    """A repeat offender crossed the consecutive-reject threshold and
    is quarantined out of delta exchanges and device waves until a
    clean full-bag resync re-admits it."""
    if not core.enabled():
        return
    core.counter("sync.quarantine").inc()
    core.event("sync.quarantine", peer=peer, rejects=int(rejects),
               **({"uuid": uuid} if uuid else {}))


def sync_readmitted(peer: str, uuid: str = "") -> None:
    """A quarantined replica served a clean validated full-bag resync
    and is back in the delta/wave fast paths."""
    if not core.enabled():
        return
    core.counter("sync.readmit").inc()
    core.event("sync.readmit", peer=peer,
               **({"uuid": uuid} if uuid else {}))


# ----------------------------------------------------------- divergence

# Per-(uuid, source) wave monitor state: wave index + per-pair
# staleness counts. Process-wide (waves on one document accumulate
# across merge_wave calls); reset() drops it for tests. Bounded:
# a 600k-round soak mints a fresh document per round, so the monitor
# evicts its least-recently-waved documents past _MON_MAX — staleness
# for a document nobody is waving is not a signal anyone reads.
_MON_LOCK = threading.Lock()
_MON: Dict[Tuple[str, str], dict] = {}
_MON_MAX = 4096


def reset() -> None:
    """Drop all divergence-monitor state (tests; obs.reset does not
    reach into the semantic layer)."""
    with _MON_LOCK:
        _MON.clear()


def first_differing_site(vv_ref: dict, vv_got: dict) -> Optional[dict]:
    """Divergence provenance between two merged version vectors
    (``sync.version_vector`` shape, ``{site: [ts, tx]}``): the first
    site — in sorted site order — whose entry differs, with both
    entries. None when the vectors are identical (digests that differ
    under identical vectors would mean the per-site prefix property
    broke, which the sync protocol precludes)."""
    for site in sorted(set(vv_ref) | set(vv_got)):
        a, b = vv_ref.get(site), vv_got.get(site)
        if a != b:
            return {"site": site, "expected": a, "got": b}
    return None


def observe_wave(uuid: str, digests: Sequence, valid: Sequence,
                 vv_of: Optional[Callable[[int], dict]] = None,
                 source: str = "wave") -> Optional[dict]:
    """Record one wave's convergence digests for document ``uuid``.

    ``digests[i]`` / ``valid[i]`` follow ``WaveResult``: a digest only
    counts where valid is truthy (fallback/poisoned rows carry no
    device digest). Emits one ``wave.digest`` event (pair count, valid
    count, distinct digest count, agreement verdict, staleness
    histogram), updates the per-pair staleness counts ("waves since
    this pair last matched the fleet's modal digest" — rows with no
    valid digest age too), sets the ``fleet.staleness.max`` / ``.mean``
    gauges, and when the valid digests disagree emits exactly one
    ``divergence`` event for the wave, with first-differing-site
    provenance when ``vv_of(pair_index) -> version_vector`` is given
    (called lazily, only for the reference and first-divergent pair).

    Returns the wave summary dict (the event's fields), or None when
    obs is off.
    """
    if not core.enabled():
        return None
    B = len(valid)
    vals = [int(digests[i]) for i in range(B) if valid[i]]
    counts: Dict[int, int] = {}
    for v in vals:
        counts[v] = counts.get(v, 0) + 1
    # the modal digest is the fleet's presumed-converged value; ties
    # break toward the earliest pair's digest (deterministic)
    modal = None
    if vals:
        best = max(counts.values())
        for i in range(B):
            if valid[i] and counts[int(digests[i])] == best:
                modal = int(digests[i])
                break
    agreed = bool(vals) and len(counts) == 1

    key = (str(uuid), source)
    with _MON_LOCK:
        st = _MON.pop(key, None)  # re-insert below: LRU order
        if st is None or len(st["stale"]) != B:
            st = {"wave": 0, "stale": [0] * B}
        _MON[key] = st
        while len(_MON) > _MON_MAX:
            _MON.pop(next(iter(_MON)))
        st["wave"] += 1
        wave_idx = st["wave"]
        stale: List[int] = st["stale"]
        ref_pair = None
        bad_pair = None
        for i in range(B):
            if valid[i] and int(digests[i]) == modal:
                stale[i] = 0
                if ref_pair is None:
                    ref_pair = i
            else:
                stale[i] += 1
                if bad_pair is None and valid[i]:
                    bad_pair = i
        hist: Dict[int, int] = {}
        for s_ in stale:
            hist[s_] = hist.get(s_, 0) + 1
        stale_max = max(stale) if stale else 0
        stale_mean = (sum(stale) / len(stale)) if stale else 0.0

    fields = {
        "uuid": str(uuid),
        "source": source,
        "wave": wave_idx,
        "pairs": B,
        "valid": len(vals),
        "distinct": len(counts),
        "agreed": agreed,
        "staleness": {str(k): v for k, v in sorted(hist.items())},
    }
    core.event("wave.digest", **fields)
    core.counter("fleet.waves").inc()
    core.gauge("fleet.staleness.max").set(stale_max)
    core.gauge("fleet.staleness.mean").set(round(stale_mean, 4))
    if vals and not agreed:
        core.counter("fleet.divergence").inc()
        div = {
            "uuid": str(uuid),
            "source": source,
            "wave": wave_idx,
            "pair": bad_pair,
            "digest": int(digests[bad_pair]) if bad_pair is not None
            else None,
            "expected": modal,
            "disagreeing": sum(1 for i in range(B)
                               if valid[i] and int(digests[i]) != modal),
        }
        if vv_of is not None and ref_pair is not None \
                and bad_pair is not None:
            try:
                prov = first_differing_site(vv_of(ref_pair),
                                            vv_of(bad_pair))
            except Exception:  # noqa: BLE001 - telemetry never raises
                prov = None
            if prov is not None:
                div["site"] = prov["site"]
                div["site_expected"] = prov["expected"]
                div["site_got"] = prov["got"]
        core.event("divergence", **div)
    return fields


def observe_tree_level(uuid: str, level: int, digests: Sequence,
                       valid: Sequence, pairs: int, byes: int = 0,
                       delta_ops: int = 0, window: int = 0,
                       path: str = "", dispatches: int = 0,
                       final: bool = False) -> Optional[dict]:
    """Record one merge-tree LEVEL's convergence evidence for document
    ``uuid`` (the hierarchical fleet-convergence rounds of
    ``parallel.tree``): a ``wave.digest`` event with ``source="tree"``
    plus a ``tree.level`` event carrying the level's shape
    (pairs/byes), divergence work (``delta_ops`` window lanes,
    ``window`` = per-side lane budget), kernel ``path``
    ("full"/"delta") and dispatch count.

    Unlike :func:`observe_wave`, intermediate levels deliberately run
    NO staleness aging and mint NO ``divergence`` incidents: mid-tree,
    each pair converges a *different* subtree, so distinct digests are
    the expected shape of a converging fleet, not a health incident —
    ``agreed`` is still reported (a symmetric fleet's levels agree,
    the CI smoke gates on it). The root level (``final=True``) has one
    pair whose digest IS the fleet's converged value; callers feed it
    to the ordinary :func:`observe_wave` monitors if they track the
    document across convergence calls.

    Returns the ``tree.level`` fields dict (the ``wave.cost`` join
    summary), or None when obs is off."""
    if not core.enabled():
        return None
    B = len(valid)
    vals = [int(digests[i]) for i in range(B) if valid[i]]
    distinct = len(set(vals))
    agreed = bool(vals) and distinct == 1
    dig_fields = {
        "uuid": str(uuid),
        "source": "tree",
        "level": int(level),
        "wave": int(level) + 1,
        "pairs": B,
        "valid": len(vals),
        "distinct": distinct,
        "agreed": agreed,
    }
    core.event("wave.digest", **dig_fields)
    fields = {
        "uuid": str(uuid),
        "level": int(level),
        "pairs": int(pairs),
        "byes": int(byes),
        "delta_ops": int(delta_ops),
        "window": int(window),
        "path": str(path),
        "dispatches": int(dispatches),
        "distinct": distinct,
        "agreed": agreed,
        "final": bool(final),
    }
    core.event("tree.level", **fields)
    core.counter("tree.levels").inc()
    if final:
        core.counter("tree.converges").inc()
    return fields


def session_overflow(rows: Sequence[int]) -> None:
    """A FleetSession wave blew its resident token budget (the session
    raises after this — the event is the post-mortem breadcrumb)."""
    if not core.enabled():
        return
    core.counter("fleet.session_overflow").inc()
    core.event("fleet.session_overflow", rows=list(rows))


def token_headroom(slack: int, site: str) -> None:
    """Gauge the token-budget headroom of a dispatch: how many tokens
    of the (pow2-quantized) ``u_max`` the current fleet does NOT need.
    Zero-adjacent headroom means the next divergence spike overflows
    and retries/falls back; ``site`` is ``wave`` or ``session``."""
    if not core.enabled():
        return
    core.gauge(f"fleet.token_headroom.{site}").set(int(slack))


# -------------------------------------------------------------------- gc


def gc_compacted(examined: int, reclaimed: int, refused: bool = False,
                 frontier: bool = False, uuid: str = "") -> None:
    """One ``gc.compact`` run's evidence: node counts in/out, whether
    the EDN safety valve declined the result, whether a stability
    frontier bounded the drop set."""
    if not core.enabled():
        return
    core.counter("gc.runs").inc()
    core.counter("gc.nodes_examined").inc(int(examined))
    core.counter("gc.nodes_reclaimed").inc(int(reclaimed))
    if refused:
        core.counter("gc.safety_valve").inc()
    core.event("gc.compact", examined=int(examined),
               reclaimed=int(reclaimed), refused=bool(refused),
               frontier=bool(frontier),
               **({"uuid": uuid} if uuid else {}))


# ------------------------------------------------------------ collections


def lazy_materialized(ct) -> None:
    """A lazy tree's weave was materialized (``shared.ensure_weave``
    paid the full rebuild). Records the weave length vs live-value
    count and the tombstone ratio — the exact quantity compaction
    exists to reclaim. List trees get the real hide-scan numbers;
    other shapes record lengths only."""
    if not core.enabled():
        return
    weave = ct.weave
    nodes = len(ct.nodes)
    fields = {"type": str(getattr(ct, "type", "?")), "nodes": nodes}
    if isinstance(weave, list):
        # imported lazily from the caller's own package: ensure_weave
        # runs inside collections, so this is always already loaded
        from ..collections.clist import hide_q
        from ..ids import ROOT_ID, is_special

        live = 0
        values = 0
        for i, n in enumerate(weave):
            if n[0] == ROOT_ID or is_special(n[2]):
                continue
            values += 1
            nxt = weave[i + 1] if i + 1 < len(weave) else None
            if not hide_q(n, nxt):
                live += 1
        ratio = (values - live) / values if values else 0.0
        fields.update(weave_len=len(weave), values=values, live=live,
                      tombstone_ratio=round(ratio, 4))
        core.gauge("collection.tombstone_ratio").set(round(ratio, 4))
        core.gauge("collection.weave_len").set(len(weave))
        core.gauge("collection.live").set(live)
    elif isinstance(weave, dict):
        fields.update(weave_len=sum(len(v) for v in weave.values()),
                      keys=len(weave))
    core.counter("collection.lazy_materialize").inc()
    core.event("collection.materialize", **fields)
