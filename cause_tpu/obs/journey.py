"""Journey reconstruction: clock-skew-corrected causal chains from
``xtrace`` hop streams, with per-hop SLO decomposition.

``xtrace`` (the write side) emits one ``xtrace.hop`` record per hop a
trace takes — mint → send → recv → admit → journal → tick → wave →
apply → converged — each from whatever process the hop ran in, into
that process's own obs sidecar. This module is the read side: feed it
the merged streams and it re-links the hops per trace, maps every
process's raw wall timestamps onto ONE timebase (the median of the
``xtrace.clock`` offset samples the hello/ping exchanges produced),
and answers the two questions the per-process layers cannot:

- **where did THIS op's time go?** — :func:`JourneyFold.journey`
  returns one trace's corrected, causally-ordered hop timeline with
  per-step deltas and orphan flags (a hop whose parent span never
  appears has lost evidence — the journey is incomplete, not merely
  slow);
- **where does the FLEET's p99 go?** — :func:`JourneyFold.report`
  folds every finished journey's step deltas into per-edge mergeable
  histograms (``mint→send``, ``send→recv`` — the wire edge —
  ``admit→journal``, ``tick→wave``, ``apply→converged``, ...), so the
  end-to-end SLO decomposes into the hop that actually owns the tail.

Clock correction: every ``xtrace.clock`` record is one NTP-style
half-RTT estimate of ``remote_clock - local_clock`` from an observer
pid to a remote pid. The fold takes the per-edge median (robust to the
odd delayed exchange), picks the most-observed remote pid as the
reference timebase (the server — every client measured an edge to it),
and shifts each observer pid's hop timestamps by its median offset.
Pids with no edge to the reference stay uncorrected (same-host
processes share a clock anyway); cross-host journeys without a clock
edge render, but their wire-edge deltas are labeled by the caller's
own skew.

Retention is tail-based: the live fold (``obs watch``) keeps full hop
detail only for the worst journeys by total latency (everything else
folds into the histograms and is dropped), bounded by
``exemplar_max``; the CLI constructs the fold with ``retain_all=True``
and keeps everything, so any trace id printed by ``obs lag`` can be
drilled into.

Read side only: works with obs OFF (analyzing someone else's
sidecars); stdlib only, no jax/numpy.

CLI::

    python -m cause_tpu.obs journey <trace_id> a.jsonl b.jsonl ...
    python -m cause_tpu.obs journey --worst 5 a.jsonl b.jsonl ...
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .lag import LagHistogram
from .xtrace import HOP_ORDER

__all__ = ["JourneyFold", "journey_report", "render_report",
           "render_journey", "main"]

# retained traces (retain_all mode; live mode evicts far earlier)
_TRACE_MAX = 8192
# hops kept per trace (a pathological retransmit storm stays bounded)
_TRACE_HOPS_MAX = 512
# clock offset samples kept per (pid, remote_pid) edge
_CLOCK_SAMPLES_MAX = 256
# finished-trace ids remembered so late hops don't resurrect a
# finalized journey (live mode)
_DONE_MAX = 8192

# terminal hop names: seeing one ends the journey (live finalization)
_TERMINAL = ("converged", "shed")

_HOP_RANK = {name: i for i, name in enumerate(HOP_ORDER)}


class JourneyFold:
    """Incremental journey reconstructor: feed obs records one at a
    time (`feed`), read per-trace timelines (`journey`), the worst
    offenders (`worst`) or the fleet-wide per-hop decomposition
    (`report`) at any point.

    ``retain_all=True`` (the CLI) keeps every trace's hops resident
    (bounded by ``_TRACE_MAX``); the default live mode finalizes a
    journey at its terminal hop (``converged``/``shed``), folds its
    step deltas into the histograms, and retains full hop detail only
    while it is among the ``exemplar_max`` worst by total latency —
    the tail-based exemplar rule."""

    __slots__ = ("retain_all", "slo_ms", "exemplar_max", "_traces",
                 "_clock", "_done", "_edge_hists", "_total_hist",
                 "_complete", "_shed", "_orphan_hops", "_finalized",
                 "_exemplars")

    def __init__(self, retain_all: bool = False,
                 slo_ms: Optional[float] = None,
                 exemplar_max: int = 8):
        self.retain_all = bool(retain_all)
        self.slo_ms = float(slo_ms) if slo_ms is not None else None
        self.exemplar_max = int(exemplar_max)
        # trace id -> {"hops": [raw hop dicts], "spans": set}
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        # (pid, remote_pid) -> [offset_us samples]
        self._clock: Dict[Tuple[int, int], List[float]] = {}
        self._done: "OrderedDict[str, None]" = OrderedDict()
        # live-mode aggregates (retain_all computes these in report())
        self._edge_hists: Dict[str, LagHistogram] = {}
        self._total_hist = LagHistogram()
        self._complete = 0
        self._shed = 0
        self._orphan_hops = 0
        self._finalized = 0
        # finalized worst journeys kept in full: [(total_ms, journey)]
        self._exemplars: List[Tuple[float, dict]] = []

    # ---------------------------------------------------------- feed

    def feed(self, e: dict) -> None:
        """Consume one obs record (non-xtrace records are free)."""
        if e.get("ev") != "event":
            return
        name = e.get("name")
        if name == "xtrace.clock":
            f = e.get("fields") or {}
            off = f.get("offset_us")
            rpid = f.get("remote_pid")
            pid = e.get("pid")
            if isinstance(off, (int, float)) and isinstance(rpid, int) \
                    and isinstance(pid, int) and pid != rpid:
                xs = self._clock.setdefault((pid, rpid), [])
                xs.append(float(off))
                del xs[:-_CLOCK_SAMPLES_MAX]
            return
        if name != "xtrace.hop":
            return
        f = e.get("fields") or {}
        tid = f.get("trace")
        if not isinstance(tid, str) or not tid:
            return
        if not self.retain_all and tid in self._done:
            return
        entry = self._traces.pop(tid, None)
        if entry is None:
            entry = {"hops": [], "spans": set()}
        self._traces[tid] = entry
        hop = {
            "hop": str(f.get("hop") or "?"),
            "span": str(f.get("span") or ""),
            "parent": str(f.get("parent") or ""),
            "pid": e.get("pid") if isinstance(e.get("pid"), int) else 0,
            "ts_us": (int(e["ts_us"])
                      if isinstance(e.get("ts_us"), (int, float)) else 0),
            "attrs": {k: v for k, v in f.items()
                      if k not in ("trace", "span", "parent", "hop")},
        }
        if len(entry["hops"]) < _TRACE_HOPS_MAX:
            entry["hops"].append(hop)
            if hop["span"]:
                entry["spans"].add(hop["span"])
        if not self.retain_all and hop["hop"] in _TERMINAL:
            self._finalize_live(tid, entry)
        while len(self._traces) > _TRACE_MAX:
            old_tid, old = self._traces.popitem(last=False)
            if not self.retain_all:
                # evicted in flight: still fold what it has
                self._finalize_live(old_tid, old)

    def feed_many(self, events) -> None:
        for e in events:
            self.feed(e)

    # --------------------------------------------------------- clock

    def offsets(self) -> Tuple[Dict[int, float], Optional[int]]:
        """Per-pid correction (add to that pid's raw ``ts_us`` to land
        on the reference timebase) and the reference pid. The
        reference is the most-observed REMOTE pid — the server every
        client took clock samples against; with no samples at all,
        every pid stays uncorrected (one-process streams)."""
        med: Dict[Tuple[int, int], float] = {}
        for edge, xs in self._clock.items():
            med[edge] = sorted(xs)[len(xs) // 2]
        votes: Dict[int, int] = {}
        for (_pid, rpid), _off in med.items():
            votes[rpid] = votes.get(rpid, 0) + 1
        if not votes:
            return {}, None
        ref = max(votes, key=lambda r: (votes[r], -r))
        out: Dict[int, float] = {ref: 0.0}
        for (pid, rpid), off in med.items():
            # offset = remote - local, so local + offset = remote time
            if rpid == ref:
                out.setdefault(pid, off)
        for (pid, rpid), off in med.items():
            # the reverse edge: the ref measured SOMEONE ELSE's clock
            if pid == ref:
                out.setdefault(rpid, -off)
        return out, ref

    # ---------------------------------------------------- finalizing

    def _build(self, tid: str, entry: dict,
               offsets: Dict[int, float]) -> dict:
        """One trace's journey: corrected causally-ordered hops with
        per-step deltas, orphan flags, the per-edge decomposition and
        the mint→terminal total."""
        hops = []
        spans = entry["spans"]
        for h in entry["hops"]:
            corrected = h["ts_us"] + offsets.get(h["pid"], 0.0)
            hops.append(dict(h, ts_corrected_us=corrected,
                             orphan=bool(h["parent"]
                                         and h["parent"] not in spans)))
        # causal order: corrected time first; the hop vocabulary rank
        # breaks exact ties (one-process streams share a clock, so
        # same-microsecond mint/send pairs keep their causal order)
        hops.sort(key=lambda h: (h["ts_corrected_us"],
                                 _HOP_RANK.get(h["hop"], len(HOP_ORDER))))
        prev_ts = None
        for h in hops:
            h["dt_ms"] = (round((h["ts_corrected_us"] - prev_ts) / 1000.0, 3)
                          if prev_ts is not None else 0.0)
            prev_ts = h["ts_corrected_us"]
        orphans = sum(1 for h in hops if h["orphan"])
        # the decomposition edges: first corrected ts per hop name,
        # consecutive present names in vocabulary order
        first_ts: Dict[str, float] = {}
        for h in hops:
            first_ts.setdefault(h["hop"], h["ts_corrected_us"])
        # observed (corrected) order, vocabulary rank breaking exact
        # ties: the truthful chain — a local apply can land before the
        # wave-completion stamp, a remote apply after it
        names = sorted(first_ts,
                       key=lambda n: (first_ts[n],
                                      _HOP_RANK.get(n, len(HOP_ORDER))))
        edges: Dict[str, float] = {}
        for a, b in zip(names, names[1:]):
            edges[f"{a}→{b}"] = round(
                (first_ts[b] - first_ts[a]) / 1000.0, 3)
        terminal = None
        for h in reversed(hops):
            if h["hop"] in _TERMINAL:
                terminal = h["hop"]
                break
        total_ms = None
        if hops:
            if "mint" in first_ts and terminal == "converged":
                total_ms = round(
                    (first_ts["converged"] - first_ts["mint"]) / 1000.0, 3)
            else:
                total_ms = round((hops[-1]["ts_corrected_us"]
                                  - hops[0]["ts_corrected_us"]) / 1000.0, 3)
        return {
            "trace": tid,
            "hops": hops,
            "pids": sorted({h["pid"] for h in hops}),
            "orphans": orphans,
            "terminal": terminal,
            "complete": bool(terminal == "converged" and not orphans
                             and "mint" in first_ts),
            "total_ms": total_ms,
            "edges": edges,
        }

    def _fold_journey(self, j: dict) -> None:
        for edge, ms in j["edges"].items():
            self._edge_hists.setdefault(
                edge, LagHistogram()).record_us(ms * 1000.0)
        if j["terminal"] == "converged" and j["total_ms"] is not None:
            self._total_hist.record_us(j["total_ms"] * 1000.0)
        if j["complete"]:
            self._complete += 1
        if j["terminal"] == "shed":
            self._shed += 1
        self._orphan_hops += j["orphans"]
        self._finalized += 1

    def _finalize_live(self, tid: str, entry: dict) -> None:
        """Live-mode journey end: fold the aggregates, keep full hop
        detail only for the tail (worst-N over the SLO)."""
        offsets, _ref = self.offsets()
        j = self._build(tid, entry, offsets)
        self._fold_journey(j)
        self._traces.pop(tid, None)
        self._done[tid] = None
        while len(self._done) > _DONE_MAX:
            self._done.popitem(last=False)
        total = j["total_ms"] or 0.0
        if self.slo_ms is not None and total <= self.slo_ms \
                and not j["orphans"]:
            return  # inside SLO and evidence-complete: aggregate only
        self._exemplars.append((total, j))
        self._exemplars.sort(key=lambda p: -p[0])
        del self._exemplars[self.exemplar_max:]

    # ---------------------------------------------------------- read

    def journey(self, trace_id: str) -> Optional[dict]:
        """One trace's reconstructed journey (retained traces and
        live-mode exemplars), or None."""
        tid = str(trace_id)
        entry = self._traces.get(tid)
        if entry is not None:
            offsets, _ref = self.offsets()
            return self._build(tid, entry, offsets)
        for _total, j in self._exemplars:
            if j["trace"] == tid:
                return j
        return None

    def worst(self, n: int = 5) -> List[dict]:
        """The ``n`` worst journeys by total latency (terminal ones
        first — an in-flight trace's total is a lower bound)."""
        offsets, _ref = self.offsets()
        js = [self._build(tid, entry, offsets)
              for tid, entry in self._traces.items()]
        js.extend(j for _t, j in self._exemplars)
        js.sort(key=lambda j: -(j["total_ms"] or 0.0))
        return js[:max(0, int(n))]

    def report(self) -> dict:
        """The fleet-wide journey report: counts, clock edges, the
        total distribution and the per-edge decomposition (sorted by
        total time owned — the hop that owns the p99 leads)."""
        offsets, ref = self.offsets()
        if self.retain_all:
            edge_hists: Dict[str, LagHistogram] = {}
            total_hist = LagHistogram()
            complete = shed = orphan_hops = finalized = inflight = 0
            for tid, entry in self._traces.items():
                j = self._build(tid, entry, offsets)
                for edge, ms in j["edges"].items():
                    edge_hists.setdefault(
                        edge, LagHistogram()).record_us(ms * 1000.0)
                if j["terminal"] == "converged" \
                        and j["total_ms"] is not None:
                    total_hist.record_us(j["total_ms"] * 1000.0)
                if j["terminal"] is None:
                    inflight += 1
                else:
                    finalized += 1
                if j["complete"]:
                    complete += 1
                if j["terminal"] == "shed":
                    shed += 1
                orphan_hops += j["orphans"]
        else:
            edge_hists = self._edge_hists
            total_hist = self._total_hist
            complete, shed = self._complete, self._shed
            orphan_hops = self._orphan_hops
            finalized = self._finalized
            inflight = len(self._traces)

        def dist(h: LagHistogram) -> dict:
            return {
                "count": h.count,
                "p50_ms": h.quantile_ms(0.50),
                "p95_ms": h.quantile_ms(0.95),
                "p99_ms": h.quantile_ms(0.99),
                "mean_ms": h.mean_ms(),
                "max_ms": (round(h.max_us / 1000.0, 4)
                           if h.max_us is not None else None),
            }

        def edge_rank(item):
            name = item[0].split("→", 1)[0]
            return _HOP_RANK.get(name, len(HOP_ORDER))

        edges = [dict(edge=name, total_ms=round(h.sum_us / 1000.0, 3),
                      **dist(h))
                 for name, h in sorted(edge_hists.items(),
                                       key=edge_rank)]
        clock_edges = []
        for (pid, rpid), xs in sorted(self._clock.items()):
            clock_edges.append({
                "pid": pid, "remote_pid": rpid, "samples": len(xs),
                "offset_us": round(sorted(xs)[len(xs) // 2], 1),
            })
        return {
            "traces": finalized + inflight,
            "finalized": finalized,
            "complete": complete,
            "shed": shed,
            "inflight": inflight,
            "orphan_hops": orphan_hops,
            "clock": {"ref_pid": ref, "edges": clock_edges},
            "total": dist(total_hist),
            "edges": edges,
        }

    def summary(self) -> dict:
        """The compact live-dashboard section (``obs watch``): scalar
        axes only, plus the worst exemplar's trace id — the drill-down
        handle the full CLI accepts."""
        rep = self.report()
        worst = self._exemplars[0] if self._exemplars else None
        return {
            "active": bool(rep["traces"] or self._clock),
            "traces": rep["traces"],
            "complete": rep["complete"],
            "shed": rep["shed"],
            "inflight": rep["inflight"],
            "orphan_hops": rep["orphan_hops"],
            "total_p50_ms": rep["total"]["p50_ms"],
            "total_p99_ms": rep["total"]["p99_ms"],
            "worst_trace": worst[1]["trace"] if worst else None,
            "worst_total_ms": worst[0] if worst else None,
            "clock_edges": len(rep["clock"]["edges"]),
        }


def journey_report(events, slo_ms: Optional[float] = None) -> dict:
    """Batch form: the whole (merged) stream in, the journey report
    out — :class:`JourneyFold` fed once, ``retain_all`` semantics."""
    fold = JourneyFold(retain_all=True, slo_ms=slo_ms)
    fold.feed_many(events)
    return fold.report()


# ---------------------------------------------------------- rendering


def render_journey(j: dict) -> str:
    """One trace's human timeline."""
    head = (f"trace {j['trace']}: "
            + (f"{j['total_ms']:g} ms" if j["total_ms"] is not None
               else "in flight")
            + f", {len(j['hops'])} hop(s) across "
            f"{len(j['pids'])} process(es)")
    if j["terminal"]:
        head += f", terminal={j['terminal']}"
    if j["orphans"]:
        head += f", {j['orphans']} ORPHAN hop(s)"
    lines = [head]
    t0 = j["hops"][0]["ts_corrected_us"] if j["hops"] else 0.0
    for h in j["hops"]:
        at = (h["ts_corrected_us"] - t0) / 1000.0
        attrs = " ".join(f"{k}={v}" for k, v in sorted(h["attrs"].items()))
        lines.append(
            f"  +{at:9.3f} ms  {h['hop']:<9s} pid {h['pid']}"
            + (f"  [ORPHAN parent={h['parent']}]" if h["orphan"] else "")
            + (f"  {attrs}" if attrs else ""))
    if j["edges"]:
        steps = "  ".join(f"{e} {ms:g}ms"
                          for e, ms in j["edges"].items())
        lines.append(f"  decomposition: {steps}")
    return "\n".join(lines)


def render_report(report: dict) -> str:
    """The fleet-wide journey block."""
    lines = [
        f"journeys: {report['traces']} trace(s) — "
        f"{report['complete']} complete, {report['shed']} shed, "
        f"{report['inflight']} in flight, "
        f"{report['orphan_hops']} orphan hop(s)",
    ]
    t = report["total"]
    if t["count"]:
        lines.append(
            f"  mint→converged: p50 {t['p50_ms']:g} ms  "
            f"p95 {t['p95_ms']:g}  p99 {t['p99_ms']:g}  "
            f"max {t['max_ms']:g}  (n={t['count']})")
    ck = report["clock"]
    if ck["edges"]:
        parts = ", ".join(
            f"{c['pid']}→{c['remote_pid']}: {c['offset_us']:+g} us "
            f"(n={c['samples']})" for c in ck["edges"][:6])
        lines.append(f"  clock (ref pid {ck['ref_pid']}): {parts}"
                     + (f", ... {len(ck['edges']) - 6} more"
                        if len(ck["edges"]) > 6 else ""))
    if report["edges"]:
        lines.append("  per-hop decomposition (time owned):")
        ranked = sorted(report["edges"], key=lambda e: -e["total_ms"])
        for e in report["edges"]:
            mark = " ◀" if ranked and e is ranked[0] else ""
            lines.append(
                f"    {e['edge']:<20s} p50 {e['p50_ms']:g} ms  "
                f"p95 {e['p95_ms']:g}  max {e['max_ms']:g}  "
                f"(n={e['count']}, Σ {e['total_ms']:g} ms){mark}")
    return "\n".join(lines)


# ----------------------------------------------------------------- CLI


def main(argv=None) -> int:
    import argparse
    import json
    import os
    import re
    import sys

    from .perfetto import load_streams

    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs journey",
        description="Reconstruct cross-process op journeys from obs "
                    "JSONL stream(s): clock-skew-corrected causal hop "
                    "timelines per trace id, worst offenders, and the "
                    "fleet-wide per-hop latency decomposition. "
                    "Multiple streams (one per process) merge.")
    ap.add_argument("trace", nargs="?", default=None,
                    help="a trace id (as printed by `obs lag` / "
                         "op.lag records); omit with --worst")
    ap.add_argument("jsonl", nargs="*",
                    help="obs event file(s) (JSON lines)")
    ap.add_argument("--file", action="append", default=None,
                    metavar="PATH", dest="files",
                    help="obs event file (repeatable; unambiguous "
                         "alternative to the positional file list — "
                         "a positional that is both 16-hex and an "
                         "existing path always means the trace id)")
    ap.add_argument("--worst", type=int, default=None, metavar="N",
                    help="show the N worst journeys by total latency "
                         "instead of one trace id")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="journey SLO in ms (annotates the report)")
    ap.add_argument("--json", action="store_true",
                    help="emit JSON instead of text")
    a = ap.parse_args(argv)

    files = list(a.files or []) + list(a.jsonl)
    trace = a.trace
    # `journey --worst 5 a.jsonl b.jsonl`: the first file lands in the
    # optional trace slot. A bare 16-hex token is ALWAYS a trace id —
    # before PR 20 an unlucky file named like one (`ls > deadbeef...`)
    # silently won the os.path.exists tiebreak and was read as a
    # stream; now only a non-id-shaped existing path demotes to the
    # file list (--file skips the heuristic entirely).
    if trace is not None and not re.fullmatch(r"[0-9a-f]{16}", trace) \
            and os.path.exists(trace):
        files.insert(0, trace)
        trace = None
    if not files:
        ap.error("no obs stream files given")
    for path in files:
        if not os.path.exists(path):
            print(f"journey: no such file: {path}", file=sys.stderr)
            return 2
    if trace is None and a.worst is None:
        a.worst = 5

    fold = JourneyFold(retain_all=True, slo_ms=a.slo_ms)
    fold.feed_many(load_streams(files))

    if trace is not None:
        j = fold.journey(trace)
        if j is None:
            print(f"journey: trace {trace} not found in "
                  f"{len(files)} stream(s)", file=sys.stderr)
            return 1
        print(json.dumps(j, indent=1) if a.json else render_journey(j))
        return 0

    report = fold.report()
    worst = fold.worst(a.worst)
    if a.json:
        print(json.dumps({"report": report, "worst": worst}, indent=1))
        return 0
    print(render_report(report))
    if worst:
        print(f"\nworst {len(worst)} journey(s):")
        for j in worst:
            print(render_journey(j))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
