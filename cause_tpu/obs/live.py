"""Live telemetry: streaming aggregation + alert rules over obs
streams AS THEY ARE PRODUCED.

Every obs layer before this one (PR 1/4/5/6/9) is post-hoc: JSONL
sidecars analyzed after the run by ``obs fleet``/``gap``/``lag``.
ROADMAP item 4's sync service needs the opposite shape — a live
feedback loop where the admission controller reads sliding lag and
headroom gauges WHILE the fleet runs — and the chip-certification
windows keep wedging invisibly at round end with no in-flight signal.
This module is the read side running concurrently with the write side:

- **the incremental fold engine** (:class:`LiveFold`) — consumes obs
  records one at a time and maintains rolling fleet/cost/lag state:
  the fleet-health report (documents, staleness, divergence
  incidents, full-bag rate), the convergence-lag distributions with
  SLO attainment + burn rate (sliding p50/p95/p99 from the mergeable
  pow2 histograms), the wave cost totals with the
  O(doc)-vs-O(delta) slope, ``fleet.token_headroom`` minima,
  waves/sec, dispatch counts, and per-event-name recency (the wedge
  signal). It is built ON the batch reducers (``FleetReducer``,
  ``LagReducer``, ``CostReducer``), so its folds are bit-equal to the
  post-hoc ``lag_summary``/``fleet_report``/``costmodel_digest`` on
  the same stream — the same last-per-(pid, reset-epoch) summation
  rules ``obs.lag`` defines;
- **feeds** — in-process via the bounded subscriber hook on the PR-1
  sink (:func:`attach` → :class:`LiveAttachment`), cross-process by
  tailing one or more O_APPEND JSONL sidecars
  (:class:`StreamTailer` / :class:`MultiTailer`, rotation-aware:
  an inode change or truncation reopens from byte zero);
- **the alert-rule registry** — declarative threshold / absence /
  burn-rate rules over the snapshot (:func:`parse_rule`,
  :func:`default_rules`: ``"burn>2"``, ``"absence:wave.digest:120"``
  — the wedge detector — and ``"full_bag_rate>0.2"``), evaluated
  edge-triggered by :class:`LiveMonitor`: each rule fires ONE
  ``live.alert`` record per excursion (re-arming on recovery) and
  invokes registered callbacks — the signal surface item 4's dynamic
  batch-sizing controller subscribes to;
- **periodic rollups** — ``live.snapshot`` records (compact scalar
  summary of the fold) for the sidecar, Perfetto (named
  ``semantic:live`` track) and the ``obs watch`` dashboard /
  Prometheus endpoint (``cause_tpu.obs.watch``).

Contract (same as the rest of ``cause_tpu.obs``): stdlib + core only,
importable without jax/numpy. The read-side classes work with obs OFF
(tailing someone else's sidecar needs no local recording); the
write-side entry points are inert — :func:`attach` returns None, and
``live.alert``/``live.snapshot`` are only ever emitted through
``core.event`` (a no-op when disabled), so the obs-off invariance
(no records, no env reads, no subscriber state, byte-identical
program-cache keys) holds for the entire layer — pinned by
tests/test_live.py. On jit-reachable paths, call sites must sit
behind ``obs.enabled()`` guards — causelint rule OBS007 gates that.
"""

from __future__ import annotations

import os
import json
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from . import core
from .costmodel import CostReducer
from .fleet import FleetReducer
from .journey import JourneyFold

__all__ = [
    "LiveFold",
    "Rule",
    "parse_rule",
    "default_rules",
    "DEFAULT_RULE_SPECS",
    "LiveMonitor",
    "LiveAttachment",
    "attach",
    "StreamTailer",
    "MultiTailer",
    "snapshot_path",
]

# rolling waves/sec window (seconds): long enough to smooth a bursty
# fleet, short enough that a wedge shows as the rate falling to zero
# within a couple of dashboard refreshes
_RATE_WINDOW_S = 60.0
# wave timestamps retained for the rate estimate (bounded memory)
_RATE_TS_MAX = 8192


class LiveFold:
    """The incremental fold engine: one obs record in, rolling state
    updated. Wraps the batch-equal reducers (fleet/lag/counters/cost)
    and adds the live-only axes no post-hoc report needs — event
    recency, waves/sec, token-headroom minima. Pure read side: safe
    to run with obs off (a monitor tailing a foreign sidecar)."""

    __slots__ = ("fleet", "cost", "journeys", "first_ts_us",
                 "last_ts_us", "last_seen_us", "_wave_ts",
                 "headroom_min", "headroom_last", "heartbeat",
                 "serve_gauges", "_shed_ts", "shed_total",
                 "serve_ticks", "net_gauges", "net_counts",
                 "_reconnect_ts", "disk_faults", "journal_torn",
                 "obs_gauges")

    def __init__(self):
        self.fleet = FleetReducer()
        self.cost = CostReducer()
        # PR 19, the distributed-tracing axes: streaming journey
        # reconstruction with tail-based exemplar retention — only
        # SLO-breaching (or orphaned) journeys keep full hop detail;
        # everything else folds into the per-edge histograms
        self.journeys = JourneyFold(slo_ms=100.0)
        self.first_ts_us: Optional[int] = None
        self.last_ts_us: Optional[int] = None
        # event name -> newest ts_us (the absence rules' input)
        self.last_seen_us: Dict[str, int] = {}
        self._wave_ts: deque = deque(maxlen=_RATE_TS_MAX)
        # token-headroom gauges: site ("wave"/"session") -> min / last
        self.headroom_min: Dict[str, float] = {}
        self.headroom_last: Dict[str, float] = {}
        # the newest run.heartbeat fields (wedge triage: which ladder
        # item / wave stage was alive last)
        self.heartbeat: Optional[dict] = None
        # PR 12, the sync service's live axes: last-seen serve gauges
        # (queue_depth / resident_docs / t_batch_ms), shed-event
        # timestamps (the shed_rate window), tick count. A stream with
        # no serve.* records at all renders serve.active=False and the
        # serve absence rule stays silent (a batch soak is not a dead
        # service — it is not a service).
        self.serve_gauges: Dict[str, float] = {}
        self._shed_ts: deque = deque(maxlen=_RATE_TS_MAX)
        self.shed_total = 0
        self.serve_ticks = 0
        # PR 13, the network transport's live axes: last-seen net
        # gauges (outbound_depth / connections), event counts
        # (connects, reconnects, nacks, duplicate evidence, sheds)
        # and the reconnect timestamps behind reconnects_per_min —
        # the flap detector. A stream with no net.* records renders
        # net.active=False and the net rules stay silent (a batch
        # soak is not a dead transport — it is not a transport).
        self.net_gauges: Dict[str, float] = {}
        self.net_counts: Dict[str, int] = {}
        self._reconnect_ts: deque = deque(maxlen=_RATE_TS_MAX)
        # PR 15, the durable-storage axes: every evidenced storage
        # degradation (``serve.disk``: torn/bitrot/enospc/fsync/
        # rename) and every torn-or-corrupt journal line surfaced by
        # a replay (``serve.journal_torn`` carries the per-replay
        # counts in its fields)
        self.disk_faults = 0
        self.journal_torn = 0
        # PR 20, the telemetry plane's own health: ``obs.dropped.*``
        # gauges (per-subscriber drop counters — a saturated bounded
        # queue used to drop silently into a field nobody watched)
        # and whatever else the shipping layer gauges under ``obs.``
        self.obs_gauges: Dict[str, float] = {}

    def feed(self, e: dict) -> None:
        self.fleet.feed(e)
        self.cost.feed(e)
        self.journeys.feed(e)
        ts = e.get("ts_us")
        if isinstance(ts, (int, float)):
            ts = int(ts)
            if self.first_ts_us is None or ts < self.first_ts_us:
                self.first_ts_us = ts
            if self.last_ts_us is None or ts > self.last_ts_us:
                self.last_ts_us = ts
        ev = e.get("ev")
        name = e.get("name")
        if ev == "event" and name:
            if isinstance(ts, int):
                prev = self.last_seen_us.get(name)
                if prev is None or ts > prev:
                    self.last_seen_us[name] = ts
                if name == "wave.digest":
                    self._wave_ts.append(ts)
            if name == "run.heartbeat":
                hb = dict(e.get("fields") or {})
                if isinstance(ts, int):
                    hb["ts_us"] = ts
                self.heartbeat = hb
            elif name == "serve.tick":
                self.serve_ticks += 1
                # every tick carries the controller's current window —
                # read it here so a stable controller (no change, no
                # gauge emission) still shows its T_batch on the
                # dashboard
                tb = (e.get("fields") or {}).get("t_batch_ms")
                if isinstance(tb, (int, float)):
                    self.serve_gauges["t_batch_ms"] = float(tb)
            elif name == "serve.shed":
                self.shed_total += 1
                if isinstance(ts, int):
                    self._shed_ts.append(ts)
            elif name == "serve.disk":
                self.disk_faults += 1
            elif name == "serve.journal_torn":
                f = e.get("fields") or {}
                n = 0
                for k in ("skipped", "corrupt"):
                    v = f.get(k)
                    if isinstance(v, (int, float)):
                        n += int(v)
                self.journal_torn += max(1, n)
            elif isinstance(name, str) and name.startswith("net."):
                key = name[len("net."):]
                self.net_counts[key] = self.net_counts.get(key, 0) + 1
                if name == "net.reconnect" and isinstance(ts, int):
                    self._reconnect_ts.append(ts)
                elif name == "net.dup_ops":
                    ops = (e.get("fields") or {}).get("ops")
                    if isinstance(ops, (int, float)):
                        self.net_counts["dup_ops_suppressed"] = \
                            self.net_counts.get("dup_ops_suppressed",
                                                0) + int(ops)
        elif ev == "gauge" and isinstance(name, str):
            if name.startswith("fleet.token_headroom."):
                site = name[len("fleet.token_headroom."):]
                v = e.get("value")
                if isinstance(v, (int, float)):
                    self.headroom_last[site] = v
                    cur = self.headroom_min.get(site)
                    self.headroom_min[site] = (v if cur is None
                                               else min(cur, v))
            elif name.startswith("serve."):
                v = e.get("value")
                if isinstance(v, (int, float)):
                    self.serve_gauges[name[len("serve."):]] = v
            elif name.startswith("net."):
                v = e.get("value")
                if isinstance(v, (int, float)):
                    self.net_gauges[name[len("net."):]] = v
            elif name.startswith("obs."):
                v = e.get("value")
                if isinstance(v, (int, float)):
                    self.obs_gauges[name[len("obs."):]] = v

    def feed_many(self, events: Iterable[dict]) -> None:
        for e in events:
            self.feed(e)

    # ------------------------------------------------------ snapshot

    def now_us(self) -> int:
        """The fold's notion of "now": wall clock, floored by the
        newest record's timestamp so a replay of an old stream
        (``--once``) measures ages against the stream's own end, not
        against today."""
        wall = time.time_ns() // 1000
        if self.last_ts_us is not None and self.last_ts_us > wall:
            return self.last_ts_us
        return wall

    def waves_per_s(self, now_us: int,
                    window_s: float = _RATE_WINDOW_S) -> float:
        cutoff = now_us - int(window_s * 1e6)
        n = sum(1 for t in self._wave_ts if t >= cutoff)
        return round(n / window_s, 4)

    def shed_rate(self, now_us: int,
                  window_s: float = _RATE_WINDOW_S) -> float:
        """``serve.shed`` events per second over the rate window —
        the default ``shed_rate>0`` alert's axis: ANY shedding inside
        the window is an excursion (overload is a declared policy,
        and a declared policy firing is operator news)."""
        cutoff = now_us - int(window_s * 1e6)
        n = sum(1 for t in self._shed_ts if t >= cutoff)
        return round(n / window_s, 4)

    def reconnects_per_min(self, now_us: int,
                           window_s: float = _RATE_WINDOW_S) -> float:
        """``net.reconnect`` events per minute over the rate window —
        the default ``reconnects_per_min>k`` alert's axis: a transport
        that keeps healing is a transport that keeps failing (flap
        detection), even though every individual reconnect is the
        designed behavior."""
        cutoff = now_us - int(window_s * 1e6)
        n = sum(1 for t in self._reconnect_ts if t >= cutoff)
        return round(n * 60.0 / window_s, 4)

    def _obs_dropped(self) -> Optional[float]:
        """Total subscriber-queue drops across every bounded
        subscriber: the gauges are per-source
        (``obs.dropped.<source>``) because one healthy subscriber
        would mask another's saturation under a single shared name.
        The bare un-suffixed spelling still counts. None (never a
        fake 0) when nothing gauged drops yet — the ``obs_dropped>0``
        rule must stay inert on streams without the gauge."""
        vals = [v for k, v in self.obs_gauges.items()
                if k == "dropped" or k.startswith("dropped.")]
        if not vals:
            return None
        return sum(vals)

    def _net_outbound(self) -> Optional[float]:
        """Total queued outbound ops across every client: the gauges
        are per-client (``net.outbound_depth.<client_id>``) because a
        single shared gauge would be last-writer-wins — one drained
        client would mask another's growing partition backlog. The
        bare un-suffixed spelling still counts (hand-rolled
        streams)."""
        vals = [v for k, v in self.net_gauges.items()
                if k == "outbound_depth"
                or k.startswith("outbound_depth.")]
        if not vals:
            return None
        return sum(vals)

    def ages_s(self, now_us: int) -> Dict[str, float]:
        """Seconds since each event name was last seen (the absence
        rules' axis), plus ``"any"`` — since ANY record landed."""
        out = {name: round(max(0, now_us - ts) / 1e6, 3)
               for name, ts in self.last_seen_us.items()}
        if self.last_ts_us is not None:
            out["any"] = round(max(0, now_us - self.last_ts_us) / 1e6, 3)
        return out

    def snapshot(self, now_us: Optional[int] = None) -> dict:
        """The rolling state as one dict — the alert rules' input and
        the dashboard's render source. Sections mirror the post-hoc
        reports (``fleet_report``'s shape for fleet/sync/wave/gc,
        ``lag_summary``'s for lag, ``costmodel_digest``'s for cost),
        plus the live-only axes (rates, ages, headroom, heartbeat)."""
        now = self.now_us() if now_us is None else int(now_us)
        rep = self.fleet.report()
        incidents = rep.pop("divergence_incidents")
        snap = {
            "ts_us": now,
            "records": rep["events"],
            "fleet": {
                "documents": rep["documents"],
                "waves": rep["waves"],
                "pairs": rep["pairs"],
                "replicas": rep["replicas"],
                "agreed_documents": rep["agreed_documents"],
                "staleness": rep["staleness"],
                "divergence_incidents": len(incidents),
                "last_incidents": incidents[-3:],
            },
            "sync": rep["sync"],
            "wave": rep["wave"],
            "gc": rep["gc"],
            "recovery": rep["recovery"],
            "lag": dict(self.fleet.lag.report()),
            "cost": self.cost.digest(),
            "rates": {"waves_per_s": self.waves_per_s(now)},
            "headroom": {
                "min": (min(self.headroom_min.values())
                        if self.headroom_min else None),
                "min_by_site": dict(self.headroom_min),
                "last_by_site": dict(self.headroom_last),
            },
            "heartbeat": self.heartbeat,
            "serve": {
                "active": bool(self.serve_ticks or self.shed_total
                               or self.serve_gauges
                               or any(n.startswith("serve.")
                                      for n in self.last_seen_us)),
                "ticks": self.serve_ticks,
                "queue_depth": self.serve_gauges.get("queue_depth"),
                "resident_docs":
                    self.serve_gauges.get("resident_docs"),
                "t_batch_ms": self.serve_gauges.get("t_batch_ms"),
                "shed_rate": self.shed_rate(now),
                "sheds": self.shed_total,
                "disk_faults": self.disk_faults,
                "journal_torn": self.journal_torn,
                "wal_segments": self.serve_gauges.get("wal_segments"),
                "wal_bytes": self.serve_gauges.get("wal_bytes"),
            },
            "net": {
                "active": bool(self.net_counts or self.net_gauges
                               or any(n.startswith("net.")
                                      for n in self.last_seen_us)),
                "connects": self.net_counts.get("connect", 0),
                "reconnects": self.net_counts.get("reconnect", 0),
                "reconnects_per_min": self.reconnects_per_min(now),
                "disconnects": self.net_counts.get("disconnect", 0),
                "nacks": self.net_counts.get("nack", 0),
                "dup_frames": self.net_counts.get("dup_frame", 0),
                "dup_ops_suppressed":
                    self.net_counts.get("dup_ops_suppressed", 0),
                "ooo_frames": self.net_counts.get("ooo_frame", 0),
                "sheds": self.net_counts.get("shed", 0),
                "heartbeats": self.net_counts.get("heartbeat", 0),
                "outbound_depth": self._net_outbound(),
                "connections": self.net_gauges.get("connections"),
            },
            "obs": {
                "dropped": self._obs_dropped(),
            },
            "journey": self.journeys.summary(),
            "ages_s": self.ages_s(now),
        }
        if self.cost.waves:
            by_path = self.cost.curves_by_path()
            if len(by_path) > 1:
                snap["cost"]["by_path"] = {
                    k: v.get("verdict") for k, v in by_path.items()}
        return snap


# ------------------------------------------------------------- rules


def snapshot_path(snap: dict, path: str):
    """Resolve a dotted path (``"sync.full_bag_rate"``) into a
    snapshot dict; None when any segment is missing."""
    cur = snap
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


# threshold-rule aliases: the operator-facing names for the snapshot
# paths an admission controller (and the CLI --rules flag) cares about
RULE_ALIASES = {
    "burn": "lag.slo.burn_rate",
    "attainment": "lag.slo.attainment",
    "p50": "lag.converged.p50_ms",
    "p95": "lag.converged.p95_ms",
    "p99": "lag.converged.p99_ms",
    "window_p99": "lag.window.p99_ms",
    "pending": "lag.pending",
    "full_bag_rate": "sync.full_bag_rate",
    "fallback_rate": "wave.fallback_rate",
    "session_overflow": "wave.session_overflow",
    "divergence": "fleet.divergence_incidents",
    "headroom": "headroom.min",
    "waves_per_s": "rates.waves_per_s",
    "stale": "stale_s",
    # PR 11: the chaos/recovery axes — rejected ingest payloads, the
    # current replica-quarantine count, and the recovery-storm rate
    # (declared ladder steps per wave)
    "rejects": "sync.rejects",
    "quarantined": "sync.quarantined",
    "recovery_per_wave": "recovery.per_wave",
    "recovery_retries": "recovery.retries",
    # PR 12: the sync service's admission axes — bounded-queue depth,
    # the shed-event rate over the sliding window, and the residency
    # manager's device-resident tenant count
    "queue_depth": "serve.queue_depth",
    "shed_rate": "serve.shed_rate",
    "resident_docs": "serve.resident_docs",
    # PR 13: the network transport's axes — reconnect flap rate, wire
    # NACK count, client outbound backlog, duplicate evidence
    "reconnects_per_min": "net.reconnects_per_min",
    "net_nacks": "net.nacks",
    "net_outbound": "net.outbound_depth",
    "net_dup_frames": "net.dup_frames",
    "net_connections": "net.connections",
    # PR 15: the durable-storage axes — evidenced storage faults,
    # torn/corrupt journal lines seen by replays, live WAL size
    "disk_faults": "serve.disk_faults",
    "journal_torn": "serve.journal_torn",
    "wal_bytes": "serve.wal_bytes",
    "wal_segments": "serve.wal_segments",
    # PR 20: the telemetry plane's own drop evidence — total bounded-
    # subscriber drops gauged under obs.dropped[.source]
    "obs_dropped": "obs.dropped",
}

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}


class Rule:
    """One declarative alert rule, edge-triggered: :meth:`check`
    returns the alert fields exactly once per excursion (the rule
    re-arms when the condition clears), so a persistent breach costs
    one ``live.alert``, not one per snapshot tick — the CI smoke's
    "forced breach fires EXACTLY ONE alert" contract.

    Kinds:

    - ``threshold`` — ``<path><op><value>`` over the snapshot
      (aliases in :data:`RULE_ALIASES`); a missing/None value never
      fires (you cannot breach a percentile that does not exist yet);
    - ``absence`` — ``absence:<event>:<seconds>``: fires when the
      named event has not been seen for the given span (measured
      against the newest record when the event never appeared — a
      stream that is producing OTHER records but no ``wave.digest``
      is a wedged fleet, not an idle one). An empty stream never
      fires;
    - ``burn`` is just a threshold alias (``burn>2`` reads the SLO
      burn rate ``lag_summary`` computes).
    """

    __slots__ = ("spec", "kind", "path", "op", "limit", "event",
                 "window_s", "firing")

    def __init__(self, spec: str, kind: str, path: str = "",
                 op: str = ">", limit: float = 0.0, event: str = "",
                 window_s: float = 0.0):
        self.spec = spec
        self.kind = kind
        self.path = path
        self.op = op
        self.limit = limit
        self.event = event
        self.window_s = window_s
        self.firing = False

    def _condition(self, snap: dict) -> Optional[dict]:
        if self.kind == "absence":
            age = (snap.get("ages_s") or {}).get(self.event)
            if age is None and snap.get("records"):
                # never seen: judge against the stream's own span —
                # other records flowing while this event stays absent
                # IS the wedge shape; a silent (empty) stream is not.
                # Exception: serve.*/net.* events are judged only on
                # streams that show the respective activity — a batch
                # soak that never ran a service (or a transport) is
                # not a dead one, it is not one at all (the default
                # absence:serve.tick / absence:net.heartbeat rules
                # must not page on every long batch stream)
                prefix = self.event.split(".", 1)[0]
                if prefix not in ("serve", "net") \
                        or (snap.get(prefix) or {}).get("active"):
                    age = snap.get("span_s")
            if age is None or age <= self.window_s:
                return None
            return {"age_s": age, "window_s": self.window_s,
                    "event": self.event}
        value = snapshot_path(snap, self.path)
        if not isinstance(value, (int, float)):
            return None
        if _OPS[self.op](float(value), self.limit):
            return {"value": value, "limit": self.limit, "op": self.op,
                    "path": self.path}
        return None

    def check(self, snap: dict) -> Optional[dict]:
        hit = self._condition(snap)
        if hit is None:
            self.firing = False
            return None
        if self.firing:
            return None  # still in the same excursion
        self.firing = True
        hit["rule"] = self.spec
        hit["kind"] = self.kind
        return hit


def parse_rule(spec: str) -> Rule:
    """One rule from its declarative spec string (see :class:`Rule`).
    Raises ``ValueError`` on a malformed spec — a watch run with a
    typo'd rule must fail loudly, not silently monitor nothing."""
    s = spec.strip()
    if s.startswith("absence:"):
        parts = s.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"absence rule must be absence:<event>:<seconds>: "
                f"{spec!r}")
        try:
            window = float(parts[2])
        except ValueError:
            raise ValueError(f"absence window is not a number: {spec!r}")
        return Rule(s, "absence", event=parts[1], window_s=window)
    for op in (">=", "<=", ">", "<"):  # two-char ops first
        if op in s:
            path, _, raw = s.partition(op)
            path = path.strip()
            try:
                limit = float(raw.strip())
            except ValueError:
                raise ValueError(f"threshold is not a number: {spec!r}")
            if not path:
                raise ValueError(f"empty snapshot path: {spec!r}")
            return Rule(s, "threshold",
                        path=RULE_ALIASES.get(path, path), op=op,
                        limit=limit)
    raise ValueError(
        f"unrecognized rule {spec!r} (want <path><op><value> or "
        f"absence:<event>:<seconds>)")


# the shipped defaults: SLO burn past 2x (the error budget is being
# eaten at least twice as fast as sustainable), the wedge detector
# (a fleet that stopped waving for 120 s while still emitting other
# records), the PR-5 finding that full-bag fallbacks are the dominant
# degradation mode, and the PR-11 robustness pair — ANY replica
# sitting in quarantine is an operator page (a corrupt or hostile
# peer is being refused), and a recovery STORM (more than one
# declared ladder step per wave, sustained) means the fleet is paying
# O(doc) degradations every round instead of riding the delta path
DEFAULT_RULE_SPECS = ("burn>2", "absence:wave.digest:120",
                      "full_bag_rate>0.2", "quarantined>0",
                      "recovery_per_wave>1",
                      # PR 12, the sync-service pair: ANY shed inside
                      # the rate window (the overload policy firing is
                      # operator news), and a service whose tick
                      # heartbeat goes absent for 60 s — the in-stream
                      # twin of SyncService's own watchdog, inert on
                      # streams with no serve activity (Rule._condition)
                      "shed_rate>0", "absence:serve.tick:60",
                      # PR 13, the transport pair: a replication link
                      # whose heartbeat evidence goes absent for 120 s
                      # (clients keepalive on a seconds cadence, so
                      # this is a genuinely dead/blackholed transport,
                      # not an idle one), and a reconnect FLAP — more
                      # than 6 heals a minute means the link keeps
                      # dying; each individual reconnect is designed
                      # behavior, the sustained rate is the incident.
                      # Both inert on streams with no net activity
                      # (absence via Rule._condition's activity gate;
                      # the threshold reads a rate that stays 0.0
                      # until net.reconnect records flow)
                      "absence:net.heartbeat:120",
                      "reconnects_per_min>6",
                      # PR 15, the storage pair: ANY evidenced disk
                      # fault (torn write, bit-rot, ENOSPC, failed
                      # fsync/rename — each one is a degradation the
                      # operator should know happened even though the
                      # service absorbed it), and ANY torn/corrupt
                      # journal line surfaced by a replay (a torn tail
                      # is expected after a crash, CRC corruption
                      # never is — both deserve a page, not a buried
                      # counter). Inert on serve-less streams: both
                      # paths live under the snapshot's "serve"
                      # section, whose counters stay 0 with no serve
                      # records, and Rule._condition's activity gate
                      # keeps them silent there
                      "disk_faults>0", "journal_torn>0",
                      # PR 20, the telemetry plane's own health: ANY
                      # bounded-subscriber drop is operator news — the
                      # telemetry is best-effort by contract, but a
                      # saturated queue means the dashboard is now
                      # lying by omission and the operator must know
                      # how much. Inert on streams without the gauge
                      # (a missing value never fires a threshold rule)
                      "obs_dropped>0")


def default_rules() -> List[Rule]:
    return [parse_rule(s) for s in DEFAULT_RULE_SPECS]


# ----------------------------------------------------------- monitor


class LiveMonitor:
    """The fold + the rule registry + the emit side, as one object:
    ``feed`` records, ``evaluate`` the rules (emitting ``live.alert``
    obs events — when obs is on — and firing callbacks), ``snapshot``
    the rolling state (optionally emitting a ``live.snapshot``
    record). Thread-safe: the in-process attachment polls from
    whatever thread the caller owns while a Prometheus endpoint reads
    snapshots from the server thread."""

    def __init__(self, rules: Optional[Iterable] = None,
                 on_alert: Iterable[Callable[[dict], None]] = (),
                 source: str = "live"):
        self.fold = LiveFold()
        if rules is None:
            self.rules = default_rules()
        else:
            self.rules = [r if isinstance(r, Rule) else parse_rule(r)
                          for r in rules]
        self.on_alert = list(on_alert)
        self.source = str(source)
        self.alerts: List[dict] = []
        self.snapshots_emitted = 0
        self._lock = threading.Lock()

    def add_callback(self, fn: Callable[[dict], None]) -> None:
        """Register an alert callback (the batch-sizing controller's
        subscription point)."""
        self.on_alert.append(fn)

    def feed(self, events: Iterable[dict]) -> None:
        with self._lock:
            self.fold.feed_many(events)

    def overlay_counters(self, counters: dict, gauges: dict,
                         pid: Optional[int] = None) -> None:
        """Overlay the in-process counter registry onto the fold
        (same per-pid last-snapshot merge rule as a flushed
        ``counters`` record) WITHOUT counting a stream record — the
        fold's record count must keep matching the sidecar."""
        with self._lock:
            self.fold.fleet.feed_counters({
                "ev": "counters",
                "pid": os.getpid() if pid is None else pid,
                "counters": counters,
                "gauges": gauges,
            })

    def snapshot(self, now_us: Optional[int] = None) -> dict:
        with self._lock:
            snap = self.fold.snapshot(now_us)
            # the absence rules' never-seen fallback axis: the span of
            # the stream itself (see Rule._condition)
            if self.fold.first_ts_us is not None \
                    and self.fold.last_ts_us is not None:
                snap["span_s"] = round(
                    (snap["ts_us"] - self.fold.first_ts_us) / 1e6, 3)
                # wall-clock staleness, independent of the chosen
                # "now": a sidecar that stopped growing half an hour
                # ago is a dead run even when --once replays it
                # against its own recorded end (rule alias "stale")
                snap["stale_s"] = round(max(
                    0, time.time_ns() // 1000
                    - self.fold.last_ts_us) / 1e6, 3)
            snap["alerts_total"] = len(self.alerts)
            return snap

    def evaluate(self, now_us: Optional[int] = None,
                 snap: Optional[dict] = None) -> List[dict]:
        """Run every rule against the (given or fresh) snapshot;
        returns the alerts that fired on THIS call (edge-triggered —
        an unchanged excursion returns nothing)."""
        if snap is None:
            snap = self.snapshot(now_us)
        fired: List[dict] = []
        # rule state (edge-trigger flags) mutates under the monitor
        # lock: two threads evaluating through one excursion must not
        # both see firing=False and double-emit — "exactly one alert
        # per excursion" is a contract, not a best effort. Emission
        # and callbacks run OUTSIDE the lock (a callback may touch
        # the monitor).
        with self._lock:
            for rule in self.rules:
                hit = rule.check(snap)
                if hit is None:
                    continue
                hit["ts_us"] = snap["ts_us"]
                hit["source"] = self.source
                self.alerts.append(hit)
                fired.append(hit)
        for hit in fired:
            if core.enabled():
                core.event("live.alert", **hit)
                core.counter("live.alerts").inc()
            for fn in self.on_alert:
                try:
                    fn(hit)
                except Exception:  # noqa: BLE001 - telemetry never raises
                    pass
        return fired

    def emit_snapshot(self, now_us: Optional[int] = None) -> dict:
        """One compact ``live.snapshot`` record into the obs stream
        (no-op emit when obs is off; the dict is returned either
        way). Compact on purpose: the rollup is a dashboard row, not
        a dump of the whole fold."""
        snap = self.snapshot(now_us)
        lag = snap.get("lag") or {}
        conv = lag.get("converged") or {}
        slo = lag.get("slo") or {}
        cost = snap.get("cost") or {}
        fields = {
            "source": self.source,
            "records": snap["records"],
            "documents": snap["fleet"]["documents"],
            "waves": snap["fleet"]["waves"],
            "agreed_documents": snap["fleet"]["agreed_documents"],
            "divergence_incidents":
                snap["fleet"]["divergence_incidents"],
            "waves_per_s": snap["rates"]["waves_per_s"],
            "full_bag_rate": snap["sync"]["full_bag_rate"],
            "ops_converged": lag.get("ops_converged", 0),
            "pending": lag.get("pending", 0),
            "p50_ms": conv.get("p50_ms"),
            "p95_ms": conv.get("p95_ms"),
            "p99_ms": conv.get("p99_ms"),
            "slo_ms": slo.get("target_ms"),
            "attainment": slo.get("attainment"),
            "burn_rate": slo.get("burn_rate"),
            "verdict": slo.get("verdict"),
            "dispatches": cost.get("dispatches", 0),
            "headroom_min": snap["headroom"]["min"],
            "quarantined": snap["sync"].get("quarantined", 0),
            "recovery_steps": snap["recovery"].get("steps", 0),
            "alerts_total": snap["alerts_total"],
        }
        srv = snap.get("serve") or {}
        if srv.get("active"):
            # the service's dashboard row rides the same compact
            # record; batch streams keep their PR-10 shape untouched
            fields.update(
                queue_depth=srv.get("queue_depth"),
                shed_rate=srv.get("shed_rate"),
                resident_docs=srv.get("resident_docs"),
                t_batch_ms=srv.get("t_batch_ms"),
                serve_ticks=srv.get("ticks"),
            )
        net = snap.get("net") or {}
        if net.get("active"):
            # the transport's axes ride along only when a transport
            # actually ran (same contract as the serve section)
            fields.update(
                net_reconnects=net.get("reconnects"),
                reconnects_per_min=net.get("reconnects_per_min"),
                net_nacks=net.get("nacks"),
                net_dup_frames=net.get("dup_frames"),
                net_dup_ops=net.get("dup_ops_suppressed"),
                net_outbound=net.get("outbound_depth"),
            )
        if core.enabled():
            core.event("live.snapshot", **fields)
            with self._lock:
                self.snapshots_emitted += 1
        return snap


# ------------------------------------------------- in-process attach


class LiveAttachment:
    """A live monitor wired to THIS process's obs sink via the PR-1
    subscriber hook: :meth:`poll` drains the bounded queue into the
    fold, overlays the in-process counter registry (counters only
    reach the stream at ``flush()`` — a live reader must not wait for
    one), evaluates the rules and optionally emits a snapshot.
    Detach with :meth:`close`."""

    __slots__ = ("sub", "monitor", "_dropped_gauged")

    def __init__(self, sub, monitor: LiveMonitor):
        self.sub = sub
        self.monitor = monitor
        self._dropped_gauged = 0

    def poll(self, emit_snapshot: bool = False,
             evaluate: bool = True) -> dict:
        """Drain + fold + (evaluate, snapshot). Returns the fresh
        snapshot dict (its ``alerts_total`` includes anything fired
        by this call)."""
        # PR 20: a saturated bounded queue used to drop silently into
        # a field nobody watched — gauge it BEFORE draining so the
        # gauge record rides this very drain and the ``obs_dropped>0``
        # default rule fires on the same poll that discovered the
        # saturation. (Gauging into a still-full queue costs one more
        # drop; the gauge intentionally trails by that record — the
        # rule only needs "any", and the count converges once the
        # queue drains.)
        if self.sub.dropped != self._dropped_gauged and core.enabled():
            self._dropped_gauged = self.sub.dropped
            core.gauge(
                f"obs.dropped.{self.monitor.source}").set(
                    self.sub.dropped)
        self.monitor.feed(self.sub.drain())
        snap_regs = core.counters_snapshot()
        if snap_regs["counters"] or snap_regs["gauges"]:
            # flush-equivalent overlay: same per-pid last-snapshot
            # merge rule, sourced from the registry instead of the
            # stream, and NOT counted as a record (the fold's record
            # count keeps matching the sidecar)
            self.monitor.overlay_counters(snap_regs["counters"],
                                          snap_regs["gauges"])
        if evaluate:
            self.monitor.evaluate()
        if emit_snapshot:
            return self.monitor.emit_snapshot()
        return self.monitor.snapshot()

    @property
    def dropped(self) -> int:
        """Records the bounded queue dropped (a stalled poller)."""
        return self.sub.dropped

    @property
    def closed(self) -> bool:
        """True once detached — including by an ``obs.reset()`` /
        ``configure(reset=True)``, which drops every subscriber with
        the rest of the obs state. A closed attachment drains nothing
        forever; the holder must re-``attach()`` against the new
        state (and decide what to do with the fold so far)."""
        return self.sub.closed

    def close(self) -> None:
        core.unsubscribe(self.sub)


def attach(rules: Optional[Iterable] = None,
           on_alert: Iterable[Callable[[dict], None]] = (),
           maxlen: int = 8192,
           source: str = "live") -> Optional[LiveAttachment]:
    """Attach a live monitor to this process's obs sink. Returns None
    when obs is disabled — the obs-off contract is zero subscriber
    state, zero records, zero overhead."""
    sub = core.subscribe(maxlen)
    if sub is None:
        return None
    return LiveAttachment(sub, LiveMonitor(rules=rules,
                                           on_alert=on_alert,
                                           source=source))


# ------------------------------------------------------------- tails


class StreamTailer:
    """Tail one O_APPEND JSONL sidecar: :meth:`poll` returns the
    records appended since the last poll. Rotation-aware — an inode
    change or a truncation (size < position) reopens from byte zero,
    so a log-rotated or restarted writer is picked up without
    restarting the watcher. Torn trailing lines (a writer mid-append)
    stay buffered until their newline lands; garbage lines are
    skipped like every other obs reader."""

    __slots__ = ("path", "_fh", "_ino", "_pos", "_buf")

    def __init__(self, path: str):
        self.path = str(path)
        self._fh = None
        self._ino = None
        self._pos = 0
        self._buf = b""

    def _close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = None
        self._ino = None
        self._pos = 0
        self._buf = b""

    def poll(self) -> List[dict]:
        try:
            st = os.stat(self.path)
        except OSError:
            # not created yet (or rotated away mid-poll): wait for it
            self._close()
            return []
        if self._fh is None or st.st_ino != self._ino \
                or st.st_size < self._pos:
            self._close()
            try:
                self._fh = open(self.path, "rb")
            except OSError:
                return []
            self._ino = os.fstat(self._fh.fileno()).st_ino
        out: List[dict] = []
        try:
            self._fh.seek(self._pos)
            data = self._fh.read()
        except (OSError, ValueError):
            self._close()
            return []
        self._pos += len(data)
        self._buf += data
        lines = self._buf.split(b"\n")
        self._buf = lines.pop()  # torn tail waits for its newline
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                out.append(obj)
        return out

    def close(self) -> None:
        self._close()


class MultiTailer:
    """Several sidecars as one feed (a multi-process soak's
    per-process streams): each :meth:`poll` batch is merged by record
    timestamp across files — the same stable rule ``load_streams``
    applies to whole files, at poll-batch granularity."""

    __slots__ = ("tailers",)

    def __init__(self, paths: Iterable[str]):
        self.tailers = [StreamTailer(p) for p in paths]

    def poll(self) -> List[dict]:
        out: List[dict] = []
        for t in self.tailers:
            out.extend(t.poll())
        if len(self.tailers) > 1:
            out.sort(key=lambda e: e.get("ts_us") or 0)
        return out

    def close(self) -> None:
        for t in self.tailers:
            t.close()
