"""cause_tpu.obs — the unified trace/metrics subsystem.

Spans, counters/gauges, a bounded event ring with JSONL streaming, and
a Chrome-trace/Perfetto exporter. Importable without jax (like
``switches.py``); a no-op unless ``CAUSE_TPU_OBS=1``. See
``core.py``'s module docstring for the full contract and
``python -m cause_tpu.obs --help`` for the trace converter.
"""

from .core import (
    configure,
    counter,
    counters_snapshot,
    enabled,
    event,
    events,
    export_jsonl,
    flush,
    gauge,
    reset,
    set_platform,
    span,
    subscribe,
    unsubscribe,
)
from .perfetto import export_perfetto, load_jsonl, to_chrome_trace
from . import costmodel
from . import journey
from . import lag
from . import live
from . import semantic
from . import xtrace

__all__ = [
    "configure",
    "costmodel",
    "counter",
    "counters_snapshot",
    "enabled",
    "event",
    "events",
    "export_jsonl",
    "export_perfetto",
    "flush",
    "gauge",
    "journey",
    "lag",
    "live",
    "load_jsonl",
    "reset",
    "semantic",
    "set_platform",
    "span",
    "subscribe",
    "to_chrome_trace",
    "unsubscribe",
    "xtrace",
]
