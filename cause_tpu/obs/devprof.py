"""Device-program telemetry: compile spans, XLA cost accounting,
device-memory gauges.

Every committed perf number so far is host wall time behind the
tunnel's ~64-70 ms dispatch floor; the *device-side* cost of a
compiled program (flops, bytes moved, buffer footprint) was invisible
unless someone hand-ran a probe script. This module closes that gap
at the program caches themselves:

- ``profile_program(jitfn, args, **meta)`` — on a program-cache miss
  (``benchgen.merge_wave_scalar``), the first compile is routed
  through jax's AOT path (``lower().compile()``) under a
  ``devprof.compile`` span, and the executable's ``cost_analysis()``
  / ``memory_analysis()`` land ONCE per compiled program as a
  ``devprof.program`` obs event carrying the same switch-aware
  program identity the cache key uses. The returned wrapper serves
  the AOT executable for matching input shapes and falls back to the
  ordinary jit path otherwise — one compile on the served path (an
  AOT executable that *errors* at call time falls back too, which
  re-compiles; that abandonment is recorded, see
  ``_ProfiledProgram``).
- ``sample_device_memory(site)`` — live-array count/bytes (and the
  backend's ``memory_stats`` where it has one) as obs gauges, sampled
  at wave boundaries (``parallel/wave.py`` / ``session.py``) so
  leaks and resident-batch growth render as curves in Perfetto.
- ``arena_footprint(arena, site)`` — host-side lane-arena bytes (the
  marshal cache the waves assemble from, ``weaver/lanecache.py``).

Contract (same as the rest of ``cause_tpu.obs``): importable without
jax — jax is imported lazily inside the enabled paths only. With
``CAUSE_TPU_OBS`` unset every entry point returns immediately:
nothing is recorded, no jax attribute is touched, no ``TRACE_SWITCHES``
env var is read, and program caches store exactly what they stored
before this module existed (pinned by tests/test_devprof.py). On
traced paths, call sites must sit behind ``obs.enabled()`` guards —
causelint rule OBS003 gates that.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from . import core

__all__ = [
    "enabled",
    "profile_program",
    "program_cost",
    "sample_device_memory",
    "arena_footprint",
]


def enabled() -> bool:
    """Whether devprof records anything (== ``obs.enabled()``)."""
    return core.enabled()


# ------------------------------------------------------------- programs


def _args_signature(args) -> Tuple:
    """Cheap (shape, dtype) signature of a call's arguments — what the
    AOT executable was compiled for."""
    return tuple(
        (tuple(getattr(a, "shape", ()) or ()),
         str(getattr(a, "dtype", type(a).__name__)))
        for a in args
    )


def program_cost(compiled) -> dict:
    """Normalize a compiled executable's cost/memory analysis into the
    flat metric dict the ledger compares (deterministic for a given
    program + shapes, unlike wall time). Missing analyses are simply
    absent keys — older jax builds and some backends omit them."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-optional API
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = ca.get("flops")
        if flops is not None:
            out["flops"] = float(flops)
        ba = ca.get("bytes accessed")
        if ba is not None:
            out["bytes_accessed"] = float(ba)
        # output-bytes key spelling varies across jax/XLA versions
        for k in ("bytes accessedout{}", "bytes accessed output {}"):
            if ca.get(k) is not None:
                out["output_bytes"] = float(ca[k])
                break
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - backend-optional API
        ma = None
    if ma is not None:
        for field, key in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_buffer_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("generated_code_size_in_bytes", "code_bytes"),
        ):
            v = getattr(ma, field, None)
            if v is not None:
                out[key] = int(v)
    return out


class _ProfiledProgram:
    """The program-cache value after a profiled compile: dispatches to
    the AOT executable when the input signature matches what it was
    compiled for, otherwise falls back to the ordinary jit path (which
    re-traces for the new shapes exactly as it would have without
    devprof).

    The exception fallback is a safety valve, not a silent one: AOT
    executables can be stricter than the jit path (donation/committed
    -device rules vary by jax build), and the retry re-compiles the
    whole program — so the first abandonment emits a
    ``devprof.aot_abandoned`` event and every retry bumps the
    ``devprof.aot_fallback`` counter, otherwise the run's cost digest
    would describe an executable that never served the traffic."""

    __slots__ = ("jitfn", "compiled", "signature", "cost", "_abandoned")

    def __init__(self, jitfn, compiled, signature, cost):
        self.jitfn = jitfn
        self.compiled = compiled
        self.signature = signature
        self.cost = cost
        self._abandoned = False

    def __call__(self, *args):
        if _args_signature(args) == self.signature:
            try:
                return self.compiled(*args)
            except Exception as e:  # noqa: BLE001 - AOT strictness varies
                if core.enabled():
                    core.counter("devprof.aot_fallback").inc()
                    if not self._abandoned:
                        self._abandoned = True
                        core.event("devprof.aot_abandoned",
                                   error=f"{type(e).__name__}: "
                                         f"{str(e)[:200]}")
                return self.jitfn(*args)
        return self.jitfn(*args)


def profile_program(jitfn, args, **meta) -> Optional[_ProfiledProgram]:
    """AOT-compile ``jitfn`` for ``args`` under a ``devprof.compile``
    span, record its cost analysis once, and return the dispatch
    wrapper — or None (caller keeps the plain jit path) when obs is
    off or anything about the capture fails. ``meta`` is the program
    identity the cache key carries (kernel, budgets); the emitted
    event adds the ``TRACE_SWITCHES`` snapshot so a cost row can be
    tied to the exact strategy config, like any span."""
    if not core.enabled():
        return None
    try:
        t0 = time.perf_counter()
        with core.span("devprof.compile", **meta):
            compiled = jitfn.lower(*args).compile()
        cost = program_cost(compiled)
        core.event(
            "devprof.program",
            compile_ms=round((time.perf_counter() - t0) * 1000.0, 3),
            cost=cost,
            switches=core._switches_snapshot(),
            **meta,
        )
        for k, v in cost.items():
            core.gauge(f"devprof.program.{k}").set(v)
        return _ProfiledProgram(jitfn, compiled, _args_signature(args),
                                cost)
    except Exception:  # noqa: BLE001 - telemetry must never cost a run
        return None


# ------------------------------------------------------------- memory


def sample_device_memory(site: str) -> dict:
    """Gauge the process's live device arrays (count + bytes) and the
    default device's ``memory_stats`` where available. ``site`` names
    the boundary being sampled (``wave``, ``session`` ...) so each
    boundary renders as its own Perfetto counter track."""
    if not core.enabled():
        return {}
    try:
        import jax
    except Exception:  # noqa: BLE001 - obs stays importable without jax
        return {}
    out: dict = {}
    try:
        arrs = jax.live_arrays()
        out["live_arrays"] = len(arrs)
        out["live_bytes"] = int(sum(
            int(getattr(a, "nbytes", 0) or 0) for a in arrs
        ))
    except Exception:  # noqa: BLE001 - backend-optional API
        pass
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_in_use") is not None:
            out["device_bytes_in_use"] = int(stats["bytes_in_use"])
    except Exception:  # noqa: BLE001 - cpu backends have no stats
        pass
    for k, v in out.items():
        core.gauge(f"devprof.{k}.{site}").set(v)
    return out


def arena_footprint(arena, site: str = "lanecache") -> dict:
    """Gauge one lane arena's host-side footprint (the numpy columns
    the wave marshal reuses across versions). Cheap: ``nbytes`` sums
    over the already-allocated columns, no copies."""
    if not core.enabled():
        return {}
    try:
        cols = ("ts", "site", "tx", "cause_idx", "vclass",
                "cause_hi", "cause_lo")
        nbytes = sum(
            int(getattr(getattr(arena, c), "nbytes", 0) or 0)
            for c in cols
        )
        out = {"arena_bytes": nbytes,
               "arena_lanes": int(arena.committed_n)}
    except Exception:  # noqa: BLE001 - telemetry must never raise
        return {}
    for k, v in out.items():
        core.gauge(f"devprof.{k}.{site}").set(v)
    return out
