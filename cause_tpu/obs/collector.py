"""The central telemetry collector: the ingest half of the PR-20
fleet telemetry plane.

One :class:`CollectorServer` accepts N :class:`~cause_tpu.obs.ship
.ShipExporter` uplinks and turns the fleet's per-process obs streams
into ONE live signal surface:

- **watermark dedup** — every origin is a (host, pid, stream-epoch)
  triple with a monotone record seq assigned exporter-side; the
  collector acks the highest contiguous seq accepted and skips
  anything at or below it, so lost-ack resends, chaos-duplicated
  frames and reconnect overlaps can never double a record. A seq GAP
  is accepted only when the frame's cumulative evidenced-drop count
  accounts for it exactly (the exporter drops OLDEST, so dropped seqs
  are always the contiguous front of the unsent range); an
  unexplained gap (a reordered frame in flight) is stashed briefly
  and healed when the missing frame lands — out-of-watermark-order
  persistence never happens;
- **clock folding** — exporters sample their offset against THIS
  process on every hello/ping (``xtrace.clock_sample`` on the reply
  stamp); those ``xtrace.clock`` records ship like any other, so the
  fold's PR-19 skew machinery corrects every origin's journey hops
  onto one reference clock — journeys reconstruct from the collector
  feed ALONE;
- **durable segments** — accepted frames append to a PR-15
  :class:`~cause_tpu.serve.wal.WriteAheadLog` (rotated, CRC-trailed,
  ``python -m cause_tpu.serve scrub``-able), with retention by
  age/size (:meth:`retain`) — the collector is a sidecar archive,
  not an unbounded disk leak;
- **one fleet-wide LiveFold** — every accepted record feeds a
  :class:`~cause_tpu.obs.live.LiveMonitor`; ``obs watch --collector``
  and the Prometheus endpoint render every host's serve/net/lag/
  journey axes from the live socket feed, with per-origin (host, pid)
  labels whose cardinality is bounded by the origin LRU.

Telemetry is best-effort: a misbehaving uplink costs a closed
connection and evidence, never backpressure into a producer's hot
path. Stdlib + cause_tpu host modules only; importable without jax.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

from .. import sync
from ..collections import shared as s
from ..net import transport
from ..net.transport import FrameStream
from ..serve import wal as _wal
from . import core
from . import xtrace
from .live import LiveMonitor

__all__ = ["CollectorServer"]

# frames parked per origin waiting for an in-flight reordered
# predecessor; past this the gap is accepted as unexplained loss
# (evidence, not a wedge)
_STASH_MAX = 16
_DEFAULT_ORIGIN_LRU = 64


class _Origin:
    """One remote stream's fold-side state: the dedup watermark, the
    drop accounting, the reorder stash, and the last-seen serve/net
    gauges that become this origin's Prometheus labels."""

    __slots__ = ("host", "pid", "epoch", "watermark", "dropped_seen",
                 "missed", "dup_records", "accepted", "stash",
                 "last_us", "gauges")

    def __init__(self, host: str, pid: int, epoch: int):
        self.host = host
        self.pid = pid
        self.epoch = epoch
        self.watermark = 0
        self.dropped_seen = 0
        self.missed = 0          # seqs lost to evidenced drops
        self.dup_records = 0     # records skipped by the watermark
        self.accepted = 0
        self.stash: Dict[int, dict] = {}  # base seq -> parked frame
        self.last_us = 0
        self.gauges: Dict[str, float] = {}

    def key(self) -> Tuple[str, int, int]:
        return (self.host, self.pid, self.epoch)

    def label(self) -> str:
        return f"{self.host}:{self.pid}"


class _Conn:
    __slots__ = ("fs", "peer", "origin")

    def __init__(self, fs: FrameStream, peer: str):
        self.fs = fs
        self.peer = peer
        self.origin: Optional[_Origin] = None


class CollectorServer:
    """See the module docstring. ``start()`` spawns the accept loop;
    each uplink gets a handler thread. ``port=0`` binds ephemeral
    (read ``.port`` back). ``dir=None`` keeps records in memory only
    (tests, short smokes); give a directory for the rotated-segment
    archive."""

    def __init__(self, dir: Optional[str] = None,  # noqa: A002
                 host: str = "127.0.0.1", port: int = 0,
                 idle_timeout_s: float = transport.DEFAULT_IDLE_TIMEOUT_S,
                 rotate_bytes: int = 4 * 1024 * 1024,
                 retain_bytes: Optional[int] = None,
                 retain_s: Optional[float] = None,
                 origin_lru: int = _DEFAULT_ORIGIN_LRU,
                 rules: Optional[List] = None,
                 site: str = "obs.collector"):
        self.dir = dir
        self.idle_timeout_s = float(idle_timeout_s)
        self.retain_bytes = retain_bytes
        self.retain_s = retain_s
        self.origin_lru = int(origin_lru)
        self.site = str(site)
        self.wal: Optional[_wal.WriteAheadLog] = None
        if dir:
            os.makedirs(dir, exist_ok=True)
            self.wal = _wal.WriteAheadLog(dir,
                                          rotate_bytes=rotate_bytes)
        self.monitor = LiveMonitor(rules=rules, source="collector")
        # the full accepted stream in arrival order — the soak/smoke
        # gates' comparison surface (the WAL holds the durable copy)
        self.records: Deque[dict] = deque()
        self._origins: "OrderedDict[Tuple[str, int, int], _Origin]" = \
            OrderedDict()
        self._lock = threading.RLock()   # origins + records + wal
        self._conns: List[_Conn] = []
        self._conns_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._sock = socket.create_server((host, int(port)))
        self._sock.settimeout(0.25)  # accept-loop poll granularity
        self.host = host
        self.port = self._sock.getsockname()[1]
        self.stats = {
            "connections": 0, "frames": 0, "accepted_records": 0,
            "dup_records": 0, "missed_records": 0, "stashed_frames": 0,
            "unexplained_gaps": 0, "heartbeats": 0, "hellos": 0,
            "idle_closes": 0, "bad_frames": 0, "evicted_origins": 0,
        }
        self._stats_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    # ----------------------------------------------------- lifecycle

    def start(self) -> "CollectorServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ship-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
        with self._conns_lock:
            for conn in self._conns:
                conn.fs.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        # after the joins nothing appends; close() blocks on its
        # final fsync, so it must not ride the ingest lock
        if self.wal is not None:
            self.wal.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._sock.accept()
            except TimeoutError:
                continue
            except OSError:
                return  # listener closed (stop())
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:  # pragma: no cover
                pass
            sock.settimeout(self.idle_timeout_s)
            fs = FrameStream(sock, site=self.site)
            conn = _Conn(fs, peer=f"{addr[0]}:{addr[1]}")
            with self._conns_lock:
                self._conns.append(conn)
                self._bump("connections")
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name=f"ship-conn-{conn.peer}",
                                 daemon=True)
            self._threads = [x for x in self._threads if x.is_alive()]
            with self._conns_lock:
                self._conns = [c_ for c_ in self._conns
                               if not c_.fs.closed]
            self._threads.append(t)
            t.start()

    # ------------------------------------------------------- handler

    def _handle(self, conn: _Conn) -> None:
        fs = conn.fs
        try:
            while not self._stop.is_set():
                try:
                    frame = transport.recv_msg(
                        fs, timeout_s=self.idle_timeout_s)
                except s.CausalError as e:
                    if "read-timeout" in e.info.get("causes", ()):
                        self._bump("idle_closes")
                    return
                except OSError:
                    return
                op = frame.get("op") if isinstance(frame, dict) \
                    else None
                self._bump("frames")
                try:
                    if op == "hello":
                        reply = self._welcome(conn, frame)
                    elif op == "obs":
                        reply = self._ingest(conn, frame)
                    elif op == "ping":
                        reply = self._pong(conn, frame)
                    elif op == "snap":
                        reply = {"op": "snap",
                                 "snapshot": self.snapshot()}
                    elif op == "bye":
                        return
                    else:
                        self._bump("bad_frames")
                        reply = {"op": "nack", "reason": "bad-frame"}
                    if reply is not None:
                        sync.send_frame(fs, reply)
                except (s.CausalError, OSError):
                    # a peer that died mid-reply: telemetry is
                    # best-effort — the exporter's reconnect ladder
                    # owns what's next
                    return
        finally:
            fs.close()

    def _welcome(self, conn: _Conn, frame: dict) -> dict:
        host = str(frame.get("host") or conn.peer)
        pid = int(frame.get("pid") or 0)
        epoch = int(frame.get("epoch") or 0)
        with self._lock:
            org = self._origin((host, pid, epoch))
        conn.origin = org
        self._bump("hellos")
        if core.enabled():
            core.counter("ship.hellos").inc()
            core.event("ship.hello", origin=org.label(), epoch=epoch,
                       watermark=org.watermark, peer=conn.peer)
        reply = {"op": "welcome", "watermark": org.watermark}
        if core.enabled():
            # wall-clock stamp for the exporter's NTP-style offset
            # sample — the clock edge every origin's journey
            # correction hangs off (obs-off replies stay bare)
            reply.update(xtrace.reply_stamp())
        return reply

    def _pong(self, conn: _Conn, frame: dict) -> dict:
        self._bump("heartbeats")
        reply = {"op": "pong", "seq": int(frame.get("seq") or 0)}
        if core.enabled():
            reply.update(xtrace.reply_stamp())
        return reply

    def _origin(self, key: Tuple[str, int, int]) -> _Origin:
        """The LRU registry row for one (host, pid, epoch) — created
        on first touch, refreshed on every touch, evicted
        least-recently-active beyond ``origin_lru`` (which is what
        bounds the Prometheus label cardinality). Called under
        ``_lock``."""
        org = self._origins.get(key)
        if org is None:
            org = _Origin(*key)
            self._origins[key] = org
        self._origins.move_to_end(key)
        while len(self._origins) > self.origin_lru:
            self._origins.popitem(last=False)
            self._bump("evicted_origins")
        return org

    # -------------------------------------------------------- ingest

    def _ingest(self, conn: _Conn, frame: dict) -> dict:
        org = conn.origin
        if org is None:
            self._bump("bad_frames")
            return {"op": "nack", "reason": "no-hello"}
        recs = frame.get("records")
        base = int(frame.get("base") or 0)
        dropped = int(frame.get("dropped") or 0)
        if not isinstance(recs, list) or base <= 0:
            self._bump("bad_frames")
            return {"op": "nack", "reason": "bad-frame"}
        with self._lock:
            self._origin(org.key())  # LRU touch
            self._apply(org, base, recs, dropped)
            self._drain_stash(org)
            wm = org.watermark
        return {"op": "ack", "seq": wm}

    def _apply(self, org: _Origin, base: int, recs: List[dict],
               dropped: int) -> None:
        """One obs frame against the origin's watermark (under
        ``_lock``): skip the dup prefix, accept the fresh suffix,
        admit an evidenced-drop gap exactly, stash an unexplained
        one."""
        n = len(recs)
        nxt = org.watermark + 1
        if n == 0 or base + n - 1 <= org.watermark:
            # pure wire duplicate (chaos dup / lost-ack resend)
            org.dup_records += n
            self._bump("dup_records", n)
            return
        if base > nxt:
            gap = base - nxt
            drop_delta = dropped - org.dropped_seen
            if gap > drop_delta:
                # more missing than the exporter evidenced: an
                # in-flight reordered frame — park this one; the
                # missing predecessor (or a resend) heals it
                if len(org.stash) < _STASH_MAX:
                    org.stash[base] = {"base": base, "records": recs,
                                       "dropped": dropped}
                    self._bump("stashed_frames")
                    return
                # stash exhausted: accept the gap as unexplained loss
                # rather than wedge the stream (loud, counted)
                self._bump("unexplained_gaps")
            org.missed += gap
            self._bump("missed_records", gap)
        skip = max(0, nxt - base)
        if skip:
            org.dup_records += skip
            self._bump("dup_records", skip)
        fresh = recs[skip:]
        org.watermark = base + n - 1
        org.dropped_seen = max(org.dropped_seen, dropped)
        org.last_us = time.time_ns() // 1000
        org.accepted += len(fresh)
        self._bump("accepted_records", len(fresh))
        self.records.extend(fresh)
        self.monitor.feed(fresh)
        for rec in fresh:
            if rec.get("ev") == "gauge":
                name = rec.get("name")
                v = rec.get("value")
                if isinstance(name, str) and isinstance(v, (int, float)) \
                        and name.startswith(("serve.", "net.")):
                    org.gauges[name] = float(v)
        if self.wal is not None:
            self.wal.append(f"{org.host}:{org.pid}:{org.epoch}",
                            "obs.ship", fresh, ts_us=org.last_us)
            self.retain()

    def _drain_stash(self, org: _Origin) -> None:
        """Re-offer parked frames (under ``_lock``): after an accept
        moved the watermark, a stashed frame either lands (its gap
        closed), re-stashes (still unexplained), or collapses to a
        pure duplicate and is discarded."""
        while org.stash:
            progressed = False
            for b in sorted(org.stash):
                f = org.stash[b]
                if b + len(f["records"]) - 1 <= org.watermark:
                    org.stash.pop(b)   # superseded by a resend
                    org.dup_records += len(f["records"])
                    self._bump("dup_records", len(f["records"]))
                    progressed = True
                    break
                gap = b - (org.watermark + 1)
                if gap <= 0 or gap <= f["dropped"] - org.dropped_seen:
                    org.stash.pop(b)
                    self._apply(org, b, f["records"], f["dropped"])
                    progressed = True
                    break
            if not progressed:
                return

    # ----------------------------------------------------- retention

    def retain(self) -> dict:
        """Retention by size and age over the segment archive (under
        ``_lock`` via callers; safe to call directly too): while the
        directory exceeds ``retain_bytes`` — or the oldest CLOSED
        segment is older than ``retain_s`` — retire whole segments
        through the WAL's crash-safe GC (manifest-first, scrub finds
        no orphans). The open tail segment is never retired."""
        if self.wal is None:
            return {"retired": 0}
        retired = 0
        while True:
            segs = _wal.list_segments(self.wal.path)
            if len(segs) <= 1:
                break
            no, name = segs[0]
            fp = os.path.join(self.wal.path, name)
            too_big = (self.retain_bytes is not None
                       and self.wal.dir_bytes() > self.retain_bytes)
            too_old = False
            if self.retain_s is not None:
                try:
                    age = time.time() - os.path.getmtime(fp)
                    too_old = age > self.retain_s
                except OSError:
                    pass
            if not (too_big or too_old):
                break
            # the GC watermark that retires exactly this segment: the
            # last record seq it holds (records at or below a
            # watermark are retirable once the caller declares them
            # archived — here, age/size policy IS the declaration)
            last_seq = 0
            for kind, rec in _wal.scan_segment_file(fp):
                if kind == "rec":
                    last_seq = max(last_seq, int(rec.get("seq") or 0))
            if not last_seq:
                break
            got = self.wal.gc(last_seq)
            if not got.get("retired"):
                break
            retired += int(got["retired"])
        return {"retired": retired}

    # ------------------------------------------------------ read side

    def origins(self) -> List[dict]:
        with self._lock:
            now = time.time_ns() // 1000
            return [{
                "host": o.host, "pid": o.pid, "epoch": o.epoch,
                "watermark": o.watermark, "accepted": o.accepted,
                "missed": o.missed, "dup_records": o.dup_records,
                "age_s": (round((now - o.last_us) / 1e6, 3)
                          if o.last_us else None),
                "serve": {k[len("serve."):]: v
                          for k, v in o.gauges.items()
                          if k.startswith("serve.")},
                "net": {k[len("net."):]: v
                        for k, v in o.gauges.items()
                        if k.startswith("net.")},
            } for o in self._origins.values()]

    def snapshot(self, evaluate: bool = True) -> dict:
        """The fleet-wide fold snapshot, augmented with the
        collector's own sections: per-origin rows (the Prometheus
        label source, LRU-bounded) and the ship-plane accounting.
        ``obs watch --collector`` renders exactly this dict."""
        if evaluate:
            self.monitor.evaluate()
        snap = self.monitor.snapshot()
        snap["origins"] = self.origins()
        with self._stats_lock:
            stats = dict(self.stats)
        snap["ship"] = {
            "active": bool(stats["hellos"]),
            "origins": len(snap["origins"]),
            "accepted": stats["accepted_records"],
            "dup_records": stats["dup_records"],
            "missed": stats["missed_records"],
            "unexplained_gaps": stats["unexplained_gaps"],
            "connections": stats["connections"],
        }
        snap["alerts_recent"] = self.monitor.alerts[-5:]
        return snap

    def report(self) -> dict:
        with self._stats_lock:
            stats = dict(self.stats)
        out = {"stats": stats, "origins": self.origins(),
               "records": len(self.records)}
        if self.wal is not None:
            with self._lock:
                out["wal"] = self.wal.wal_report()
        return out
