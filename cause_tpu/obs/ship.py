"""Networked obs shipping: the exporter half of the PR-20 fleet
telemetry plane.

Every obs layer before this one assumes all processes append JSONL to
one shared filesystem and the read side merges files post-hoc — an
assumption multi-host serving breaks outright. This module ships the
records instead: a :class:`ShipExporter` rides the PR-10 bounded
subscriber hook (the SAME hook the live attachment uses), frames obs
records over the PR-13 CRC framing, and pushes them to a
:class:`~cause_tpu.obs.collector.CollectorServer` so the fleet-wide
signal surface exists WHILE the fleet runs.

Telemetry is best-effort BY CONTRACT — the opposite discipline from
the data plane:

- the hot path is never blocked or slowed: the only hot-path touch is
  the O(1) bounded-subscriber enqueue ``core.record`` already pays;
  everything else (buffering, framing, sockets, backoff sleeps) lives
  on one daemon pump thread;
- on overflow it drops OLDEST with an honest, evidenced count (the
  ``obs.dropped.ship`` gauge + ``ship.drop`` events + ``stats``),
  never NACK-parks like data — a wedged collector must cost bounded
  memory and zero admission latency;
- a healed partition ships exactly the missed suffix: records get
  per-(pid, stream-epoch) sequence numbers at enqueue, the collector
  acks a per-origin watermark, and every (re)connect's welcome
  carries that watermark back so the exporter trims what already
  landed and resends only the unacked tail (the collector's watermark
  dedup absorbs any overlap a lost ack forces).

Chaos: the ``ship`` family (partition / drop / dup / reorder) fires
ONLY inside this layer — at ``<site>.connect`` on the dial and
``<site>.send`` around each frame — so a ship-chaos soak can gate on
a bit-identical data plane while the telemetry link burns.

Obs-off invariance: :func:`attach_exporter` returns None when obs is
disabled (``core.subscribe`` returns None — zero sockets, zero
threads, zero state), so the whole shipping layer inherits the
standing contract; pinned via ``scripts/obs_off_pin.py`` and
tests/test_ship.py.

Stdlib + cause_tpu host modules only; importable without jax.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

from .. import chaos as _chaos
from .. import sync
from ..collections import shared as s
from ..net.transport import Backoff, FrameStream, recv_msg
from . import core
from . import xtrace

__all__ = ["ShipExporter", "attach_exporter", "SHIP_PROTO"]

SHIP_PROTO = 1
# unacked-record buffer bound: at ~200 B/record this is ~13 MB of
# worst-case partition backlog per process — small enough to never
# matter, deep enough to ride out minutes of collector downtime at
# steady-state record rates
DEFAULT_BUFFER_RECORDS = 65536
DEFAULT_BATCH_RECORDS = 256
DEFAULT_SUB_MAXLEN = 8192


def _now_us() -> int:
    return time.time_ns() // 1000


class ShipExporter:
    """One process's telemetry uplink (see the module docstring).
    Construct via :func:`attach_exporter` — it owns the obs-off gate.
    All socket/buffer work happens on the daemon pump thread;
    :meth:`close` flushes best-effort and detaches."""

    def __init__(self, sub, host: str, port: int,
                 buffer_records: int = DEFAULT_BUFFER_RECORDS,
                 batch_records: int = DEFAULT_BATCH_RECORDS,
                 flush_s: float = 0.05,
                 heartbeat_s: float = 2.0,
                 connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 5.0,
                 backoff: Optional[Backoff] = None,
                 site: str = "obs.ship",
                 epoch: Optional[int] = None,
                 start: bool = True):
        self.sub = sub
        self.host = str(host)
        self.port = int(port)
        self.site = str(site)
        self.buffer_records = int(buffer_records)
        self.batch_records = int(batch_records)
        self.flush_s = float(flush_s)
        self.heartbeat_s = float(heartbeat_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.read_timeout_s = float(read_timeout_s)
        self.backoff = backoff or Backoff(seed=os.getpid())
        self.origin_host = socket.gethostname()
        self.pid = os.getpid()
        # the stream epoch: one per exporter incarnation, so a
        # restarted process (same pid recycled or not) never collides
        # with its predecessor's watermark at the collector
        self.epoch = int(epoch) if epoch is not None else _now_us()
        # unacked suffix: (seq, record) in seq order; drops and ack
        # trims both pop from the LEFT, so the deque stays contiguous
        self._buf: Deque[Tuple[int, dict]] = deque()
        self._next_seq = 1
        self._held: Deque[dict] = deque()  # reorder-chaos holdbacks
        self.fs: Optional[FrameStream] = None
        self.connected = False
        self._next_dial = 0.0
        self._last_io = 0.0
        self._hb_seq = 0
        self._down_since: Optional[float] = None
        self.stats = {
            "connects": 0, "reconnects": 0, "disconnects": 0,
            "dial_failures": 0, "sent_frames": 0, "sent_records": 0,
            "acked_seq": 0, "resumed_skipped": 0, "dropped_records": 0,
            "heartbeats": 0, "clock_samples": 0, "unshipped": 0,
        }
        self._dropped_gauged = -1
        # pump() is the only socket/buffer toucher, but it runs from
        # the daemon thread AND from flush()/close() callers — one
        # cycle at a time or two windows interleave on the socket
        self._pump_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if core.enabled():
            core.event("ship.attach", host=self.origin_host,
                       pid=self.pid, epoch=self.epoch,
                       collector=f"{self.host}:{self.port}")
        if start:
            self.start()

    # ------------------------------------------------------ lifecycle

    def start(self) -> "ShipExporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="obs-ship", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.flush_s):
            try:
                self.pump()
            except Exception:  # noqa: BLE001 - telemetry never raises
                with self._pump_lock:
                    self._disconnect_locked("pump-error")

    def close(self, flush_timeout_s: float = 2.0) -> None:
        """Stop the pump, flush the unacked tail best-effort (bounded
        by ``flush_timeout_s`` — telemetry must never stall a
        shutdown), send bye, detach the subscriber. Whatever could not
        ship is counted honestly in ``stats["unshipped"]``."""
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None
        deadline = time.monotonic() + float(flush_timeout_s)
        while time.monotonic() < deadline:
            unacked = None
            try:
                unacked = self.pump()["unacked"]
            except Exception:  # noqa: BLE001 - telemetry never raises
                with self._pump_lock:
                    self._disconnect_locked("close-pump-error")
            if unacked == 0 and self.sub is not None \
                    and not len(self.sub.queue):
                break
            time.sleep(0.02)
        with self._pump_lock:
            self._close_locked()
        core.unsubscribe(self.sub)

    def _close_locked(self) -> None:
        self._ingest_locked()
        self.stats["unshipped"] = len(self._buf)
        if self.fs is not None:
            try:
                sync.send_frame(self.fs, {"op": "bye"})
            except (s.CausalError, OSError):
                pass
            try:
                self.fs.close()
            except OSError:
                pass
            self.fs = None
        self.connected = False

    # --------------------------------------------------------- intake

    def _ingest_locked(self) -> int:
        """Drain the bounded subscriber into the unacked buffer,
        assigning per-(pid, epoch) seqs; overflow drops OLDEST with
        evidence. Returns records ingested."""
        if self.sub is None:
            return 0
        drained = self.sub.drain()
        for rec in drained:
            self._buf.append((self._next_seq, rec))
            self._next_seq += 1
        over = len(self._buf) - self.buffer_records
        if over > 0:
            for _ in range(over):
                self._buf.popleft()
            self.stats["dropped_records"] += over
            if core.enabled():
                core.event("ship.drop", dropped=over,
                           total=self._total_dropped_locked(),
                           buffered=len(self._buf))
        self._gauge_drops_locked()
        return len(drained)

    def _total_dropped_locked(self) -> int:
        return self.stats["dropped_records"] + int(self.sub.dropped)

    def total_dropped(self) -> int:
        """Every record this exporter evidenced as lost before the
        wire: subscriber-queue drops (a stalled pump) plus buffer
        drops (a long partition). The collector's per-origin gap
        accounting must equal exactly this."""
        with self._pump_lock:
            return self._total_dropped_locked()

    def _gauge_drops_locked(self) -> None:
        total = self._total_dropped_locked()
        if total != self._dropped_gauged and core.enabled():
            self._dropped_gauged = total
            core.gauge("obs.dropped.ship").set(total)

    # ----------------------------------------------------------- wire

    def _dial_locked(self) -> None:
        if _chaos.enabled() and _chaos.ship_partition(self.site):
            raise s.CausalError(
                "ship: chaos-injected telemetry partition",
                {"causes": {"ship-unreachable"},
                 "site": self.site})
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(self.read_timeout_s)
        self.fs = FrameStream(sock, site=self.site)

    def _connect_locked(self) -> None:
        try:
            self._dial_locked()
            t0 = _now_us()
            sync.send_frame(self.fs, {
                "op": "hello", "kind": "ship", "proto": SHIP_PROTO,
                "host": self.origin_host, "pid": self.pid,
                "epoch": self.epoch, "next_seq": self._next_seq,
            })
            welcome = recv_msg(self.fs, self.read_timeout_s)
            t1 = _now_us()
        except (s.CausalError, OSError) as e:
            self.stats["dial_failures"] += 1
            self._schedule_redial_locked()
            if core.enabled():
                why = (sorted(e.info.get("causes", ()))
                       if isinstance(e, s.CausalError) else ["os-error"])
                core.event("ship.dial_failed", why=why,
                           next_dial_ms=round(
                               (self._next_dial - time.monotonic())
                               * 1000.0, 1))
            return
        if welcome.get("op") != "welcome":
            self.stats["dial_failures"] += 1
            self._schedule_redial_locked()
            try:
                self.fs.close()
            except OSError:
                pass
            self.fs = None
            return
        # the hello RTT doubles as a clock sample against the
        # collector — the xtrace.clock record it mints SHIPS like any
        # other record, so the collector's fold corrects every
        # origin's hop timestamps onto one reference clock (the PR-19
        # skew machinery, fed over the wire instead of merged files)
        if xtrace.clock_sample(welcome, t0, t1,
                               via="ship-hello") is not None:
            self.stats["clock_samples"] += 1
        wm = int(welcome.get("watermark") or 0)
        skipped = 0
        while self._buf and self._buf[0][0] <= wm:
            self._buf.popleft()
            skipped += 1
        self.stats["resumed_skipped"] += skipped
        self.stats["acked_seq"] = max(self.stats["acked_seq"], wm)
        self.connected = True
        self._last_io = time.monotonic()
        self.backoff.reset()
        self.stats["connects"] += 1
        first = self.stats["connects"] == 1
        if not first:
            self.stats["reconnects"] += 1
        if core.enabled():
            if first:
                core.event("ship.connect", watermark=wm,
                           resumed_skipped=skipped)
            else:
                mttr_ms = (round((time.monotonic() - self._down_since)
                                 * 1000.0, 1)
                           if self._down_since is not None else None)
                core.event("ship.reconnect", watermark=wm,
                           resumed_skipped=skipped, mttr_ms=mttr_ms)
        self._down_since = None

    def _schedule_redial_locked(self) -> None:
        if self._down_since is None:
            self._down_since = time.monotonic()
        self._next_dial = time.monotonic() \
            + self.backoff.next_ms() / 1000.0

    def _disconnect_locked(self, why: str) -> None:
        if self.fs is not None:
            try:
                self.fs.close()
            except OSError:
                pass
            self.fs = None
        if self.connected:
            self.connected = False
            self.stats["disconnects"] += 1
            self._held.clear()  # holdbacks die with their connection
            if core.enabled():
                core.event("ship.disconnect", why=why,
                           unacked=len(self._buf))
        self._schedule_redial_locked()

    def _send_locked(self, frame: dict) -> None:
        """One frame through the ship-family chaos seam: ``drop``
        vanishes it silently, ``reorder`` holds it back until the next
        send overtakes it, ``dup`` puts it on the wire twice. Raises
        on real socket errors (the caller disconnects)."""
        dup = False
        if _chaos.enabled():
            if _chaos.ship_drop(self.site):
                self.stats["sent_frames"] += 1  # "sent", locally
                return
            if _chaos.ship_reorder(self.site):
                self._held.append(frame)
                return
            dup = _chaos.ship_dup(self.site)
        try:
            sync.send_frame(self.fs, frame)
            if dup:
                sync.send_frame(self.fs, frame)
            while self._held:
                # deliver holdbacks AFTER the overtaking frame — the
                # collector's out-of-order stash heals the swap
                sync.send_frame(self.fs, self._held.popleft())
        except OSError as e:
            raise s.CausalError(
                "ship: send failed", {"causes": {"ship-reset"}}) from e
        self.stats["sent_frames"] += 1
        self._last_io = time.monotonic()

    # ----------------------------------------------------------- pump

    def pump(self) -> dict:
        """One pump cycle (the thread's body; callable directly in
        tests): ingest → maybe dial → ship the unacked window → drain
        acks → heartbeat. Returns a small progress dict."""
        with self._pump_lock:
            return self._pump_locked()

    def _pump_locked(self) -> dict:
        self._ingest_locked()
        now = time.monotonic()
        if not self.connected:
            if now >= self._next_dial:
                self._connect_locked()
            return {"connected": self.connected,
                    "unacked": len(self._buf)}
        sent = 0
        try:
            if self._buf:
                sent = self._ship_window_locked()
            elif self._held:
                # a reorder holdback with no follow-up traffic: flush
                # it now (delayed, not lost)
                while self._held:
                    sync.send_frame(self.fs, self._held.popleft())
                self._last_io = time.monotonic()
            if not self._buf \
                    and now - self._last_io >= self.heartbeat_s:
                self._heartbeat_locked()
        except (s.CausalError, OSError) as e:
            why = (",".join(sorted(e.info.get("causes", ())))
                   if isinstance(e, s.CausalError) else "os-error")
            self._disconnect_locked(why)
        self._gauge_drops_locked()
        return {"connected": self.connected, "sent_frames": sent,
                "unacked": len(self._buf)}

    def _ship_window_locked(self) -> int:
        """Frame and send the whole unacked suffix (pipelined — the
        reorder fault needs two frames in flight to mean anything),
        then drain one ack per frame. A lost frame shows as acks
        stopping short; the stranded suffix stays buffered and the
        next cycle resends it (the collector dup-skips overlap)."""
        entries = list(self._buf)
        frames = 0
        for i in range(0, len(entries), self.batch_records):
            chunk = entries[i:i + self.batch_records]
            self._send_locked({
                "op": "obs", "base": chunk[0][0],
                "dropped": self._total_dropped_locked(),
                "records": [rec for _seq, rec in chunk],
            })
            frames += 1
        if self._held:
            # the window ended on a holdback with nothing left to
            # overtake it — flush now (delayed one frame, not lost)
            try:
                while self._held:
                    sync.send_frame(self.fs, self._held.popleft())
            except OSError as e:
                raise s.CausalError(
                    "ship: send failed",
                    {"causes": {"ship-reset"}}) from e
        self.stats["sent_records"] += len(entries)
        last_seq = entries[-1][0]
        progressed = False
        # ack budget > frames: a chaos-duplicated frame gets acked
        # TWICE, and stale acks from a previous partially-drained
        # window may still sit in the socket. The first ``frames``
        # reads are owed and wait the full timeout; past that the
        # drain turns opportunistic (50 ms) so extras clear without
        # stalling the pump
        got = 0
        for _ in range(frames * 2 + 4):
            try:
                reply = recv_msg(
                    self.fs,
                    self.read_timeout_s if got < frames else 0.05)
            except s.CausalError:
                if got < frames and not progressed:
                    raise  # nothing landed: a dead/blackholed link
                break      # partial progress: resend the rest later
            got += 1
            if reply.get("op") != "ack":
                continue
            ack = int(reply.get("seq") or 0)
            if ack > self.stats["acked_seq"]:
                self.stats["acked_seq"] = ack
                progressed = True
            while self._buf and self._buf[0][0] <= ack:
                self._buf.popleft()
            if ack >= last_seq:
                break
        self._last_io = time.monotonic()
        return frames

    def _heartbeat_locked(self) -> None:
        self._hb_seq += 1
        t0 = _now_us()
        self._send_locked({"op": "ping", "seq": self._hb_seq})
        reply = recv_msg(self.fs, self.read_timeout_s)
        t1 = _now_us()
        self.stats["heartbeats"] += 1
        if reply.get("op") == "pong" and xtrace.clock_sample(
                reply, t0, t1, via="ship-ping") is not None:
            self.stats["clock_samples"] += 1
        self._last_io = time.monotonic()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Pump until the unacked buffer drains (or the deadline).
        Test/smoke helper — production callers just close()."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            st = self.pump()
            if st["unacked"] == 0 and not len(self.sub.queue):
                return True
            time.sleep(0.02)
        return False


def attach_exporter(host: str, port: int,
                    maxlen: int = DEFAULT_SUB_MAXLEN,
                    **kw) -> Optional[ShipExporter]:
    """Attach a telemetry uplink to this process's obs sink. Returns
    None when obs is disabled — the obs-off contract is zero sockets,
    zero threads, zero subscriber state (``core.subscribe`` is the
    gate, exactly like ``live.attach``)."""
    sub = core.subscribe(maxlen)
    if sub is None:
        return None
    return ShipExporter(sub, host, port, **kw)


def parse_endpoint(raw: str) -> Optional[Tuple[str, int]]:
    """``"host:port"`` from the ``CAUSE_TPU_OBS_SHIP`` knob (bare
    ``":port"`` means loopback). None on anything unparseable — a
    typo'd endpoint must not take the service down; the exporter
    simply is not wired and the local sidecar still has everything."""
    raw = (raw or "").strip()
    if not raw:
        return None
    host, sep, port = raw.rpartition(":")
    if not sep:
        return None
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        return None
