"""The persistent perf ledger: every bench/harvest/soak artifact as an
append-only, platform-partitioned JSONL trajectory
(``measurements/ledger.jsonl``).

Why a ledger and not PERF.md tables: the round-2 provenance slip (a
``cpu-fallback`` artifact cited as a TPU number) happened because the
bench trajectory lived in hand-edited prose, and BENCH_r05 still
records a fallback run whose ``vs_baseline: 0.0`` is
indistinguishable-at-a-glance from a real regression. Ledger rows are
machine-readable, carry their provenance (source artifact, platform,
kernel, config, schema version, devprof cost digest), and the checker
enforces the two rules the prose kept breaking:

- **strict platform partitioning** — rows are only ever compared to
  rows with the *identical* ``platform`` string, so ``cpu-fallback``
  can never shadow or regress-against ``tpu``;
- **fallback quarantine** — fallback rows (and failed runs) are kept
  for the record but excluded from every baseline/regression
  comparison.

``check()`` computes the per-partition trajectory and a regression
verdict: *deterministic* cost metrics (obs counters, devprof
``cost_analysis`` flops/bytes — stable for a given program + shapes)
gate on every platform including CI's CPU smoke; *wall time* gates
only inside same-platform real-chip windows (``tpu`` rows), because
host timings behind the tunnel floor are too noisy to fail a build
on. ``backfill()`` imports the committed ``BENCH_r01..r05.json``
artifacts (driver wrapper format) and the bench JSON lines inside
``measurements/*.log`` with their honest platform tags.

CLI (see ``python -m cause_tpu.obs ledger --help``)::

    python -m cause_tpu.obs ledger --backfill
    python -m cause_tpu.obs ledger --ingest BENCH.json --obs side.jsonl
    python -m cause_tpu.obs ledger --check

Stdlib-only, importable without jax/numpy, like the rest of
``cause_tpu.obs``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

from .perfetto import load_jsonl, merged_final_counters

__all__ = [
    "LEDGER_SCHEMA",
    "default_path",
    "load",
    "append",
    "normalize_bench",
    "devprof_digest",
    "costmodel_row_digest",
    "ingest",
    "ingest_record",
    "backfill",
    "check",
    "main",
]

LEDGER_SCHEMA = 1

# deterministic-metric tolerance: cost_analysis flops/bytes are exact
# for one XLA build but drift slightly across versions; 5% covers that
# without hiding a real algorithmic regression (those move integer
# factors)
DET_TOL = 0.05
# wall-time tolerance inside a same-platform chip window: generous —
# the tunnel floor and queueing jitter are real, a >25% p50 slide is
# not noise
WALL_TOL = 0.25

_BENCH_METRIC_PREFIX = "p50 batched merge+weave"


def default_path() -> str:
    """``CAUSE_TPU_LEDGER`` if set, else ``measurements/ledger.jsonl``
    next to the repo root (this module lives in cause_tpu/obs/)."""
    env = os.environ.get("CAUSE_TPU_LEDGER", "").strip()
    if env:
        return env
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "measurements", "ledger.jsonl")


def load(path: Optional[str] = None) -> List[dict]:
    """All ledger rows, oldest first (torn/garbage lines skipped —
    same parser as the obs sidecars)."""
    path = path or default_path()
    if not os.path.exists(path):
        return []
    return load_jsonl(path)


def append(row: dict, path: Optional[str] = None) -> dict:
    """Append one row (O_APPEND single write, like the obs sink —
    concurrent writers interleave at line granularity)."""
    path = path or default_path()
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        os.write(fd, (json.dumps(row, default=str) + "\n").encode())
    finally:
        os.close(fd)
    return row


def _natural(name: str) -> Tuple:
    """Filename sort key that orders embedded round numbers
    numerically: append order IS the trajectory ``check()`` gates on,
    and lexicographic order would put ``bench_tpu_r10.log`` BEFORE
    ``bench_tpu_r3.log`` — the partition's "latest" row would be an
    old run and a real regression in r10 would never gate."""
    return tuple(int(p) if p.isdigit() else p
                 for p in re.split(r"(\d+)", name))


def _fingerprint(row: dict) -> Tuple:
    """Idempotence key for backfill: one artifact, one row."""
    return (row.get("kind"), row.get("source"), row.get("platform"),
            row.get("metric"), row.get("value_ms"),
            row.get("single_dispatch_ms"))


def normalize_bench(artifact: dict, source: str = "") -> dict:
    """One bench artifact -> one ledger row.

    Accepts both the raw bench.py JSON line and the driver's wrapper
    format (``{"n", "cmd", "rc", "tail", "parsed"}`` — the committed
    ``BENCH_rNN.json`` shape); a wrapper whose ``parsed`` is null (the
    all-attempts-failed round) becomes a quarantined ``platform:
    "none"`` row so the trajectory records the failure without ever
    comparing against it."""
    rec = artifact
    if isinstance(artifact, dict) and "parsed" in artifact \
            and ("cmd" in artifact or "rc" in artifact):
        rec = artifact.get("parsed")
    if not isinstance(rec, dict):
        rec = {}
    platform = str(rec.get("platform", "") or "none")
    metric = str(rec.get("metric", "") or "")
    value = rec.get("value")
    fallback = bool(rec.get(
        "fallback", platform in ("cpu-fallback", "none")))
    row = {
        "schema": LEDGER_SCHEMA,
        "kind": "bench",
        "source": source,
        "ingested_us": time.time_ns() // 1000,
        "platform": platform,
        "fallback": fallback,
        "smoke": bool(rec.get("smoke", "[smoke size]" in metric)),
        "kernel": rec.get("kernel"),
        "config": rec.get("config"),
        "metric": metric,
        "value_ms": value,
        "single_dispatch_ms": rec.get("single_dispatch_ms"),
        "vs_target": rec.get("vs_target", rec.get("vs_baseline")),
        "artifact_schema_version": rec.get("schema_version"),
        # quarantined rows are recorded, never compared
        "quarantined": fallback or value is None,
    }
    if rec.get("checksum_deviation"):
        row["checksum_deviation"] = True
    if rec.get("error"):
        row["error"] = str(rec["error"])[:300]
    return row


def devprof_digest(obs_jsonl: str) -> dict:
    """The deterministic-metric digest of one run's obs sidecar: the
    summed devprof program costs plus each pid's LAST counter snapshot
    merged across pids (bench parent + abandoned children share one
    sidecar; see ``python -m cause_tpu.obs --summary`` for the same
    per-pid rule)."""
    out: dict = {"devprof": {}, "counters": {}}
    if not obs_jsonl or not os.path.exists(obs_jsonl):
        return out
    events = load_jsonl(obs_jsonl)
    cost_sum: Dict[str, float] = {}
    n_programs = 0
    for e in events:
        if e.get("ev") == "event" and e.get("name") == "devprof.program":
            cost = (e.get("fields") or {}).get("cost") or {}
            n_programs += 1
            for k, v in cost.items():
                if isinstance(v, (int, float)):
                    cost_sum[k] = cost_sum.get(k, 0) + v
    if n_programs:
        cost_sum["programs"] = n_programs
        out["devprof"] = cost_sum
    out["counters"] = merged_final_counters(events)
    return out


def ingest_record(rec: dict, source: str = "", obs_jsonl: str = "",
                  path: Optional[str] = None,
                  kind: str = "bench",
                  extra: Optional[dict] = None) -> dict:
    """Append one already-parsed artifact record as a normalized row,
    with the sidecar's devprof/counter digest when an obs JSONL is
    given. The in-memory half of ``ingest()`` — bench.py holds its
    artifact line already parsed and must not round-trip it through a
    temp file just to land a ledger row. ``extra`` merges additional
    row fields verbatim (the gap CLI's ``--kind gap`` summary rides
    here) without ever overriding the normalized provenance keys."""
    row = normalize_bench(rec, source=source)
    row["kind"] = kind
    if kind != "bench":
        # harvest/soak/gap artifacts carry no bench-shaped value_ms,
        # so the bench heuristic would quarantine every one of them
        # and the deterministic-metric gate would be silently inert
        # for the non-bench kinds — for those rows only a fallback
        # platform quarantines
        row["quarantined"] = bool(row["fallback"])
    if obs_jsonl:
        digest = devprof_digest(obs_jsonl)
        if digest.get("devprof"):
            row["devprof"] = digest["devprof"]
        if digest.get("counters"):
            row["counters"] = digest["counters"]
        cost = costmodel_row_digest(obs_jsonl)
        if cost:
            row["cost"] = cost
    if extra:
        for k, v in extra.items():
            row.setdefault(k, v)
    return append(row, path)


def costmodel_row_digest(obs_jsonl: str) -> dict:
    """The cost-model extension of a ledger row: the sidecar's
    ``wave.cost`` aggregate (waves, dispatches, delta ops, slope
    verdict — ``costmodel.costmodel_digest``). Empty when the stream
    carries no wave.cost events, so pre-PR-6 ingests are unchanged."""
    if not obs_jsonl or not os.path.exists(obs_jsonl):
        return {}
    from .costmodel import costmodel_digest

    return costmodel_digest(load_jsonl(obs_jsonl))


def ingest(artifact_path: str, source: str = "",
           obs_jsonl: str = "", path: Optional[str] = None,
           kind: str = "bench") -> dict:
    """Parse a bench/harvest/soak artifact file (the LAST JSON line of
    the file — bench artifacts are often tee'd logs) and append the
    normalized row via ``ingest_record``."""
    rec = None
    with open(artifact_path) as f:
        text = f.read()
    try:
        rec = json.loads(text)
    except ValueError:
        for line in reversed(text.splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                rec = parsed
                break
    if not isinstance(rec, dict):
        raise ValueError(f"{artifact_path}: no JSON artifact found")
    return ingest_record(rec, source=source
                         or os.path.basename(artifact_path),
                         obs_jsonl=obs_jsonl, path=path, kind=kind)


def backfill(root: Optional[str] = None,
             path: Optional[str] = None) -> List[dict]:
    """Import the committed trajectory: ``BENCH_r*.json`` (driver
    wrapper format, in round order) and every bench JSON line inside
    ``measurements/*.log``, each with the platform tag its artifact
    honestly recorded. Idempotent: rows already in the ledger (by
    artifact fingerprint) are skipped."""
    root = root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = path or default_path()
    have = {_fingerprint(r) for r in load(path)}
    added: List[dict] = []

    def _add(row: dict) -> None:
        if _fingerprint(row) in have:
            return
        have.add(_fingerprint(row))
        added.append(append(row, path))

    for bench_path in sorted(glob.glob(os.path.join(root,
                                                    "BENCH_r*.json")),
                             key=_natural):
        try:
            with open(bench_path) as f:
                artifact = json.load(f)
        except (OSError, ValueError):
            continue
        _add(normalize_bench(artifact,
                             source=os.path.basename(bench_path)))

    for log_path in sorted(glob.glob(os.path.join(root, "measurements",
                                                  "*.log")),
                           key=_natural):
        base = os.path.basename(log_path)
        try:
            with open(log_path, errors="replace") as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not (line.startswith("{")
                    and _BENCH_METRIC_PREFIX in line):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "platform" not in rec:
                continue
            _add(normalize_bench(rec, source=base))
    return added


# ------------------------------------------------------------- checker


def _partition_key(row: dict) -> Tuple:
    """The ONLY grouping rows are ever compared within: same kind
    (bench rows never gate against harvest/soak rows), identical
    platform string, same size class, same kernel, same config (the
    allstream/beststream A/B flips select different sort/gather
    algorithms — different flops, different wall time). Anything else
    is a different experiment."""
    return (row.get("kind") or "bench", row.get("platform"),
            bool(row.get("smoke")),
            row.get("kernel") or "?", row.get("config") or "default")


def check(path: Optional[str] = None,
          rows: Optional[List[dict]] = None) -> dict:
    """The trajectory + regression verdict. Returns::

        {"rows": N, "partitions": {...}, "regressions": [...],
         "ok": bool}

    Regression kinds: ``devprof`` (cost_analysis flops/bytes grew past
    DET_TOL vs the previous row that recorded them), ``counters``
    (``program_cache.miss`` grew — a re-trace storm), ``wall_time``
    (same-platform ``tpu`` p50 slid past WALL_TOL vs the partition's
    best). Quarantined rows never participate; rows are NEVER compared
    across different ``platform`` values."""
    rows = load(path) if rows is None else rows
    parts: Dict[Tuple, List[dict]] = {}
    quarantined = 0
    for r in rows:
        if r.get("quarantined"):
            quarantined += 1
            continue
        parts.setdefault(_partition_key(r), []).append(r)

    regressions: List[dict] = []
    partitions: Dict[str, dict] = {}
    for key, series in parts.items():
        kind, platform, smoke, kernel, config = key
        label = (f"{platform}|{'smoke' if smoke else 'full'}"
                 f"|{kernel}|{config}")
        if kind != "bench":
            label = f"{kind}|{label}"
        partitions[label] = {
            "rows": len(series),
            "trajectory": [
                {"source": r.get("source"), "value_ms": r.get("value_ms")}
                for r in series
            ],
        }
        if len(series) < 2:
            continue
        latest = series[-1]
        prev = series[:-1]

        def _regress(kind, metric, before, after, against):
            regressions.append({
                "kind": kind, "partition": label, "metric": metric,
                "before": before, "after": after,
                "against": against.get("source"),
                "source": latest.get("source"),
            })

        lat_dev = latest.get("devprof") or {}
        if lat_dev:
            for r in reversed(prev):
                ref = r.get("devprof") or {}
                if not ref:
                    continue
                for m in ("flops", "bytes_accessed"):
                    b, a = ref.get(m), lat_dev.get(m)
                    if b and a and a > b * (1 + DET_TOL):
                        _regress("devprof", m, b, a, r)
                break
        lat_ctr = latest.get("counters") or {}
        if lat_ctr.get("program_cache.miss") is not None:
            for r in reversed(prev):
                ref = (r.get("counters") or {}).get("program_cache.miss")
                if ref is None:
                    continue
                if lat_ctr["program_cache.miss"] > ref:
                    _regress("counters", "program_cache.miss", ref,
                             lat_ctr["program_cache.miss"], r)
                break
        if platform == "tpu" and latest.get("value_ms"):
            best = [r for r in prev if r.get("value_ms")]
            if best:
                ref = min(best, key=lambda r: r["value_ms"])
                if latest["value_ms"] > ref["value_ms"] * (1 + WALL_TOL):
                    _regress("wall_time", "value_ms", ref["value_ms"],
                             latest["value_ms"], ref)

    return {
        "rows": len(rows),
        "quarantined": quarantined,
        "partitions": partitions,
        "regressions": regressions,
        "ok": not regressions,
    }


def pending(path: Optional[str] = None,
            rows: Optional[List[dict]] = None) -> dict:
    """The chip-pending claim matrix (PR 20): every (kind, smoke,
    kernel, config) experiment that has committed rows but NO
    un-quarantined ``platform == "tpu"`` row — i.e. every claim the
    ledger is still owed real-chip evidence for. ROADMAP item 1's
    tunnel-window checklist is generated from this instead of
    hand-maintained prose: the next TPU window runs exactly these
    partitions. Quarantined rows never claim (a fallback-poisoned
    tpu row is not evidence)."""
    rows = load(path) if rows is None else rows
    groups: Dict[Tuple, Dict] = {}
    for r in rows:
        if r.get("quarantined"):
            continue
        kind, platform, smoke, kernel, config = _partition_key(r)
        g = groups.setdefault((kind, smoke, kernel, config), {
            "platforms": {}, "latest_source": None})
        g["platforms"][platform] = \
            g["platforms"].get(platform, 0) + 1
        g["latest_source"] = r.get("source") or g["latest_source"]
    pend = []
    claimed = 0
    for (kind, smoke, kernel, config), g in sorted(
            groups.items(), key=lambda kv: [str(x) for x in kv[0]]):
        has_tpu = any(str(p) == "tpu" for p in g["platforms"])
        if has_tpu:
            claimed += 1
            continue
        pend.append({
            "kind": kind, "smoke": smoke, "kernel": kernel,
            "config": config,
            "platforms": dict(sorted(g["platforms"].items(),
                                     key=lambda kv: str(kv[0]))),
            "latest_source": g["latest_source"],
        })
    return {"partitions": len(groups), "claimed": claimed,
            "pending": pend}


def render_pending(matrix: dict) -> str:
    lines = [f"chip-pending claim matrix: {len(matrix['pending'])} "
             f"pending / {matrix['partitions']} partition(s) "
             f"({matrix['claimed']} tpu-claimed)"]
    if not matrix["pending"]:
        lines.append("  (every partition has a tpu row — nothing "
                     "owed)")
        return "\n".join(lines)
    lines.append(f"  {'kind':<9s} {'size':<6s} {'kernel':<22s} "
                 f"{'config':<22s} evidence so far")
    for p in matrix["pending"]:
        ev = ", ".join(f"{plat}:{n}"
                       for plat, n in p["platforms"].items())
        lines.append(
            f"  {p['kind']:<9s} {'smoke' if p['smoke'] else 'full':<6s} "
            f"{p['kernel']:<22s} {p['config']:<22s} {ev}")
    return "\n".join(lines)


# ----------------------------------------------------------------- CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs ledger",
        description="Persistent platform-partitioned perf ledger: "
                    "ingest bench artifacts, backfill the committed "
                    "trajectory, gate on regressions.")
    ap.add_argument("--ledger", default="",
                    help="ledger path (default: CAUSE_TPU_LEDGER or "
                         "measurements/ledger.jsonl)")
    ap.add_argument("--backfill", action="store_true",
                    help="import BENCH_r*.json + measurements/*.log "
                         "bench lines (idempotent)")
    ap.add_argument("--root", default="",
                    help="repo root for --backfill (default: this "
                         "checkout)")
    ap.add_argument("--ingest", default="",
                    help="bench artifact file to append (last JSON "
                         "line wins)")
    ap.add_argument("--obs", default="",
                    help="obs JSONL sidecar of the --ingest run "
                         "(devprof/counter digest lands in the row)")
    ap.add_argument("--source", default="",
                    help="source tag for --ingest rows")
    ap.add_argument("--kind", default="bench",
                    help="row kind for --ingest (bench/harvest/soak/"
                         "gap — gap rows carry a north-star summary "
                         "and gate like any non-bench kind: platform-"
                         "partitioned, quarantined only on fallback)")
    ap.add_argument("--check", action="store_true",
                    help="regression verdict; exit 1 on any regression")
    ap.add_argument("--pending", action="store_true",
                    help="render the chip-pending claim matrix: every "
                         "kind/config partition lacking an "
                         "un-quarantined tpu row (the next TPU "
                         "window's checklist)")
    ap.add_argument("--json", action="store_true",
                    help="with --pending: emit the matrix as JSON")
    a = ap.parse_args(argv)
    path = a.ledger or None

    did_something = False
    if a.backfill:
        added = backfill(root=a.root or None, path=path)
        print(f"ledger: backfilled {len(added)} row(s) -> "
              f"{path or default_path()}", file=sys.stderr)
        did_something = True
    if a.ingest:
        row = ingest(a.ingest, source=a.source, obs_jsonl=a.obs,
                     path=path, kind=a.kind)
        print(f"ledger: ingested {row['platform']} row from "
              f"{a.ingest}", file=sys.stderr)
        did_something = True
    if a.pending:
        matrix = pending(path)
        print(json.dumps(matrix, indent=1) if a.json
              else render_pending(matrix))
        did_something = True
    if a.check:
        verdict = check(path)
        print(json.dumps(verdict, indent=1))
        return 0 if verdict["ok"] else 1
    if not did_something:
        ap.print_help(sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
