"""``python -m cause_tpu.obs watch`` — the fleet watch dashboard.

The terminal face of ``cause_tpu.obs.live``: tail one or more obs
JSONL sidecars (or read them once), run the incremental fold + alert
rules, and redraw one glanceable block — fleet shape, convergence
staleness, lag percentiles with the SLO verdict and burn rate,
full-bag and fallback rates, waves/sec, dispatch counts, token
-headroom minima, the last run heartbeat, per-event recency, and
every alert the rules fired. Curses-free on purpose: plain ANSI
home-and-clear redraw works over any ssh tunnel, inside tmux, and in
a CI log (where ``--once`` prints the block exactly once).

    python -m cause_tpu.obs watch events.jsonl                # live tail
    python -m cause_tpu.obs watch a.jsonl b.jsonl --once      # one shot
    python -m cause_tpu.obs watch events.jsonl --rules "burn>2" \\
        --rules "absence:run.heartbeat:600"
    python -m cause_tpu.obs watch events.jsonl --serve-port 9464
    python -m cause_tpu.obs watch --collector host:9419       # fleet

``--collector HOST:PORT`` (PR 20) reads the fleet-wide fold from a
running :class:`~cause_tpu.obs.collector.CollectorServer` over its
socket feed instead of tailing local files — every host's serve/net/
lag/journey axes appear WHILE the fleet runs, no file merging. The
snapshot arrives with per-origin (host, pid) rows; the Prometheus
endpoint emits them as labeled serve/net series so multi-origin
scrapes never clobber each other (label cardinality is bounded by the
collector's origin LRU, not by traffic).

``--serve-port`` additionally serves the snapshot as Prometheus text
(``/metrics``, stdlib http.server — no client dependency) and as JSON
(``/``), so a scraper or the item-4 admission controller reads the
same numbers the dashboard shows. With ``CAUSE_TPU_OBS=1`` the watch
process also emits its periodic ``live.snapshot`` rollups (and any
``live.alert`` firings) into its own obs stream — watching a watcher
works.

Stdlib-only, importable without jax/numpy, like every other obs
reader.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Callable, List, Optional

from .live import DEFAULT_RULE_SPECS, LiveMonitor, MultiTailer
from .perfetto import load_streams

__all__ = ["render", "prometheus_text", "serve_metrics", "main"]

_CLEAR = "\x1b[H\x1b[2J"   # home + clear (first frame)
_HOME = "\x1b[H"           # home (subsequent frames)
_EOS = "\x1b[0J"           # clear below the rendered block


def _g(v, none="-"):
    """Compact number formatting with an explicit missing marker."""
    if v is None:
        return none
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def render(snap: dict, alerts: List[dict], paths: List[str],
           clock: Optional[float] = None) -> str:
    """The dashboard block (plain text; the live loop wraps it in the
    ANSI redraw, ``--once`` prints it bare)."""
    fleet = snap.get("fleet") or {}
    lag = snap.get("lag") or {}
    conv = lag.get("converged") or {}
    slo = lag.get("slo") or {}
    win = lag.get("window") or {}
    sync = snap.get("sync") or {}
    wave = snap.get("wave") or {}
    cost = snap.get("cost") or {}
    rates = snap.get("rates") or {}
    head = snap.get("headroom") or {}
    ages = snap.get("ages_s") or {}
    when = time.strftime("%H:%M:%S",
                         time.gmtime(clock if clock is not None
                                     else snap.get("ts_us", 0) / 1e6))
    lines = [
        f"live telemetry [{when}] — {len(paths)} stream(s), "
        f"{snap.get('records', 0)} record(s)"
        + (f", span {_g(snap.get('span_s'))} s"
           if snap.get("span_s") is not None else ""),
        f"  fleet: {fleet.get('documents', 0)} document(s), "
        f"{fleet.get('waves', 0)} wave(s), "
        f"{fleet.get('replicas', 0)} replicas; "
        f"{fleet.get('agreed_documents', 0)}/{fleet.get('documents', 0)}"
        f" agreed, {fleet.get('divergence_incidents', 0)} divergence "
        f"incident(s)",
    ]
    if fleet.get("staleness"):
        hist = "  ".join(f"{k} behind: {v}"
                         for k, v in fleet["staleness"].items())
        lines.append(f"  staleness: {hist}")
    if lag.get("ops_converged"):
        lines.append(
            f"  lag: {lag['ops_converged']} converged "
            f"(p50 {_g(conv.get('p50_ms'))} ms  "
            f"p95 {_g(conv.get('p95_ms'))}  "
            f"p99 {_g(conv.get('p99_ms'))}), "
            f"{lag.get('pending', 0)} pending; "
            f"SLO {_g(slo.get('target_ms'))} ms -> "
            f"{slo.get('verdict') or '-'}"
            + (f" ({100 * slo['attainment']:.1f}% within, "
               f"burn {_g(slo.get('burn_rate'))}x)"
               if slo.get("attainment") is not None else ""))
        if win:
            lines.append(
                f"  window (last {win.get('n')}): "
                f"p50 {_g(win.get('p50_ms'))} ms  "
                f"p95 {_g(win.get('p95_ms'))}  "
                f"p99 {_g(win.get('p99_ms'))}  "
                f"(burn {_g(win.get('burn_rate'))}x)")
    elif lag.get("pending"):
        lines.append(f"  lag: 0 converged, {lag['pending']} PENDING "
                     "(no fleet-wide digest agreement yet)")
    else:
        lines.append("  lag: no convergence-lag records")
    lines.append(
        f"  sync: {sync.get('delta_rounds', 0)} delta round(s), "
        f"{sync.get('full_bag', 0)} full-bag "
        f"({100 * (sync.get('full_bag_rate') or 0):.1f}%); "
        f"waves/sec {_g(rates.get('waves_per_s'))}")
    lines.append(
        f"  waves: {wave.get('pairs', 0)} pair-merges, "
        f"{100 * (wave.get('fallback_rate') or 0):.1f}% fallback, "
        f"{wave.get('overflow_retries', 0)} overflow retrie(s), "
        f"{wave.get('session_overflow', 0)} session overflow(s)")
    if cost:
        slope = (cost.get("slope") or {}).get("verdict")
        by = cost.get("by_path")
        lines.append(
            f"  cost: {cost.get('waves', 0)} wave(s), "
            f"{cost.get('dispatches', 0)} dispatch(es), "
            f"{cost.get('delta_ops', 0)} delta op(s), "
            f"{_g(cost.get('wall_ms'))} ms"
            + (f", slope {slope}" if slope else "")
            + (f" [{', '.join(f'{k}:{v}' for k, v in by.items())}]"
               if by else ""))
    if head.get("min") is not None:
        per = ", ".join(f"{k} {_g(v)}" for k, v
                        in sorted(head.get("min_by_site", {}).items()))
        lines.append(f"  headroom: min {_g(head['min'])} ({per})")
    srv = snap.get("serve") or {}
    if srv.get("active"):
        lines.append(
            f"  serve: {srv.get('ticks', 0)} tick(s), "
            f"queue depth {_g(srv.get('queue_depth'))}, "
            f"{_g(srv.get('resident_docs'))} resident doc(s), "
            f"T_batch {_g(srv.get('t_batch_ms'))} ms; "
            f"{srv.get('sheds', 0)} shed(s) "
            f"({_g(srv.get('shed_rate'))}/s)")
        if srv.get("wal_bytes") is not None or srv.get("disk_faults") \
                or srv.get("journal_torn"):
            lines.append(
                f"  wal: {_g(srv.get('wal_segments'))} segment(s), "
                f"{_g(srv.get('wal_bytes'))} bytes; "
                f"{srv.get('disk_faults', 0)} disk fault(s), "
                f"{srv.get('journal_torn', 0)} torn/corrupt line(s)")
    net = snap.get("net") or {}
    if net.get("active"):
        lines.append(
            f"  net: {_g(net.get('connections'))} connection(s), "
            f"{net.get('connects', 0)} connect(s) "
            f"({net.get('reconnects', 0)} re, "
            f"{_g(net.get('reconnects_per_min'))}/min), "
            f"{net.get('nacks', 0)} nack(s), "
            f"{net.get('dup_frames', 0)} dup frame(s) "
            f"+ {net.get('dup_ops_suppressed', 0)} op(s) suppressed, "
            f"outbound {_g(net.get('outbound_depth'))}")
    jy = snap.get("journey") or {}
    if jy.get("active"):
        line = (
            f"  journeys: {jy.get('traces', 0)} trace(s) "
            f"({jy.get('complete', 0)} complete, "
            f"{jy.get('shed', 0)} shed, "
            f"{jy.get('inflight', 0)} in flight), "
            f"{jy.get('orphan_hops', 0)} orphan hop(s); "
            f"mint→converged p50 {_g(jy.get('total_p50_ms'))} ms "
            f"p99 {_g(jy.get('total_p99_ms'))}")
        lines.append(line)
        if jy.get("worst_trace"):
            lines.append(
                f"    worst: {_g(jy.get('worst_total_ms'))} ms — "
                f"`obs journey {jy['worst_trace']}`")
    shp = snap.get("ship") or {}
    if shp.get("active"):
        lines.append(
            f"  ship: {shp.get('origins', 0)} origin(s), "
            f"{shp.get('accepted', 0)} record(s) accepted, "
            f"{shp.get('dup_records', 0)} dup-skipped, "
            f"{shp.get('missed', 0)} missed (evidenced), "
            f"{shp.get('unexplained_gaps', 0)} unexplained gap(s)")
        for o in (snap.get("origins") or [])[:8]:
            lines.append(
                f"    {o['host']}:{o['pid']}: wm {o['watermark']}, "
                f"{o['accepted']} accepted, {o['missed']} missed, "
                f"last {_g(o.get('age_s'))} s ago")
    hb = snap.get("heartbeat")
    if hb:
        hb_age = ages.get("run.heartbeat")
        desc = " ".join(f"{k}={v}" for k, v in hb.items()
                        if k not in ("ts_us",))
        lines.append(f"  heartbeat: {desc}"
                     + (f"  ({_g(hb_age)} s ago)"
                        if hb_age is not None else ""))
    recency = [(n, a) for n, a in sorted(ages.items())
               if n in ("any", "wave.digest", "wave.cost",
                        "run.heartbeat", "lag.window")]
    if recency:
        lines.append("  ages: " + "  ".join(f"{n} {_g(a)}s"
                                            for n, a in recency))
    lines.append(f"  alerts: {len(alerts)} fired")
    for a in alerts[-8:]:
        when_a = time.strftime("%H:%M:%S",
                               time.gmtime(a.get("ts_us", 0) / 1e6))
        if a.get("kind") == "absence":
            lines.append(
                f"    [{when_a}] {a['rule']}: no {a['event']} for "
                f"{_g(a.get('age_s'))} s (limit {_g(a.get('window_s'))})")
        else:
            lines.append(
                f"    [{when_a}] {a['rule']}: {a.get('path')} = "
                f"{_g(a.get('value'))} (limit {a.get('op')} "
                f"{_g(a.get('limit'))})")
    return "\n".join(lines)


# -------------------------------------------------------- prometheus

# metric name -> (snapshot path, prometheus type)
_PROM_METRICS = (
    ("cause_tpu_live_records", "records", "counter"),
    ("cause_tpu_live_documents", "fleet.documents", "gauge"),
    ("cause_tpu_live_waves_total", "fleet.waves", "counter"),
    ("cause_tpu_live_replicas", "fleet.replicas", "gauge"),
    ("cause_tpu_live_agreed_documents", "fleet.agreed_documents",
     "gauge"),
    ("cause_tpu_live_divergence_incidents",
     "fleet.divergence_incidents", "counter"),
    ("cause_tpu_live_ops_converged", "lag.ops_converged", "counter"),
    ("cause_tpu_live_ops_pending", "lag.pending", "gauge"),
    ("cause_tpu_live_lag_p50_ms", "lag.converged.p50_ms", "gauge"),
    ("cause_tpu_live_lag_p95_ms", "lag.converged.p95_ms", "gauge"),
    ("cause_tpu_live_lag_p99_ms", "lag.converged.p99_ms", "gauge"),
    ("cause_tpu_live_window_p99_ms", "lag.window.p99_ms", "gauge"),
    ("cause_tpu_live_slo_target_ms", "lag.slo.target_ms", "gauge"),
    ("cause_tpu_live_slo_attainment", "lag.slo.attainment", "gauge"),
    ("cause_tpu_live_slo_burn_rate", "lag.slo.burn_rate", "gauge"),
    ("cause_tpu_live_full_bag_rate", "sync.full_bag_rate", "gauge"),
    ("cause_tpu_live_wave_fallback_rate", "wave.fallback_rate",
     "gauge"),
    ("cause_tpu_live_waves_per_s", "rates.waves_per_s", "gauge"),
    ("cause_tpu_live_dispatches_total", "cost.dispatches", "counter"),
    ("cause_tpu_live_delta_ops_total", "cost.delta_ops", "counter"),
    ("cause_tpu_live_headroom_min", "headroom.min", "gauge"),
    ("cause_tpu_live_serve_queue_depth", "serve.queue_depth", "gauge"),
    ("cause_tpu_live_serve_resident_docs", "serve.resident_docs",
     "gauge"),
    ("cause_tpu_live_serve_shed_rate", "serve.shed_rate", "gauge"),
    ("cause_tpu_live_serve_sheds_total", "serve.sheds", "counter"),
    ("cause_tpu_live_serve_t_batch_ms", "serve.t_batch_ms", "gauge"),
    ("cause_tpu_live_serve_disk_faults_total", "serve.disk_faults",
     "counter"),
    ("cause_tpu_live_serve_journal_torn_total", "serve.journal_torn",
     "counter"),
    ("cause_tpu_live_serve_wal_segments", "serve.wal_segments",
     "gauge"),
    ("cause_tpu_live_serve_wal_bytes", "serve.wal_bytes", "gauge"),
    ("cause_tpu_live_net_connections", "net.connections", "gauge"),
    ("cause_tpu_live_net_connects_total", "net.connects", "counter"),
    ("cause_tpu_live_net_reconnects_total", "net.reconnects",
     "counter"),
    ("cause_tpu_live_net_reconnects_per_min",
     "net.reconnects_per_min", "gauge"),
    ("cause_tpu_live_net_nacks_total", "net.nacks", "counter"),
    ("cause_tpu_live_net_dup_frames_total", "net.dup_frames",
     "counter"),
    ("cause_tpu_live_net_dup_ops_total", "net.dup_ops_suppressed",
     "counter"),
    ("cause_tpu_live_net_outbound_depth", "net.outbound_depth",
     "gauge"),
    ("cause_tpu_live_journey_traces_total", "journey.traces",
     "counter"),
    ("cause_tpu_live_journey_complete_total", "journey.complete",
     "counter"),
    ("cause_tpu_live_journey_inflight", "journey.inflight", "gauge"),
    ("cause_tpu_live_journey_orphan_hops_total",
     "journey.orphan_hops", "counter"),
    ("cause_tpu_live_journey_p99_ms", "journey.total_p99_ms",
     "gauge"),
    ("cause_tpu_live_alerts_total", "alerts_total", "counter"),
)


def _prom_name(raw: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in raw)


def _prom_label(raw) -> str:
    return str(raw).replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(snap: dict) -> str:
    """The snapshot as Prometheus exposition text (version 0.0.4):
    one line per known metric, Nones skipped — a scraper sees only
    what the stream actually measured. A collector snapshot's
    per-origin rows additionally emit every serve/net gauge as a
    (host, pid)-labeled series — without the labels a multi-origin
    scrape is last-writer-wins per metric name, i.e. one arbitrary
    host's queue depth wearing the fleet's name. Series cardinality
    is bounded by the collector's origin LRU: an evicted origin's
    row simply stops being exported."""
    from .live import snapshot_path

    lines = []
    for name, path, kind in _PROM_METRICS:
        v = snapshot_path(snap, path)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {v:g}")
    typed = set()
    for o in snap.get("origins") or []:
        labels = (f'{{host="{_prom_label(o.get("host"))}"'
                  f',pid="{_prom_label(o.get("pid"))}"}}')
        for sect in ("serve", "net"):
            for k, v in sorted((o.get(sect) or {}).items()):
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                name = f"cause_tpu_origin_{sect}_{_prom_name(k)}"
                if name not in typed:
                    typed.add(name)
                    lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name}{labels} {v:g}")
    return "\n".join(lines) + "\n"


def serve_metrics(port: int, get_snapshot: Callable[[], dict]):
    """Serve ``/metrics`` (Prometheus text) and ``/`` (snapshot JSON)
    on a daemon thread. Returns ``(server, actual_port)`` — pass port
    0 for an ephemeral port (tests)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            try:
                snap = get_snapshot()
                if self.path.split("?")[0].rstrip("/") == "/metrics":
                    body = prometheus_text(snap).encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = (json.dumps(snap, default=str) + "\n").encode()
                    ctype = "application/json"
            except Exception as e:  # noqa: BLE001 - serve 500, never die
                body = f"error: {type(e).__name__}: {e}\n".encode()
                self.send_response(500)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet: the dashboard owns stdout
            pass

    server = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
    thread = threading.Thread(target=server.serve_forever,
                              name="obs-watch-metrics", daemon=True)
    thread.start()
    return server, server.server_address[1]


# ---------------------------------------------------- collector feed


class _CollectorFeed:
    """One persistent connection to a CollectorServer: ``snap()``
    requests the fleet-wide fold snapshot ({"op": "snap"}) and
    returns it, reconnecting lazily across ticks — a collector
    restart costs one missed frame, not a dead dashboard. The watch
    side is a pure reader: no hello, no origin row, no watermark."""

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 5.0):
        self.host = host
        self.port = int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self.read_timeout_s = float(read_timeout_s)
        self.fs = None
        self.last_error: Optional[str] = None

    def snap(self) -> Optional[dict]:
        import socket

        from .. import sync as _sync
        from ..collections import shared as _s
        from ..net import transport as _transport

        try:
            if self.fs is None:
                sock = socket.create_connection(
                    (self.host, self.port),
                    timeout=self.connect_timeout_s)
                sock.settimeout(self.read_timeout_s)
                self.fs = _transport.FrameStream(sock,
                                                 site="obs.watch")
            _sync.send_frame(self.fs, {"op": "snap"})
            reply = _transport.recv_msg(self.fs, self.read_timeout_s)
        except (_s.CausalError, OSError) as e:
            self.last_error = f"{type(e).__name__}: {e}"
            self.close()
            return None
        if reply.get("op") != "snap":
            self.last_error = f"unexpected reply op {reply.get('op')!r}"
            return None
        self.last_error = None
        return reply.get("snapshot")

    def close(self) -> None:
        if self.fs is not None:
            try:
                self.fs.close()
            except OSError:
                pass
            self.fs = None


# --------------------------------------------------------------- CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs watch",
        description="Live fleet watch over obs JSONL stream(s): "
                    "incremental fold (fleet health, lag/SLO, cost, "
                    "rates, heartbeats), declarative alert rules, "
                    "ANSI-redraw dashboard, optional Prometheus "
                    "endpoint. --once renders a single snapshot and "
                    "exits (CI, cron, tunnel checks).")
    ap.add_argument("jsonl", nargs="*",
                    help="obs event file(s) to tail (JSON lines; "
                         "files may not exist yet in live mode). "
                         "Not used with --collector.")
    ap.add_argument("--collector", default=None, metavar="HOST:PORT",
                    help="read the fleet-wide snapshot from a running "
                         "CollectorServer's socket feed instead of "
                         "tailing local files")
    ap.add_argument("--rules", action="append", default=None,
                    metavar="SPEC",
                    help="alert rule (repeatable): <path><op><value> "
                         "(aliases: burn, p99, full_bag_rate, "
                         "pending, headroom, waves_per_s, ...) or "
                         "absence:<event>:<seconds>. Default: "
                         + ", ".join(DEFAULT_RULE_SPECS))
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll/redraw interval seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="read the stream(s) once, render one "
                         "snapshot + alerts, exit")
    ap.add_argument("--json", action="store_true",
                    help="machine output: with --once one "
                         "{snapshot, alerts} document; live mode one "
                         "JSON line per interval instead of the ANSI "
                         "dashboard")
    ap.add_argument("--serve-port", type=int, default=None,
                    metavar="PORT",
                    help="serve /metrics (Prometheus text) + / "
                         "(snapshot JSON) on 127.0.0.1:PORT")
    ap.add_argument("--duration", type=float, default=None,
                    metavar="SECONDS",
                    help="live mode: stop after this many seconds "
                         "(default: run until interrupted)")
    a = ap.parse_args(argv)

    if a.collector is not None:
        from .ship import parse_endpoint

        if a.jsonl:
            print("watch: give obs JSONL file(s) OR --collector, "
                  "not both", file=sys.stderr)
            return 2
        ep = parse_endpoint(a.collector)
        if ep is None:
            print(f"watch: bad --collector endpoint: {a.collector!r} "
                  "(want HOST:PORT)", file=sys.stderr)
            return 2
        return _collector_main(a, _CollectorFeed(*ep))
    if not a.jsonl:
        print("watch: give obs JSONL file(s) or --collector "
              "HOST:PORT", file=sys.stderr)
        return 2

    try:
        monitor = LiveMonitor(rules=a.rules)
    except ValueError as e:
        print(f"watch: {e}", file=sys.stderr)
        return 2

    if a.once:
        for path in a.jsonl:
            if not os.path.exists(path):
                print(f"watch: no such file: {path}", file=sys.stderr)
                return 2
        monitor.feed(load_streams(a.jsonl))
        # a replayed historical stream is judged against its OWN end,
        # not today's clock: an absence rule must detect a wedge
        # inside the recorded run, not the age of the file
        end_us = monitor.fold.last_ts_us
        snap = monitor.emit_snapshot(now_us=end_us)
        monitor.evaluate(now_us=end_us, snap=snap)
        snap = monitor.snapshot(now_us=snap["ts_us"])
        try:
            if a.json:
                print(json.dumps({"snapshot": snap,
                                  "alerts": monitor.alerts},
                                 default=str, indent=1))
            else:
                print(render(snap, monitor.alerts, a.jsonl,
                             clock=snap["ts_us"] / 1e6))
        except BrokenPipeError:
            # `obs watch ... --once | head` is the normal tunnel
            # one-liner; a closed pipe is the reader's choice, not
            # an error
            try:
                sys.stdout.close()
            except OSError:
                pass
        return 0

    server = None
    tail = MultiTailer(a.jsonl)
    latest = {"snap": monitor.snapshot()}
    if a.serve_port is not None:
        server, port = serve_metrics(a.serve_port,
                                     lambda: latest["snap"])
        print(f"watch: serving /metrics on 127.0.0.1:{port}",
              file=sys.stderr)
    deadline = (time.monotonic() + a.duration
                if a.duration is not None else None)
    first = True
    try:
        while True:
            monitor.feed(tail.poll())
            snap = monitor.emit_snapshot()
            monitor.evaluate(snap=snap)
            snap = monitor.snapshot()
            latest["snap"] = snap
            if a.json:
                print(json.dumps({"snapshot": snap,
                                  "alerts_fired": len(monitor.alerts)},
                                 default=str), flush=True)
            else:
                block = render(snap, monitor.alerts, a.jsonl,
                               clock=time.time())
                prefix = _CLEAR if first else _HOME
                sys.stdout.write(prefix + block + "\n" + _EOS)
                sys.stdout.flush()
            first = False
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(max(0.05, a.interval))
    except (KeyboardInterrupt, BrokenPipeError):
        pass
    finally:
        tail.close()
        if server is not None:
            server.shutdown()
    if not a.json:
        try:
            sys.stdout.write("\n")
        except (OSError, ValueError):
            pass
    return 0


def _collector_main(a, feed: _CollectorFeed) -> int:
    """The --collector loop: same dashboard, snapshots pulled from
    the collector's socket feed (rules run collector-side — its
    fleet-wide monitor already evaluated them; ``alerts_recent``
    rides the snapshot)."""
    label = [f"collector {feed.host}:{feed.port}"]
    if a.once:
        snap = feed.snap()
        feed.close()
        if snap is None:
            print(f"watch: collector unreachable: {feed.last_error}",
                  file=sys.stderr)
            return 2
        alerts = snap.get("alerts_recent") or []
        if a.json:
            print(json.dumps({"snapshot": snap, "alerts": alerts},
                             default=str, indent=1))
        else:
            print(render(snap, alerts, label,
                         clock=snap.get("ts_us", 0) / 1e6))
        return 0
    server = None
    latest = {"snap": {}}
    if a.serve_port is not None:
        server, port = serve_metrics(a.serve_port,
                                     lambda: latest["snap"])
        print(f"watch: serving /metrics on 127.0.0.1:{port}",
              file=sys.stderr)
    deadline = (time.monotonic() + a.duration
                if a.duration is not None else None)
    first = True
    try:
        while True:
            snap = feed.snap()
            if snap is not None:
                latest["snap"] = snap
            alerts = (snap or latest["snap"]).get(
                "alerts_recent") or []
            if a.json:
                print(json.dumps(
                    {"snapshot": snap,
                     "unreachable": feed.last_error}, default=str),
                    flush=True)
            elif snap is not None:
                block = render(snap, alerts, label,
                               clock=time.time())
                prefix = _CLEAR if first else _HOME
                sys.stdout.write(prefix + block + "\n" + _EOS)
                sys.stdout.flush()
                first = False
            else:
                sys.stdout.write(
                    f"watch: collector unreachable "
                    f"({feed.last_error}); retrying\n")
                sys.stdout.flush()
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(max(0.05, a.interval))
    except (KeyboardInterrupt, BrokenPipeError):
        pass
    finally:
        feed.close()
        if server is not None:
            server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
