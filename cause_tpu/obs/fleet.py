"""Fleet health from an obs event stream: ``python -m cause_tpu.obs
fleet events.jsonl``.

The read side of ``cause_tpu.obs.semantic``: given any obs JSONL (a
soak sidecar, a CI fleet smoke, a 600k-round overnight run), aggregate
the CRDT-semantic vocabulary into one operator-facing report —

- **fleet shape** — documents observed, replica pairs (and replicas:
  a pair is two replicas), waves run;
- **convergence** — the staleness histogram of the LAST wave per
  document (how many pairs are 0, 1, 2... waves behind the fleet's
  modal digest) and every ``divergence`` incident with its
  first-differing-site provenance;
- **degradation rates** — delta-sync rounds vs full-bag fallbacks,
  wave pairs vs host-merge fallbacks vs overflow retries, session
  token-budget overflows;
- **GC** — compaction runs, nodes examined/reclaimed, safety-valve
  declines;
- **collections** — lazy-weave materializations and the last
  tombstone ratio;
- **convergence lag** — the ``obs.lag`` tracer's summary (ops
  converged, create→converged p50/p99, SLO verdict) when the stream
  carries ``lag.window`` records; the full distribution and the
  per-replica worst offenders render through
  ``python -m cause_tpu.obs lag``.

Multiple JSONL streams (a multi-process soak's per-process sidecars)
merge by timestamp before aggregation, so "the last wave per
document" is well-defined across processes. Counters are merged with
the shared per-pid last-snapshot rule
(``perfetto.merged_final_counters``), so a sidecar shared by a parent
and an abandoned child reports the sum, not whichever flushed last.
Stdlib-only, importable without jax, like the rest of ``cause_tpu.obs``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from .lag import LagReducer
from .perfetto import CountersReducer, load_streams

__all__ = ["FleetReducer", "fleet_report", "render", "load_streams",
           "main"]


def _rate(part: float, whole: float) -> float:
    return round(part / whole, 4) if whole else 0.0


class FleetReducer:
    """Incremental form of :func:`fleet_report`: feed obs records one
    at a time (a live tail, an in-process subscriber queue) and render
    the fleet-health report at any point. ``fleet_report`` is this
    reducer fed with the whole stream — one shared body, bit-equal by
    construction (the ``obs.live`` acceptance property).

    State is bounded by fleet shape, not stream length: one last-wave
    record per document (the semantic monitor's own LRU rule bounds
    distinct documents at the write side), one counter snapshot per
    pid, one lag record per (pid, epoch) — plus the divergence
    incident list, bounded at ``incidents_max`` (drops are counted,
    never silent; a healthy fleet mints zero incidents)."""

    __slots__ = ("records", "_last_wave", "_waves", "_incidents",
                 "incidents_dropped", "incidents_max", "_counters",
                 "lag")

    def __init__(self, incidents_max: int = 10000):
        self.records = 0
        self._last_wave: Dict[str, dict] = {}
        self._waves = 0
        self._incidents: List[dict] = []
        self.incidents_dropped = 0
        self.incidents_max = int(incidents_max)
        self._counters = CountersReducer()
        self.lag = LagReducer()

    def feed_counters(self, e: dict) -> None:
        """Overlay a counters snapshot WITHOUT counting it as a
        stream record — the in-process live attachment samples the
        counter registry directly (counters only reach the stream at
        flush), and that overlay must not make the fold's record
        count disagree with the sidecar it mirrors."""
        self._counters.feed(e)

    def feed(self, e: dict) -> None:
        """Consume one obs record (spans and foreign events are
        counted but otherwise free)."""
        self.records += 1
        self._counters.feed(e)
        self.lag.feed(e)
        if e.get("ev") != "event":
            return
        name = e.get("name")
        if name == "wave.digest":
            f = e.get("fields") or {}
            # the LAST wave per DOCUMENT (stream order, regardless of
            # wave/session source) is its current state — a doc
            # observed by both merge_wave and a FleetSession is still
            # ONE doc, and summing per-source histograms would double
            # -count its pairs and report agreed_documents > documents
            self._last_wave[str(f.get("uuid"))] = f
            self._waves += 1
        elif name == "divergence":
            f = e.get("fields") or {}
            if len(self._incidents) >= self.incidents_max:
                self.incidents_dropped += 1
                return
            self._incidents.append({
                "uuid": f.get("uuid"), "source": f.get("source"),
                "wave": f.get("wave"), "pair": f.get("pair"),
                "site": f.get("site"),
                "site_expected": f.get("site_expected"),
                "site_got": f.get("site_got"),
                "disagreeing": f.get("disagreeing"),
            })

    def report(self) -> dict:
        """The fleet-health dict (see :func:`fleet_report`)."""
        counters = self._counters.totals()
        staleness: Dict[str, int] = {}
        pairs = 0
        agreed_now = 0
        for f in self._last_wave.values():
            pairs = max(pairs, int(f.get("pairs") or 0))
            if f.get("agreed"):
                agreed_now += 1
            for bucket, n in (f.get("staleness") or {}).items():
                staleness[str(bucket)] = staleness.get(str(bucket), 0) + n

        delta_rounds = counters.get("sync.delta_rounds", 0)
        full_bag = counters.get("sync.full_bag", 0)
        wave_pairs = counters.get("wave.pairs", 0)
        fallback = counters.get("wave.fallback", 0)
        poisoned = counters.get("wave.poisoned", 0)
        overflow = counters.get("wave.overflow_retry", 0)
        examined = counters.get("gc.nodes_examined", 0)
        reclaimed = counters.get("gc.nodes_reclaimed", 0)
        rejects = counters.get("sync.reject", 0)
        quarantines = counters.get("sync.quarantine", 0)
        readmits = counters.get("sync.readmit", 0)
        rec_steps = counters.get("recovery.steps", 0)

        out = {
            "events": self.records,
            "documents": len(self._last_wave),
            "waves": self._waves,
            "pairs": pairs,
            "replicas": 2 * pairs,
            "agreed_documents": agreed_now,
            "staleness": dict(sorted(staleness.items(),
                                     key=lambda kv: int(kv[0]))),
            "divergence_incidents": list(self._incidents),
            "sync": {
                "delta_rounds": delta_rounds,
                "delta_nodes": counters.get("sync.delta_nodes", 0),
                "full_bag": full_bag,
                "full_bag_rate": _rate(full_bag,
                                       delta_rounds + full_bag),
                # PR 11: validate-before-apply rejects and the replica
                # quarantine they escalate to (quarantined = entries
                # minus re-admissions — the CURRENT quarantine count)
                "rejects": rejects,
                "quarantines": quarantines,
                "readmits": readmits,
                "quarantined": max(0, quarantines - readmits),
            },
            "wave": {
                "pairs": wave_pairs,
                "fallback": fallback,
                "fallback_rate": _rate(fallback, wave_pairs),
                "poisoned": poisoned,
                "overflow_retries": overflow,
                "session_overflow":
                    counters.get("fleet.session_overflow", 0),
            },
            # PR 11: the recovery ladder's evidence — every declared
            # delta->full->double_budget->host transition, retries of
            # transient dispatch failures, checkpoint restores, and
            # the storm axis (steps per wave) the live alert reads
            "recovery": {
                "steps": rec_steps,
                "by_step": {
                    step: counters[f"recovery.step.{step}"]
                    for step in ("full", "double_budget", "host")
                    if counters.get(f"recovery.step.{step}")
                },
                "retries": counters.get("recovery.retry", 0),
                "exhausted": counters.get("recovery.exhausted", 0),
                "restores": counters.get("recovery.restores", 0),
                "chaos_injected": sum(
                    v for k, v in counters.items()
                    if k.startswith("chaos.injected.")),
                "per_wave": _rate(rec_steps, self._waves),
            },
            "gc": {
                "runs": counters.get("gc.runs", 0),
                "nodes_examined": examined,
                "nodes_reclaimed": reclaimed,
                "reclaim_rate": _rate(reclaimed, examined),
                "safety_valve": counters.get("gc.safety_valve", 0),
            },
            "collections": {
                "lazy_materializations":
                    counters.get("collection.lazy_materialize", 0),
            },
            "lag": self._lag_section(),
        }
        if self.incidents_dropped:
            out["divergence_incidents_dropped"] = self.incidents_dropped
        return out

    def _lag_section(self) -> dict:
        """The compact convergence-lag block of the fleet report (the
        full distribution lives in ``python -m cause_tpu.obs lag``)."""
        rep = self.lag.report()
        conv = rep["converged"]
        return {
            "ops_converged": rep["ops_converged"],
            "pending": rep["pending"],
            "p50_ms": conv["p50_ms"],
            "p99_ms": conv["p99_ms"],
            "slo": rep["slo"],
        }


def fleet_report(events: List[dict]) -> dict:
    """Aggregate one obs event stream into the fleet-health dict the
    CLI renders (see module docstring for the sections). Total: the
    report is well-defined on an EMPTY stream — every section zeroes
    out — because an operator's first question to a broken run is
    "did anything record at all?". The batch form of
    :class:`FleetReducer` — one shared body, so the live fold cannot
    drift from this report."""
    r = FleetReducer()
    for e in events:
        r.feed(e)
    return r.report()


def render(report: dict) -> str:
    """The human layout of ``fleet_report`` — one glanceable block."""
    lines = [
        f"fleet: {report['replicas']} replicas "
        f"({report['pairs']} pairs, {report['documents']} document(s)), "
        f"{report['waves']} wave(s), {report['events']} events",
        f"  converged now: {report['agreed_documents']}"
        f"/{report['documents']} document(s)",
    ]
    if report["staleness"]:
        hist = "  ".join(f"{k} wave(s) behind: {v} pair(s)"
                         for k, v in report["staleness"].items())
        lines.append(f"  staleness: {hist}")
    else:
        lines.append("  staleness: no wave digests recorded")
    inc = report["divergence_incidents"]
    lines.append(f"  divergence incidents: {len(inc)}")
    for d in inc[:10]:
        lines.append(
            f"    wave {d['wave']} pair {d['pair']}: first differing "
            f"site {d['site']!r} (expected {d['site_expected']}, got "
            f"{d['site_got']}; {d['disagreeing']} pair(s) disagree)")
    if len(inc) > 10:
        lines.append(f"    ... {len(inc) - 10} more")
    s = report["sync"]
    lines.append(
        f"  sync: {s['delta_rounds']} delta round(s) "
        f"({s['delta_nodes']} nodes), {s['full_bag']} full-bag "
        f"fallback(s) ({100 * s['full_bag_rate']:.1f}%)")
    if s.get("rejects") or s.get("quarantines"):
        lines.append(
            f"  ingest: {s['rejects']} payload reject(s), "
            f"{s['quarantines']} quarantine(s), {s['readmits']} "
            f"readmission(s) ({s['quarantined']} replica(s) "
            f"quarantined now)")
    w = report["wave"]
    lines.append(
        f"  waves: {w['pairs']} pair-merges, {w['fallback']} host "
        f"fallback(s) ({100 * w['fallback_rate']:.1f}%), "
        f"{w['poisoned']} poisoned, {w['overflow_retries']} overflow "
        f"retrie(s), {w['session_overflow']} session overflow(s)")
    rec = report.get("recovery") or {}
    if rec.get("steps") or rec.get("retries") or rec.get("restores") \
            or rec.get("chaos_injected"):
        by = ", ".join(f"{k}: {v}" for k, v in
                       (rec.get("by_step") or {}).items())
        lines.append(
            f"  recovery: {rec['steps']} ladder step(s)"
            + (f" ({by})" if by else "")
            + f", {rec['retries']} retrie(s), "
              f"{rec['restores']} restore(s), "
              f"{rec['chaos_injected']} chaos fault(s) injected "
              f"({rec['per_wave']:.2f} step(s)/wave)")
    g = report["gc"]
    lines.append(
        f"  gc: {g['runs']} run(s), {g['nodes_examined']} examined, "
        f"{g['nodes_reclaimed']} reclaimed "
        f"({100 * g['reclaim_rate']:.1f}%), {g['safety_valve']} "
        f"safety-valve decline(s)")
    lines.append(
        f"  collections: "
        f"{report['collections']['lazy_materializations']} lazy "
        f"materialization(s)")
    lag = report.get("lag") or {}
    slo = lag.get("slo") or {}
    if lag.get("ops_converged"):
        lines.append(
            f"  lag: {lag['ops_converged']} op(s) converged "
            f"(p50 {lag['p50_ms']:g} ms, p99 {lag['p99_ms']:g} ms, "
            f"{lag['pending']} pending), SLO {slo['target_ms']:g} ms "
            f"-> {slo['verdict']}")
    elif lag.get("pending"):
        # zero converged with ops pending is a STUCK fleet, not an
        # untraced one — the distinction an operator pages on
        lines.append(
            f"  lag: 0 ops converged, {lag['pending']} PENDING "
            f"(no wave reached fleet-wide digest agreement)")
    else:
        lines.append("  lag: no convergence-lag records")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs fleet",
        description="Render fleet health (replicas, staleness, "
                    "divergence incidents, overflow/fallback/GC rates, "
                    "convergence-lag summary) from one or more obs "
                    "JSONL event streams (multiple streams — a multi-"
                    "process soak's sidecars — merge by timestamp).")
    ap.add_argument("jsonl", nargs="+",
                    help="obs event file(s) (JSON lines)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    a = ap.parse_args(argv)
    for path in a.jsonl:
        if not os.path.exists(path):
            print(f"fleet: no such file: {path}", file=sys.stderr)
            return 2
    report = fleet_report(load_streams(a.jsonl))
    if a.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
