"""Cross-process trace propagation: one causal identity per op batch.

Every other obs axis (PRs 1-10) is per-process: wire frames carry no
trace context, WAL records can't be joined back to the op that
produced them, and multi-stream evidence merges by raw wall timestamps
— meaningless across hosts with skewed clocks. This module is the
write side of the fix (``journey`` is the read side): a **trace**
(one minted op batch) moves through named **hops**, each hop one
``xtrace.hop`` event carrying ``trace``/``span``/``parent`` ids, so a
later reader can reconstruct the full causal chain an op took —
mint → send → (wire) → recv → admit → journal → tick → wave → apply →
converged — across process and host boundaries.

Design rules:

- **obs-off is zero**: every public API checks ``enabled()`` first
  and returns ``None`` without touching state, reading the
  environment, or allocating. The wire/journal context fields exist
  ONLY when the emitting process has obs on (the byte-identity pin in
  ``scripts/obs_off_pin.py`` holds the receipts); receivers treat
  them as optional keys, so old/new endpoints interoperate freely.
- **spans are cheap ids, not timers**: a hop is an instant event (the
  obs record's ``ts_us`` is its wall-clock time); latency between
  hops is the READER's subtraction, after per-connection clock-offset
  correction. ``parent`` makes the chain checkable: a journey with a
  hop whose parent span is missing has lost evidence (an "orphan").
- **cross-thread continuation is explicit**: the wire carries
  ``{"t": trace, "s": span}`` context; in-process handoffs (queue
  entries, WAL rows) carry the bare trace id and the per-trace
  last-span registry links the chain — admission threads and the
  service tick thread never share a thread-local.
- **op ids join the lag tracer**: :func:`bind_ops` maps node ids to
  their trace so ``op.lag`` / ``lag.replica`` records (and the
  ``converged`` hop) can print trace ids the ``journey`` CLI accepts
  — the lag→journey drill-down. The registry is LRU-bounded like
  every other obs registry.

Clock-offset estimation rides the existing request/response pairs
(hello→welcome, ping→pong): when obs is on the server stamps its
reply with ``ts_us``/``pid`` and the client emits one ``xtrace.clock``
event per exchange — ``offset_us ≈ server_ts - midpoint(t0, t1)``,
the classic NTP half-RTT estimate. The journey reader takes the
median per (observer pid → remote pid) edge.

Stdlib only, in-process, thread-safe. NEVER call from inside a jit
trace (causelint XTR001 enforces the ``obs.enabled()`` guard).
"""

from __future__ import annotations

import os as _os
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

from . import core

__all__ = [
    "enabled", "reset", "new_trace", "hop", "wire_context",
    "continue_from", "bind_ops", "trace_of", "last_span",
    "clock_sample", "reply_stamp", "HOP_ORDER",
]

# canonical hop vocabulary, in causal order (the journey reader uses
# this to label decomposition edges; unknown hop names still work)
HOP_ORDER = ("mint", "send", "recv", "admit", "journal", "defer",
             "tick", "replay", "wave", "apply", "converged", "shed")

_OPS_MAX = 16384     # op-id -> trace registry bound (entries)
_LAST_MAX = 4096     # trace -> last-span registry bound (entries)

_LOCK = threading.Lock()
# op id (node id tuple) -> trace id; LRU (insertion refreshed on bind)
_OPS: "OrderedDict[object, str]" = OrderedDict()
# trace id -> last span id emitted for it (the cross-thread parent)
_LAST: "OrderedDict[str, str]" = OrderedDict()
_SPAN_N = 0


def enabled() -> bool:
    """Whether tracing records anything (== ``obs.enabled()``)."""
    return core.enabled()


def reset() -> None:
    """Drop all trace state (tests, bench warm phases; delegated to by
    ``obs.reset()`` so one reset reaches every tracer)."""
    global _SPAN_N
    with _LOCK:
        _OPS.clear()
        _LAST.clear()
        _SPAN_N = 0


def _new_span_locked() -> str:
    global _SPAN_N
    _SPAN_N += 1
    return f"{_os.getpid():x}.{_SPAN_N:x}"


def new_trace() -> Optional[str]:
    """Mint a fresh trace id (None when obs is off). The id is random
    (collision-safe across hosts) and printable — the ``journey`` CLI
    accepts it verbatim."""
    if not core.enabled():
        return None
    return _os.urandom(8).hex()


def last_span(trace: str) -> Optional[str]:
    """The last span id emitted for ``trace`` in THIS process (the
    default parent for a cross-thread continuation), or None."""
    if not core.enabled():
        return None
    with _LOCK:
        return _LAST.get(str(trace))


def hop(name: str, trace: Optional[str],
        parent: Optional[str] = None, **attrs) -> Optional[str]:
    """Record one hop on ``trace``: emits an ``xtrace.hop`` event and
    returns the hop's span id (the parent for whatever follows).
    ``parent=None`` links to the trace's last in-process span — the
    queue-entry/WAL-row handoff case; pass ``parent=""`` explicitly
    for a root hop (mint). No-op (None) when obs is off or ``trace``
    is falsy, so callers may pass an unminted trace straight
    through."""
    if not core.enabled() or not trace:
        return None
    trace = str(trace)
    with _LOCK:
        span = _new_span_locked()
        if parent is None:
            parent = _LAST.get(trace) or ""
        _LAST.pop(trace, None)
        _LAST[trace] = span
        while len(_LAST) > _LAST_MAX:
            _LAST.popitem(last=False)
    core.event("xtrace.hop", trace=trace, span=span,
               parent=str(parent), hop=str(name), **attrs)
    return span


def wire_context(trace: Optional[str],
                 span: Optional[str]) -> Optional[dict]:
    """The frame-attachable context for a hop: ``{"t": .., "s": ..}``.
    None when obs is off or either id is missing — the caller attaches
    nothing and the frame bytes stay pinned."""
    if not core.enabled() or not trace or not span:
        return None
    return {"t": str(trace), "s": str(span)}


def continue_from(ctx) -> Tuple[Optional[str], Optional[str]]:
    """Validate an inbound wire context: ``(trace, parent_span)``, or
    ``(None, None)`` for anything malformed (the wire is a trust
    boundary — a garbage ctx must degrade to an untraced frame, never
    an exception on the admission path)."""
    if not core.enabled() or not isinstance(ctx, dict):
        return (None, None)
    t, sp = ctx.get("t"), ctx.get("s")
    if not isinstance(t, str) or not t or len(t) > 64 \
            or not isinstance(sp, str) or not sp or len(sp) > 64:
        return (None, None)
    return (t, sp)


def bind_ops(trace: Optional[str], op_ids: Iterable) -> None:
    """Join ``op_ids`` (node id tuples) to ``trace`` so the lag tracer
    can print trace ids and the ``converged`` hop can find its trace.
    First bind wins — a replay re-binding an id keeps the original
    trace."""
    if not core.enabled() or not trace:
        return
    trace = str(trace)
    with _LOCK:
        for op in op_ids:
            try:
                key = tuple(op) if isinstance(op, list) else op
            except TypeError:
                key = op
            if key not in _OPS:
                _OPS[key] = trace
        while len(_OPS) > _OPS_MAX:
            _OPS.popitem(last=False)


def trace_of(op_id) -> Optional[str]:
    """The trace an op id was bound to, or None (off, or unbound)."""
    if not core.enabled():
        return None
    try:
        key = tuple(op_id) if isinstance(op_id, list) else op_id
    except TypeError:
        key = op_id
    with _LOCK:
        return _OPS.get(key)


def traces_of(op_ids: Iterable) -> List[str]:
    """Distinct traces of ``op_ids``, first-seen order (off -> [])."""
    if not core.enabled():
        return []
    out: List[str] = []
    seen = set()
    with _LOCK:
        for op in op_ids:
            try:
                key = tuple(op) if isinstance(op, list) else op
            except TypeError:
                key = op
            t = _OPS.get(key)
            if t is not None and t not in seen:
                seen.add(t)
                out.append(t)
    return out


def reply_stamp() -> Dict[str, int]:
    """The server-side reply fields behind clock estimation:
    ``{"ts_us", "pid"}``. Callers merge into a reply ONLY when obs is
    on (the obs-off frame bytes are pinned)."""
    return {"ts_us": time.time_ns() // 1000, "pid": _os.getpid()}


def clock_sample(reply: dict, t0_us: int, t1_us: int,
                 via: str = "") -> Optional[float]:
    """Estimate the remote clock offset from one request/response pair
    and emit the ``xtrace.clock`` event the journey reader folds:
    ``reply`` is the peer's response (carrying ``ts_us``/``pid`` when
    its obs is on), ``t0_us``/``t1_us`` the local WALL-clock
    microseconds around the exchange. Returns the offset estimate in
    microseconds (remote - local), or None when the peer sent no stamp
    (old server, or obs off on its side)."""
    if not core.enabled() or not isinstance(reply, dict):
        return None
    ts = reply.get("ts_us")
    rpid = reply.get("pid")
    if not isinstance(ts, int) or not isinstance(rpid, int):
        return None
    mid = (int(t0_us) + int(t1_us)) / 2.0
    offset = float(ts) - mid
    core.event("xtrace.clock", remote_pid=rpid,
               offset_us=round(offset, 1),
               rtt_us=int(t1_us) - int(t0_us), via=str(via))
    return offset
