"""Convergence-lag tracer: per-op create→converged latency.

Every existing obs layer measures what a wave COSTS (dispatch
accounting, devprof flops, wave wall spans). None of them measures the
quantity a user of a serving-shaped sync fleet actually experiences:
how long an op takes from its creation at a site to visibility on
every replica. The north star is stated in exactly those units
(<100 ms convergence for 1024-replica fleets), and ROADMAP item 4's
adaptive wave batching needs a latency signal to batch *against* —
SafarDB's split (accelerator owns merge, host owns admission/ordering)
only works if the host can see per-op replication lag against an SLO.

This module is that signal, as op-level provenance resolved against
the events the substrate already emits:

- **stamping** — ops are stamped host-side at mutation time
  (``collections/shared.py``'s ``insert`` funnel — every conj/extend/
  cons/insert lands there) and at ingest time (``sync.apply_delta``),
  with site, lamport and a monotonic clock captured OUTSIDE jit; the
  first stamp wins, so an op created in-process and later synced to a
  sibling replica keeps its true creation time;
- **resolution** — visibility comes from the substrate's own wave
  evidence: every merge wave / session wave (``_observe_semantics`` in
  ``parallel/wave.py``, shared with ``parallel/session.py``) marks the
  document's stamped ops *locally woven* (the wave's kernel wove them
  into the device-resident weave), and the first wave whose
  convergence digests AGREE across every replica pair holding the
  document marks them *fleet-converged*; merge-tree convergence
  (``parallel/tree.py``) resolves at its final level the same way.
  Two lags per op: create→woven and create→converged;
- **aggregation** — mergeable log-bucketed streaming histograms
  (HDR-style pow2 buckets over microseconds: bounded memory, bounded
  relative error, merge = per-bucket sum), a sliding window of recent
  converged lags surfaced as ``lag.p50_ms``/``.p95_ms``/``.p99_ms``
  gauges (Perfetto counter tracks), and SLO attainment + burn rate
  against a configurable target (default: the 100 ms north star,
  ``CAUSE_TPU_LAG_SLO_MS`` / :func:`set_slo`);
- **events** — per-op ``op.lag`` records (sampled per resolution
  batch — histograms always see every op), one cumulative
  ``lag.window`` record per resolving wave (window percentiles, the
  mergeable histogram state, exact SLO counters), and per-replica
  ``lag.replica`` apply-lag records from the sync ingest path (which
  replicas apply other sites' ops slowest — the worst-offender axis
  the CLI ranks);
- **the read side** — ``python -m cause_tpu.obs lag events.jsonl...``
  renders the distribution, the per-replica apply-lag worst offenders
  and the SLO verdict from any obs stream(s); :func:`lag_summary` is
  the same aggregation as a library call (the ``obs fleet`` report
  folds it in).

Resolution granularity is the wave: an op stamped for a document is
considered included in the document's next wave (the instrumented
paths stamp at mutation/ingest and wave afterwards), so lag resolves
at wave boundaries — exactly the granularity a wave-batching admission
controller can act on.

Contract (same as the rest of ``cause_tpu.obs``): stdlib + core only,
importable without jax/numpy; with ``CAUSE_TPU_OBS`` unset every entry
point returns immediately — no records, no registry state, no env or
``TRACE_SWITCHES`` reads, byte-identical program-cache keys (pinned by
tests/test_lag.py). On jit-reachable paths, call sites must sit behind
``obs.enabled()`` guards — causelint rule OBS006 gates that. State is
bounded everywhere: documents LRU-evict past ``_DOC_MAX`` (the
semantic-monitor rule — a 600k-round soak mints a uuid per round),
per-document op maps FIFO-evict past ``_OPS_MAX``, per-replica
apply-lag histograms LRU-evict past ``_REPLICA_MAX``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import core
from . import xtrace

__all__ = [
    "SLO_DEFAULT_MS",
    "SLO_GOAL",
    "LagHistogram",
    "enabled",
    "reset",
    "set_slo",
    "slo_ms",
    "op_created",
    "ops_applied",
    "wave_observed",
    "level_observed",
    "pending_ops",
    "current_epoch",
    "LagReducer",
    "lag_summary",
    "render",
    "main",
]

# BASELINE.json config 5 / the north star: convergence under 100 ms.
SLO_DEFAULT_MS = 100.0
# the attainment objective the burn rate is judged against: 99% of ops
# converge within the target; the error budget is the remaining 1%,
# and burn_rate = (observed breach fraction) / (error budget) — 1.0
# burns the budget exactly, >1.0 exhausts it early (SRE convention)
SLO_GOAL = 0.99

# state bounds (see module docstring)
_DOC_MAX = 4096
_OPS_MAX = 32768
_REPLICA_MAX = 256
# per-op op.lag events emitted per resolution batch; histograms and
# counters always account every op — the sample only bounds stream size
_OP_EVENT_SAMPLE = 64
# distinct traces earning a "converged" journey hop per wave (PR 19)
_TRACE_HOP_MAX = 256
# sliding window of recent converged lags behind the p50/p95/p99 gauges
_WINDOW_MAX = 256


class LagHistogram:
    """A mergeable log-bucketed (HDR-style) latency histogram.

    Bucket ``b`` holds lags in ``[2^(b-1), 2^b)`` microseconds (bucket
    0 holds sub-microsecond lags), so ~40 buckets cover ns..hours with
    a bounded √2 relative error per recorded value; exact count/sum/
    min/max ride alongside. Merging two histograms is a per-bucket sum
    — the property that makes multi-process streams and multi-stream
    CLI inputs aggregate without any raw-sample replay."""

    __slots__ = ("buckets", "count", "sum_us", "min_us", "max_us")

    def __init__(self):
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.sum_us = 0
        self.min_us = None  # type: Optional[int]
        self.max_us = None  # type: Optional[int]

    def record_us(self, us: float) -> None:
        u = max(0, int(us))
        b = u.bit_length()
        self.buckets[b] = self.buckets.get(b, 0) + 1
        self.count += 1
        self.sum_us += u
        self.min_us = u if self.min_us is None else min(self.min_us, u)
        self.max_us = u if self.max_us is None else max(self.max_us, u)

    def merge(self, other: "LagHistogram") -> "LagHistogram":
        for b, n in other.buckets.items():
            self.buckets[b] = self.buckets.get(b, 0) + n
        self.count += other.count
        self.sum_us += other.sum_us
        for attr, pick in (("min_us", min), ("max_us", max)):
            o = getattr(other, attr)
            if o is not None:
                mine = getattr(self, attr)
                setattr(self, attr, o if mine is None else pick(mine, o))
        return self

    @staticmethod
    def _bounds(b: int) -> Tuple[float, float]:
        return (0.0 if b == 0 else float(1 << (b - 1)), float(1 << b))

    def quantile_ms(self, q: float) -> Optional[float]:
        """The q-quantile in ms (linear interpolation inside the
        straddling pow2 bucket, clamped to the exact observed min/max).
        None on an empty histogram."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0.0
        val = float(self.max_us or 0)
        for b in sorted(self.buckets):
            n = self.buckets[b]
            if cum + n >= target:
                lo, hi = self._bounds(b)
                frac = (target - cum) / n
                val = lo + frac * (hi - lo)
                break
            cum += n
        if self.min_us is not None:
            val = max(val, float(self.min_us))
        if self.max_us is not None:
            val = min(val, float(self.max_us))
        return round(val / 1000.0, 4)

    def mean_ms(self) -> Optional[float]:
        if not self.count:
            return None
        return round(self.sum_us / self.count / 1000.0, 4)

    def within_us(self, limit_us: float) -> float:
        """Estimated count of recorded lags <= ``limit_us`` (buckets
        fully below count whole; the straddling bucket interpolates)."""
        if limit_us < 0:
            return 0.0
        got = 0.0
        for b, n in self.buckets.items():
            lo, hi = self._bounds(b)
            if hi <= limit_us:
                got += n
            elif lo <= limit_us:
                got += n * (limit_us - lo) / (hi - lo)
        return got

    def to_fields(self) -> dict:
        """The JSON-serializable mergeable state (``lag.window`` /
        ``lag.replica`` event payloads)."""
        return {
            "buckets": {str(b): n for b, n in sorted(self.buckets.items())},
            "count": self.count,
            "sum_us": self.sum_us,
            "min_us": self.min_us,
            "max_us": self.max_us,
        }

    @classmethod
    def from_fields(cls, fields: Optional[dict]) -> "LagHistogram":
        h = cls()
        f = fields or {}
        for b, n in (f.get("buckets") or {}).items():
            try:
                h.buckets[int(b)] = int(n)
            except (TypeError, ValueError):
                continue
        h.count = int(f.get("count") or 0)
        h.sum_us = int(f.get("sum_us") or 0)
        for attr in ("min_us", "max_us"):
            v = f.get(attr)
            if isinstance(v, (int, float)):
                setattr(h, attr, int(v))
        return h


# ------------------------------------------------------------- state

_LOCK = threading.Lock()
# uuid -> {"new": {op_id: stamp}, "woven": {op_id: stamp}} with stamp =
# (perf_counter, site, lamport); LRU over documents, FIFO over ops
_DOCS: Dict[str, dict] = {}
# replica site_id -> (generation, apply-lag histogram). The histogram
# is cumulative per generation: LRU eviction past _REPLICA_MAX drops a
# replica's in-memory history, so a returning replica starts a FRESH
# generation — the generation rides in every ``lag.replica`` record,
# and the read side merges across generations instead of letting the
# restarted cumulative record clobber the richer pre-eviction one
# replica -> [generation, histogram, worst (lag_us, trace_id) | None]
_REPLICAS: Dict[str, list] = {}
_REPLICA_GEN = 0
_HIST_WOVEN = LagHistogram()
_HIST_CONVERGED = LagHistogram()
_WINDOW: List[float] = []        # recent converged lags, ms
_CONVERGED_TOTAL = 0
_BREACH_TOTAL = 0
_SLO_MS: Optional[float] = None  # lazily resolved (enabled paths only)
# cumulative-record generation: ``lag.window``/``lag.replica`` carry
# histograms cumulative SINCE THE LAST reset(), so the read side must
# not collapse records across a reset to one last-per-pid value (a
# multi-fleet BENCH_LAG run resets between fleets — without the epoch
# every fleet but the last would vanish from the merged report)
_EPOCH = 0


def enabled() -> bool:
    """Whether the lag tracer records anything (== ``obs.enabled()``)."""
    return core.enabled()


def reset() -> None:
    """Drop all lag-tracer state (tests, bench warm phases; obs.reset
    does not reach into this layer)."""
    global _CONVERGED_TOTAL, _BREACH_TOTAL, _SLO_MS, _EPOCH
    with _LOCK:
        _DOCS.clear()
        _REPLICAS.clear()
        _HIST_WOVEN.__init__()
        _HIST_CONVERGED.__init__()
        del _WINDOW[:]
        _CONVERGED_TOTAL = 0
        _BREACH_TOTAL = 0
        _SLO_MS = None
        _EPOCH += 1


def set_slo(ms: Optional[float]) -> None:
    """Pin the convergence SLO target (None re-reads the environment
    on next enabled use; soak's ``--slo-ms`` flag lands here)."""
    global _SLO_MS
    _SLO_MS = float(ms) if ms is not None else None


def slo_ms() -> float:
    """The active SLO target: :func:`set_slo`'s pin, else
    ``CAUSE_TPU_LAG_SLO_MS``, else the 100 ms north star. Called from
    enabled paths only (the obs-off contract is zero env reads)."""
    global _SLO_MS
    if _SLO_MS is None:
        raw = os.environ.get("CAUSE_TPU_LAG_SLO_MS", "").strip()
        try:
            _SLO_MS = float(raw) if raw else SLO_DEFAULT_MS
        except ValueError:
            _SLO_MS = SLO_DEFAULT_MS
    return _SLO_MS


def _doc(uuid: str) -> dict:
    """The document's op registry, LRU-refreshed. Caller holds _LOCK.
    ``hwm`` is the highest lamport among the document's RESOLVED ops:
    a full-bag resend replays every node of the document, and without
    the watermark each replay would re-stamp thousands of long-
    converged ops as freshly created (their near-zero "lags" would
    swamp the distribution). Ops at or below the watermark are
    replays, not new work — O(1) memory instead of a resolved-id set."""
    d = _DOCS.pop(uuid, None)
    if d is None:
        d = {"new": {}, "woven": {}, "hwm": -1}
    _DOCS[uuid] = d
    while len(_DOCS) > _DOC_MAX:
        _DOCS.pop(next(iter(_DOCS)))
    return d


def _bound_ops(ops: Dict) -> None:
    while len(ops) > _OPS_MAX:
        ops.pop(next(iter(ops)))


def _site_of(op_id) -> str:
    """The origin site of a node id ``(ts, site, tx)`` — best-effort
    (foreign key shapes stringify)."""
    try:
        return str(op_id[1])
    except (TypeError, IndexError):
        return "?"


def _lamport_of(op_id):
    try:
        return int(op_id[0])
    except (TypeError, IndexError, ValueError):
        return None


# ---------------------------------------------------------- stamping


def op_created(uuid: str, op_ids: Iterable, t: Optional[float] = None) -> None:
    """Stamp newly-minted ops for document ``uuid`` (host-side, at the
    mutation funnel — OUTSIDE any jit trace). ``op_ids`` are node ids
    ``(ts, site, tx)``; the first stamp for an id wins, so a replayed
    or re-ingested op keeps its original creation time."""
    if not core.enabled():
        return
    now = time.perf_counter() if t is None else t
    u = str(uuid)
    n = 0
    with _LOCK:
        d = _doc(u)
        new, woven = d["new"], d["woven"]
        for op in op_ids:
            if op in new or op in woven:
                continue
            # no watermark filter here (unlike ops_applied): the
            # insert funnel's idempotency check returns before the
            # stamp point for true replays, so everything reaching
            # this path is genuinely new work — including fresh
            # concurrent ops minted by stale replicas at lamports the
            # fleet already converged past, which are exactly the
            # worst-lag tail the tracer must not drop
            new[op] = now
            n += 1
        _bound_ops(new)
    if n:
        core.counter("lag.ops_created").inc(n)


def ops_applied(uuid: str, op_ids: Iterable, replica: str = "") -> None:
    """Sync-ingest resolution + stamping: ops in a received delta (or
    full bag) just became visible on ``replica``. Ops already stamped
    in-process record their create→applied lag into the replica's
    apply-lag histogram (the per-replica worst-offender axis); unknown
    ops are stamped now (ingest time IS their local creation time).
    Emits one cumulative ``lag.replica`` record per call."""
    global _REPLICA_GEN
    if not core.enabled():
        return
    now = time.perf_counter()
    u = str(uuid)
    rep = str(replica) if replica else "?"
    applied = 0
    stamped = 0
    with _LOCK:
        d = _doc(u)
        new, woven = d["new"], d["woven"]
        entry = _REPLICAS.pop(rep, None)
        if entry is None:
            _REPLICA_GEN += 1
            entry = [_REPLICA_GEN, LagHistogram(), None]
        gen, hist = entry[0], entry[1]
        _REPLICAS[rep] = entry
        while len(_REPLICAS) > _REPLICA_MAX:
            _REPLICAS.pop(next(iter(_REPLICAS)))
        for op in op_ids:
            stamp = new.get(op)
            if stamp is None:
                stamp = woven.get(op)
            if stamp is not None:
                lag_us = (now - stamp) * 1e6
                hist.record_us(lag_us)
                applied += 1
                # worst-offender exemplar: the replica's slowest apply
                # keeps its trace id, so `obs lag` can print the exact
                # id `obs journey` drills into (PR 19)
                if entry[2] is None or lag_us > entry[2][0]:
                    entry[2] = (lag_us, xtrace.trace_of(op))
            else:
                lam = _lamport_of(op)
                if lam is not None and lam <= d["hwm"]:
                    # a full-bag resend replays every node of the
                    # document; the watermark keeps long-converged
                    # ops from re-entering as freshly created. Known
                    # approximation: a stale replica's fresh
                    # concurrent op arriving BY SYNC at a lamport the
                    # fleet converged past is skipped too (only ids
                    # could distinguish it, at unbounded memory);
                    # ops stamped at their own mutation funnel —
                    # the common case — are unaffected
                    continue
                new[op] = now
                stamped += 1
        _bound_ops(new)
        hist_fields = hist.to_fields()
        worst = entry[2]
    if stamped:
        core.counter("lag.ops_created").inc(stamped)
    if applied:
        core.counter("lag.ops_applied").inc(applied)
        extra = {}
        if worst is not None:
            extra["worst_lag_ms"] = round(worst[0] / 1000.0, 3)
            if worst[1]:
                extra["worst_trace"] = worst[1]
        core.event("lag.replica", replica=rep, uuid=u,
                   applied=applied, epoch=_EPOCH, gen=gen,
                   hist=hist_fields, **extra)


# -------------------------------------------------------- resolution


def _resolve_locked(u: str, agreed: bool, now: float):
    """Move the document's pending ops through woven (always) and
    converged (on digest agreement). Caller holds _LOCK. Returns the
    per-op sample lists + window snapshot the emitter needs."""
    global _CONVERGED_TOTAL, _BREACH_TOTAL
    d = _doc(u)
    new, woven = d["new"], d["woven"]
    woven_out: List[Tuple[object, float]] = []
    for op, stamp in new.items():
        _HIST_WOVEN.record_us((now - stamp) * 1e6)
        woven_out.append((op, stamp))
        woven[op] = stamp
    new.clear()
    _bound_ops(woven)
    conv_out: List[Tuple[object, float]] = []
    breaches = 0
    slo = slo_ms()
    if agreed and woven:
        for op, stamp in woven.items():
            lag_ms = (now - stamp) * 1000.0
            _HIST_CONVERGED.record_us(lag_ms * 1000.0)
            conv_out.append((op, stamp))
            _WINDOW.append(lag_ms)
            if lag_ms > slo:
                breaches += 1
            lam = _lamport_of(op)
            if lam is not None and lam > d["hwm"]:
                d["hwm"] = lam
        woven.clear()
        del _WINDOW[:-_WINDOW_MAX]
        _CONVERGED_TOTAL += len(conv_out)
        _BREACH_TOTAL += breaches
    return woven_out, conv_out, breaches, slo


def _window_stats(window: Sequence[float], slo: float) -> dict:
    """p50/p95/p99 + breach fraction + burn rate of the sliding
    window (tiny: sort is fine)."""
    if not window:
        return {}
    xs = sorted(window)
    n = len(xs)

    def pct(q: float) -> float:
        return round(xs[min(n - 1, int(q * n))], 3)

    breach = sum(1 for x in xs if x > slo) / n
    budget = 1.0 - SLO_GOAL
    return {
        "n": n,
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
        "p99_ms": pct(0.99),
        "breach_frac": round(breach, 4),
        "burn_rate": round(breach / budget, 2),
    }


def wave_observed(uuid: str, agreed: bool, source: str = "wave",
                  level: Optional[int] = None) -> Optional[dict]:
    """One wave completed for document ``uuid``: every op stamped
    before it is now locally woven (the wave's kernel wove the whole
    document), and — when the wave's convergence digests ``agreed``
    across all replica pairs — fleet-converged. Emits sampled per-op
    ``op.lag`` events, one cumulative ``lag.window`` record, and the
    sliding-window percentile gauges. Returns the ``lag.window``
    fields (None when obs is off or nothing resolved)."""
    if not core.enabled():
        return None
    now = time.perf_counter()
    u = str(uuid)
    with _LOCK:
        woven_out, conv_out, breaches, slo = _resolve_locked(
            u, bool(agreed), now)
        if not woven_out and not conv_out:
            return None
        window = list(_WINDOW)
        fields = {
            "uuid": u,
            "source": str(source),
            "epoch": _EPOCH,
            "woven": len(woven_out),
            "converged": len(conv_out),
            "pending": sum(len(d["new"]) + len(d["woven"])
                           for d in _DOCS.values()),
            "slo_ms": slo,
            "slo_breach": breaches,
            "converged_total": _CONVERGED_TOTAL,
            "breach_total": _BREACH_TOTAL,
            "hist_woven": _HIST_WOVEN.to_fields(),
            "hist_converged": _HIST_CONVERGED.to_fields(),
        }
    if level is not None:
        fields["level"] = int(level)
    for phase, batch in (("woven", woven_out), ("converged", conv_out)):
        core.counter(f"lag.ops_{phase}").inc(len(batch))
        for op, stamp in batch[:_OP_EVENT_SAMPLE]:
            extra = {}
            tr = xtrace.trace_of(op)
            if tr:
                # the lag→journey drill-down (PR 19): this id is
                # exactly what `obs journey <trace>` accepts
                extra["trace"] = tr
            core.event("op.lag", uuid=u, phase=phase,
                       site=_site_of(op), lamport=_lamport_of(op),
                       lag_ms=round((now - stamp) * 1000.0, 3),
                       source=str(source), **extra)
    # terminal journey hop (PR 19): one "converged" hop per distinct
    # trace whose ops just fleet-converged, carrying that trace's
    # WORST create→converged lag (the per-hop SLO decomposition's
    # final edge). Bounded per wave like every other emission here.
    if conv_out:
        worst_by_trace: Dict[str, float] = {}
        for op, stamp in conv_out:
            tr = xtrace.trace_of(op)
            if tr is None:
                continue
            lag_ms = (now - stamp) * 1000.0
            if lag_ms > worst_by_trace.get(tr, -1.0):
                worst_by_trace[tr] = lag_ms
            if len(worst_by_trace) >= _TRACE_HOP_MAX:
                break
        for tr, lag_ms in worst_by_trace.items():
            xtrace.hop("converged", tr, uuid=u,
                       lag_ms=round(lag_ms, 3), source=str(source))
    if breaches:
        core.counter("lag.slo_breach").inc(breaches)
    win = _window_stats(window, slo)
    if win:
        fields["window"] = win
        core.gauge("lag.p50_ms").set(win["p50_ms"])
        core.gauge("lag.p95_ms").set(win["p95_ms"])
        core.gauge("lag.p99_ms").set(win["p99_ms"])
    core.event("lag.window", **fields)
    return fields


def level_observed(uuid: str, agreed: bool, level: int,
                   final: bool) -> Optional[dict]:
    """Merge-tree resolution: intermediate levels converge SUBTREES
    (distinct digests are a converging fleet's expected shape — no op
    converges yet), so only the final level's fleet-wide agreement
    resolves; level 0 still marks the document's stamped ops woven
    (the first full-width level wove every replica's lanes)."""
    if not core.enabled():
        return None
    if final:
        return wave_observed(uuid, agreed, source="tree", level=level)
    if level == 0:
        return wave_observed(uuid, False, source="tree", level=level)
    return None


def current_epoch() -> int:
    """The live cumulative-record generation (bumped by every
    :func:`reset`): pass it to :func:`lag_summary` to scope a report
    to records emitted SINCE the last reset — e.g. one bench fleet's
    measured block — without positional ring arithmetic (the bounded
    ring may evict arbitrarily between a snapshot and the read)."""
    return _EPOCH


def pending_ops(uuid: Optional[str] = None) -> int:
    """Stamped-but-unresolved op count (one document, or all)."""
    with _LOCK:
        if uuid is not None:
            d = _DOCS.get(str(uuid))
            return len(d["new"]) + len(d["woven"]) if d else 0
        return sum(len(d["new"]) + len(d["woven"])
                   for d in _DOCS.values())


# -------------------------------------------------------- read side


class LagReducer:
    """The incremental twin of :func:`lag_summary`: feed obs records
    ONE AT A TIME (a live tail, an in-process subscriber queue) and
    ask for the report at any point. ``lag_summary`` itself is this
    reducer fed with the whole stream, so the two are bit-equal by
    construction — the acceptance property ``obs.live`` pins.

    The merge rule is unchanged: cumulative ``lag.window`` records
    collapse to the LAST per (pid, reset-epoch) and then SUM;
    ``lag.replica`` records collapse per (pid, epoch, replica,
    generation). Memory is bounded by the number of distinct
    (pid, epoch[, replica, gen]) keys in the stream — process count ×
    reset count, not op count."""

    __slots__ = ("_windows", "_replicas")

    def __init__(self):
        # key -> fields; dict preserves FIRST-insertion order under
        # reassignment, exactly like the batch pass's last-per-key
        # fold, so merge order (and float summation order) is
        # identical to the whole-stream pass
        self._windows: Dict[Tuple, dict] = {}
        self._replicas: Dict[Tuple, dict] = {}

    def feed(self, e: dict) -> None:
        """Consume one obs record (non-lag records are free)."""
        if e.get("ev") != "event":
            return
        name = e.get("name")
        if name == "lag.window":
            f = e.get("fields") or {}
            self._windows[(e.get("pid", 0), f.get("epoch"))] = f
        elif name == "lag.replica":
            f = e.get("fields") or {}
            self._replicas[(e.get("pid", 0), f.get("epoch"),
                            f.get("replica"), f.get("gen"))] = f

    def report(self, slo_ms_override: Optional[float] = None,
               epoch: Optional[int] = None) -> dict:
        """The lag report (see :func:`lag_summary` for the fields).
        Cheap relative to the stream: cost is proportional to the
        number of distinct cumulative-record keys, so a live monitor
        can call it on every snapshot tick."""
        windows = [f for f in self._windows.values()
                   if epoch is None or f.get("epoch") == epoch]
        h_woven = LagHistogram()
        h_conv = LagHistogram()
        converged_total = 0
        breach_total = 0
        pending = 0
        recorded_slo = None
        last_win = {}
        for f in windows:
            h_woven.merge(LagHistogram.from_fields(f.get("hist_woven")))
            h_conv.merge(LagHistogram.from_fields(f.get("hist_converged")))
            converged_total += int(f.get("converged_total") or 0)
            breach_total += int(f.get("breach_total") or 0)
            pending += int(f.get("pending") or 0)
            if f.get("slo_ms") is not None:
                recorded_slo = float(f["slo_ms"])
            if f.get("window"):
                last_win = f["window"]
        slo = (float(slo_ms_override) if slo_ms_override is not None
               else (recorded_slo if recorded_slo is not None
                     else SLO_DEFAULT_MS))
        if converged_total and (slo_ms_override is None
                                or recorded_slo == slo):
            within = converged_total - breach_total
            exact = True
        else:
            within = h_conv.within_us(slo * 1000.0)
            exact = False
        attainment = (within / h_conv.count) if h_conv.count else None
        budget = 1.0 - SLO_GOAL

        def dist(h: LagHistogram) -> dict:
            return {
                "count": h.count,
                "p50_ms": h.quantile_ms(0.50),
                "p90_ms": h.quantile_ms(0.90),
                "p95_ms": h.quantile_ms(0.95),
                "p99_ms": h.quantile_ms(0.99),
                "mean_ms": h.mean_ms(),
                "max_ms": (round(h.max_us / 1000.0, 4)
                           if h.max_us is not None else None),
            }

        replicas = []
        rep_hists: Dict[str, LagHistogram] = {}
        rep_worst: Dict[str, tuple] = {}  # (worst_lag_ms, trace)
        for f in self._replicas.values():
            if epoch is not None and f.get("epoch") != epoch:
                continue
            h = LagHistogram.from_fields(f.get("hist"))
            if not h.count:
                continue
            rep = str(f.get("replica"))
            rep_hists.setdefault(rep, LagHistogram()).merge(h)
            w = f.get("worst_lag_ms")
            if isinstance(w, (int, float)) \
                    and w > rep_worst.get(rep, (-1.0, None))[0]:
                rep_worst[rep] = (float(w), f.get("worst_trace"))
        for rep, h in rep_hists.items():
            row = {
                "replica": rep,
                "count": h.count,
                "p95_ms": h.quantile_ms(0.95),
                "max_ms": (round(h.max_us / 1000.0, 4)
                           if h.max_us is not None else None),
            }
            worst = rep_worst.get(rep)
            if worst is not None and worst[1]:
                # the drill-down id: `obs journey <worst_trace>`
                row["worst_trace"] = worst[1]
            replicas.append(row)
        replicas.sort(key=lambda r: -(r["p95_ms"] or 0.0))

        return {
            "windows": len(windows),
            "ops_woven": h_woven.count,
            "ops_converged": h_conv.count,
            "pending": pending,
            "woven": dist(h_woven),
            "converged": dist(h_conv),
            "slo": {
                "target_ms": slo,
                "goal": SLO_GOAL,
                "attainment": (round(attainment, 4)
                               if attainment is not None else None),
                "attainment_exact": exact,
                "breaches": (breach_total if exact
                             else (round(h_conv.count - within, 1)
                                   if h_conv.count else 0)),
                "burn_rate": (round((1.0 - attainment) / budget, 2)
                              if attainment is not None else None),
                "verdict": (None if attainment is None
                            else ("OK" if attainment >= SLO_GOAL
                                  else "BREACH")),
            },
            "window": last_win,
            "replicas": replicas,
        }


def lag_summary(events: Sequence[dict],
                slo_ms_override: Optional[float] = None,
                epoch: Optional[int] = None) -> dict:
    """Aggregate one (merged) obs event stream into the lag report the
    CLI renders: cumulative woven/converged distributions (merged
    per-pid histogram states from the last ``lag.window`` per
    process), exact SLO attainment + burn rate (re-derived from the
    histogram when ``slo_ms_override`` differs from the recorded
    target), the last sliding-window percentiles, and the per-replica
    apply-lag worst offenders. Empty streams report zeros — the first
    question to a broken run is "did anything record at all?".
    ``epoch`` scopes the report to one cumulative-record generation
    (:func:`current_epoch` — one in-process reset span); by default
    every generation in the stream is summed.

    Implementation-wise this IS :class:`LagReducer` fed with the whole
    stream — the batch pass and the live incremental fold share one
    body, so they cannot drift apart."""
    r = LagReducer()
    for e in events:
        r.feed(e)
    return r.report(slo_ms_override=slo_ms_override, epoch=epoch)


def render(report: dict) -> str:
    """The human layout of :func:`lag_summary` — one glanceable
    block."""
    s = report["slo"]
    lines = [
        f"convergence lag: {report['ops_converged']} op(s) converged, "
        f"{report['ops_woven']} woven, {report['pending']} pending "
        f"({report['windows']} window record(s))",
    ]

    def dist_line(label: str, d: dict) -> str:
        if not d["count"]:
            return f"  {label}: no ops resolved"
        return (f"  {label}: p50 {d['p50_ms']:g} ms  "
                f"p95 {d['p95_ms']:g}  p99 {d['p99_ms']:g}  "
                f"max {d['max_ms']:g}  (mean {d['mean_ms']:g}, "
                f"n={d['count']})")

    lines.append(dist_line("create→woven    ", report["woven"]))
    lines.append(dist_line("create→converged", report["converged"]))
    if s["verdict"] is None:
        lines.append(f"  SLO {s['target_ms']:g} ms: no converged ops "
                     "to judge")
    else:
        lines.append(
            f"  SLO {s['target_ms']:g} ms: {100 * s['attainment']:.1f}% "
            f"within target (goal {100 * s['goal']:.0f}%, "
            f"burn {s['burn_rate']:g}x"
            + ("" if s["attainment_exact"] else ", histogram-estimated")
            + f") -> {s['verdict']}")
    win = report.get("window") or {}
    if win:
        lines.append(
            f"  sliding window (last {win['n']}): "
            f"p50 {win['p50_ms']:g} ms  p95 {win['p95_ms']:g}  "
            f"p99 {win['p99_ms']:g}  (burn {win['burn_rate']:g}x)")
    reps = report.get("replicas") or []
    if reps:
        lines.append("  worst replica apply-lag:")
        for r in reps[:5]:
            tr = r.get("worst_trace")
            lines.append(
                f"    {r['replica']}: p95 {r['p95_ms']:g} ms "
                f"(max {r['max_ms']:g}, n={r['count']})"
                + (f"  worst trace {tr}" if tr else ""))
        if len(reps) > 5:
            lines.append(f"    ... {len(reps) - 5} more replica(s)")
    return "\n".join(lines)


# ----------------------------------------------------------------- CLI


def main(argv=None) -> int:
    import argparse
    import json
    import sys

    from .perfetto import load_streams

    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs lag",
        description="Render per-op convergence-lag distributions "
                    "(create→woven, create→converged), per-replica "
                    "apply-lag worst offenders and the SLO verdict "
                    "from obs JSONL stream(s). Multiple streams merge "
                    "by timestamp (multi-process soaks).")
    ap.add_argument("jsonl", nargs="+",
                    help="obs event file(s) (JSON lines)")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="SLO target in ms (default: the stream's "
                         "recorded target, else the 100 ms north star)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    a = ap.parse_args(argv)
    for path in a.jsonl:
        if not os.path.exists(path):
            print(f"lag: no such file: {path}", file=sys.stderr)
            return 2
    report = lag_summary(load_streams(a.jsonl), slo_ms_override=a.slo_ms)
    if a.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
