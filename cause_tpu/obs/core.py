"""The unified trace/metrics substrate (spans, counters, events).

Every timing and diagnostic signal in this repo used to be ad-hoc:
bench.py, scripts/harvest.py and a dozen probe/profile scripts each
reinvented timers, log formats and checksum provenance. This module is
the ONE substrate they all report through:

- **spans** — ``with obs.span("weave.sort", strategy="matrix"):`` —
  record wall time (epoch-anchored, perf_counter-measured), pid/tid,
  nesting (parent id + depth), the reporting process's platform tag,
  and the full ``TRACE_SWITCHES`` snapshot as program identity, so a
  number in a trace can always be tied back to the exact strategy
  config that produced it;
- a **counter/gauge registry** — program-cache hits/misses, lane-cache
  hits, wave fallbacks, checksum-gate outcomes, certification
  revocations — aggregated in-process and snapshotted into the event
  stream by ``flush()`` (and automatically at exit);
- a bounded in-process **event ring buffer** (newest events win) with
  JSONL export, plus a streaming **sink**: when an output path is
  configured every event is appended to it the moment it is recorded,
  one JSON line per event, via a single O_APPEND write. That makes the
  sink safe for the bench's child-process isolation: an ABANDONED
  child (never killed — tunnel rule) keeps streaming its events into
  the sidecar file, and concurrent parent/child appends interleave at
  line granularity;
- a **Chrome-trace/Perfetto exporter** (``cause_tpu.obs.perfetto``,
  ``python -m cause_tpu.obs``) so any bench or soak run opens in a
  trace viewer.

Dependency-light on purpose (stdlib + ``cause_tpu.switches`` only,
like switches.py itself): bench.py's parent process and the watcher's
``certified_env`` path must be able to import it without jax.

Off by default: with ``CAUSE_TPU_OBS`` unset (or ``0``), ``span()``
returns a shared no-op context manager, ``counter()``/``gauge()``
return a shared no-op instrument, nothing is recorded, no file is
opened, and — load-bearing for program identity — NO ``TRACE_SWITCHES``
environment variable is ever read (the snapshot happens only on
enabled-span close). Enable with ``CAUSE_TPU_OBS=1``; stream with
``CAUSE_TPU_OBS_OUT=<path>``; bound the ring with
``CAUSE_TPU_OBS_RING`` (default 65536 events).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from ..switches import TRACE_SWITCHES

__all__ = [
    "configure",
    "enabled",
    "span",
    "event",
    "counter",
    "gauge",
    "counters_snapshot",
    "events",
    "flush",
    "export_jsonl",
    "set_platform",
    "reset",
    "subscribe",
    "unsubscribe",
]

_TRUTHY = ("1", "true", "yes")
_DEFAULT_RING = 65536


class _NullSpan:
    """The disabled-mode span: one shared instance, every method a
    no-op. Deliberately tiny — the disabled ``span()`` call is on trace
    -time and wave hot paths and must stay sub-microsecond."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


class _NullInstrument:
    """Disabled-mode counter/gauge: shared, inert."""

    __slots__ = ()

    def inc(self, n=1):
        return self

    def set(self, value):
        return self

    @property
    def value(self):
        return 0


_NULL_SPAN = _NullSpan()
_NULL_INSTRUMENT = _NullInstrument()

# default bound of one in-process subscriber queue (obs.live's
# monitor drains on its own cadence; a stalled reader must cost the
# writer one deque append and nothing else)
_SUBSCRIBER_QUEUE = 8192


class Subscription:
    """One bounded in-process subscriber on the sink: every record the
    substrate emits is appended to this queue the moment it lands in
    the ring (newest win when the reader falls behind — ``dropped``
    counts the loss honestly). Readers drain with :meth:`drain` on
    their own cadence; the writer never blocks and never runs reader
    code (no callback re-entrancy under the state lock). Created via
    :func:`subscribe`, torn down via :func:`unsubscribe`."""

    __slots__ = ("queue", "dropped", "lock", "closed")

    def __init__(self, maxlen: int):
        self.queue = deque(maxlen=max(1, int(maxlen)))
        self.dropped = 0
        self.lock = threading.Lock()
        self.closed = False

    def push(self, obj: dict) -> None:
        with self.lock:
            if self.closed:
                return
            if len(self.queue) == self.queue.maxlen:
                self.dropped += 1
            self.queue.append(obj)

    def drain(self) -> list:
        """All queued records, oldest first (and empties the queue)."""
        with self.lock:
            out = list(self.queue)
            self.queue.clear()
        return out


class _State:
    """One process-wide obs state (enabled flag, registry, ring,
    sink). Re-created by configure(reset=True) for tests."""

    __slots__ = (
        "enabled", "out", "ring", "counters", "gauges", "lock",
        "tls", "fd", "platform", "ids", "atexit_armed", "subscribers",
    )

    def __init__(self, enabled_: bool, out: str, ring_size: int):
        self.enabled = enabled_
        self.out = out
        self.ring = deque(maxlen=max(1, int(ring_size)))
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.lock = threading.Lock()
        self.tls = threading.local()
        self.fd = None            # lazily opened O_APPEND sink
        self.platform = os.environ.get("JAX_PLATFORMS", "").split(",")[0]
        self.ids = itertools.count(1)
        self.atexit_armed = False
        # in-process live subscribers (obs.live monitors); empty
        # tuple in the common case so record() pays one attribute
        # read, nothing else
        self.subscribers: tuple = ()

    # ---------------------------------------------------------- sink
    def write_line(self, obj: dict) -> None:
        """Append one JSON line to the sink (if any). A single
        os.write of the whole line on an O_APPEND fd: parent and
        abandoned-child writers interleave at line granularity, and an
        IO failure never takes the instrumented program down."""
        if not self.out:
            return
        try:
            if self.fd is None:
                d = os.path.dirname(os.path.abspath(self.out))
                if d:
                    os.makedirs(d, exist_ok=True)
                self.fd = os.open(
                    self.out, os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                    0o644,
                )
            os.write(self.fd,
                     (json.dumps(obj, default=str) + "\n").encode())
        except OSError:
            self.out = ""  # sink is best-effort; stop retrying

    def record(self, obj: dict) -> None:
        self.ring.append(obj)
        self.write_line(obj)
        for sub in self.subscribers:
            sub.push(obj)


_STATE: Optional[_State] = None
_STATE_LOCK = threading.Lock()


def _resolve_state() -> _State:
    global _STATE
    st = _STATE
    if st is None:
        with _STATE_LOCK:
            st = _STATE
            if st is None:
                on = os.environ.get("CAUSE_TPU_OBS", "").strip().lower()
                out = os.environ.get("CAUSE_TPU_OBS_OUT", "").strip()
                try:
                    ring = int(os.environ.get("CAUSE_TPU_OBS_RING",
                                              "") or _DEFAULT_RING)
                except ValueError:
                    ring = _DEFAULT_RING
                st = _State(on in _TRUTHY, out, ring)
                _STATE = st
                if st.enabled:
                    _arm_atexit(st)
    return st


def _arm_atexit(st: _State) -> None:
    if not st.atexit_armed:
        st.atexit_armed = True
        atexit.register(_atexit_flush)


def _atexit_flush() -> None:
    # the final counter snapshot: an abandoned bench child exits
    # naturally (SystemExit between phases), so its counters land in
    # the sidecar even though nobody waits for it
    st = _STATE
    if st is not None and st.enabled and (st.counters or st.gauges):
        flush()


def configure(enabled: Optional[bool] = None,
              out: Optional[str] = None,
              ring_size: Optional[int] = None,
              reset: bool = False) -> None:
    """Reconfigure obs at runtime (tests, --obs-out script flags).
    ``reset=True`` drops recorded events/counters and re-reads the
    environment for anything not explicitly given."""
    global _STATE
    with _STATE_LOCK:
        cur = _STATE
        if reset or cur is None:
            if cur is not None:
                if cur.fd is not None:
                    try:
                        os.close(cur.fd)
                    except OSError:
                        pass
                # a reset drops ALL obs state, subscribers included —
                # mark them closed so a live attachment polling a
                # dead queue can SEE it died (sub.closed) instead of
                # silently draining nothing forever
                with cur.lock:
                    for s in cur.subscribers:
                        s.closed = True
                    cur.subscribers = ()
            _STATE = None
    if reset:
        # one reset reaches every tracer layered on this core: the
        # lag registries and the xtrace span/op registries would
        # otherwise leak state (and trace bindings) across test cases
        # and bench fleets. Late imports — both modules import core
        # at module level, so the top of this file cannot import them
        from . import lag as _lag
        from . import xtrace as _xtrace

        _lag.reset()
        _xtrace.reset()
    with _STATE_LOCK:
        if reset and enabled is None and out is None \
                and ring_size is None:
            return
    st = _resolve_state()
    with st.lock:
        if enabled is not None:
            st.enabled = bool(enabled)
        if out is not None:
            if st.fd is not None and out != st.out:
                try:
                    os.close(st.fd)
                except OSError:
                    pass
                st.fd = None
            st.out = out
        if ring_size is not None and ring_size != st.ring.maxlen:
            st.ring = deque(st.ring, maxlen=max(1, int(ring_size)))
        if st.enabled:
            _arm_atexit(st)


def reset() -> None:
    """Drop all obs state and re-read the environment on next use."""
    configure(reset=True)


def enabled() -> bool:
    return _resolve_state().enabled


def set_platform(platform: str) -> None:
    """Tag subsequent events with the confirmed backend platform
    (callers that initialized jax know it; obs itself never imports
    jax, so it cannot ask)."""
    st = _resolve_state()
    st.platform = str(platform)


def subscribe(maxlen: int = _SUBSCRIBER_QUEUE) -> Optional[Subscription]:
    """Attach a bounded in-process subscriber to the sink: every
    subsequently recorded event/span/gauge/counter snapshot is queued
    for the subscriber to :meth:`Subscription.drain` on its own
    cadence (the ``obs.live`` in-process feed). Returns None when obs
    is disabled — the obs-off contract is zero state, so a disabled
    process keeps no subscriber registry at all. An ``obs.reset()`` /
    ``configure(reset=True)`` detaches every subscriber and marks it
    ``closed`` — the holder must re-subscribe against the new state
    (``live.LiveAttachment.closed`` surfaces this)."""
    st = _resolve_state()
    if not st.enabled:
        return None
    sub = Subscription(maxlen)
    with st.lock:
        st.subscribers = st.subscribers + (sub,)
    return sub


def unsubscribe(sub: Optional[Subscription]) -> None:
    """Detach a subscriber (idempotent; None is a no-op so callers can
    pass the obs-off :func:`subscribe` result straight back)."""
    if sub is None:
        return
    sub.closed = True
    st = _STATE
    if st is None:
        return
    with st.lock:
        st.subscribers = tuple(s for s in st.subscribers if s is not sub)


def _switches_snapshot() -> Dict[str, str]:
    """The program-identity snapshot stamped on spans: the raw values
    of every TRACE_SWITCHES env var that is set. Read ONLY on enabled
    -span close — disabled mode must not add env reads anywhere near
    trace-time identity."""
    out = {}
    for k in TRACE_SWITCHES:
        # the ONE sanctioned TRACE_SWITCHES read in obs: it runs only
        # on enabled-span close (disabled mode returns _NULL_SPAN and
        # never reaches this function), so the obs-off zero-reads
        # contract holds
        v = os.environ.get(k, "")  # causelint: disable=OBS001 -- enabled-span close only; obs-off never reaches here
        if v:
            out[k] = v
    return out


class _Span:
    """An enabled span: context manager recording one "span" event on
    close. ``set(**attrs)`` adds attributes mid-flight."""

    __slots__ = ("st", "name", "attrs", "sid", "parent", "depth",
                 "t0", "ts_us")

    def __init__(self, st: _State, name: str, attrs: dict):
        self.st = st
        self.name = name
        self.attrs = attrs
        self.sid = next(st.ids)
        self.parent = 0
        self.depth = 0
        self.t0 = 0.0
        self.ts_us = 0

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        st = self.st
        stack = getattr(st.tls, "stack", None)
        if stack is None:
            stack = st.tls.stack = []
        if stack:
            self.parent = stack[-1]
        self.depth = len(stack)
        stack.append(self.sid)
        self.ts_us = time.time_ns() // 1000
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur_us = int((time.perf_counter() - self.t0) * 1e6)
        st = self.st
        stack = getattr(st.tls, "stack", None)
        if stack and stack[-1] == self.sid:
            stack.pop()
        rec = {
            "ev": "span",
            "name": self.name,
            "ts_us": self.ts_us,
            "dur_us": dur_us,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "id": self.sid,
            "parent": self.parent,
            "depth": self.depth,
            "platform": st.platform,
            "switches": _switches_snapshot(),
        }
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        with st.lock:
            st.record(rec)
        return False


class _Counter:
    __slots__ = ("st", "name")

    def __init__(self, st: _State, name: str):
        self.st = st
        self.name = name

    def inc(self, n=1):
        st = self.st
        with st.lock:
            st.counters[self.name] = st.counters.get(self.name, 0) + n
        return self

    @property
    def value(self):
        return self.st.counters.get(self.name, 0)


class _Gauge:
    __slots__ = ("st", "name")

    def __init__(self, st: _State, name: str):
        self.st = st
        self.name = name

    def set(self, value):
        """Record the new value in the registry AND as a timestamped
        ``gauge`` event — a gauge is a sampled time series (devprof
        memory curves), so each set must land in the stream, not just
        in the flush-time snapshot (which only has flush resolution)."""
        st = self.st
        rec = {
            "ev": "gauge",
            "name": self.name,
            "ts_us": time.time_ns() // 1000,
            "pid": os.getpid(),
            "platform": st.platform,
            "value": value,
        }
        with st.lock:
            st.gauges[self.name] = value
            st.record(rec)
        return self

    @property
    def value(self):
        return self.st.gauges.get(self.name, 0)


def span(name: str, **attrs):
    """A wall-time span. Disabled mode returns the shared no-op."""
    st = _resolve_state()
    if not st.enabled:
        return _NULL_SPAN
    return _Span(st, name, attrs)


def event(name: str, **fields) -> None:
    """An instant event (harvest ladder decisions, checksum-gate
    outcomes, overflow retries). ``fields`` must be JSON-serializable
    (non-serializable values are stringified)."""
    st = _resolve_state()
    if not st.enabled:
        return
    stack = getattr(st.tls, "stack", None)
    rec = {
        "ev": "event",
        "name": name,
        "ts_us": time.time_ns() // 1000,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "parent": stack[-1] if stack else 0,
        "platform": st.platform,
    }
    if fields:
        rec["fields"] = fields
    with st.lock:
        st.record(rec)


def counter(name: str):
    """The named monotonic counter (disabled mode: shared no-op)."""
    st = _resolve_state()
    if not st.enabled:
        return _NULL_INSTRUMENT
    return _Counter(st, name)


def gauge(name: str):
    """The named last-value gauge (disabled mode: shared no-op)."""
    st = _resolve_state()
    if not st.enabled:
        return _NULL_INSTRUMENT
    return _Gauge(st, name)


def counters_snapshot() -> dict:
    """{"counters": {...}, "gauges": {...}} — current aggregate
    values (empty dicts when disabled)."""
    st = _resolve_state()
    with st.lock:
        return {"counters": dict(st.counters),
                "gauges": dict(st.gauges)}


def flush() -> None:
    """Snapshot the counter/gauge registry into the event stream (and
    the sink). Call at phase boundaries; also runs at exit."""
    st = _resolve_state()
    if not st.enabled:
        return
    with st.lock:
        rec = {
            "ev": "counters",
            "ts_us": time.time_ns() // 1000,
            "pid": os.getpid(),
            "platform": st.platform,
            "counters": dict(st.counters),
            "gauges": dict(st.gauges),
        }
        st.record(rec)


def events() -> list:
    """A snapshot list of the ring buffer's events (oldest first)."""
    st = _resolve_state()
    with st.lock:
        return list(st.ring)


def export_jsonl(path: str) -> int:
    """Write the ring buffer (plus a final counter snapshot) to
    ``path`` as JSON lines; returns the number of lines written."""
    st = _resolve_state()
    with st.lock:
        evs = list(st.ring)
        snap = {
            "ev": "counters",
            "ts_us": time.time_ns() // 1000,
            "pid": os.getpid(),
            "platform": st.platform,
            "counters": dict(st.counters),
            "gauges": dict(st.gauges),
        }
    evs.append(snap)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        for e in evs:
            f.write(json.dumps(e, default=str) + "\n")
    return len(evs)
