"""Chrome-trace/Perfetto export of obs event streams.

Converts the JSONL event schema of ``cause_tpu.obs.core`` into the
Chrome Trace Event JSON format that https://ui.perfetto.dev (and
chrome://tracing) open directly:

- ``span`` events become complete ("ph": "X") slices on a
  per-process/per-thread track, with the span attributes AND the
  ``TRACE_SWITCHES`` program-identity snapshot as args;
- ``event`` records become instant events ("ph": "i", thread scope);
  events in the CRDT-semantic vocabulary (``semantic.
  SEMANTIC_EVENT_PREFIXES`` — ``sync.*``, ``wave.digest``,
  ``divergence``, ``gc.*``, ``collection.*``, ``fleet.*``) are routed
  onto their own NAMED instant-event track per family (a synthetic
  tid with ``thread_name`` metadata), so fleet health reads as
  labelled swim-lanes above the span tracks instead of dots buried in
  whichever thread happened to emit them;
- ``counters`` snapshots become one counter track per metric
  ("ph": "C"), so program-cache hit/miss rates and fallback counts
  plot as time series next to the spans they explain;
- ``gauge`` records (every ``obs.gauge(...).set()``) become counter
  -track samples too, at set-time resolution — devprof's live-array
  and device-memory gauges render as curves, not flush-time steps.

Stdlib-only, like the rest of ``cause_tpu.obs``.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional

__all__ = ["to_chrome_trace", "export_perfetto", "load_jsonl",
           "load_streams", "CountersReducer", "merged_final_counters"]

# synthetic-tid base for the named semantic tracks: far above any real
# OS thread id's low bits mattering for display, stable across runs so
# diffs of exported traces stay comparable
_SEMANTIC_TID_BASE = 0x5EA00000


def _semantic_family(name: str) -> Optional[str]:
    """The semantic track family of an instant event's name, or None
    for ordinary (thread-track) events."""
    from .semantic import SEMANTIC_EVENT_PREFIXES

    for prefix in SEMANTIC_EVENT_PREFIXES:
        if name == prefix or name.startswith(prefix):
            return prefix.rstrip(".")
    return None


def load_jsonl(path: str) -> List[dict]:
    """Parse an obs JSONL file (skipping any torn/garbage lines — an
    abandoned writer may have lost the race with process death)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                out.append(obj)
    return out


def load_streams(paths: Iterable[str]) -> List[dict]:
    """Load one or more obs JSONL streams as ONE event list — the one
    multi-stream merge rule the ``fleet`` and ``lag`` CLIs share.
    Multiple streams merge by record timestamp (stable sort:
    same-timestamp records keep their per-file order), so
    per-document "last wave" state and cumulative per-pid records
    aggregate correctly across a multi-process soak's sidecars; a
    single stream is returned in file order, untouched."""
    paths = list(paths)
    events: List[dict] = []
    for p in paths:
        events.extend(load_jsonl(p))
    if len(paths) > 1:
        events.sort(key=lambda e: e.get("ts_us") or 0)
    return events


class CountersReducer:
    """Incremental form of :func:`merged_final_counters`: feed obs
    records one at a time (a live tail), read the merged totals at any
    point. Counter snapshots are cumulative PER PROCESS, so the state
    is each pid's LAST snapshot; :meth:`totals` sums across pids in
    first-seen-pid order — the identical fold the batch function runs,
    so the two are bit-equal on the same stream."""

    __slots__ = ("_per_pid", "include_gauges")

    def __init__(self, include_gauges: bool = False):
        self._per_pid: dict = {}
        self.include_gauges = include_gauges

    def feed(self, e: dict) -> None:
        if e.get("ev") != "counters":
            return
        merged = dict(e.get("counters") or {})
        if self.include_gauges:
            merged.update(e.get("gauges") or {})
        self._per_pid[e.get("pid", 0)] = merged

    def totals(self) -> dict:
        out: dict = {}
        for snap in self._per_pid.values():
            for name, value in snap.items():
                out[name] = out.get(name, 0) + value
        return out


def merged_final_counters(events: Iterable[dict],
                          include_gauges: bool = False) -> dict:
    """The stream's final counter values: counter snapshots are
    cumulative PER PROCESS, so keep each pid's LAST snapshot and sum
    across pids (a shared sidecar interleaves parent + abandoned-child
    flushes — last-wins across pids would report whichever process
    flushed last). The one merge rule shared by ``--summary``, the
    ledger's devprof digest and the live fold (which runs the same
    body incrementally via :class:`CountersReducer`)."""
    r = CountersReducer(include_gauges=include_gauges)
    for e in events:
        r.feed(e)
    return r.totals()


def _args_of(e: dict) -> dict:
    args = {}
    for k, v in (e.get("attrs") or {}).items():
        args[k] = v
    for k, v in (e.get("switches") or {}).items():
        args[k] = v
    if e.get("platform"):
        args["platform"] = e["platform"]
    return args


def to_chrome_trace(events: Iterable[dict]) -> dict:
    """The Chrome Trace Event envelope for an obs event stream."""
    trace: List[dict] = []
    pids = set()
    # (pid, family) -> synthetic tid for the named semantic tracks;
    # allocation order is first-seen, names come from thread_name
    # metadata emitted at the end
    semantic_tids: dict = {}
    for e in events:
        ev = e.get("ev")
        pid = e.get("pid", 0)
        pids.add(pid)
        if ev == "span":
            trace.append({
                "name": e.get("name", "?"),
                "cat": "obs",
                "ph": "X",
                "ts": e.get("ts_us", 0),
                "dur": max(1, e.get("dur_us", 1)),
                "pid": pid,
                "tid": e.get("tid", 0),
                "args": _args_of(e),
            })
        elif ev == "event":
            args = dict(e.get("fields") or {})
            if e.get("platform"):
                args.setdefault("platform", e["platform"])
            name = e.get("name", "?")
            family = _semantic_family(name)
            if family is not None:
                tid = semantic_tids.setdefault(
                    (pid, family),
                    _SEMANTIC_TID_BASE + len(semantic_tids))
            else:
                tid = e.get("tid", 0)
            trace.append({
                "name": name,
                "cat": "obs.semantic" if family is not None else "obs",
                "ph": "i",
                "s": "t",
                "ts": e.get("ts_us", 0),
                "pid": pid,
                "tid": tid,
                "args": args,
            })
        elif ev == "gauge":
            trace.append({
                "name": e.get("name", "?"),
                "cat": "obs",
                "ph": "C",
                "ts": e.get("ts_us", 0),
                "pid": pid,
                "args": {"value": e.get("value", 0)},
            })
        elif ev == "counters":
            ts = e.get("ts_us", 0)
            merged = dict(e.get("counters") or {})
            merged.update(e.get("gauges") or {})
            for name, value in sorted(merged.items()):
                trace.append({
                    "name": name,
                    "cat": "obs",
                    "ph": "C",
                    "ts": ts,
                    "pid": pid,
                    "args": {"value": value},
                })
    for pid in sorted(pids):
        trace.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": f"cause_tpu pid {pid}"},
        })
    for (pid, family), tid in sorted(semantic_tids.items(),
                                     key=lambda kv: kv[1]):
        trace.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": f"semantic:{family}"},
        })
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def export_perfetto(path: str, events: Optional[Iterable[dict]] = None,
                    jsonl: Optional[str] = None) -> int:
    """Write a Perfetto-openable trace JSON to ``path`` from either an
    in-memory event list, a JSONL file, or (default) the live ring
    buffer. Returns the number of trace events written."""
    if events is None:
        if jsonl is not None:
            events = load_jsonl(jsonl)
        else:
            from .core import events as _ring

            events = _ring()
    doc = to_chrome_trace(events)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])
