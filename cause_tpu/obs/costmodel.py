"""The wave cost model: dispatch accounting joined to divergence.

PERF.md's whole thesis — waves pay full-document-width cost even when
replicas diverge by a handful of ops — lived in prose. PR 5's semantic
events measure divergence per wave (delta rounds, token headroom) and
PR 4's devprof prices programs at compile time, but nothing joined
them into one record, so "cost ∝ document size, not divergence" had no
machine-checkable artifact and the planned delta-native device weave
(ROADMAP item 1) had no ready-made acceptance gate. This module is
that join, in three layers:

- **dispatch accounting** — every device program invocation at the
  program-cache call sites (``benchgen.merge_wave_scalar``), the wave
  kernels (``parallel/wave.py``) and the session's resident-splice
  path (``parallel/session.py``) lands via :func:`record_dispatch`
  with a program-identity string; the open wave window counts
  invocations and distinct identities, and the dispatch-floor budget
  arithmetic PERF.md narrates (floor_ms × dispatches vs measured
  wall) becomes computed fields instead of prose;
- **the cost-vs-divergence join** — each wave emits ONE ``wave.cost``
  event carrying the wave's semantic evidence (delta ops noted by the
  sync layer and the session delta path, token budget used, full-bag
  count) NEXT TO its cost (dispatches, the devprof flops/bytes digest
  of the programs run when known, wall span), so any obs stream
  directly yields the cost-vs-divergence curve that motivates — and
  later gates — the delta-native weave;
- **the gap report** — ``python -m cause_tpu.obs gap`` reads the
  committed perf ledger plus any obs JSONL stream and renders the
  north-star decomposition: best same-platform headline, the dispatch
  -floor share, per-phase shares from ``stages.prefix`` events when
  present, the cost-vs-divergence slope with an explicit
  O(doc)-vs-O(delta) verdict, and the projected headline if cost
  scaled with the measured divergence.

Contract (same as the rest of ``cause_tpu.obs``): stdlib + core only,
importable without jax/numpy; with ``CAUSE_TPU_OBS`` unset every entry
point returns immediately — no records, no registry state, no
``TRACE_SWITCHES`` reads, byte-identical program-cache keys (pinned by
tests/test_costmodel.py). On jit-reachable paths, call sites must sit
behind ``obs.enabled()`` guards — causelint rule OBS005 gates that.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import core

__all__ = [
    "DISPATCH_FLOOR_MS",
    "DISPATCH_FLOOR_RANGE_MS",
    "NORTH_STAR_MS",
    "enabled",
    "reset",
    "register_program",
    "record_dispatch",
    "note_delta_ops",
    "note_full_bag",
    "wave_begin",
    "wave_abandon",
    "wave_cost",
    "CostReducer",
    "costmodel_digest",
    "cost_vs_divergence",
    "tree_decomposition",
    "gap_report",
    "render_gap",
]

# The axon tunnel's measured per-dispatch floor (PERF.md: "a measured
# ~64-70 ms dispatch floor is included in every number"). The midpoint
# is the budget constant; the range states the measurement honestly.
DISPATCH_FLOOR_RANGE_MS = (64.0, 70.0)
DISPATCH_FLOOR_MS = sum(DISPATCH_FLOOR_RANGE_MS) / 2.0
# BASELINE.json config 5: p50 < 100 ms on one chip.
NORTH_STAR_MS = 100.0

# verdict rule: over the observed divergence range, the fitted slope
# must move the cost by at least this fraction OF THE MEAN COST before
# the curve counts as O(delta) — i.e. cost must scale MATERIALLY with
# divergence, not merely correlate (a 3 ms drift on a 1000 ms wave is
# O(doc) however tight its fit). Below the threshold the wave is
# paying document width regardless of divergence.
_SLOPE_EXPLAINS = 0.5

_LOCK = threading.Lock()
# program identity -> devprof cost dict ({"flops", "bytes_accessed",
# ...}); bounded LRU — identities are few (one per compiled program)
_PROGRAMS: Dict[str, dict] = {}
_PROGRAMS_MAX = 512
# uuid -> host-side divergence evidence noted since that document's
# last wave.cost (sync deltas, full-bag fallbacks); bounded like the
# semantic monitor — a soak mints a uuid per round
_PENDING_OPS: Dict[str, int] = {}
_PENDING_BAGS: Dict[str, int] = {}
_PENDING_MAX = 4096
_TLS = threading.local()  # .window — the open per-thread wave window


def enabled() -> bool:
    """Whether the cost model records anything (== ``obs.enabled()``)."""
    return core.enabled()


def reset() -> None:
    """Drop all cost-model state (tests; obs.reset does not reach into
    this layer)."""
    with _LOCK:
        _PROGRAMS.clear()
        _PENDING_OPS.clear()
        _PENDING_BAGS.clear()
    _TLS.window = None


def _bound(d: Dict, cap: int) -> None:
    while len(d) > cap:
        d.pop(next(iter(d)))


# --------------------------------------------------------- accounting


def register_program(program: str, cost: Optional[dict]) -> None:
    """Remember a compiled program's devprof cost digest under its
    identity string, so later ``wave.cost`` events can attach the
    flops/bytes of the programs a wave actually ran. Called at the
    program-cache miss right after ``devprof.profile_program``."""
    if not core.enabled():
        return
    with _LOCK:
        _PROGRAMS.pop(program, None)
        _PROGRAMS[program] = dict(cost or {})
        _bound(_PROGRAMS, _PROGRAMS_MAX)


def record_dispatch(program: str, site: str = "", n: int = 1) -> None:
    """One (or ``n``) device program invocation(s) with identity
    ``program``. Bumps the global ``costmodel.dispatches`` counter and
    attributes the invocation to the calling thread's open wave
    window, when one is open (dispatches outside any wave — session
    splices, bench warmups — still count globally)."""
    if not core.enabled():
        return
    core.counter("costmodel.dispatches").inc(n)
    if site:
        core.counter(f"costmodel.dispatches.{site}").inc(n)
    w = getattr(_TLS, "window", None)
    if w is not None:
        w["dispatches"] += int(n)
        w["programs"].add(str(program))


def note_delta_ops(uuid: str, n: int) -> None:
    """Host-side divergence evidence: ``n`` delta ops (synced nodes,
    appended lanes) landed on document ``uuid`` since its last wave.
    Drained into the next ``wave.cost`` for that document, so the
    event's ``delta_ops`` matches the semantic stream's delta
    accounting."""
    if not core.enabled():
        return
    u = str(uuid)
    with _LOCK:
        _PENDING_OPS[u] = _PENDING_OPS.pop(u, 0) + int(n)
        _bound(_PENDING_OPS, _PENDING_MAX)


def note_full_bag(uuid: str, n: int = 1) -> None:
    """A full-bag (O(doc) resend) degradation landed on ``uuid`` since
    its last wave; drained into the next ``wave.cost`` like
    :func:`note_delta_ops`."""
    if not core.enabled():
        return
    u = str(uuid)
    with _LOCK:
        _PENDING_BAGS[u] = _PENDING_BAGS.pop(u, 0) + int(n)
        _bound(_PENDING_BAGS, _PENDING_MAX)


# ------------------------------------------------------- wave windows


def wave_begin(source: str) -> Optional[dict]:
    """Open this thread's wave window: subsequent
    :func:`record_dispatch` calls attribute to it until
    :func:`wave_cost` closes it. Re-entrant by replacement — a window
    leaked by a raised wave is simply superseded."""
    if not core.enabled():
        return None
    w = {"source": str(source), "t0": time.perf_counter(),
         "dispatches": 0, "programs": set()}
    _TLS.window = w
    return w


def wave_abandon() -> None:
    """Drop the open window without emitting (overflowed session waves:
    their digests are garbage and ``fleet.session_overflow`` already
    records the incident)."""
    _TLS.window = None


def wave_cost(uuid: str = "", pairs: int = 0, lanes: int = 0,
              tokens: Optional[int] = None, token_budget: int = 0,
              delta_ops: int = 0, full_bag: int = 0,
              poisoned: int = 0, overflow_retries: int = 0,
              semantic: Optional[dict] = None,
              path: str = "", level: Optional[int] = None,
              bucket: Optional[int] = None,
              batch_rows: Optional[int] = None,
              uuids: Optional[Sequence[str]] = None) -> Optional[dict]:
    """Close the open wave window and emit ONE ``wave.cost`` event —
    the per-wave join of cost and divergence:

    - cost: ``dispatches`` / distinct ``programs`` from the window,
      wall span since :func:`wave_begin`, the dispatch-floor budget
      (``floor_budget_ms = DISPATCH_FLOOR_MS × dispatches`` — the
      minimum a tunnel-floored chip pays for this wave regardless of
      kernel speed), and the devprof flops/bytes sum of the programs
      run where :func:`register_program` priced them;
    - divergence: ``delta_ops`` (the caller's directly-measured ops —
      session delta lanes — plus everything :func:`note_delta_ops`
      accumulated for ``uuid``), ``tokens`` used vs ``token_budget``,
      ``full_bag`` count (caller's fallbacks plus noted full bags),
      and the wave's semantic summary (``wave.digest`` fields) when
      given;
    - scale: ``pairs`` and ``lanes`` (the O(doc) axis the divergence
      fields are judged against);
    - ``path``: which wave generation ran — ``"full"`` (document-width
      kernel) or ``"delta"`` (the delta-native window weave). The gap
      report fits a separate cost-vs-divergence curve per path, so a
      sweep stream renders the O(doc) control verdict NEXT TO the
      delta path's O(delta) verdict instead of mixing them;
    - ``level``: the merge-tree round this wave IS, when the wave is
      one level of a ``parallel.tree`` reduction — joined with the
      ``tree.level`` semantic events into the gap report's per-level
      cost decomposition;
    - ``bucket`` / ``batch_rows``: cross-tenant batched serving — the
      pow2 window budget this dispatch's rows shared and how many
      rows rode it, so the gap report and the live fold can attribute
      the dispatch-count collapse (one floor per BUCKET, not per
      tenant). ``uuids`` lists every document the bucket served:
      their :func:`note_delta_ops` accumulations all drain into this
      one event instead of dangling.

    Returns the emitted fields (or None when obs is off / no window).
    """
    if not core.enabled():
        return None
    w = getattr(_TLS, "window", None)
    _TLS.window = None
    if w is None:
        return None
    wall_ms = (time.perf_counter() - w["t0"]) * 1000.0
    u = str(uuid)
    drain = [u] + [str(x) for x in (uuids or ()) if str(x) != u]
    with _LOCK:
        pend_ops = sum(_PENDING_OPS.pop(x, 0) for x in drain)
        pend_bags = sum(_PENDING_BAGS.pop(x, 0) for x in drain)
        devprof_sum: Dict[str, float] = {}
        for p in w["programs"]:
            for k, v in (_PROGRAMS.get(p) or {}).items():
                if isinstance(v, (int, float)):
                    devprof_sum[k] = devprof_sum.get(k, 0) + v
    dispatches = int(w["dispatches"])
    fields: dict = {
        "uuid": u,
        "source": w["source"],
        "pairs": int(pairs),
        "lanes": int(lanes),
        "delta_ops": int(delta_ops) + pend_ops,
        "full_bag": int(full_bag) + pend_bags,
        "poisoned": int(poisoned),
        "overflow_retries": int(overflow_retries),
        "dispatches": dispatches,
        "programs": len(w["programs"]),
        "wall_ms": round(wall_ms, 3),
        "floor_ms": DISPATCH_FLOOR_MS,
        "floor_budget_ms": round(DISPATCH_FLOOR_MS * dispatches, 3),
    }
    if path:
        fields["path"] = str(path)
    if level is not None:
        fields["level"] = int(level)
    if bucket is not None:
        fields["bucket"] = int(bucket)
    if batch_rows is not None:
        fields["batch_rows"] = int(batch_rows)
    if uuids is not None:
        fields["tenants"] = len(uuids)
    if tokens is not None:
        fields["tokens"] = int(tokens)
        fields["token_budget"] = int(token_budget)
    if devprof_sum:
        fields["devprof"] = devprof_sum
    if semantic:
        # the divergence join proper: the wave.digest summary rides
        # next to the cost numbers (agreed/distinct/valid — staleness
        # histograms stay on the wave.digest event itself)
        fields["semantic"] = {
            k: semantic[k]
            for k in ("agreed", "distinct", "valid", "wave")
            if k in semantic
        }
    core.event("wave.cost", **fields)
    core.counter("costmodel.waves").inc()
    # Perfetto counter tracks: each set lands as a timestamped gauge
    # event, so dispatches and divergence render as curves next to the
    # wave spans they price
    core.gauge("costmodel.dispatches.wave").set(dispatches)
    core.gauge("costmodel.delta_ops.wave").set(fields["delta_ops"])
    if tokens is not None:
        core.gauge("costmodel.tokens.wave").set(int(tokens))
    return fields


# ---------------------------------------------------------- analysis


def _wave_cost_events(events: Sequence[dict]) -> List[dict]:
    return [e.get("fields") or {} for e in events
            if e.get("ev") == "event" and e.get("name") == "wave.cost"]


def _divergence_of(f: dict) -> Optional[int]:
    """The wave's divergence measure: delta ops where the stream
    recorded them (zero counts — a converged wave that still paid
    full cost is the strongest O(doc) evidence), else the kernel's
    token count (the segment-union work size — divergent regions
    explode to tokens, the shared base dedupes). A full-bag wave with
    no delta count is excluded: its divergence was shipped as O(doc),
    not measured."""
    if f.get("delta_ops"):
        return int(f["delta_ops"])
    if f.get("full_bag"):
        # full-bag work with no delta count: divergence was shipped
        # as O(doc), never measured — the tokens of the surviving
        # live rows would understate it
        return None
    if f.get("tokens"):
        return int(f["tokens"])
    if "delta_ops" in f:
        return 0
    return None


def cost_vs_divergence(waves: Sequence[dict]) -> dict:
    """Least-squares fit of wave cost (wall ms) against wave
    divergence over a stream of ``wave.cost`` fields, with the
    explicit O(doc)-vs-O(delta) verdict the delta-native roadmap item
    gates on:

    - ``O(delta)`` — over the observed divergence range the fitted
      slope moves the cost by at least half its MEAN: cost scales
      materially with divergence;
    - ``O(doc)`` — it does not: waves pay document-width cost however
      small the divergence (the PERF.md claim, now computed — a tiny
      correlated drift on a large flat cost stays O(doc));
    - ``insufficient-data`` — fewer than two waves, or no divergence
      spread to regress over.
    """
    pts = []
    for f in waves:
        x = _divergence_of(f)
        y = f.get("wall_ms")
        if x is not None and isinstance(y, (int, float)):
            pts.append((float(x), float(y)))
    return _fit_points(pts)


def _fit_points(pts: Sequence[Tuple[float, float]]) -> dict:
    """The fit proper, over already-extracted (divergence, wall_ms)
    points — shared by the batch pass and :class:`CostReducer` so the
    live fold's curve is bit-equal to the batch one."""
    out: dict = {"points": len(pts)}
    if len(pts) < 2:
        out["verdict"] = "insufficient-data"
        return out
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    n = len(pts)
    mx = sum(xs) / n
    my = sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in xs)
    syy = sum((y - my) ** 2 for y in ys)
    sxy = sum((x - mx) * (y - my) for x, y in pts)
    out.update(
        divergence_min=min(xs), divergence_max=max(xs),
        cost_min_ms=round(min(ys), 3), cost_max_ms=round(max(ys), 3),
        mean_cost_ms=round(my, 3),
    )
    if sxx == 0:
        out["verdict"] = "insufficient-data"
        return out
    slope = sxy / sxx
    intercept = my - slope * mx
    corr = (sxy / (sxx * syy) ** 0.5) if syy > 0 else 0.0
    # how far the divergence slope moves the cost over the observed
    # range, relative to the MEAN cost (see _SLOPE_EXPLAINS): a
    # negative slope is noise, not delta-scaling
    explained = max(slope, 0.0) * (max(xs) - min(xs))
    ratio = explained / my if my > 0 else 0.0
    out.update(
        slope_ms_per_op=round(slope, 6),
        intercept_ms=round(intercept, 3),
        corr=round(corr, 4),
        explained_ratio=round(ratio, 4),
        verdict="O(delta)" if ratio >= _SLOPE_EXPLAINS else "O(doc)",
    )
    return out


class CostReducer:
    """Incremental form of :func:`costmodel_digest`: feed obs records
    one at a time (a live tail, a subscriber queue), read the
    cost-model aggregate at any point. Totals accumulate in stream
    order — integer sums are exact and the wall-ms float sum runs in
    the identical order as the batch pass, so :meth:`digest` is
    bit-equal to ``costmodel_digest(events)`` on the same stream.

    Regression points are kept per path too (``"delta"`` / ``"full"``
    / the emitting source), on bounded oldest-dropped deques
    (``points_max``) — a live monitor over an unbounded stream must
    not grow without bound, and the fold must stay O(1) per record;
    truncation is reported honestly, pooled via ``points_dropped``
    and per path in the ``curves_by_path`` fits."""

    __slots__ = ("waves", "dispatches", "delta_ops", "full_bag",
                 "wall_ms", "lanes_max", "_pts", "_pts_by_path",
                 "_dropped_by_path", "points_max", "points_dropped")

    def __init__(self, points_max: int = 65536):
        self.waves = 0
        self.dispatches = 0
        self.delta_ops = 0
        self.full_bag = 0
        self.wall_ms = 0.0
        self.lanes_max = 0
        self.points_max = int(points_max)
        self._pts = deque(maxlen=self.points_max)
        self._pts_by_path: Dict[str, deque] = {}
        self._dropped_by_path: Dict[str, int] = {}
        self.points_dropped = 0

    def feed(self, e: dict) -> None:
        if e.get("ev") != "event" or e.get("name") != "wave.cost":
            return
        f = e.get("fields") or {}
        self.waves += 1
        self.dispatches += int(f.get("dispatches") or 0)
        self.delta_ops += int(f.get("delta_ops") or 0)
        self.full_bag += int(f.get("full_bag") or 0)
        self.wall_ms += float(f.get("wall_ms") or 0.0)
        self.lanes_max = max(self.lanes_max, int(f.get("lanes") or 0))
        x = _divergence_of(f)
        y = f.get("wall_ms")
        if x is not None and isinstance(y, (int, float)):
            pt = (float(x), float(y))
            if len(self._pts) == self.points_max:
                self.points_dropped += 1
            self._pts.append(pt)
            path = str(f.get("path") or f.get("source") or "?")
            by = self._pts_by_path.get(path)
            if by is None:
                by = self._pts_by_path[path] = deque(
                    maxlen=self.points_max)
            if len(by) == by.maxlen:
                self._dropped_by_path[path] = \
                    self._dropped_by_path.get(path, 0) + 1
            by.append(pt)

    def curve(self) -> dict:
        """The pooled cost-vs-divergence fit (``_fit_points``)."""
        return _fit_points(self._pts)

    def curves_by_path(self) -> Dict[str, dict]:
        """Per-path fits, only meaningful with >1 path (the delta
        -vs-full A/B shape ``gap_report`` renders). A path whose
        deque truncated carries its own ``points_dropped`` — the
        verdict was fitted over a window, and the reader must know."""
        out = {}
        for k, v in sorted(self._pts_by_path.items()):
            fit = _fit_points(v)
            if self._dropped_by_path.get(k):
                fit["points_dropped"] = self._dropped_by_path[k]
            out[k] = fit
        return out

    def digest(self) -> dict:
        """``costmodel_digest``'s dict (empty when no waves fed)."""
        if not self.waves:
            return {}
        out = {
            "waves": self.waves,
            "dispatches": self.dispatches,
            "delta_ops": self.delta_ops,
            "full_bag": self.full_bag,
            "wall_ms": round(self.wall_ms, 3),
            "lanes_max": self.lanes_max,
        }
        out["slope"] = self.curve()
        if self.points_dropped:
            out["points_dropped"] = self.points_dropped
        return out


def costmodel_digest(events: Sequence[dict]) -> dict:
    """The cost-model aggregate of one obs stream — the ledger row
    extension (``row["cost"]``): wave/dispatch totals, divergence
    totals, and the slope verdict. Empty dict when the stream carries
    no ``wave.cost`` events. The batch form of :class:`CostReducer` —
    one shared body, so live folds match ledger digests bit-for-bit."""
    r = CostReducer()
    for e in events:
        r.feed(e)
    return r.digest()


# --------------------------------------------------------- gap report


def _best_bench_rows(rows: Sequence[dict]) -> Dict[str, dict]:
    """Best (lowest headline) non-quarantined full-size bench row per
    platform string — the only rows a headline claim may cite."""
    best: Dict[str, dict] = {}
    for r in rows:
        if (r.get("kind") or "bench") != "bench":
            continue
        if r.get("quarantined") or r.get("smoke"):
            continue
        v = r.get("value_ms")
        if not isinstance(v, (int, float)):
            continue
        p = str(r.get("platform") or "?")
        if p not in best or v < best[p]["value_ms"]:
            best[p] = r
    return best


def _stage_shares(events: Sequence[dict]) -> List[dict]:
    """Per-phase shares from ``stages.prefix`` events (the jaxw5 stage
    ladder), when the stream carries them: each stage's delta over the
    FULL prefix's p50. Last ladder wins (streams may hold several)."""
    ladder: Dict[str, dict] = {}
    for e in events:
        if e.get("ev") == "event" and e.get("name") == "stages.prefix":
            f = e.get("fields") or {}
            if f.get("stage") and f.get("p50_ms") is not None:
                ladder[str(f["stage"])] = f
    if not ladder:
        return []
    full = ladder.get("FULL") or max(
        ladder.values(), key=lambda f: f["p50_ms"])
    total = float(full["p50_ms"]) or 1.0
    out = []
    for name, f in ladder.items():
        delta = float(f.get("delta_ms") or 0.0)
        out.append({"stage": name, "delta_ms": round(delta, 3),
                    "share": round(delta / total, 4)})
    out.sort(key=lambda d: -d["delta_ms"])
    return out


def tree_decomposition(events: Sequence[dict]) -> Optional[dict]:
    """Per-level cost decomposition of merge-tree convergence runs in
    one obs stream: join each ``tree.level`` semantic event with the
    ``wave.cost`` events carrying the same level index (a stream may
    hold several tree runs; levels aggregate). None when the stream
    carries no tree levels."""
    levels: Dict[int, dict] = {}
    for e in events:
        if e.get("ev") != "event":
            continue
        f = e.get("fields") or {}
        if e.get("name") == "tree.level":
            lv = levels.setdefault(int(f.get("level") or 0), {
                "level": int(f.get("level") or 0), "waves": 0,
                "pairs": 0, "delta_ops": 0, "dispatches": 0,
                "wall_ms": 0.0, "paths": set(), "agreed": 0,
            })
            lv["waves"] += 1
            lv["pairs"] += int(f.get("pairs") or 0)
            lv["delta_ops"] += int(f.get("delta_ops") or 0)
            lv["dispatches"] += int(f.get("dispatches") or 0)
            if f.get("path"):
                lv["paths"].add(str(f["path"]))
            if f.get("agreed"):
                lv["agreed"] += 1
        elif e.get("name") == "wave.cost" and f.get("level") is not None:
            lv = levels.get(int(f["level"]))
            if lv is not None:
                lv["wall_ms"] += float(f.get("wall_ms") or 0.0)
    if not levels:
        return None
    out = []
    total = sum(lv["wall_ms"] for lv in levels.values()) or 1.0
    for k in sorted(levels):
        lv = levels[k]
        lv["paths"] = "+".join(sorted(lv["paths"])) or "?"
        lv["wall_ms"] = round(lv["wall_ms"], 3)
        lv["share"] = round(lv["wall_ms"] / total, 4)
        out.append(lv)
    post = [lv for lv in out if lv["level"] > 0]
    return {
        "rounds": len(out),
        "levels": out,
        "wall_ms": round(sum(lv["wall_ms"] for lv in out), 3),
        # the tree's acceptance shape: later levels ride the delta
        # path (inter-level divergence shrinks as subtrees converge)
        "post_level0_delta_share": round(
            sum(1 for lv in post if "delta" in lv["paths"])
            / len(post), 4) if post else None,
    }


def gap_report(rows: Sequence[dict],
               events: Optional[Sequence[dict]] = None,
               target_ms: float = NORTH_STAR_MS,
               floor_ms: float = DISPATCH_FLOOR_MS) -> dict:
    """The north-star decomposition from the perf ledger plus an
    optional obs stream. Total on empty inputs (every section states
    its absence) — the first question to a broken run is "is there any
    evidence at all?"."""
    events = list(events or [])
    best = _best_bench_rows(rows)
    head = best.get("tpu")
    head_note = ""
    if head is None and best:
        head = min(best.values(), key=lambda r: r["value_ms"])
        head_note = ("no tpu row in the ledger; best available "
                     "platform shown — the 100 ms target is defined "
                     "on tpu")
    waves = _wave_cost_events(events)
    report: dict = {
        "target_ms": target_ms,
        "floor_ms": floor_ms,
        "floor_range_ms": list(DISPATCH_FLOOR_RANGE_MS),
        "ledger_rows": len(rows),
        "stream_waves": len(waves),
        "platforms": {
            p: {"value_ms": r["value_ms"],
                "single_dispatch_ms": r.get("single_dispatch_ms"),
                "kernel": r.get("kernel"), "source": r.get("source")}
            for p, r in sorted(best.items())
        },
    }
    if head is not None:
        single = head.get("single_dispatch_ms")
        report["headline"] = {
            "value_ms": head["value_ms"],
            "single_dispatch_ms": single,
            "platform": head.get("platform"),
            "kernel": head.get("kernel"),
            "source": head.get("source"),
            "gap_x": round(float(head["value_ms"]) / target_ms, 2),
        }
        if head_note:
            report["headline"]["note"] = head_note
        # dispatch-floor arithmetic, lifted from PERF.md prose: the
        # floor's share of a single dispatch (amortized bursts pay it
        # once per burst), and the per-wave floor budget under the
        # stream's measured dispatches-per-wave
        dpw = None
        if waves:
            ds = sorted(int(f.get("dispatches") or 0) for f in waves)
            dpw = ds[len(ds) // 2]
        report["dispatch_floor"] = {
            "floor_ms": floor_ms,
            "dispatches_per_wave": dpw,
            "floor_budget_ms": (round(floor_ms * dpw, 3)
                                if dpw is not None else floor_ms),
            "share_of_single": (
                round(floor_ms / float(single), 4)
                if isinstance(single, (int, float)) and single else None),
            "share_of_target": round(floor_ms / target_ms, 4),
        }
    else:
        report["headline"] = None
    stages = _stage_shares(events)
    if stages:
        report["stages"] = stages
    tree = tree_decomposition(events)
    if tree:
        report["tree"] = tree
    curve = cost_vs_divergence(waves)
    report["cost_vs_divergence"] = curve
    # per-path curves: when the stream carries waves from more than
    # one generation ("delta" vs "full", else the emitting source),
    # each gets its own slope verdict — the delta-native acceptance
    # gate is "O(delta) for the delta path AND O(doc) for the
    # full-weave control", which one pooled fit cannot express
    groups: Dict[str, List[dict]] = {}
    for f in waves:
        groups.setdefault(
            str(f.get("path") or f.get("source") or "?"), []
        ).append(f)
    if len(groups) > 1:
        report["cost_vs_divergence_by_path"] = {
            k: cost_vs_divergence(v) for k, v in sorted(groups.items())
        }
    # projection: if wave cost scaled with the measured divergence
    # (the delta-native weave's promise), the headline would shrink to
    # its divergence fraction — floored by the dispatch floor, which
    # no kernel can amortize below one dispatch
    fracs = [(_divergence_of(f) or 0) / float(f["lanes"])
             for f in waves
             if f.get("lanes") and _divergence_of(f) is not None]
    if head is not None and fracs:
        fracs.sort()
        frac = fracs[len(fracs) // 2]
        projected = max(floor_ms, float(head["value_ms"]) * frac)
        report["projected"] = {
            "divergence_fraction": round(frac, 6),
            "headline_ms": round(projected, 3),
            "gap_x": round(projected / target_ms, 2),
            "assumes": "cost scales with measured divergence "
                       "(the delta-native weave contract)",
        }
    return report


def render_gap(report: dict) -> str:
    """The human layout of :func:`gap_report` — one glanceable
    decomposition block."""
    lines = [f"north-star gap (target {report['target_ms']:g} ms, "
             f"dispatch floor {report['floor_ms']:g} ms "
             f"[{report['floor_range_ms'][0]:g}-"
             f"{report['floor_range_ms'][1]:g}])"]
    head = report.get("headline")
    if head is None:
        lines.append("  headline: NO eligible bench row in the ledger "
                     "(nothing non-quarantined at full size)")
    else:
        lines.append(
            f"  headline: {head['value_ms']:g} ms amortized "
            f"({head['platform']}, {head['kernel']}, {head['source']})"
            f" = {head['gap_x']:g}x off target")
        if head.get("note"):
            lines.append(f"    note: {head['note']}")
        if head.get("single_dispatch_ms"):
            lines.append(f"  single dispatch: "
                         f"{head['single_dispatch_ms']:g} ms")
        fl = report.get("dispatch_floor") or {}
        if fl:
            share = fl.get("share_of_single")
            lines.append(
                f"  dispatch floor: {fl['floor_budget_ms']:g} ms/wave"
                + (f" ({fl['dispatches_per_wave']} dispatch(es)/wave)"
                   if fl.get("dispatches_per_wave") is not None else "")
                + (f", {100 * share:.1f}% of a single dispatch"
                   if share is not None else "")
                + f", {100 * fl['share_of_target']:.0f}% of the target")
    for st in report.get("stages", []):
        lines.append(f"  phase {st['stage']}: {st['delta_ms']:g} ms "
                     f"({100 * st['share']:.1f}%)")
    tree = report.get("tree")
    if tree:
        lines.append(
            f"  merge tree: {tree['rounds']} round(s), "
            f"{tree['wall_ms']:g} ms total"
            + (f", post-level-0 delta share "
               f"{100 * tree['post_level0_delta_share']:.0f}%"
               if tree.get("post_level0_delta_share") is not None
               else ""))
        for lv in tree["levels"]:
            lines.append(
                f"    level {lv['level']}: {lv['pairs']} pair(s), "
                f"{lv['delta_ops']} delta op(s), "
                f"{lv['dispatches']} dispatch(es), "
                f"{lv['wall_ms']:g} ms ({100 * lv['share']:.1f}%), "
                f"path {lv['paths']}")
    def _curve_line(c, label="cost vs divergence"):
        if c.get("verdict") == "insufficient-data":
            return (f"  {label}: insufficient data "
                    f"({c.get('points', 0)} wave(s) in the stream)")
        return (
            f"  {label}: {c['points']} waves, divergence "
            f"{c['divergence_min']:g}-{c['divergence_max']:g} ops, "
            f"slope {c['slope_ms_per_op']:g} ms/op "
            f"(corr {c['corr']:g}, explains "
            f"{100 * c['explained_ratio']:.0f}% of spread) -> "
            f"verdict: {c['verdict']}")

    c = report.get("cost_vs_divergence") or {}
    if c:
        lines.append(_curve_line(c))
    for name, cp in sorted(
            (report.get("cost_vs_divergence_by_path") or {}).items()):
        lines.append(_curve_line(cp, label=f"path {name}"))
    proj = report.get("projected")
    if proj:
        lines.append(
            f"  projected if cost ∝ divergence: {proj['headline_ms']:g}"
            f" ms ({proj['gap_x']:g}x target; measured divergence "
            f"fraction {proj['divergence_fraction']:g})")
    return "\n".join(lines)


# ----------------------------------------------------------------- CLI


def main(argv=None) -> int:
    import argparse
    import json
    import os
    import sys

    from . import ledger as ledger_mod
    from .perfetto import load_jsonl

    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs gap",
        description="Render the north-star gap decomposition from the "
                    "committed perf ledger plus any obs JSONL streams "
                    "(dispatch-floor share, per-phase shares, the "
                    "cost-vs-divergence slope with its O(doc)-vs-"
                    "O(delta) verdict, and the projected headline if "
                    "cost scaled with divergence).")
    ap.add_argument("--ledger", default="",
                    help="ledger path (default: CAUSE_TPU_LEDGER or "
                         "measurements/ledger.jsonl)")
    ap.add_argument("--obs", action="append", default=[],
                    help="obs JSONL stream(s) carrying wave.cost / "
                         "stages.prefix events (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    ap.add_argument("--append", action="store_true",
                    help="also land the report as a --kind gap summary "
                         "row in the ledger (platform-partitioned like "
                         "every other row)")
    ap.add_argument("--source", default="obs-gap",
                    help="source tag for the --append row")
    ap.add_argument("--target", type=float, default=NORTH_STAR_MS,
                    help="target ms (default: the 100 ms north star)")
    ap.add_argument("--floor", type=float, default=DISPATCH_FLOOR_MS,
                    help="dispatch floor ms (default: the measured "
                         "tunnel floor midpoint)")
    a = ap.parse_args(argv)

    rows = ledger_mod.load(a.ledger or None)
    events: List[dict] = []
    for path in a.obs:
        if not os.path.exists(path):
            print(f"gap: no such obs stream: {path}", file=sys.stderr)
            return 2
        events.extend(load_jsonl(path))
    report = gap_report(rows, events, target_ms=a.target,
                        floor_ms=a.floor)
    if a.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_gap(report))
    if a.append:
        head = report.get("headline") or {}
        platform = head.get("platform")
        if not platform:
            # no headline in the target ledger (fresh/scratch): tag
            # the row with the stream's own platform so it still
            # partitions honestly instead of quarantining as "none"
            plats = [e.get("platform") for e in events
                     if e.get("ev") == "event"
                     and e.get("name") == "wave.cost"
                     and e.get("platform")]
            platform = plats[0] if plats else "none"
        row = ledger_mod.ingest_record(
            {"platform": platform,
             "metric": f"north-star gap decomposition "
                       f"(target {a.target:g} ms)",
             "value": None,
             "kernel": head.get("kernel"),
             "config": "gap-report"},
            source=a.source, path=a.ledger or None, kind="gap",
            extra={"gap": report},
        )
        print(f"gap: ledger row ({row['platform']}) -> "
              f"{a.ledger or ledger_mod.default_path()}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
