"""CLI: the obs toolbox.

    python -m cause_tpu.obs events.jsonl -o trace.json   # Perfetto
    python -m cause_tpu.obs stages [--smoke] [--reps N]  # stage ladder
    python -m cause_tpu.obs ledger --check               # perf ledger
    python -m cause_tpu.obs fleet events.jsonl           # fleet health
    python -m cause_tpu.obs gap [--obs events.jsonl]     # gap report
    python -m cause_tpu.obs lag events.jsonl             # lag tracer
    python -m cause_tpu.obs journey <trace|--worst N> .. # journeys
    python -m cause_tpu.obs watch events.jsonl [--once]  # live watch

The default (first) form converts an obs JSONL event stream to a
Perfetto trace — open the output at https://ui.perfetto.dev (or
chrome://tracing); with ``--summary`` it also prints per-span-name
aggregate wall times and the final counter values. ``stages`` runs
the jaxw5 stage-prefix profiler (``cause_tpu.obs.stages``); ``ledger``
manages the persistent perf ledger (``cause_tpu.obs.ledger``).
"""

from __future__ import annotations

import argparse
import json
import sys

from .perfetto import export_perfetto, load_jsonl, merged_final_counters


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "stages":
        # imports jax — resolved only when asked for
        from .stages import main as stages_main

        return stages_main(argv[1:])
    if argv and argv[0] == "ledger":
        from .ledger import main as ledger_main

        return ledger_main(argv[1:])
    if argv and argv[0] == "fleet":
        from .fleet import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "gap":
        from .costmodel import main as gap_main

        return gap_main(argv[1:])
    if argv and argv[0] == "lag":
        from .lag import main as lag_main

        return lag_main(argv[1:])
    if argv and argv[0] == "journey":
        from .journey import main as journey_main

        return journey_main(argv[1:])
    if argv and argv[0] == "watch":
        from .watch import main as watch_main

        return watch_main(argv[1:])
    return _convert_main(argv)


def _convert_main(argv) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs",
        description="Convert obs JSONL events to a Perfetto/Chrome "
                    "trace (and/or print a summary).")
    ap.add_argument("jsonl", help="obs event file (JSON lines)")
    ap.add_argument("-o", "--out", default="",
                    help="write the Perfetto trace JSON here "
                         "(default: <jsonl>.perfetto.json)")
    ap.add_argument("--summary", action="store_true",
                    help="print per-span aggregates and counters")
    a = ap.parse_args(argv)

    events = load_jsonl(a.jsonl)
    out = a.out or (a.jsonl + ".perfetto.json")
    n = export_perfetto(out, events=events)
    print(f"{out}: {n} trace events from {len(events)} records",
          file=sys.stderr)

    if a.summary:
        agg: dict = {}
        for e in events:
            if e.get("ev") == "span":
                name = e.get("name", "?")
                tot, cnt = agg.get(name, (0, 0))
                agg[name] = (tot + e.get("dur_us", 0), cnt + 1)
        counters = merged_final_counters(events, include_gauges=True)
        for name in sorted(agg, key=lambda n_: -agg[n_][0]):
            tot, cnt = agg[name]
            print(json.dumps({"span": name, "total_ms":
                              round(tot / 1000.0, 3), "count": cnt}))
        if counters:
            print(json.dumps({"counters": counters}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
