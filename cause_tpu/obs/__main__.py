"""CLI: convert an obs JSONL event stream to a Perfetto trace.

    python -m cause_tpu.obs events.jsonl -o trace.json

Open the output at https://ui.perfetto.dev (or chrome://tracing).
With ``--summary`` it also prints per-span-name aggregate wall times
and the final counter values — the quick look before reaching for the
viewer.
"""

from __future__ import annotations

import argparse
import json
import sys

from .perfetto import export_perfetto, load_jsonl


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs",
        description="Convert obs JSONL events to a Perfetto/Chrome "
                    "trace (and/or print a summary).")
    ap.add_argument("jsonl", help="obs event file (JSON lines)")
    ap.add_argument("-o", "--out", default="",
                    help="write the Perfetto trace JSON here "
                         "(default: <jsonl>.perfetto.json)")
    ap.add_argument("--summary", action="store_true",
                    help="print per-span aggregates and counters")
    a = ap.parse_args(argv)

    events = load_jsonl(a.jsonl)
    out = a.out or (a.jsonl + ".perfetto.json")
    n = export_perfetto(out, events=events)
    print(f"{out}: {n} trace events from {len(events)} records",
          file=sys.stderr)

    if a.summary:
        agg: dict = {}
        # counter snapshots are cumulative PER PROCESS: keep each
        # pid's last snapshot and sum across pids (a shared sidecar
        # interleaves parent + abandoned-child flushes — last-wins
        # across pids would report whichever process flushed last)
        per_pid: dict = {}
        for e in events:
            if e.get("ev") == "span":
                name = e.get("name", "?")
                tot, cnt = agg.get(name, (0, 0))
                agg[name] = (tot + e.get("dur_us", 0), cnt + 1)
            elif e.get("ev") == "counters":
                merged = dict(e.get("counters") or {})
                merged.update(e.get("gauges") or {})
                per_pid[e.get("pid", 0)] = merged
        counters: dict = {}
        for snap in per_pid.values():
            for name, value in snap.items():
                counters[name] = counters.get(name, 0) + value
        for name in sorted(agg, key=lambda n_: -agg[n_][0]):
            tot, cnt = agg[name]
            print(json.dumps({"span": name, "total_ms":
                              round(tot / 1000.0, 3), "count": cnt}))
        if counters:
            print(json.dumps({"counters": counters}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
