"""The stage profiler: the jaxw5 ``stage=`` prefix ladder as obs spans.

``scripts/probe_v5_stages.py`` and ``scripts/profile_phases.py`` each
carried a private compile-warm-then-median timing loop whose numbers
lived only in stdout. This module is the ONE timing loop
(``timed_median``) plus the v5 cumulative-prefix ladder
(``run_v5_stage_ladder``), both recording through obs — every warm
compile, every rep, and every stage delta lands in the same
JSONL/Perfetto stream as the bench and wave spans, so a stage
attribution is a trace artifact, not a scrollback line.

Stages (jaxw5 early returns, each checksumming its live outputs so XLA
cannot DCE the prefix): A segment ordering + explode/dedupe; B token
construction; C token sort + dedupe; D cause resolution; E token-width
ranking + kills; FULL adds lane expansion + visibility.

CLI: ``python -m cause_tpu.obs stages [--smoke] [--reps N]
[--allstream] [--shape B,NB,ND,CAP] [--obs-out PATH]`` — enables obs
for the run and streams the ladder's spans to the sidecar.

Unlike the rest of ``cause_tpu.obs`` this module imports jax/numpy
(lazily, inside the entry points): it exists to *run* kernels. It is
deliberately NOT imported by ``cause_tpu.obs.__init__``.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Callable, List, Optional, Tuple

from . import core

__all__ = ["STAGES", "timed_median", "run_v5_stage_ladder", "main"]

# the jaxw5 prefix ladder, cumulative; None = the full kernel
STAGES = ("A", "B", "C", "D", "E", None)


def timed_median(name: str, fn: Callable, *args,
                 reps: int = 3) -> Tuple[object, float, List[float]]:
    """The shared timing loop: compile + warm once (under a
    ``stages.warm`` span — on a fresh program this IS the XLA compile
    wall time), then ``reps`` timed executions (``stages.rep`` spans),
    forcing each with a host fetch (``np.asarray``; the only reliable
    sync on the axon tunnel). Returns (last output, median ms, all
    samples). Works identically with obs off — the spans are no-ops
    and the perf_counter numbers remain."""
    import numpy as np

    with core.span("stages.warm", program=name):
        out = np.asarray(fn(*args))
    samples: List[float] = []
    for i in range(max(1, int(reps))):
        with core.span("stages.rep", program=name, rep=i):
            t0 = time.perf_counter()
            out = np.asarray(fn(*args))
            samples.append((time.perf_counter() - t0) * 1000.0)
    p50 = float(np.median(samples))
    core.event("stages.result", program=name, p50_ms=round(p50, 3),
               samples=[round(s, 3) for s in samples])
    return out, p50, samples


_ALLSTREAM_FLIPS = (
    ("CAUSE_TPU_SORT", "bitonic"),  # causelint: disable=TID002 -- probe's own A/B flip, deliberately restated
    ("CAUSE_TPU_GATHER", "rowgather"),  # causelint: disable=TID002 -- probe's own A/B flip, deliberately restated
    ("CAUSE_TPU_SEARCH", "matrix"),  # causelint: disable=TID002 -- probe's own A/B flip, deliberately restated
)


def _apply_allstream() -> dict:
    """The stage probe's deliberate A/B flip of its own config (NOT
    the beststream candidate — the stage probe wants the bitonic sort
    specifically), so the restated names are intentional. Returns the
    prior values so the ladder can restore them: unlike the old probe
    script (bounded by process exit), ``run_v5_stage_ladder`` is a
    library API — leaking the flips would silently re-key every later
    ``merge_wave_scalar`` in the same interpreter."""
    saved = {k: os.environ.get(k) for k, _ in _ALLSTREAM_FLIPS}  # causelint: disable=OBS001 -- saving the probe's own A/B keys for restore; obs-off never reaches the ladder
    for k, v in _ALLSTREAM_FLIPS:
        os.environ[k] = v  # causelint: disable=TID002,OBS001 -- probe flips its own A/B config; obs-off never reaches the ladder
    return saved


def _restore_env(saved: dict) -> None:
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)  # causelint: disable=OBS001 -- restoring the pre-ladder A/B config; obs-off never reaches the ladder
        else:
            os.environ[k] = v  # causelint: disable=TID002,OBS001 -- restoring the pre-ladder A/B config


def run_v5_stage_ladder(smoke: bool = False, reps: int = 3,
                        allstream: bool = False,
                        shape: Optional[Tuple[int, int, int, int]] = None,
                        echo: Callable[[str], None] = None) -> List[dict]:
    """Time the kernel truncated at each stage checkpoint at the
    north-star bench shape (or ``--smoke`` / an explicit ``shape``)
    and report per-stage increments — the measurement the isolated
    re-implementations in probe_v5.py can't give: the *actual*
    compiled prefix cost, gathers, vmap batching and all.

    Every stage lands as a ``stages.prefix`` obs event (stage, p50,
    delta) on top of the per-rep spans from ``timed_median``, keyed to
    the platform and switch snapshot like everything else in the
    stream. Returns the stage dicts in ladder order."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from .. import benchgen
    from ..benchgen import LANE_KEYS5, enable_compile_cache
    from ..weaver.jaxw5 import merge_weave_kernel_v5

    def _echo(line: str) -> None:
        (echo or (lambda s: print(s, flush=True)))(line)

    enable_compile_cache()
    saved_env = _apply_allstream() if allstream else {}
    try:
        if shape is not None:
            B, NB, ND, CAP = shape
        elif smoke:
            B, NB, ND, CAP = 8, 800, 100, 1024
        else:
            B, NB, ND, CAP = 1024, 9_000, 1_000, 10_240

        platform = jax.devices()[0].platform
        core.set_platform(platform)
        _echo(f"platform={platform} B={B} cap={CAP}")
        with core.span("stages.marshal", B=B, cap=CAP):
            batch = benchgen.batched_pair_lanes(
                n_replicas=B, n_base=NB, n_div=ND, capacity=CAP,
                hide_every=8,
            )
            v5 = benchgen.batched_v5_inputs(batch, CAP)
            u = benchgen.v5_token_budget(v5)
        _echo(f"u_budget={u} S={v5['sg_len'].shape[1]} "
              f"N={v5['hi'].shape[1]}")
        dev = {k: jax.device_put(v5[k]) for k in LANE_KEYS5}
        args = [dev[k] for k in LANE_KEYS5]

        progs = {}

        def prog_for(stage):
            if stage not in progs:
                def row(*xs):
                    out = merge_weave_kernel_v5(*xs, u_max=u, k_max=u,
                                                stage=stage)
                    if stage is None:
                        rank, visible, conflict, overflow = out
                        return (jnp.sum(rank.astype(jnp.float32))
                                + jnp.sum(visible.astype(jnp.float32))
                                + conflict.astype(jnp.float32)
                                + overflow.astype(jnp.float32))
                    return out

                progs[stage] = jax.jit(
                    lambda *xs: jnp.sum(jax.vmap(row)(*xs))
                )
            return progs[stage]

        results: List[dict] = []
        prev = 0.0
        for stage in STAGES:
            name = stage or "FULL"
            p = prog_for(stage)
            try:
                _, med, _samples = timed_median(
                    f"stages.prefix.{name}", p, *args, reps=reps)
            except Exception as e:  # noqa: BLE001 - keep probing
                _echo(f"prefix->{name} FAILED "
                      f"{type(e).__name__}: "
                      f"{str(e).splitlines()[0][:120]}")
                core.event("stages.prefix", stage=name,
                           error=str(e)[:200])
                continue
            _echo(f"prefix->{name:4s} {med:9.1f} ms   "
                  f"(+{med - prev:8.1f} ms)")
            core.event("stages.prefix", stage=name,
                       p50_ms=round(med, 3),
                       delta_ms=round(med - prev, 3))
            results.append({"stage": name, "p50_ms": med,
                            "delta_ms": med - prev})
            prev = med
        core.flush()
        return results
    finally:
        _restore_env(saved_env)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.obs stages",
        description="Cumulative-prefix stage timing of the v5 kernel "
                    "through obs spans (the probe_v5_stages.py ladder "
                    "as a first-class obs artifact).")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--allstream", action="store_true",
                    help="profile the streaming configuration "
                         "(rowgather + bitonic + matrix search)")
    ap.add_argument("--shape", default="",
                    help="explicit B,n_base,n_div,capacity (overrides "
                         "--smoke; e.g. 2,30,6,64 for a tiny run)")
    ap.add_argument("--obs-out", default="",
                    help="stream the run's obs events to this JSONL "
                         "path (default: record into the ring only)")
    a = ap.parse_args(argv)
    shape = None
    if a.shape:
        try:
            parts = tuple(int(x) for x in a.shape.split(","))
            if len(parts) != 4:
                raise ValueError
            shape = parts
        except ValueError:
            ap.error("--shape wants B,n_base,n_div,capacity")
    core.configure(enabled=True,
                   out=a.obs_out if a.obs_out else None)
    results = run_v5_stage_ladder(smoke=a.smoke, reps=a.reps,
                                  allstream=a.allstream, shape=shape)
    if a.obs_out:
        print(f"stages: obs events -> {a.obs_out}", file=sys.stderr)
    return 0 if results else 1


if __name__ == "__main__":
    raise SystemExit(main())
