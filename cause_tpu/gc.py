"""Tombstone / weave GC: drop nodes that can no longer affect what a
reader sees.

The reference ROADMAPS this and ships nothing ("Garbage collect
hidden nodes ... in the weave", reference README.md:254): reads and
writes stay O(n) over every tombstone forever. ``compact`` is that
wish, built: a new tree whose node bag keeps only

- the nodes the current weave renders (the ``hide_q`` scan for lists,
  the per-key LWW winner for maps — reference list.cljc:48-55,
  map.cljc:47-59 semantics);
- the transitive CAUSE closure of anything kept (a kept node's cause
  chain must survive or reconstitution fails cause-must-exist);
- every special (hide / h.hide / h.show) targeting a kept node, to a
  fixpoint — a kept-but-hidden ancestor must keep its hide marker or
  it would spring back to visibility.

Everything else — tombstoned runs, their hide markers, overwritten
LWW values, history specials whose effects are fully materialized —
is dropped, and the caches are reconstituted from the surviving bag
(the ordinary ``refresh_caches`` path, so the compacted tree is a
plain tree: serde, merge, sync, device weavers all Just Work).

What reclaims and what cannot — two interior-hole rules compose:

- the RGA skeleton reality: list causes chain through predecessors,
  so an interior tombstone that visible text was typed after remains
  as cause-chain skeleton — removing it would dangle descendants;
- the SYNC-soundness rule (found by the round-5 soak, seed 700216):
  only per-site yarn SUFFIXES may drop. An interior yarn hole breaks
  the per-site prefix property sync deltas assume — a resend can
  carry a victim whose marker (another site's interior hole) is never
  resent, resurrecting the deletion after an ordinary sync with no
  cause-must-exist failure to trigger the fallback. Suffix-only
  dropping makes victim and marker travel together.

What GCs wholesale under both rules: hidden TAILS (delete-at-end:
61/91 nodes measured), undone branches, and any site whose entire
remaining contribution is obsolete (a map writer fully superseded by
later sites: its whole yarn drops). What stays: interior deletions,
and same-site LWW churn (every overwritten write sits below the
site's newest kept write — sound, and honestly 0 reclaimed).

Safety valve: compaction re-renders the compacted tree and compares
EDN with the original; any divergence (an exotic special interleaving
the conservative rules miss) returns the ORIGINAL handle unchanged —
compact() is always LOCALLY semantics-preserving, best-effort on
size.

Fleet-safety contract — the classic CRDT tombstone-GC precondition:
dropping a deletion (victim + hide marker) is only safe once EVERY
peer has seen the deletion. A peer that holds the victim but not its
hide marker would merge the victim back VISIBLY, and because the
victim's cause can survive compaction, that merge passes
cause-must-exist — no full-bag fallback fires, and if this replica
was the deletion's last carrier it is lost fleet-wide. Two ways to
hold the precondition:

- ``compact(handle, stable_vv=...)`` — the enforced form: pass the
  STABILITY FRONTIER (pointwise minimum of every peer's version
  vector — ``stability_frontier``; vectors come from
  ``sync.version_vector`` exchanges). Nodes above the frontier are
  never dropped, so any state a peer might still be missing
  survives, marker and all.
- ``compact(handle)`` — the quiesce form: caller asserts all peers
  are fully synced (single replica, checkpoint barrier, cold
  storage). The reference's "at rest storage is reduced" framing
  (reference README.md:19).

What the sync fallback DOES cover: a peer's delta that references a
dropped node as a CAUSE fails cause-must-exist and triggers the
full-bag frame (sync.py module docstring), re-importing the dropped
region — re-sync cost, not data loss. The frontier exists for the
case the fallback cannot see (surviving cause, missing marker).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from . import obs
from .collections import shared as s
from .collections.clist import hide_q, weave as list_weave
from .collections.cmap import BLANK, active_node, weave as map_weave
from .ids import ROOT_ID, is_id

__all__ = ["compact", "compact_stats", "stability_frontier"]


def stability_frontier(*version_vectors: dict) -> Dict[str, list]:
    """The pointwise minimum of peer version vectors (sync.py's
    ``{site: [ts, tx]}`` shape, compared lexicographically): every
    peer holds every site's nodes up to its frontier entry. A site
    absent from ANY peer's vector is absent from the frontier
    (nothing of that site is fleet-stable yet). Feed the result to
    ``compact(handle, stable_vv=...)``."""
    if not version_vectors:
        return {}
    out = {k: list(v) for k, v in version_vectors[0].items()}
    for vv in version_vectors[1:]:
        for site in list(out):
            if site not in vv:
                del out[site]
            else:
                out[site] = min(out[site], list(vv[site]))
    return out


def _closure(nodes: dict, keep: Set[tuple]) -> Set[tuple]:
    """Cause ancestors of everything kept, plus specials targeting
    kept nodes, to a fixpoint."""
    # function-level: arrays drags numpy in, and `import cause_tpu`
    # (hence the jax-free, numpy-free causelint CLI and bench.py's
    # parent process) must stay stdlib-importable
    from .weaver.arrays import vclass_of

    keep = set(keep)
    # specials grouped by (id-)target once, so the fixpoint loop is
    # O(kept + specials) instead of O(kept * nodes)
    by_target: Dict[tuple, list] = {}
    for nid, (cause, value) in nodes.items():
        if vclass_of(value) > 0 and is_id(cause):
            by_target.setdefault(tuple(cause), []).append(nid)

    stack = list(keep)
    while stack:
        nid = stack.pop()
        cause = nodes[nid][0]
        if is_id(cause):
            cid = tuple(cause)
            if cid != ROOT_ID and cid in nodes and cid not in keep:
                keep.add(cid)
                stack.append(cid)
        for spec in by_target.get(nid, ()):
            if spec not in keep:
                keep.add(spec)
                stack.append(spec)
    return keep


def _rebuild(handle, ct, new_nodes: dict, weave_fn):
    """Reconstitute a tree from the surviving bag (fresh caches), on
    the same uuid/site/lamport so minting and merging continue
    unchanged."""
    fresh = ct.evolve(nodes=new_nodes, yarns={},
                      weave=type(ct.weave)() if isinstance(ct.weave,
                                                          dict) else [])
    fresh = s.spin(fresh)
    fresh = weave_fn(fresh)
    return type(handle)(fresh)


def _list_kept(handle) -> Set[tuple]:
    wv = list(handle.get_weave())
    keep: Set[tuple] = set()
    for i, n in enumerate(wv):
        if n[0] == ROOT_ID:
            continue
        nxt = wv[i + 1] if i + 1 < len(wv) else None
        if not hide_q(n, nxt):
            keep.add(n[0])
    return keep


def _map_kept(handle) -> Set[tuple]:
    keep: Set[tuple] = set()
    for k, wv in handle.get_weave().items():
        win = active_node(k, wv)
        if win is not BLANK and win[0] != ROOT_ID:
            keep.add(win[0])
    return keep


def compact_stats(before, after) -> dict:
    """The evidence line: node counts around a compaction."""
    nb, na = len(before.ct.nodes), len(after.ct.nodes)
    return {"nodes_before": nb, "nodes_after": na,
            "dropped": nb - na}


def compact(handle, stable_vv: Optional[dict] = None):
    """GC a CausalList or CausalMap handle (see module docstring).
    Returns a new handle of the same type — or the ORIGINAL handle
    when compaction finds nothing to drop or the safety valve
    declines it.

    ``stable_vv``: the fleet stability frontier (``{site: [ts,
    tx]}``, ``stability_frontier`` over peer ``sync.version_vector``
    outputs). When given, nodes ABOVE the frontier ((ts, tx) newer
    than the site's entry, or a site absent from it) are exempt from
    dropping — the fleet-safe form. When None, the caller asserts a
    quiesce point."""
    from .collections.clist import CausalList
    from .collections.cmap import CausalMap

    ct = getattr(handle, "ct", None)
    if ct is None:
        raise s.CausalError(
            "compact() GCs CausalList / CausalMap handles; compact "
            "base collections individually",
            {"causes": {"type-missmatch"},
             "type": type(handle).__name__},
        )
    if isinstance(handle, CausalList):
        kept0, weave_fn = _list_kept(handle), list_weave
    elif isinstance(handle, CausalMap):
        kept0, weave_fn = _map_kept(handle), map_weave
    else:
        raise s.CausalError(
            "compact() GCs CausalList / CausalMap handles; compact "
            "base collections individually",
            {"causes": {"type-missmatch"},
             "type": getattr(ct, "type", type(handle).__name__)},
        )

    nodes = dict(ct.nodes)
    keep = _closure(nodes, kept0)
    if stable_vv is not None:
        # fleet-safety frontier: anything a peer might not have seen
        # (newer than the frontier) must survive, and keeping a hidden
        # node re-pulls its markers/ancestors — re-run the closure
        # over the additions
        unstable = {
            nid for nid in nodes
            if nid != ROOT_ID
            and [nid[0], nid[2]] > list(
                stable_vv.get(nid[1], [-1, -1]))
        }
        if unstable - keep:
            keep = _closure(nodes, keep | unstable)

    # sync-soundness (round-5 soak catch, seed 700216): only per-site
    # yarn SUFFIXES may drop. An interior hole — a dropped node below
    # a surviving same-site node — breaks the per-site prefix property
    # the sync deltas assume: the victim's site tip can regress (so a
    # peer resends the victim) while the marker's site tip survives
    # (so the marker is never resent), and the deletion resurrects
    # VISIBLY after an ordinary sync, with no cause-must-exist failure
    # to trigger the full-bag fallback. Suffix-only dropping makes
    # victim and marker travel together in every resend. Fixpoint:
    # re-kept nodes pull their markers/ancestors, which can raise a
    # site's kept maximum again.
    by_site: Dict[str, list] = {}
    for nid in nodes:
        if nid != ROOT_ID:
            by_site.setdefault(nid[1], []).append(nid)
    for ids in by_site.values():
        ids.sort()
    while True:
        pre = len(keep)
        for ids in by_site.values():
            mx = None
            for nid in reversed(ids):
                if nid in keep:
                    mx = nid
                    break
            if mx is not None:
                for nid in ids:
                    if nid > mx:
                        break
                    keep.add(nid)
        keep = _closure(nodes, keep)
        if len(keep) == pre:
            break

    if ROOT_ID in nodes:
        keep.add(ROOT_ID)  # the sentinel head always survives
    if len(keep) >= len(nodes):
        if obs.enabled():
            obs.semantic.gc_compacted(len(nodes), 0,
                                      frontier=stable_vv is not None,
                                      uuid=ct.uuid)
        return handle  # nothing to drop
    new_nodes = {nid: nodes[nid] for nid in keep}
    out = _rebuild(handle, ct, new_nodes, weave_fn)

    # safety valve: semantics must be untouched, or we decline
    from . import causal_to_edn

    if causal_to_edn(out) != causal_to_edn(handle):
        # pragma: no cover - conservative rules cover
        if obs.enabled():
            obs.semantic.gc_compacted(len(nodes), 0, refused=True,
                                      frontier=stable_vv is not None,
                                      uuid=ct.uuid)
        return handle
    if obs.enabled():
        obs.semantic.gc_compacted(len(nodes), len(nodes) - len(keep),
                                  frontier=stable_vv is not None,
                                  uuid=ct.uuid)
    return out
