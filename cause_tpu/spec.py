"""Structural validation of causal values — the spec schema.

The reference types its data with clojure.spec (reference:
src/causal/collections/shared.cljc:20-73): ids, tx-ids, nodes, special
values, yarns, weaves, and the causal-tree map itself. cause_tpu keeps
the same shapes as plain tuples/dicts; this module is the runnable
schema — predicates for every spec plus a whole-tree validator used by
tests and debugging (not on hot paths).

The validators check structure AND the core invariants the reference
encodes in prose and specs:

- ids are ``(nat-int ts, site-id string, nat-int tx-index)`` with the
  root exactly ``(0, "0", 0)``;
- yarns are per-site, strictly time-sorted, and consistent with the
  canonical ``nodes`` store;
- the weave holds exactly the store's nodes (a permutation for lists; a
  per-key partition of mini-weaves for maps, each rooted at the
  sentinel);
- every id-shaped cause resolves inside the tree.
"""

from __future__ import annotations

from typing import List

from .collections import shared as s
from .ids import ROOT_ID, ROOT_NODE, SITE_ID_LENGTH, is_id, is_key

__all__ = [
    "valid_site_id",
    "valid_id",
    "valid_tx_id",
    "valid_node",
    "valid_value",
    "validate_tree",
    "explain_tree",
]


def valid_site_id(x) -> bool:
    """Site ids are 13-char strings, or "0" for the root site
    (shared.cljc:25,35-38)."""
    return isinstance(x, str) and (x == "0" or len(x) == SITE_ID_LENGTH)


def valid_id(x) -> bool:
    """``(lamport-ts, site-id, tx-index)`` (shared.cljc:40)."""
    return is_id(x) and valid_site_id(x[1])


def valid_tx_id(x) -> bool:
    """``(lamport-ts, site-id)`` (shared.cljc:41)."""
    return (
        isinstance(x, tuple)
        and len(x) == 2
        and isinstance(x[0], int)
        and x[0] >= 0
        and valid_site_id(x[1])
    )


def valid_value(x) -> bool:
    """Node values: any EDN-ish value, a special, or a nested ref
    (shared.cljc:46-52). Everything hashable-or-plain passes; this
    predicate exists for symmetry and future tightening."""
    return True


def valid_node(x) -> bool:
    """``(id, cause, value)`` where cause is an id or a key
    (shared.cljc:55-57)."""
    return (
        isinstance(x, tuple)
        and len(x) == 3
        and valid_id(x[0])
        and (valid_id(x[1]) or is_key(x[1]) or x[1] is None)
    )


def explain_tree(ct) -> List[str]:
    """All invariant violations of a causal tree (empty = valid). The
    runnable equivalent of ``s/explain ::causal-tree``."""
    problems: List[str] = []

    from .collections.ccounter import COUNTER_TYPE
    from .collections.cset import SET_TYPE

    if ct.type not in (s.LIST_TYPE, s.MAP_TYPE, SET_TYPE, COUNTER_TYPE):
        problems.append(f"unknown tree type {ct.type!r}")
        return problems
    if not isinstance(ct.lamport_ts, int) or ct.lamport_ts < 0:
        problems.append(f"bad lamport-ts {ct.lamport_ts!r}")
    if not isinstance(ct.uuid, str) or not ct.uuid:
        problems.append(f"bad uuid {ct.uuid!r}")
    if not valid_site_id(ct.site_id):
        problems.append(f"bad site-id {ct.site_id!r}")

    # set/counter trees are list-shaped (root sentinel, id causes,
    # flat list weave) — they share every list invariant
    is_list = ct.type in (s.LIST_TYPE, SET_TYPE, COUNTER_TYPE)

    # ---- canonical store
    for nid, body in ct.nodes.items():
        if not valid_id(nid):
            problems.append(f"bad id {nid!r}")
            continue
        if not isinstance(body, tuple) or len(body) != 2:
            problems.append(f"bad node body for {nid!r}")
            continue
        cause = body[0]
        if nid == ROOT_ID:
            continue
        if is_id(cause) and tuple(cause) not in ct.nodes:
            problems.append(f"dangling cause {cause!r} of {nid!r}")
        if is_list and not is_id(cause):
            problems.append(f"list node {nid!r} has non-id cause {cause!r}")
        if nid[0] > ct.lamport_ts:
            problems.append(
                f"node {nid!r} is newer than the tree clock {ct.lamport_ts}"
            )
    if is_list and ROOT_ID not in ct.nodes:
        problems.append("list tree is missing the root sentinel")

    # ---- yarns: per-site, strictly ascending, consistent with nodes
    yarn_ids = set()
    for site, yarn in ct.yarns.items():
        prev = None
        for n in yarn:
            if n[0][1] != site:
                problems.append(f"yarn {site!r} holds foreign node {n[0]!r}")
            if prev is not None and not (prev < n[0]):
                problems.append(f"yarn {site!r} is not time-sorted at {n[0]!r}")
            prev = n[0]
            if n[0] not in ct.nodes or ct.nodes[n[0]] != (n[1], n[2]):
                problems.append(f"yarn node {n[0]!r} disagrees with the store")
            yarn_ids.add(n[0])
    if yarn_ids != set(ct.nodes):
        problems.append("yarns and store hold different node sets")

    # ---- weave: same node set as the store, correct shape
    if is_list:
        if not isinstance(ct.weave, list):
            problems.append("list weave is not a list")
        else:
            weave_ids = [n[0] for n in ct.weave]
            if sorted(weave_ids) != sorted(ct.nodes):
                problems.append("list weave is not a permutation of the store")
            elif ct.weave and ct.weave[0] != ROOT_NODE:
                problems.append("list weave does not start at the root")
            else:
                for n in ct.weave[1:]:
                    if ct.nodes.get(n[0]) != (n[1], n[2]):
                        problems.append(
                            f"weave node {n[0]!r} disagrees with the store"
                        )
    else:
        if not isinstance(ct.weave, dict):
            problems.append("map weave is not a dict of key-weaves")
        else:
            woven = []
            for k, kw in ct.weave.items():
                if not kw or kw[0] != ROOT_NODE:
                    problems.append(f"key-weave {k!r} missing its root")
                    continue
                woven.extend(n[0] for n in kw[1:])
                for n in kw[1:]:
                    body = ct.nodes.get(n[0])
                    # in-weave causes are rewritten to the root for
                    # key-caused nodes (map.cljc:77): a root-caused
                    # entry must be filed under its store key, an
                    # id-caused one must keep its store cause; values
                    # must agree either way
                    if (
                        body is None
                        or body[1] != n[2]
                        or (n[1] == ROOT_ID and body[0] != k)
                        or (n[1] != ROOT_ID and body[0] != n[1])
                    ):
                        problems.append(
                            f"key-weave node {n[0]!r} disagrees with the store"
                        )
            if sorted(woven) != sorted(ct.nodes):
                problems.append("map weave does not partition the store")

    return problems


def validate_tree(ct) -> bool:
    """True iff the tree satisfies every invariant; raise-free."""
    return not explain_tree(ct)
