"""Node model: ids, special values, root sentinel, uid generation.

This is the cause_tpu equivalent of the reference spec schema
(reference: src/causal/collections/shared.cljc:20-73 and src/causal/util.cljc:12-23):

- an **id** is a ``(lamport_ts, site_id, tx_index)`` triple
  (shared.cljc:40); ``lamport_ts`` and ``tx_index`` are non-negative ints,
  ``site_id`` is a 13-char random string or ``"0"`` (shared.cljc:25,35-38).
  The total order over ids is plain lexicographic tuple comparison, which is
  exactly the reference's ``<<`` / ``compare`` order (util.cljc:4-10).
- a **tx-id** is the first two fields ``(lamport_ts, site_id)``
  (shared.cljc:41); ``tx_index`` is the within-transaction tie-breaker.
- a **node** is an ``(id, cause, value)`` triple (shared.cljc:55-57).
  ``cause`` is an id (lists) or a key (maps); ``value`` is any
  EDN-like Python value, a special, or a nested collection ref.
- **special values** ``HIDE``/``H_HIDE``/``H_SHOW`` (shared.cljc:21) are the
  tombstone / history-hide / history-show markers. Specials do not compose:
  hiding a hide is not a show (reference: src/causal/core.cljc:13-14).
- the **root** ``ROOT_ID = (0, "0", 0)`` / ``ROOT_NODE`` (shared.cljc:22-23)
  is the sentinel head of every list weave.

Everything here is host-side. On device (see cause_tpu.weaver.arrays) ids
become structured int32 lanes with site ids interned to order-preserving
integer ranks, and values are reduced to a value-class lane.
"""

from __future__ import annotations

import random

__all__ = [
    "Keyword",
    "K",
    "Special",
    "HIDE",
    "H_HIDE",
    "H_SHOW",
    "SPECIALS",
    "is_special",
    "ROOT_ID",
    "ROOT_NODE",
    "UUID_LENGTH",
    "SITE_ID_LENGTH",
    "is_id",
    "is_key",
    "node",
    "node_from_kv",
    "get_tx",
    "new_uid",
    "new_site_id",
]


class Special:
    """One of the three special causal markers.

    Interned singletons; identity comparison is safe. Mirrors the
    reference special keywords :causal/hide, :causal/h.hide,
    :causal/h.show (shared.cljc:21).
    """

    __slots__ = ("name",)
    _interned: dict = {}
    _allowed = ("hide", "h.hide", "h.show")

    def __new__(cls, name: str) -> "Special":
        if name not in cls._allowed:
            raise ValueError(f"unknown special keyword: {name!r}")
        inst = cls._interned.get(name)
        if inst is None:
            inst = super().__new__(cls)
            object.__setattr__(inst, "name", name)
            cls._interned[name] = inst
        return inst

    def __setattr__(self, *a):  # immutable
        raise AttributeError("Special values are immutable")

    def __repr__(self) -> str:
        return f":causal/{self.name}"

    def __reduce__(self):  # pickle round-trips to the interned instance
        return (Special, (self.name,))

    # Specials sort after every non-special in no particular user-visible
    # order; they only need a *stable* order among themselves for the
    # host-side sorted containers (yarns never tie on id, so this is a
    # belt-and-braces fallback, never semantics).
    def __lt__(self, other):
        if isinstance(other, Special):
            return self.name < other.name
        return NotImplemented


class Keyword:
    """An interned symbolic key, the Python stand-in for EDN keywords.

    Map keys in the reference are keywords or strings
    (shared.cljc:42-43); the distinction matters to the CausalBase
    flattener, where a *string* inside a list explodes into char nodes
    while a keyword is stored whole (base/core.cljc:145-147). Plain
    Python strings also work as keys everywhere; use Keyword when you
    need the keyword behavior (or keyword-looking output).
    """

    __slots__ = ("name",)
    _interned: dict = {}

    def __new__(cls, name: str) -> "Keyword":
        inst = cls._interned.get(name)
        if inst is None:
            inst = super().__new__(cls)
            object.__setattr__(inst, "name", name)
            cls._interned[name] = inst
        return inst

    def __setattr__(self, *a):
        raise AttributeError("Keywords are immutable")

    def __repr__(self) -> str:
        return f":{self.name}"

    def __reduce__(self):
        return (Keyword, (self.name,))

    def __lt__(self, other):
        if isinstance(other, Keyword):
            return self.name < other.name
        return NotImplemented


K = Keyword


HIDE = Special("hide")
H_HIDE = Special("h.hide")
H_SHOW = Special("h.show")
SPECIALS = frozenset((HIDE, H_HIDE, H_SHOW))


def is_special(v) -> bool:
    """True for the three special markers (shared.cljc:21)."""
    return type(v) is Special


ROOT_ID = (0, "0", 0)
ROOT_NODE = (ROOT_ID, None, None)

UUID_LENGTH = 21
SITE_ID_LENGTH = 13

# Alphabet chosen so uids are valid identifier-ish tokens; first char is
# never a digit (reference: src/causal/util.cljc:12-13).
_FIRST_CHAR_ALPHABET = "ABCDEFGHIJKLMNOPQRSTUVWXYZ_abcdefghijklmnopqrstuvwxyz"
_ID_ALPHABET = "0123456789" + _FIRST_CHAR_ALPHABET

_rng = random.Random()


def new_uid(length: int = UUID_LENGTH) -> str:
    """Globally unique id string (reference: util.cljc:15-23)."""
    first = _rng.choice(_FIRST_CHAR_ALPHABET)
    rest = "".join(_rng.choice(_ID_ALPHABET) for _ in range(length - 1))
    return first + rest


def new_site_id() -> str:
    """13-char site identifier (shared.cljc:25,75)."""
    return new_uid(SITE_ID_LENGTH)


def is_id(x) -> bool:
    """Structural check for an id triple (shared.cljc:40).

    Like the reference's ``spec/valid? ::id`` this is a structural
    predicate, so a map key that happens to be an (int, str, int) triple
    is indistinguishable from an id — same ambiguity as the reference.
    """
    return (
        type(x) is tuple
        and len(x) == 3
        and type(x[0]) is int
        and x[0] >= 0
        and type(x[1]) is str
        and type(x[2]) is int
        and x[2] >= 0
    )


def is_key(x) -> bool:
    """Structural check for a map key cause (shared.cljc:42-43).

    The reference allows keywords and strings as map keys; we allow any
    hashable non-id value, with strings playing the keyword role.
    """
    return not is_id(x)


def node(lamport_ts: int, site_id: str, *rest):
    """Create a node for insertion into a causal collection.

    Mirrors the 4- and 5-arity forms of the reference ``new-node``
    (shared.cljc:77-98)::

        node(ts, site, cause, value)            # tx_index defaults to 0
        node(ts, site, tx_index, cause, value)
    """
    if len(rest) == 2:
        tx_index, (cause, value) = 0, rest
    elif len(rest) == 3:
        tx_index, cause, value = rest
    else:
        raise TypeError("node() takes (ts, site, cause, value) or (ts, site, tx, cause, value)")
    nid = (lamport_ts, site_id, tx_index)
    if cause == nid:
        raise ValueError("a node's cause cannot equal its own id")
    return (nid, cause, value)


def node_from_kv(kv):
    """Map a ``(id, (cause, value))`` entry of the nodes store back to a
    node triple (the 1-arity reference ``new-node``, shared.cljc:79-80)."""
    nid, (cause, value) = kv
    return (nid, cause, value)


def get_tx(n):
    """The ``(lamport_ts, site_id)`` transaction tuple of a node
    (shared.cljc:100-102)."""
    return n[0][:2]
