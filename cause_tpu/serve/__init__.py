"""The resilient sync service — the long-lived serving loop, designed
failure-first (ROADMAP item 4; SafarDB's offload split, arXiv:
2603.08003: the accelerator owns merge, the host owns admission and
ordering of replicated-data-type ops).

Everything below this package is batch-mode machinery the previous
rounds built and certified: delta-native waves (PR 7), the merge tree
(PR 8), the lag SLO tracer (PR 9), the live feed (PR 10), and the
fault substrate (PR 11: chaos engine, recovery ladder, checkpoint/
restore). This package is the service those layers were built FOR —
and its design question is explicitly the robustness one: what happens
when the OFFERED LOAD, not the operator, decides what happens next?

- :mod:`cause_tpu.serve.ingest` — bounded-queue admission: per-site
  deltas validated at the boundary (``sync.validate_node_items`` —
  poison never enters the queue; quarantine semantics preserved),
  coalesced per tenant, journaled WRITE-AHEAD (admitted ops are never
  lost), with a declared three-rung shed ladder (defer cold tenants →
  reject-with-retry-after → drop oldest **unadmitted**) where every
  shed is an evidenced ``serve.shed`` event;
- :mod:`cause_tpu.serve.controller` — the adaptive T_batch controller:
  the PERF.md Round-9 inversion
  ``p99 ≈ T_batch + floor×dispatches + slope×batch_ops`` solved for
  ``T_batch``, driven by the ``live.snapshot`` feedback term (sliding
  SLO burn) and the ``fleet.token_headroom`` capacity term, clamped
  and hysteresis-damped so alert flapping cannot oscillate the batch
  size;
- :mod:`cause_tpu.serve.residency` — lanecache LRU residency for hot
  documents: cold tenants spill to host as checkpoint-grade packs
  (PR 11's serde path) and a touch restores GATED on digest
  bit-identity, so a zipf-hot tenant population larger than device
  memory degrades to re-upload cost, never to wrong answers;
- :mod:`cause_tpu.serve.service` — the lifecycle: ``serve.tick``
  heartbeats with a watchdog, graceful drain (stop admission → flush
  queue → converge → checkpoint), and restore-from-checkpoint that
  replays the ingest journal above each tenant's applied watermark and
  resumes steady-state delta waves;
- :mod:`cause_tpu.serve.wal` — the durable-storage lifecycle (PR 15):
  a segmented write-ahead log with per-record CRC32 trailers,
  size/age rotation, an fsync policy (``none``/``batch``/``always``),
  and crash-safe post-checkpoint GC bounding long-running disk usage
  — drop-in for ``IngestJournal`` (same record schema + ``iter_from``
  contract), with the chaos ``disk`` family injected at its write
  seams;
- :mod:`cause_tpu.serve.scrub` — the offline storage scrubber
  (``python -m cause_tpu.serve scrub``): walks WAL segments and
  checkpoint packs, reports CRC failures / torn records / GC-eligible
  bytes, exits nonzero on corruption.

Import discipline: this ``__init__`` and the host-side modules
(ingest, controller) are importable without jax — jax-touching pieces
(sessions, residency restore) import lazily inside the functions that
need them, the same rule the obs package follows. Acceptance
instrument: ``scripts/serve_soak.py`` (open-loop zipf-hot/bursty load
at multiples of the measured steady-state rate, with and without
``--chaos``; ``--kind serve`` ledger rows).
"""

from .ingest import Admission, IngestJournal, IngestQueue
from .controller import BatchController
from .wal import WriteAheadLog, open_journal

__all__ = [
    "Admission",
    "BatchController",
    "BatchScheduler",
    "IngestJournal",
    "IngestQueue",
    "ResidencyManager",
    "ServiceCrashed",
    "SyncService",
    "WriteAheadLog",
    "open_journal",
]


def __getattr__(name):
    # ResidencyManager/SyncService pull in the jax-backed session
    # machinery; resolve them lazily so `import cause_tpu.serve` stays
    # jax-free for pure admission/controller users (CI lint job,
    # pure-weaver processes)
    if name in ("ResidencyManager",):
        from .residency import ResidencyManager

        return ResidencyManager
    if name in ("BatchScheduler",):
        # the cross-tenant batch scheduler dispatches device programs
        # (jax-backed) — same lazy rule as the session machinery
        from .batch import BatchScheduler

        return BatchScheduler
    if name in ("SyncService", "ServiceCrashed"):
        from . import service as _service

        return getattr(_service, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
