"""Cross-tenant wave batching: one fused device dispatch per pow2
bucket serves every ready tenant's delta window.

The serve tick used to pay one wave — and therefore one ~67 ms
dispatch floor (``obs.costmodel.DISPATCH_FLOOR_MS``) — per touched
tenant per tick. But the delta-native wave's device program
(``weaver.jaxwd.batched_delta_weave``) is already vmap-batched across
rows, and its window assembly (``parallel.wave.assemble_delta_window``)
is pure host work over cached views with no dependence on any
session's resident capacity. So N tenants whose frontiers share a
window budget can ride ONE dispatch: stack their windows as batch
rows, weave once, split the per-row digests back per tenant.

:class:`BatchScheduler` is that external driver, built on the
session-layer hooks factored out of ``FleetSession._delta_wave``:

- **bucket** — tenants group by ``FleetSession.bucket_key`` (the pow2
  window budget ``w_cap``); every member of a bucket shares the
  compiled XLA program shape, so the weave is one dispatch per
  DISTINCT budget, not per tenant. Batch rows are padded to the next
  pow2 with copies of row 0 (outputs discarded), so the program shape
  also survives tenant-count churn tick to tick;
- **dispatch** — one ``batched_delta_weave`` per bucket, through the
  recovery ladder's retry rung, with the injectable chaos seams the
  per-tenant path has (stall, budget exhaustion);
- **split back** — per-row digests, ranks and visibility are fetched
  once for the whole bucket and handed to each member's
  ``complete_window`` (per-tenant semantics — ``wave.digest``
  agreement, staleness, lag resolution — are observed per tenant,
  unchanged by batching; the rank splice is deferred until something
  reads the resident weave);
- **fallback** — a tenant with no frontier, or whose window overflows
  its bucket, runs its own full-width ``wave()`` (re-establish, with
  recovery-ladder evidence) WITHOUT dragging its bucket-mates down
  the slow path.

Cost accounting: each bucket emits one ``wave.cost`` with ``bucket``
and ``batch_rows`` fields (``path="batched"``), draining every member
tenant's pending delta-op evidence, so the gap report and the live
fold can attribute the dispatch-count collapse: ``floor_budget_ms``
scales with #buckets, not #tenants.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .. import chaos as _chaos
from .. import obs
from ..obs import xtrace

__all__ = ["BatchScheduler"]


class BatchScheduler:
    """Group ready sessions by pow2 bucket, run one fused delta-wave
    dispatch per bucket, split the results back per tenant."""

    def __init__(self, site: str = "serve"):
        self.site = str(site)
        # last wave_fleet's shape, for the serve.tick event
        self.last_buckets = 0
        self.last_batch_rows = 0
        self.last_fallbacks = 0
        # tick-scoped {uuid: [trace ids]} for per-tenant wave hops
        self._traces_by_uuid: Dict[str, list] = {}

    def wave_fleet(self, sessions,
                   traces_by_uuid=None) -> Dict[str, np.ndarray]:
        """One batched wave over ``{uuid: FleetSession}``: every
        session ends wave-current; returns ``{uuid: digest array}``
        bit-identical to per-tenant ``wave()`` calls.
        ``traces_by_uuid`` (PR 19, obs-on ticks) maps tenants to the
        trace ids riding this tick so each fused bucket span fans out
        per-tenant "wave" child hops."""
        self._traces_by_uuid = traces_by_uuid or {}
        digests: Dict[str, np.ndarray] = {}
        fallback: List[str] = []
        buckets: Dict[int, list] = {}
        for uuid, sess in sessions.items():
            if _chaos.enabled() and sess.bucket_key \
                    and _chaos.budget_exhaust("session"):
                # injected window-budget exhaustion: this tenant alone
                # drops to the full-width rung, same as in wave()
                sess.abandon_frontier("budget-exhaustion",
                                      site=self.site)
            pack = sess.window_pack()
            if pack is None:
                fallback.append(uuid)
            else:
                buckets.setdefault(pack["w_cap"], []).append(
                    (uuid, sess, pack))
        self.last_buckets = len(buckets)
        self.last_batch_rows = 0
        for wcap in sorted(buckets):
            self._wave_bucket(wcap, buckets[wcap], digests, fallback)
        for uuid in fallback:
            # full-width re-establish, one tenant at a time: the
            # recovery evidence rode the frontier drop that put the
            # tenant here (update-level degrade, abandon_frontier)
            digests[uuid] = sessions[uuid].wave()
            if obs.enabled():
                for tr in self._traces_by_uuid.get(uuid, ()):
                    xtrace.hop("wave", tr, uuid=uuid, path="full")
        self.last_fallbacks = len(fallback)
        return digests

    def _wave_bucket(self, wcap: int, group, digests, fallback):
        from ..benchgen import LANE_KEYS5
        from ..parallel import recovery as _recovery
        from ..parallel.wave import assemble_delta_window
        from ..weaver import jaxwd
        from ..weaver.arrays import next_pow2

        import jax.numpy as jnp

        n_w = 2 * wcap
        views: list = []
        s_parts, anchor_parts, pdig_parts = [], [], []
        row_of = []  # (uuid, sess, first row, row count)
        for uuid, sess, pack in group:
            row_of.append((uuid, sess, len(views), pack["rows"]))
            views.extend(pack["views"])
            s_parts.append(np.asarray(pack["s"]))
            anchor_parts.append(np.asarray(pack["anchor"]))
            pdig_parts.append(np.asarray(pack["prefix_digest"]))
        n_real = len(views)
        n_pad = int(next_pow2(max(1, n_real)))
        if n_pad > n_real:
            # pad with copies of the first row so the program shape is
            # (wcap, pow2 rows) — stable across tenant-count churn;
            # padded rows' outputs are sliced off below
            pad = n_pad - n_real
            views = views + [views[0]] * pad
            s_parts.append(np.repeat(s_parts[0][:1], pad))
            anchor_parts.append(np.repeat(anchor_parts[0][:1], pad))
            pdig_parts.append(np.repeat(pdig_parts[0][:1], pad))
        s_arr = np.concatenate(s_parts).astype(np.int32)
        anchor_arr = np.concatenate(anchor_parts).astype(np.int32)
        pdig = np.concatenate(pdig_parts).astype(np.uint32)
        uuids = [u for u, _se, _lo, _n in row_of]
        self.last_batch_rows += n_pad
        if _chaos.enabled():
            # one stall draw per dispatch, the same rate the
            # per-tenant path pays per wave
            _chaos.stall_point("session")
        if obs.enabled():
            from ..obs import costmodel as _cm

            _cm.wave_begin(self.site)
            obs.event("run.heartbeat", stage="serve.batch_wave",
                      bucket=int(wcap), tenants=len(group),
                      batch_rows=n_pad)
        with obs.span("serve.batch_wave", bucket=int(wcap),
                      tenants=len(group), rows=n_real):
            with obs.span("serve.batch_assemble"):
                lanes, starts, counts = assemble_delta_window(
                    views, s_arr, anchor_arr, wcap, n_w)
            r0 = s_arr.astype(np.int32) - 1
            rank_w, vis_w, digest, ovf = _recovery.run_dispatch(
                "session",
                lambda: jaxwd.batched_delta_weave(
                    *(jnp.asarray(lanes[k]) for k in LANE_KEYS5),
                    jnp.asarray(pdig), jnp.asarray(r0),
                    u_max=n_w, k_max=n_w))
            # one host fetch for the whole bucket; rows split per
            # tenant below without further device work
            out = np.asarray(digest)
            ovf_np = np.asarray(ovf)
            rank_np = np.asarray(rank_w)
            vis_np = np.asarray(vis_w)
            if obs.enabled():
                from ..obs import costmodel as _cm

                _cm.record_dispatch(
                    f"serve:batch:w{int(wcap)}x{n_pad}", site="serve")
        delta_ops = 0
        full_bags = 0
        for uuid, sess, r_lo, rows in row_of:
            sl = slice(r_lo, r_lo + rows)
            if bool(ovf_np[sl].any()):  # pragma: no cover -
                # structurally unreachable at u_max = N_w (the same
                # budget rule as _delta_wave); kept so a future budget
                # change degrades this tenant alone, not its bucket
                obs.counter("serve.batch_row_overflow").inc()
                sess.abandon_frontier("window-overflow",
                                      site=self.site)
                fallback.append(uuid)
                continue
            d, f = sess.pop_divergence()
            delta_ops += d
            full_bags += f
            digests[uuid] = sess.complete_window(
                rank_np[sl], vis_np[sl], out[sl],
                starts[sl], counts[sl])
            if obs.enabled():
                # the bucket span fans out per-tenant child hops:
                # each trace's "wave" hop names the fused dispatch
                # (bucket + rows) that actually served it
                for tr in self._traces_by_uuid.get(uuid, ()):
                    xtrace.hop("wave", tr, uuid=uuid,
                               path="batched", bucket=int(wcap),
                               batch_rows=n_pad)
        if obs.enabled():
            from ..obs import costmodel as _cm
            from ..obs import devprof

            devprof.sample_device_memory("serve.batch")
            _cm.wave_cost(
                uuid=f"bucket:w{int(wcap)}",
                pairs=n_real,
                lanes=sum(2 * int(se.capacity) * n
                          for _u, se, _lo, n in row_of),
                tokens=int(counts[:n_real].sum()) + 2 * n_real,
                token_budget=int(n_w) * n_pad,
                delta_ops=delta_ops,
                full_bag=full_bags,
                path="batched",
                bucket=int(wcap),
                batch_rows=n_pad,
                uuids=uuids,
            )
