"""The adaptive T_batch controller — PERF.md Round 9, inverted live.

The lag SLO decomposes as ``p99 ≈ T_batch + floor×dispatches +
slope×batch_ops`` (PERF.md "Round 9"): an op created at the start of a
coalescing window waits ``T_batch`` for admission, then one wave wall
(dispatch floor × dispatches per wave, plus the delta-native slope
over the batch's ops). Everything on the right except ``T_batch`` is
measured by the cost model, so the controller's steady-state target is
the inversion solved for ``T_batch``:

    T_target = slo_ms − floor_ms × dispatches_per_wave
                      − slope_ms_per_op × batch_ops

driven by exactly the two live terms PR 10 built the snapshot for:

- **feedback** — the sliding SLO burn rate (``lag.slo.burn_rate``):
  burning ≥2x sustainable shrinks T_batch multiplicatively (wave
  sooner, smaller batches); burn comfortably under 1 relaxes back
  toward the inversion target;
- **capacity** — the ``fleet.token_headroom`` minimum: headroom
  thinner than one batch's worth of ops means the next divergence
  spike overflows the compiled window budget, so T_batch halves
  (smaller windows) regardless of what the SLO says.

Damping: the result is clamped to ``[t_min_ms, t_max_ms]``, a change
smaller than the hysteresis fraction is ignored, per-update movement
is bounded to 2x/0.5x, and a post-change cooldown holds the value for
a few ticks — so an edge-triggered alert flapping on a threshold
cannot oscillate the batch size (pinned in tests/test_serve.py). The
controller is a pure consumer: feed it ``live.snapshot`` dicts (or a
``LiveMonitor`` snapshot) and read ``t_batch_ms``; it never touches
the queue or the sessions itself.

Stdlib-only, importable without jax (the obs-reader rule): the floor
constant imports lazily from the cost model with a CPU-honest
override for hosts where the tunnel floor is not the real constant.
"""

from __future__ import annotations

from typing import Optional

from .. import obs

__all__ = ["BatchController"]

# burn thresholds: >BURN_HIGH shrinks now, <BURN_LOW may relax
_BURN_HIGH = 2.0
_BURN_LOW = 1.0
_SHRINK = 0.5          # multiplicative shrink under pressure
_RELAX = 1.25          # multiplicative relax toward the target
_STEP_CAP = 2.0        # max per-update movement (both directions)


class BatchController:
    """See the module docstring. ``update(snapshot)`` returns the
    (possibly unchanged) ``t_batch_ms``; ``on_alert`` is the
    edge-triggered interrupt side (register it as a ``LiveMonitor``
    callback) — a ``burn`` alert forces the shrink branch on the next
    update even if the sliding burn has not crossed yet."""

    def __init__(self, slo_ms: float = 100.0,
                 t_min_ms: float = 5.0, t_max_ms: float = 2000.0,
                 floor_ms: Optional[float] = None,
                 hysteresis: float = 0.2, cooldown_ticks: int = 2,
                 initial_ms: Optional[float] = None):
        if floor_ms is None:
            from ..obs.costmodel import DISPATCH_FLOOR_MS

            floor_ms = DISPATCH_FLOOR_MS
        self.slo_ms = float(slo_ms)
        self.t_min_ms = float(t_min_ms)
        self.t_max_ms = float(t_max_ms)
        self.floor_ms = float(floor_ms)
        self.hysteresis = float(hysteresis)
        self.cooldown_ticks = int(cooldown_ticks)
        self.t_batch_ms = float(
            initial_ms if initial_ms is not None
            else min(t_max_ms, max(t_min_ms, slo_ms / 2.0)))
        self._cooldown = 0
        self._alert_pressure = False
        self.changes = 0
        self.last_terms: dict = {}

    # ------------------------------------------------------- interrupts

    def on_alert(self, alert: dict) -> None:
        """LiveMonitor callback: burn/p99 excursions arm the shrink
        branch for the next update. Edge-triggered by construction
        (the monitor emits once per excursion) and consumed once —
        flapping rules cannot pump the controller."""
        rule = str(alert.get("rule", ""))
        if rule.startswith(("burn", "p99", "window_p99", "shed_rate")):
            self._alert_pressure = True

    # ----------------------------------------------------------- update

    def target_ms(self, snapshot: dict) -> float:
        """The Round-9 inversion against one snapshot's measured cost
        terms (floor × dispatches/wave + slope × batch ops), clamped.
        Pure — no controller state touched."""
        cost = snapshot.get("cost") or {}
        waves = cost.get("waves") or 0
        d_per_wave = (cost.get("dispatches", 0) / waves) if waves else 1.0
        batch_ops = (cost.get("delta_ops", 0) / waves) if waves else 0.0
        slope = ((cost.get("slope") or {}).get("slope_ms_per_op")
                 or 0.0)
        t = self.slo_ms - self.floor_ms * d_per_wave \
            - slope * batch_ops
        return min(self.t_max_ms, max(self.t_min_ms, t))

    def update(self, snapshot: dict) -> float:
        """One control tick against a ``live.snapshot`` dict. Applies
        feedback (burn) and capacity (headroom) to the inversion
        target, then hysteresis/step-cap/cooldown damping. Emits one
        ``serve.control`` event per actual change (obs on)."""
        lag = snapshot.get("lag") or {}
        slo = lag.get("slo") or {}
        burn = slo.get("burn_rate")
        head = (snapshot.get("headroom") or {}).get("min")
        cost = snapshot.get("cost") or {}
        waves = cost.get("waves") or 0
        batch_ops = (cost.get("delta_ops", 0) / waves) if waves else 0.0

        target = self.target_ms(snapshot)
        proposed = self.t_batch_ms
        why = "steady"
        pressure = self._alert_pressure or (
            isinstance(burn, (int, float)) and burn > _BURN_HIGH)
        if pressure:
            proposed = self.t_batch_ms * _SHRINK
            why = "burn"
        elif burn is None or burn < _BURN_LOW:
            # comfortable: relax toward (never past) the inversion
            if self.t_batch_ms < target:
                proposed = min(target, self.t_batch_ms * _RELAX)
                why = "relax"
            elif self.t_batch_ms > target:
                proposed = target
                why = "target"
        # capacity term: headroom thinner than ~one batch of ops means
        # the compiled window budget is about to overflow — halve,
        # whatever the SLO arithmetic says
        if isinstance(head, (int, float)) \
                and head < max(8.0, 2.0 * batch_ops) \
                and proposed > self.t_batch_ms * _SHRINK:
            proposed = self.t_batch_ms * _SHRINK
            why = "headroom"

        # damping ladder: step cap, clamp, hysteresis, cooldown
        proposed = min(self.t_batch_ms * _STEP_CAP,
                       max(self.t_batch_ms / _STEP_CAP, proposed))
        proposed = min(self.t_max_ms, max(self.t_min_ms, proposed))
        self.last_terms = {
            "target_ms": round(target, 3), "burn": burn,
            "headroom_min": head, "why": why,
            "batch_ops": round(batch_ops, 2),
        }
        if self._cooldown > 0:
            # the alert flag SURVIVES cooldown (consumed only past
            # this gate): an edge-triggered alert fires once per
            # excursion, so discarding it here would lose the shrink
            # entirely if the sliding burn then settles under the
            # threshold
            self._cooldown -= 1
            return self.t_batch_ms
        self._alert_pressure = False
        if self.t_batch_ms > 0 and abs(proposed - self.t_batch_ms) \
                / self.t_batch_ms < self.hysteresis:
            return self.t_batch_ms
        old = self.t_batch_ms
        self.t_batch_ms = proposed
        self._cooldown = self.cooldown_ticks
        self.changes += 1
        if obs.enabled():
            obs.counter("serve.control_changes").inc()
            obs.gauge("serve.t_batch_ms").set(round(proposed, 3))
            obs.event("serve.control", old_ms=round(old, 3),
                      new_ms=round(proposed, 3), **self.last_terms)
        return self.t_batch_ms
