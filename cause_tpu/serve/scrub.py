"""The offline storage scrubber: ``python -m cause_tpu.serve scrub``.

Durability claims rot silently — a WAL segment can sit bit-rotted for
weeks before a restore trips over it. The scrubber is the offline
audit that finds out FIRST: it walks every WAL segment (live and
retired) record by record re-checking each CRC trailer, parses the
serve checkpoint manifest and every tenant pack it names, and reports
torn records, CRC failures, missing/stray packs and GC-eligible bytes
— exiting nonzero on any corruption so a cron job or CI step gates on
it directly.

Also home to ``bench-fsync``, the micro-bench behind PERF.md Round
15's fsync-policy overhead table (same append path, one tmp WAL per
policy).

Jax-free and obs-free by construction: the scrubber must run against
a dead service's directories from a bare operator shell. It reuses
:mod:`cause_tpu.serve.wal`'s codec helpers rather than duplicating
the line format.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from .wal import (WAL_MANIFEST_NAME, WriteAheadLog, list_segments,
                  scan_segment_file)

__all__ = ["scrub_wal", "scrub_checkpoints", "bench_fsync", "cli"]

# duplicated from .service (which imports jax-adjacent machinery) so
# the scrubber stays importable on a bare host
_SERVE_MANIFEST_NAME = "serve_manifest.json"


def _scrub_one_dir(path: str, watermark: int) -> dict:
    segs = []
    for no, name in list_segments(path):
        seg = {"name": name, "records": 0, "torn": 0,
               "crc_failures": 0, "legacy": 0, "bytes": 0,
               "first_seq": None, "last_seq": None}
        fp = os.path.join(path, name)
        try:
            seg["bytes"] = os.path.getsize(fp)
            for kind, e in scan_segment_file(fp):
                if kind in ("rec", "legacy"):
                    seg["records"] += 1
                    if kind == "legacy":
                        seg["legacy"] += 1
                    q = int(e.get("seq", 0))
                    if seg["first_seq"] is None:
                        seg["first_seq"] = q
                    else:
                        seg["first_seq"] = min(seg["first_seq"], q)
                    seg["last_seq"] = (q if seg["last_seq"] is None
                                       else max(seg["last_seq"], q))
                elif kind == "corrupt":
                    seg["crc_failures"] += 1
                else:
                    seg["torn"] += 1
        except OSError:
            seg["torn"] += 1
        segs.append(seg)
    # GC-eligible: sealed (non-last) segments wholly at/below the
    # watermark — exactly what the next wal.gc() pass would retire
    gc_bytes = gc_segments = 0
    for seg in segs[:-1]:
        if (seg["last_seq"] or 0) <= watermark:
            gc_bytes += seg["bytes"]
            gc_segments += 1
    return {"path": path, "segments": segs,
            "records": sum(g["records"] for g in segs),
            "torn": sum(g["torn"] for g in segs),
            "crc_failures": sum(g["crc_failures"] for g in segs),
            "legacy": sum(g["legacy"] for g in segs),
            "bytes": sum(g["bytes"] for g in segs),
            "gc_eligible_segments": gc_segments,
            "gc_eligible_bytes": gc_bytes}


def scrub_wal(path: str, watermark: Optional[int] = None,
              retired: Optional[str] = None) -> dict:
    """Walk a WAL directory (and optionally its retire dir): every
    line of every segment re-classified through the shared codec.
    ``watermark`` overrides the WAL manifest's ``gc_watermark`` for
    the GC-eligible accounting (pass the serve manifest's watermark
    to preview what the next checkpoint's GC will reclaim)."""
    manifest = None
    mpath = os.path.join(path, WAL_MANIFEST_NAME)
    manifest_ok = True
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            if not (isinstance(manifest, dict)
                    and "~wal_manifest" in manifest):
                manifest, manifest_ok = None, False
        except (OSError, ValueError):
            manifest_ok = False
    if watermark is None:
        watermark = int((manifest or {}).get("gc_watermark") or 0)
    rep = _scrub_one_dir(path, int(watermark))
    rep["watermark"] = int(watermark)
    rep["manifest_ok"] = manifest_ok
    if retired and os.path.isdir(retired):
        rep["retired"] = _scrub_one_dir(retired, int(watermark))
    rep["clean"] = (rep["torn"] == 0 and rep["crc_failures"] == 0
                    and manifest_ok)
    return rep


def scrub_checkpoints(path: str) -> dict:
    """Audit a serve checkpoint directory: the manifest must parse,
    every tenant pack it names must exist and parse as a pack dict,
    and anything else matching the pack/tmp patterns is a stray the
    post-checkpoint sweep missed (reported, not an error)."""
    rep = {"path": path, "manifest_ok": False, "tenants": 0,
           "packs_ok": 0, "packs_bad": [], "packs_missing": [],
           "stray_files": [], "errors": 0}
    mpath = os.path.join(path, _SERVE_MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        if not (isinstance(manifest, dict)
                and "~serve_manifest" in manifest):
            raise ValueError("not a serve manifest")
        rep["manifest_ok"] = True
    except (OSError, ValueError):
        rep["errors"] += 1
        return rep
    tenants = manifest.get("tenants") or {}
    rep["tenants"] = len(tenants)
    rep["gc_watermark"] = int(manifest.get("gc_watermark") or 0)
    live = {_SERVE_MANIFEST_NAME}
    for uuid, info in tenants.items():
        rel = info.get("file")
        live.add(rel)
        fp = os.path.join(path, rel)
        try:
            with open(fp) as f:
                pack = json.load(f)
            if not isinstance(pack, dict):
                raise ValueError("pack is not a dict")
            rep["packs_ok"] += 1
        except OSError:
            rep["packs_missing"].append(rel)
            rep["errors"] += 1
        except ValueError:
            rep["packs_bad"].append(rel)
            rep["errors"] += 1
    try:
        for name in sorted(os.listdir(path)):
            if name in live:
                continue
            if name.endswith(".ckpt.json") or ".tmp." in name:
                rep["stray_files"].append(name)
    except OSError:
        rep["errors"] += 1
    return rep


def bench_fsync(n: int = 2000, tmp_dir: Optional[str] = None) -> dict:
    """Append ``n`` one-op records under each fsync policy against a
    throwaway WAL; returns per-policy wall µs/append — the PERF.md
    Round 15 table."""
    import shutil
    import tempfile

    out = {}
    items = [{"node": "bench", "op": 1}]
    for policy in ("none", "batch", "always"):
        d = tempfile.mkdtemp(dir=tmp_dir, prefix=f"walbench-{policy}-")
        try:
            w = WriteAheadLog(os.path.join(d, "wal"), fsync=policy)
            t0 = time.perf_counter()
            for i in range(n):
                w.append("bench", "site", items)
            dt = time.perf_counter() - t0
            w.close()
            out[policy] = {"n": n,
                           "us_per_append": round(dt / n * 1e6, 2),
                           "appends_per_s": round(n / dt, 1),
                           "fsyncs": w.stats["fsyncs"]}
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return out


# --------------------------------------------------------------- CLI


def _print_wal_report(rep: dict) -> None:
    print(f"wal {rep['path']}: {rep['records']} records in "
          f"{len(rep['segments'])} segments ({rep['bytes']} bytes), "
          f"watermark {rep['watermark']}")
    print(f"  torn={rep['torn']} crc_failures={rep['crc_failures']} "
          f"legacy={rep['legacy']} manifest_ok={rep['manifest_ok']}")
    print(f"  gc-eligible: {rep['gc_eligible_segments']} segments / "
          f"{rep['gc_eligible_bytes']} bytes")
    for seg in rep["segments"]:
        flag = ""
        if seg["torn"] or seg["crc_failures"]:
            flag = "  <-- DAMAGED"
        print(f"    {seg['name']}: recs={seg['records']} "
              f"seq=[{seg['first_seq']},{seg['last_seq']}] "
              f"torn={seg['torn']} crc={seg['crc_failures']}{flag}")
    if "retired" in rep:
        r = rep["retired"]
        print(f"  retired {r['path']}: {r['records']} records, "
              f"torn={r['torn']} crc_failures={r['crc_failures']}")


def cli(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cause_tpu.serve",
        description="serve-layer storage tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("scrub", help="audit WAL segments and "
                        "checkpoint packs; exit 1 on corruption")
    sp.add_argument("--wal", help="WAL directory (or legacy journal "
                    "file) to scrub")
    sp.add_argument("--retired", help="retired-segment dir to include")
    sp.add_argument("--checkpoint", help="serve checkpoint dir to "
                    "audit (its gc_watermark also prices the WAL's "
                    "GC-eligible bytes)")
    sp.add_argument("--json", action="store_true",
                    help="emit one JSON report to stdout")
    bp = sub.add_parser("bench-fsync", help="measure per-append "
                        "overhead of each fsync policy")
    bp.add_argument("--n", type=int, default=2000)
    bp.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.cmd == "bench-fsync":
        rep = bench_fsync(args.n)
        if args.json:
            print(json.dumps(rep, indent=2))
        else:
            for policy, r in rep.items():
                print(f"fsync={policy:6s} {r['us_per_append']:>9.2f} "
                      f"us/append  {r['appends_per_s']:>10.1f} "
                      f"appends/s  ({r['fsyncs']} fsyncs)")
        return 0

    if not args.wal and not args.checkpoint:
        ap.error("scrub needs --wal and/or --checkpoint")
    report = {}
    bad = False
    watermark = None
    if args.checkpoint:
        ck = scrub_checkpoints(args.checkpoint)
        report["checkpoint"] = ck
        watermark = ck.get("gc_watermark")
        bad = bad or ck["errors"] > 0
    if args.wal:
        if os.path.isdir(args.wal):
            w = scrub_wal(args.wal, watermark=watermark,
                          retired=args.retired)
            report["wal"] = w
            bad = bad or not w["clean"]
        else:
            # legacy single-file journal: same codec walk, one "file"
            w = {"path": args.wal, "records": 0, "torn": 0,
                 "crc_failures": 0, "legacy": 0, "segments": [],
                 "bytes": 0, "gc_eligible_segments": 0,
                 "gc_eligible_bytes": 0, "watermark": watermark or 0,
                 "manifest_ok": True}
            try:
                w["bytes"] = os.path.getsize(args.wal)
                for kind, e in scan_segment_file(args.wal):
                    if kind in ("rec", "legacy"):
                        w["records"] += 1
                        if kind == "legacy":
                            w["legacy"] += 1
                    elif kind == "corrupt":
                        w["crc_failures"] += 1
                    else:
                        w["torn"] += 1
            except OSError:
                w["torn"] += 1
            w["clean"] = w["torn"] == 0 and w["crc_failures"] == 0
            report["wal"] = w
            bad = bad or not w["clean"]
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        if "wal" in report:
            _print_wal_report(report["wal"])
        if "checkpoint" in report:
            ck = report["checkpoint"]
            print(f"checkpoint {ck['path']}: manifest_ok="
                  f"{ck['manifest_ok']} tenants={ck['tenants']} "
                  f"packs_ok={ck['packs_ok']} errors={ck['errors']}")
            for rel in ck.get("packs_missing", []):
                print(f"    MISSING pack {rel}")
            for rel in ck.get("packs_bad", []):
                print(f"    BAD pack {rel}")
            for name in ck.get("stray_files", []):
                print(f"    stray {name}")
        print("CORRUPTION DETECTED" if bad else "clean")
    return 1 if bad else 0
