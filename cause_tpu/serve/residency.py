"""LRU residency for hot documents: device memory as a cache, host
packs as the backing store — wrong answers structurally impossible.

A zipf-hot tenant population is larger than device memory by
assumption (millions of cold documents, a hot head in the thousands).
The residency manager keeps at most ``capacity`` tenants' device
state (their :class:`FleetSession`s — resident lanes, rank/visibility,
delta frontier) and spills the LRU tail to host:

- **evict** = a checkpoint-grade pack via PR 11's serde path
  (``FleetSession.checkpoint()`` — node bags + base64 arrays + the
  frontier), written to ``spill_dir`` when given (atomic rename) or
  held in memory; the session AND its host handles drop, so eviction
  genuinely frees both device and host working state;
- **touch** of an evicted tenant = ``FleetSession.restore`` — GATED
  on digest bit-identity (one lane upload + one digest dispatch must
  reproduce the packed digests or the restore REFUSES with
  ``checkpoint-mismatch``). A torn or tampered pack can cost a
  re-upload and a loud error; it can never cost a wrong answer.

Every transition is evidence: ``serve.evict`` / ``serve.restore``
events, eviction/restore counters, and the ``serve.resident_docs``
gauge the live snapshot and watch dashboard read.

Evict requires the session to be wave-current (an update since the
last wave makes the checkpoint unprovable — ``FleetSession`` refuses,
PR 11); the service guarantees that by waving every touched tenant
before sleeping, and :meth:`evict` surfaces the ``no-wave`` refusal
rather than dropping state it cannot pack.
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Dict, List, Optional

from .. import obs

__all__ = ["ResidencyManager"]


class ResidencyManager:
    """See the module docstring. Single-threaded by design (the
    service's tick loop owns it); the soak's generator threads never
    touch residency directly."""

    # the owning service's batched-tick mode: every inserted/restored
    # session is marked for the deferred-splice path so a restored
    # tenant rejoins its bucket instead of paying per-tenant splices
    batched = False

    def __init__(self, capacity: int, spill_dir: Optional[str] = None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._resident: "OrderedDict[str, object]" = OrderedDict()
        self._spilled: Dict[str, object] = {}  # uuid -> pack dict|path
        self.stats = {"evictions": 0, "restores": 0}

    # ------------------------------------------------------- queries

    @property
    def resident_docs(self) -> int:
        return len(self._resident)

    def resident(self) -> List[str]:
        return list(self._resident)

    def spilled(self) -> List[str]:
        return list(self._spilled)

    def __contains__(self, uuid: str) -> bool:
        return uuid in self._resident or uuid in self._spilled

    def buckets(self) -> Dict[int, List[str]]:
        """Resident tenants grouped by their pow2 batch-bucket key
        (``FleetSession.bucket_key``; 0 = next wave runs full width).
        The batched tick's marshaling unit: every tenant under one
        key rides one fused dispatch. Sets the ``serve.buckets``
        gauge as a side effect."""
        out: Dict[int, List[str]] = {}
        for uuid, sess in self._resident.items():
            out.setdefault(int(getattr(sess, "bucket_key", 0)),
                           []).append(uuid)
        if obs.enabled():
            obs.gauge("serve.buckets").set(len(out))
        return out

    # ----------------------------------------------------- transitions

    def _gauge(self) -> None:
        if obs.enabled():
            obs.gauge("serve.resident_docs").set(len(self._resident))

    def insert(self, uuid: str, session) -> None:
        """Register a (new or restored) session as resident, evicting
        LRU tenants past capacity. The inserted tenant is the MRU."""
        uuid = str(uuid)
        session.defer_device = self.batched
        self._resident[uuid] = session
        self._resident.move_to_end(uuid)
        self._spilled.pop(uuid, None)
        while len(self._resident) > self.capacity:
            self.evict(next(iter(self._resident)))
        self._gauge()

    def evict(self, uuid: str) -> None:
        """Spill one resident tenant to a checkpoint-grade pack. The
        session must be wave-current (FleetSession.checkpoint's
        contract) — a ``no-wave`` refusal propagates loudly."""
        uuid = str(uuid)
        sess = self._resident[uuid]
        # pack FIRST, drop from the resident map only on success — a
        # no-wave/pack refusal must leave the tenant resident (loud
        # error, state intact), never in neither map
        if self.spill_dir:
            path = os.path.join(self.spill_dir, f"{uuid}.ckpt.json")
            sess.checkpoint_to(path)
            pack = path
        else:
            pack = sess.checkpoint()
        del self._resident[uuid]
        self._spilled[uuid] = pack
        self.stats["evictions"] += 1
        if obs.enabled():
            obs.counter("serve.evictions").inc()
            obs.event("serve.evict", uuid=uuid,
                      resident=len(self._resident),
                      spilled=len(self._spilled))
        self._gauge()

    def get(self, uuid: str):
        """Touch one tenant: the resident session (MRU-bumped), or a
        digest-gated restore from its spill pack (evicting LRU
        tenants to make room), or None for a tenant this manager has
        never seen. A pack that fails the digest gate raises
        ``CausalError(checkpoint-mismatch)`` — never a silently wrong
        session."""
        uuid = str(uuid)
        sess = self._resident.get(uuid)
        if sess is not None:
            self._resident.move_to_end(uuid)
            return sess
        pack = self._spilled.get(uuid)
        if pack is None:
            return None
        from ..parallel.session import FleetSession

        # make room BEFORE the restore uploads device state: the
        # capacity bound must hold at every instant — transiently
        # holding capacity+1 sessions would OOM exactly in the
        # memory-pressure regime this manager exists to manage
        while len(self._resident) >= self.capacity:
            self.evict(next(iter(self._resident)))
        sess = FleetSession.restore(pack)  # the digest gate lives here
        self.stats["restores"] += 1
        if obs.enabled():
            obs.counter("serve.restores").inc()
            obs.event("serve.restore", uuid=uuid,
                      resident=len(self._resident) + 1)
        if self.spill_dir and isinstance(pack, str):
            try:
                os.unlink(pack)
            except OSError:  # pragma: no cover - cleanup best-effort
                pass
        self.insert(uuid, sess)
        return sess

    def get_many(self, uuids: List[str]) -> "OrderedDict[str, object]":
        """Touch a GROUP for one batched tick: every named tenant
        resident and MRU-bumped before any of them updates, so the
        restores' evictions can only hit tenants OUTSIDE the group
        (wave-current between ticks — evictable). The group must fit
        device memory: more than ``capacity`` uuids cannot be
        co-resident, and silently splitting here would hide the
        working-set overflow the caller has to chunk around. Unknown
        uuids are simply absent from the result (the caller's
        unknown-tenant path stays loud)."""
        uuids = [str(u) for u in uuids]
        if len(uuids) > self.capacity:
            raise ValueError(
                f"get_many: group of {len(uuids)} exceeds residency "
                f"capacity {self.capacity} — chunk the group")
        out: "OrderedDict[str, object]" = OrderedDict()
        for uuid in uuids:
            sess = self.get(uuid)
            if sess is not None:
                out[uuid] = sess
        return out

    def sweep_spill(self) -> int:
        """Retention for the spill directory (PR 15: spill packs join
        the post-checkpoint GC policy): remove every ``*.ckpt.json``
        pack no longer backing a spilled tenant — a restored tenant's
        leftover pack, a crashed process's stale tmp — and return the
        bytes reclaimed. Live packs (anything ``self._spilled`` points
        at) are never touched."""
        if not self.spill_dir:
            return 0
        live = {os.path.basename(p) for p in self._spilled.values()
                if isinstance(p, str)}
        freed = 0
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return 0
        for name in names:
            if name in live:
                continue
            if not (name.endswith(".ckpt.json") or ".tmp." in name):
                continue
            fp = os.path.join(self.spill_dir, name)
            try:
                nb = os.path.getsize(fp)
                os.unlink(fp)
            except OSError:  # pragma: no cover - sweep is best-effort
                continue
            freed += nb
        return freed

    # ---------------------------------------------------- checkpointing

    def checkpoint_all(self, out_dir: str) -> Dict[str, dict]:
        """Pack EVERY tenant (resident sessions checkpointed, spilled
        packs copied) into ``out_dir`` — the drain's persistence step.
        Returns ``{uuid: {"file": relpath}}`` for the manifest."""
        os.makedirs(out_dir, exist_ok=True)
        out: Dict[str, dict] = {}
        for uuid, sess in self._resident.items():
            rel = f"{uuid}.ckpt.json"
            sess.checkpoint_to(os.path.join(out_dir, rel))
            out[uuid] = {"file": rel}
        for uuid, pack in self._spilled.items():
            rel = f"{uuid}.ckpt.json"
            dst = os.path.join(out_dir, rel)
            # tmp-fd fsync before each rename: post-checkpoint WAL GC
            # retires segments on the strength of these files, so a
            # torn pack after a crash is real data loss, not a retry.
            # The DIRECTORY entries are fsynced once by the caller
            # (service checkpoint fsync_dir after the manifest swap),
            # not per file here.
            if isinstance(pack, str):
                if os.path.abspath(pack) != os.path.abspath(dst):
                    blob = open(pack).read()
                    tmp = f"{dst}.tmp.{os.getpid()}"
                    with open(tmp, "w") as f:
                        f.write(blob)
                        f.flush()
                        os.fsync(f.fileno())
                    # causelint: disable-next-line=DUR002 -- caller fsyncs out_dir once after the manifest swap (one dir fsync per drain, not one per tenant)
                    os.replace(tmp, dst)
            else:
                tmp = f"{dst}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(json.dumps(pack))
                    f.flush()
                    os.fsync(f.fileno())
                # causelint: disable-next-line=DUR002 -- caller fsyncs out_dir once after the manifest swap (one dir fsync per drain, not one per tenant)
                os.replace(tmp, dst)
            out[uuid] = {"file": rel}
        return out
