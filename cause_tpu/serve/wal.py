"""The segmented CRC write-ahead log — the durable-storage lifecycle
behind the serve layer's zero-admitted-op-loss guarantee.

:class:`IngestJournal` (PR 12) proved the WRITE-AHEAD contract but
kept the storage story a single ever-growing file with flush-but-no-
fsync appends: no reclamation after checkpoints, no defense against
bit-rot, no policy for a full disk. This module is the same journal
contract (record schema ``{"seq", "uuid", "site", "items", "ts_us"}``,
``append``/``iter_from``/``skipped``/``close``, drop-in for
``SyncService.restore`` and the net server's watermark seeding) with
real storage engineering underneath:

- **segments** — records land in numbered segment files
  (``wal-<n>.seg``) under one directory; segments rotate on size
  (``rotate_bytes``) and age (``rotate_s``), so retention has a unit
  smaller than "the whole history";
- **per-record CRC32 trailer** — every line is
  ``<json>\\t#<crc32 hex>``; a torn tail is an unparseable line
  (counted in ``skipped``, as before) and a BIT-ROTTED record — valid
  shape, wrong bytes — fails its CRC (counted in ``corrupt``), so
  at-rest corruption is detected, not silently replayed. Legacy
  bare-JSON lines (an old single-file journal's schema) still parse,
  so pre-WAL journals restore through :func:`open_journal` unchanged;
- **fsync policy** — ``none`` (flush only, the old behavior),
  ``batch`` (default: fsync every ``fsync_batch_n`` appends or
  ``fsync_batch_ms``, piggybacked on the appending thread) or
  ``always`` (fsync per append); overridable via the registered
  ``CAUSE_TPU_WAL_FSYNC`` env knob, measured in PERF.md Round 15;
- **crash-safe GC** — :meth:`gc` retires every SEALED segment whose
  records all sit at-or-below the caller's minimum live watermark
  (the serve manifest's ``gc_watermark`` — every such record is
  already applied AND checkpointed by its tenant). The WAL manifest
  (watermark + lifetime retirement accounting) is atomically renamed
  BEFORE any segment is unlinked, and a crash mid-GC leaves only
  below-watermark segments behind for the next pass — replay above
  the watermark is bit-identical before and after GC (pinned in
  tests), and long-running disk usage is BOUNDED while the
  single-file baseline (``appended_bytes``) grows monotonically.
  ``retire_dir`` renames retired segments aside instead of unlinking
  (archival mode — the soak's oracle replays them);
- **chaos seams** — the PR-15 ``disk`` family injects here: ``torn``
  and ``enospc`` fail the append (never acked — admission's
  durability rung refuses with ``retry_after_ms``), ``bitrot``
  corrupts an acked record's durable copy (CRC detects it; the op
  survives in service memory and the next checkpoint), ``fsync``
  fails a flush (the WAL rotates to a fresh segment), ``rename``
  aborts a GC manifest swap (segments intact, retried next cycle).
  Every degradation is one evidenced ``serve.disk`` event.

Stdlib-only and importable without jax (the obs rule): the WAL is
host work by definition.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

from .. import chaos as _chaos
from .. import obs
from ..collections import shared as s
from .ingest import IngestJournal

__all__ = ["WriteAheadLog", "open_journal", "FSYNC_POLICIES",
           "WAL_MANIFEST_NAME", "list_segments", "scan_segment_file",
           "fsync_dir"]

FSYNC_POLICIES = ("none", "batch", "always")
WAL_MANIFEST_NAME = "wal_manifest.json"
WAL_MANIFEST_VERSION = 1
_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".seg"
_CHAOS_SITE = "serve.wal"


# ------------------------------------------------------ record codec


def encode_record(rec: dict) -> str:
    """One journal line: the record JSON plus a tab-separated CRC32
    trailer over the JSON bytes (``json.dumps`` escapes raw tabs, so
    the LAST tab always splits body from trailer)."""
    body = json.dumps(rec)
    return (body + "\t#"
            + format(zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF,
                     "08x") + "\n")


def decode_line(line: str) -> Tuple[str, Optional[dict]]:
    """Classify one journal line: ``("rec", entry)`` for a CRC-clean
    trailered record, ``("legacy", entry)`` for a bare-JSON
    (pre-WAL) line, ``("corrupt", None)`` for a trailered line whose
    CRC does not match its body (bit-rot), ``("torn", None)`` for
    anything unparseable, ``("blank", None)`` for whitespace."""
    line = line.strip()
    if not line:
        return ("blank", None)
    body, sep, trailer = line.rpartition("\t")
    if sep and len(trailer) == 9 and trailer[0] == "#":
        try:
            want = int(trailer[1:], 16)
        except ValueError:
            want = None
        if want is not None:
            if zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF != want:
                return ("corrupt", None)
            try:
                e = json.loads(body)
            except ValueError:
                return ("torn", None)
            if isinstance(e, dict) and "seq" in e:
                return ("rec", e)
            return ("torn", None)
    try:
        e = json.loads(line)
    except ValueError:
        return ("torn", None)
    if isinstance(e, dict) and "seq" in e:
        return ("legacy", e)
    return ("torn", None)


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a DIRECTORY — makes a just-landed rename
    (or unlink) durable on POSIX. Some platforms refuse to open a
    directory read-only or to fsync the fd; both are quietly fine
    (the file-content fsync before the rename carries the integrity
    guarantee, this carries the name)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    finally:
        os.close(fd)


def list_segments(path: str) -> List[Tuple[int, str]]:
    """``(number, filename)`` for every segment file under ``path``,
    sorted by segment number (creation order == seq order)."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(path)
    except OSError:
        return out
    for n in names:
        if n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX):
            try:
                no = int(n[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
            except ValueError:
                continue
            out.append((no, n))
    out.sort()
    return out


def scan_segment_file(fp: str) -> Iterator[Tuple[str, Optional[dict]]]:
    """Yield ``decode_line`` classifications for one segment file —
    the shared walk the WAL's scans, the scrubber and the soak's
    oracle all use."""
    with open(fp, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            kind, e = decode_line(line)
            if kind != "blank":
                yield (kind, e)


# -------------------------------------------------------------- WAL


class WriteAheadLog:
    """See the module docstring. ``path`` is a DIRECTORY (the drop-in
    contract: ``.path`` is whatever the serve manifest's ``journal``
    field carries, and :func:`open_journal` routes a directory here
    and a file to :class:`IngestJournal`). Thread-safe like the
    journal it replaces: generators append while the service thread
    drains/GCs."""

    def __init__(self, path: str, rotate_bytes: int = 4 * 1024 * 1024,
                 rotate_s: Optional[float] = None,
                 fsync: Optional[str] = None,
                 fsync_batch_n: int = 64,
                 fsync_batch_ms: float = 50.0,
                 retire_dir: Optional[str] = None):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        if fsync is None:
            fsync = (os.environ.get("CAUSE_TPU_WAL_FSYNC", "").strip()
                     or "batch")
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r} "
                f"(want one of {FSYNC_POLICIES})")
        self.fsync_policy = fsync
        self.rotate_bytes = int(rotate_bytes)
        self.rotate_s = rotate_s
        self.fsync_batch_n = int(fsync_batch_n)
        self.fsync_batch_ms = float(fsync_batch_ms)
        self.retire_dir = retire_dir
        if retire_dir:
            os.makedirs(retire_dir, exist_ok=True)
        self._lock = threading.Lock()
        self.skipped = 0   # torn/unparseable lines, LATEST scan
        self.corrupt = 0   # CRC-mismatch lines, LATEST scan
        self.appended_bytes = 0  # lifetime bytes written — the
        # monotonic single-file baseline the bounded-disk gate
        # compares live usage against
        self.gc_watermark = 0
        self.stats = {"appends": 0, "append_failures": 0,
                      "rotations": 0, "fsyncs": 0, "fsync_failures": 0,
                      "gc_segments": 0, "gc_bytes": 0, "gc_aborts": 0}
        self._pending_fsync = 0
        self._last_fsync_s = time.monotonic()
        with self._lock:
            self._read_manifest_locked()
        # resume: index every existing segment (seq continues past the
        # max on disk AND past the manifest's max — after a full GC
        # there may be no record left to scan, and reusing a retired
        # seq would corrupt every watermark downstream)
        self._seq = max(self.gc_watermark, self._manifest_max_seq)
        self._index: List[dict] = []   # sealed segments, in order
        self.skipped = 0
        self.corrupt = 0
        segs = list_segments(self.path)
        for no, name in segs:
            sg = self._scan_segment_meta(name, no)
            self._index.append(sg)
            if sg["last_seq"]:
                self._seq = max(self._seq, sg["last_seq"])
        if self._index:
            active = self._index.pop()
            self._fh = open(os.path.join(self.path, active["name"]),
                            "a", encoding="utf-8")
            active["opened_s"] = time.monotonic()
            self._active = active
        else:
            self._active = None
            self._open_active_locked(1)
        with self._lock:
            self._gauges_locked()

    # -------------------------------------------------- construction

    def _scan_segment_meta(self, name: str, no: int) -> dict:
        first = last = None
        size = 0
        fp = os.path.join(self.path, name)
        try:
            size = os.path.getsize(fp)
            for kind, e in scan_segment_file(fp):
                if kind in ("rec", "legacy"):
                    q = int(e.get("seq", 0))
                    first = q if first is None else min(first, q)
                    last = q if last is None else max(last, q)
                elif kind == "corrupt":
                    self.corrupt += 1
                else:
                    self.skipped += 1
        except OSError:
            pass
        return {"name": name, "no": no, "first_seq": first,
                "last_seq": last, "bytes": size,
                "opened_s": time.monotonic()}

    def _read_manifest_locked(self) -> None:
        self._manifest_max_seq = 0
        p = os.path.join(self.path, WAL_MANIFEST_NAME)
        try:
            with open(p) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(m, dict) or "~wal_manifest" not in m:
            return  # advisory — the scrubber flags a broken one
        self.gc_watermark = int(m.get("gc_watermark") or 0)
        self._manifest_max_seq = int(m.get("max_seq") or 0)
        self.stats["gc_segments"] = int(m.get("retired_segments") or 0)
        self.stats["gc_bytes"] = int(m.get("retired_bytes") or 0)

    def _write_manifest_locked(self) -> None:
        m = {"~wal_manifest": WAL_MANIFEST_VERSION,
             "gc_watermark": self.gc_watermark,
             "max_seq": self._seq,
             "retired_segments": self.stats["gc_segments"],
             "retired_bytes": self.stats["gc_bytes"],
             "fsync": self.fsync_policy,
             "ts_us": time.time_ns() // 1000}
        p = os.path.join(self.path, WAL_MANIFEST_NAME)
        tmp = f"{p}.tmp.{os.getpid()}"
        # the rename below is gc()'s crash-safe commit point BEFORE
        # segments are unlinked — it must be durable regardless of the
        # append fsync policy, or a crash could persist the unlinks
        # while losing the manifest (watermark/max_seq reset to 0 and
        # the seq counter would reuse retired seqs). An OSError here
        # propagates and aborts the GC with segments intact, same as a
        # failed os.replace would.
        with open(tmp, "w") as f:
            f.write(json.dumps(m))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
        fsync_dir(self.path)

    def _open_active_locked(self, no: int) -> None:
        name = f"{_SEG_PREFIX}{no:08d}{_SEG_SUFFIX}"
        self._fh = open(os.path.join(self.path, name), "a",
                        encoding="utf-8")
        self._active = {"name": name, "no": no, "first_seq": None,
                        "last_seq": None, "bytes": 0,
                        "opened_s": time.monotonic()}

    # ------------------------------------------------------ evidence

    def _disk_event_locked(self, op: str, why: str) -> None:
        if obs.enabled():
            obs.counter("serve.disk_faults").inc()
            obs.event("serve.disk", op=op, why=why, path=self.path,
                      segment=self._active["name"])

    def _gauges_locked(self) -> None:
        if obs.enabled():
            live = sum(sg["bytes"] for sg in self._index) \
                + (self._active["bytes"] if self._active else 0)
            obs.gauge("serve.wal_segments").set(
                len(self._index) + (1 if self._active else 0))
            obs.gauge("serve.wal_bytes").set(live)

    # -------------------------------------------------------- append

    def append(self, uuid: str, site: str, items: list,
               ts_us: Optional[int] = None,
               trace: Optional[list] = None) -> int:
        """Durably record one admitted batch; returns its seq. Same
        contract as ``IngestJournal.append`` (write BEFORE the queue
        acknowledges), plus the disk chaos seams: a failed append
        raises ``CausalError`` naming the cause — the caller must NOT
        acknowledge (admission's durability rung refuses the offer)
        and the seq is not consumed. ``trace`` (PR 19): trace ids
        recorded in the row only when given, so replay re-links the
        journey; obs-off segment bytes stay pinned."""
        with self._lock:
            self._maybe_rotate_locked()
            seq = self._seq + 1
            rec = {"seq": seq, "uuid": str(uuid), "site": str(site),
                   "items": items,
                   "ts_us": int(ts_us if ts_us is not None
                                else time.time_ns() // 1000)}
            if trace:
                rec["trace"] = list(trace)
            body = json.dumps(rec)
            crc_hex = format(
                zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF, "08x")
            if _chaos.enabled():
                if _chaos.disk_enospc(_CHAOS_SITE):
                    self.stats["append_failures"] += 1
                    self._disk_event_locked("append", "enospc")
                    raise s.CausalError(
                        "wal: append refused (no space left)",
                        {"causes": {"wal-enospc"}, "path": self.path})
                if _chaos.disk_torn(_CHAOS_SITE):
                    # a crash mid-write: a prefix of the line lands
                    # (its own line, so later appends stay parseable)
                    # and the append FAILS — the op is never acked,
                    # the producer re-offers, the next scan counts
                    # exactly one torn line
                    torn = body[: max(1, len(body) // 2)] + "\n"
                    self._write_locked(torn)
                    self.stats["append_failures"] += 1
                    self._disk_event_locked("append", "torn")
                    raise s.CausalError(
                        "wal: append torn (crash mid-write)",
                        {"causes": {"wal-torn"}, "path": self.path})
                flip = _chaos.disk_bitrot(_CHAOS_SITE,
                                          len(body.encode("utf-8")),
                                          seq=seq, rec=rec)
                if flip is not None:
                    # at-rest rot of an ACKED record: the durable copy
                    # is wrong (CRC trailer still covers the original
                    # bytes, so the scan detects it), but the op was
                    # applied in memory and the next checkpoint
                    # persists it — detection + checkpoint bounding is
                    # the story, not un-acking. json.dumps output is
                    # printable ASCII, so ^0x01 never mints a newline.
                    raw = bytearray(body.encode("utf-8"))
                    raw[flip] ^= 0x01
                    body = raw.decode("latin-1")
                    self._disk_event_locked("append", "bitrot")
            self._write_locked(body + "\t#" + crc_hex + "\n")
            self._seq = seq
            a = self._active
            if a["first_seq"] is None:
                a["first_seq"] = seq
            a["last_seq"] = seq
            self.stats["appends"] += 1
            self._fsync_maybe_locked()
            self._gauges_locked()
        return seq

    def _write_locked(self, text: str) -> None:
        self._fh.write(text)
        self._fh.flush()
        n = len(text)
        self._active["bytes"] += n
        self.appended_bytes += n

    def _fsync_maybe_locked(self) -> None:
        p = self.fsync_policy
        if p == "none":
            return
        self._pending_fsync += 1
        now = time.monotonic()
        if p == "always" or self._pending_fsync >= self.fsync_batch_n \
                or (now - self._last_fsync_s) * 1000.0 \
                >= self.fsync_batch_ms:
            if not self._fsync_locked(now):
                # a descriptor that failed fsync has undefined durable
                # state: rotate to a fresh segment/fd
                self._rotate_locked(final_sync=False)

    def _fsync_locked(self, now: Optional[float] = None) -> bool:
        """fsync the active descriptor; returns success. Never rotates
        — the CALLER decides what a failure means, because this runs
        both standalone (append path — rotate to a fresh fd) and as a
        rotation's final sync (rotating from in here would reenter
        ``_rotate_locked`` and seal the same segment twice)."""
        ok = True
        if _chaos.enabled() and _chaos.disk_fsync_fail(_CHAOS_SITE):
            ok = False
        else:
            try:
                os.fsync(self._fh.fileno())
            except OSError:  # pragma: no cover - real media failure
                ok = False
        if ok:
            self.stats["fsyncs"] += 1
        else:
            self.stats["fsync_failures"] += 1
            self._disk_event_locked("fsync", "fsync-failed")
        self._pending_fsync = 0
        self._last_fsync_s = now if now is not None else time.monotonic()
        return ok

    # ------------------------------------------------------ rotation

    def _maybe_rotate_locked(self) -> None:
        a = self._active
        if a["bytes"] <= 0:
            return
        if a["bytes"] >= self.rotate_bytes \
                or (self.rotate_s is not None
                    and time.monotonic() - a["opened_s"]
                    >= self.rotate_s):
            self._rotate_locked()

    def _rotate_locked(self, final_sync: bool = True) -> None:
        a = self._active
        if a["bytes"] <= 0:
            return
        if final_sync and self.fsync_policy != "none" \
                and self._pending_fsync:
            # failure is evidenced inside; no further action here —
            # this fd is being retired anyway and its replacement is
            # a fresh descriptor
            self._fsync_locked()
        try:
            self._fh.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass
        self._index.append(a)
        self.stats["rotations"] += 1
        self._open_active_locked(a["no"] + 1)
        self._gauges_locked()

    # ---------------------------------------------------------- scan

    def _scan(self) -> Iterator[dict]:
        # ``skipped``/``corrupt`` are the LATEST scan's counts, same
        # contract as IngestJournal (summing scans would over-report
        # one torn line as several)
        self.skipped = 0
        self.corrupt = 0
        with self._lock:
            self._fh.flush()
            names = [sg["name"] for sg in self._index] \
                + [self._active["name"]]
        for name in names:
            fp = os.path.join(self.path, name)
            if not os.path.exists(fp):
                continue
            for kind, e in scan_segment_file(fp):
                if kind in ("rec", "legacy"):
                    yield e
                elif kind == "corrupt":
                    self.corrupt += 1
                else:
                    self.skipped += 1

    def iter_from(self, min_seq_exclusive: int = 0) -> Iterator[dict]:
        """Entries with ``seq > min_seq_exclusive``, journal order —
        the drop-in replay contract restore and the net server's
        watermark seeding depend on."""
        wm = int(min_seq_exclusive)
        for e in self._scan():
            if int(e.get("seq", 0)) > wm:
                yield e

    # ------------------------------------------------------------ GC

    def gc(self, min_live_seq: int) -> dict:
        """Retire every sealed segment whose records all sit at or
        below ``min_live_seq`` (the serve manifest's minimum live
        watermark — everything below it is applied AND checkpointed by
        its tenant). Crash-safe order: the WAL manifest (watermark +
        retirement accounting) is atomically renamed FIRST, then
        segments are unlinked (or renamed into ``retire_dir``); the
        chaos crash point ``serve.wal.gc`` fires between the two, and
        a crash there leaves only below-watermark segments for the
        next pass — replay above the watermark is identical either
        way. A sealed segment with no valid record (all torn — every
        line unacknowledged by construction) retires at any
        watermark. Returns retirement accounting."""
        wm = int(min_live_seq)
        with self._lock:
            if _chaos.enabled() and _chaos.disk_rename_fail(
                    _CHAOS_SITE):
                # the manifest swap failed: segments intact, watermark
                # unadvanced, retried next cycle — evidenced, never
                # silent
                self.stats["gc_aborts"] += 1
                self._disk_event_locked("gc", "rename-failed")
                return {"retired": 0, "retired_bytes": 0,
                        "watermark": self.gc_watermark,
                        "aborted": True}
            self.gc_watermark = max(self.gc_watermark, wm)
            retire = [sg for sg in self._index
                      if (sg["last_seq"] or 0) <= self.gc_watermark]
            self._write_manifest_locked()
            if retire and _chaos.enabled() \
                    and _chaos.should_crash("serve.wal.gc"):  # causelint: disable=DUR004 -- the seam MUST sit between the manifest swap and the unlinks, both under _lock by design; the raise unwinds the with, and a real crash releases the lock with the process
                from .service import ServiceCrashed

                raise ServiceCrashed(
                    "chaos: crash point at serve.wal.gc "
                    "(manifest written, segments not yet retired)")
            n = b = 0
            for sg in retire:
                src = os.path.join(self.path, sg["name"])
                try:
                    if self.retire_dir:
                        os.replace(src, os.path.join(self.retire_dir,
                                                     sg["name"]))
                    else:
                        os.unlink(src)
                except OSError:  # pragma: no cover - skip, retry later
                    continue
                self._index.remove(sg)
                n += 1
                b += sg["bytes"]
            self.stats["gc_segments"] += n
            self.stats["gc_bytes"] += b
            if n:
                self._write_manifest_locked()
            self._gauges_locked()
            return {"retired": n, "retired_bytes": b,
                    "watermark": self.gc_watermark, "aborted": False}

    # ------------------------------------------------------- queries

    def dir_bytes(self) -> int:
        """Live WAL directory size (segments + manifest) — the
        bounded-disk gate's measure."""
        with self._lock:
            names = [sg["name"] for sg in self._index] \
                + [self._active["name"], WAL_MANIFEST_NAME]
        total = 0
        for name in names:
            try:
                total += os.path.getsize(os.path.join(self.path, name))
            except OSError:
                continue
        return total

    def wal_report(self) -> dict:
        with self._lock:
            report = {"segments": len(self._index) + 1,
                      "appended_bytes": self.appended_bytes,
                      "gc_watermark": self.gc_watermark,
                      "fsync": self.fsync_policy,
                      "stats": dict(self.stats)}
        # dir_bytes takes the lock itself — must stay outside it
        report["live_bytes"] = self.dir_bytes()
        return report

    def close(self) -> None:
        with self._lock:
            try:
                if self.fsync_policy != "none" and self._pending_fsync:
                    # causelint: disable-next-line=LCK003 -- the final fsync rides _lock by design: close() must not race an append into a half-synced handle, and nothing contends after close
                    os.fsync(self._fh.fileno())
                    self.stats["fsyncs"] += 1
            except OSError:  # pragma: no cover - close is best-effort
                pass
            try:
                self._fh.close()
            except OSError:  # pragma: no cover - close is best-effort
                pass


def open_journal(path: str, **wal_kwargs):
    """The journal constructor restore paths use: a DIRECTORY is a
    :class:`WriteAheadLog`, anything else is a legacy single-file
    :class:`IngestJournal` — so old manifests (whose ``journal`` field
    names a file) keep restoring unchanged."""
    p = str(path)
    if os.path.isdir(p):
        return WriteAheadLog(p, **wal_kwargs)
    return IngestJournal(p)
