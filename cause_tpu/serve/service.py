"""The long-lived serving loop: admission → coalesce → wave, with a
crash-safe lifecycle.

One :class:`SyncService` owns the three serve pieces (queue,
controller, residency) plus the per-tenant journal watermarks, and
runs the loop the whole obs substrate was built to observe:

- **tick** — drain admitted batches, route each per-site delta to its
  tenant pair's side (stable site hash), apply through the validated
  merge path (``sync.apply_delta``: delta evidence, lag stamping, cost
  joins all come for free), splice the appends into the resident
  session (``FleetSession.update``) and run ONE wave — the delta-
  native steady state. Each tick emits one ``serve.tick`` event and a
  ``run.heartbeat``, polls the live attachment, and lets the
  controller move ``T_batch``;
- **watchdog** — a daemon thread watching the tick heartbeat; a tick
  age past ``watchdog_s`` emits one ``serve.watchdog`` event per
  excursion (the in-process twin of the ``absence:serve.tick`` live
  rule);
- **drain** — stop admission → flush the queue (deferred entries
  included) → every tenant wave-current → checkpoint everything
  (per-tenant packs + one atomic manifest with the journal
  watermarks);
- **restore** — rebuild every tenant from its pack (digest
  bit-identity gated, PR 11), then replay the ingest journal ABOVE
  each tenant's manifest watermark — so a crash at ANY point between
  admission and checkpoint loses zero admitted ops (the journal is
  write-ahead; replayed merges are idempotent and the PR-9 lamport
  watermark keeps re-applied converged ops out of the lag
  distribution). The restored fleet resumes steady-state DELTA waves
  (the frontier rides the pack).

Chaos: the engine's crash points (``serve.tick`` / ``serve.drain``)
raise :class:`ServiceCrashed` — the harness drops the service object
(all in-memory state: queue contents, sessions, watermarks) and calls
:meth:`SyncService.restore`, exactly the soak's session-crash shape
one level up.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

from .. import chaos as _chaos
from .. import obs
from .. import serde
from .. import sync
from ..collections import shared as s
from ..obs import xtrace
from .batch import BatchScheduler
from .controller import BatchController
from .ingest import IngestQueue
from .residency import ResidencyManager
from .wal import fsync_dir, open_journal

__all__ = ["ServiceCrashed", "SyncService"]

MANIFEST_VERSION = 1
MANIFEST_NAME = "serve_manifest.json"


class ServiceCrashed(RuntimeError):
    """A chaos-injected service crash: the harness must drop this
    instance and ``SyncService.restore`` from the last checkpoint +
    journal. Nothing else in the repo raises it."""


class SyncService:
    """See the module docstring. Construction wires the live
    attachment (obs on) with the controller registered as the alert
    callback; tenants register via :meth:`add_tenant` (or arrive via
    :meth:`restore`)."""

    def __init__(self, queue: IngestQueue,
                 controller: Optional[BatchController] = None,
                 residency: Optional[ResidencyManager] = None,
                 checkpoint_dir: Optional[str] = None,
                 d_max: int = 64, watchdog_s: Optional[float] = None,
                 batched: bool = True):
        self.queue = queue
        if queue.tenant_known is None:
            # close the front door to uuids nobody serves — such an op
            # would be journaled and acknowledged but never appliable
            queue.tenant_known = self._knows_tenant
        self.controller = controller or BatchController()
        self.residency = residency or ResidencyManager(capacity=64)
        # cross-tenant batched ticks (PR 18): touched tenants' delta
        # windows ride ONE fused dispatch per pow2 bucket instead of
        # one wave per tenant. ``batched=False`` is the escape hatch —
        # the per-tenant path, kept for the bit-identity pin and for
        # bisection; digests, journal contents and lag resolution are
        # identical either way.
        self.batched = bool(batched)
        self.residency.batched = self.batched
        self._scheduler = BatchScheduler(site="serve")
        self.checkpoint_dir = checkpoint_dir
        self.d_max = int(d_max)
        self.watchdog_s = watchdog_s
        self.tenants: Dict[str, dict] = {}  # uuid -> {"applied_seq"}
        self.ticks = 0
        self.last_tick_us = 0
        self._watchdog_thread = None
        self._watchdog_stop = threading.Event()
        self._watchdog_firing = False
        self._live = None
        self._ship = None
        if obs.enabled():
            from ..obs import live as _live

            self._live = _live.attach(
                on_alert=[self.controller.on_alert], source="serve")
            # PR 20: the fleet telemetry uplink — every obs record
            # this process mints ships to the collector named by
            # CAUSE_TPU_OBS_SHIP ("host:port"). Best-effort by
            # contract: an unreachable collector costs a bounded
            # buffer + evidenced drops, never admission latency; an
            # unparseable endpoint is ignored (the local sidecar
            # still has everything).
            endpoint = ship_mod = None
            raw = os.environ.get("CAUSE_TPU_OBS_SHIP")
            if raw:
                from ..obs import ship as ship_mod

                endpoint = ship_mod.parse_endpoint(raw)
            if endpoint is not None:
                self._ship = ship_mod.attach_exporter(*endpoint)

    # ------------------------------------------------------- tenants

    def _knows_tenant(self, uuid: str) -> bool:
        return uuid in self.tenants

    def add_tenant(self, left, right,
                   d_max: Optional[int] = None) -> str:
        """Register one tenant document as the replica pair (left,
        right) — distinct sites of one uuid. Uploads the session and
        runs the first (full) wave so the tenant is immediately
        checkpointable/evictable. ``d_max`` overrides the service's
        delta budget for THIS tenant (a hot tenant earns a wider
        window); tenants with different budgets land in different
        pow2 batch buckets — heterogeneity costs extra dispatches per
        tick, never correctness."""
        from ..parallel.session import FleetSession

        uuid = str(left.ct.uuid)
        if uuid in self.tenants:
            # the PR-13 foot-gun: evolve() KEEPS the uuid, so two
            # tenants built from one ancestor collide here — and a
            # silent overwrite cross-wires both tenants' journal
            # watermarks and residency slots (it corrupted the first
            # net soak run). Mint a fresh clist per tenant instead.
            if obs.enabled():
                obs.counter("serve.refusals").inc()
                obs.event("serve.refusal", op="add_tenant",
                          why="duplicate-tenant", uuid=uuid)
            raise s.CausalError(
                "serve: duplicate tenant uuid",
                {"causes": {"duplicate-tenant"}, "uuid": uuid,
                 "why": "evolve() keeps the uuid — a second tenant "
                        "must start from a fresh clist, not an "
                        "evolve() of an already-registered one"})
        sess = FleetSession([(left, right)],
                            d_max=self.d_max if d_max is None
                            else int(d_max))
        sess.wave()
        self.residency.insert(uuid, sess)
        self.tenants[uuid] = {"applied_seq": 0}
        return uuid

    # ---------------------------------------------------------- tick

    @staticmethod
    def _side_of(site: str, side_ids) -> int:
        """Stable site→side routing: a delta from one of the pair's
        OWN sites lands on that replica (its causes live there by
        construction); a foreign site hashes to a stable side, so all
        of one site's deltas land on one side of the pair, preserving
        the per-site prefix order the delta protocol assumes."""
        site = str(site)
        if site == side_ids[0]:
            return 0
        if site == side_ids[1]:
            return 1
        return zlib.crc32(site.encode()) & 1

    def _apply_batches(self, uuid: str, entries: List,
                       sess=None, wave: bool = True):
        """COALESCE one tenant's drained batches into one wave batch
        per side, apply, and wave once — the admission queue's whole
        point: a deep backlog costs two merges of the unioned delta
        (O(coalesced ops)), not one merge per journaled batch, so the
        tick wall scales with the offered op rate, never with how far
        behind the service fell. The union is sound because a site's
        re-offered deltas are cumulative (yarn suffixes nest) and
        identical nodes union idempotently. Sides whose causes are
        not yet visible (cross-site ordering inside one tick) retry
        after the other side; a union that still fails is retried on
        the other replica before being declared poison — admitted ops
        are never silently dropped.

        ``wave=False`` stops before the wave (the batched tick waves
        all touched tenants at once via the scheduler); ``sess`` skips
        the residency touch when the caller already holds the session
        (``get_many``). Returns the session."""
        if sess is None:
            sess = self.residency.get(uuid)
        if sess is None:
            if obs.enabled():
                obs.counter("serve.refusals").inc()
                obs.event("serve.refusal", op="apply",
                          why="unknown-tenant", uuid=uuid)
            raise s.CausalError(
                "serve: batch for unknown tenant",
                {"causes": {"unknown-tenant"}, "uuid": uuid})
        left, right = sess.pairs[0]
        sides = [left, right]
        side_ids = (str(left.ct.site_id), str(right.ct.site_id))
        unions: List[dict] = [{}, {}]
        for e in entries:
            i = self._side_of(e.site, side_ids)
            unions[i].update(serde.decode_node_items(e.items))
        pending = [i for i in (0, 1) if unions[i]]
        for attempt in (0, 1):
            retry = []
            for i in pending:
                try:
                    sides[i] = sync.apply_delta(sides[i], unions[i])
                except s.CausalError as ce:
                    if "cause-must-exist" not in \
                            ce.info.get("causes", ()):
                        raise
                    if attempt == 0:
                        retry.append(i)
                        continue
                    # last resort: a foreign-site delta whose causes
                    # live only on the other replica — try the other
                    # side before declaring it poison
                    sides[1 - i] = sync.apply_delta(sides[1 - i],
                                                    unions[i])
            pending = retry
            if not pending:
                break
        sess.update([(sides[0], sides[1])])
        if wave:
            sess.wave()
        self.tenants[uuid]["applied_seq"] = max(
            self.tenants[uuid]["applied_seq"],
            max(e.seq for e in entries))
        return sess

    def tick(self, max_ops: Optional[int] = None) -> dict:
        """One service tick: drain → apply/update per touched tenant →
        wave (batched: one fused dispatch per pow2 bucket over ALL
        touched tenants; unbatched: one wave per tenant) → poll the
        live feed → move T_batch. Returns a small summary dict (ops
        drained, tenants touched, current t_batch_ms, queue depth
        after, and the tick's bucket/dispatch accounting).

        The default drain bound is ``d_max`` — the session's delta
        window budget. Coalescing more ops than the window holds
        would bounce every touched tenant to the O(doc) full-width
        wave (measured at ~70x a delta wave on this substrate), so a
        deep backlog drains as several cheap delta ticks instead of
        one catastrophic full one; a SINGLE batch larger than the
        window still degrades loudly rather than wedging the queue
        (the queue always yields at least one batch)."""
        if _chaos.enabled() and _chaos.should_crash("serve.tick"):
            raise ServiceCrashed("chaos: crash point at serve.tick")
        self.ticks += 1
        self.last_tick_us = time.time_ns() // 1000
        self._watchdog_firing = False
        entries = self.queue.drain(self.d_max if max_ops is None
                                   else max_ops)
        by_tenant: Dict[str, List] = {}
        for e in entries:
            by_tenant.setdefault(e.uuid, []).append(e)
        known: List = []
        for uuid, batch in by_tenant.items():
            if uuid not in self.tenants:
                # the door predicate makes this unreachable for new
                # offers; a batch admitted before its tenant vanished
                # is an orphan — skipped LOUDLY, never a crashed tick
                # that drops the other tenants' drained entries
                if obs.enabled():
                    obs.counter("serve.orphan_batches").inc()
                    obs.event("serve.orphan_batch", uuid=uuid,
                              ops=sum(e.ops for e in batch))
                continue
            known.append((uuid, batch))
        # trace continuation (PR 19): every drained entry's traces get
        # a "tick" hop here and the per-tenant map rides into the
        # scheduler so the fused bucket span can fan out per-tenant
        # "wave" child hops
        traces_by_uuid: Dict[str, List[str]] = {}
        if obs.enabled():
            for uuid, batch in known:
                seen = traces_by_uuid.setdefault(uuid, [])
                for e in batch:
                    for tr in (e.traces or ()):
                        xtrace.hop("tick", tr, uuid=uuid, seq=e.seq,
                                   ops=e.ops, tick=self.ticks)
                        if tr not in seen:
                            seen.append(tr)
        # the tick's device dispatch count, read from the costmodel
        # counter (not inferred): the batched tick's whole claim is
        # that this collapses from O(#tenants) to O(#buckets)
        disp0 = obs.counter("costmodel.dispatches").value \
            if obs.enabled() else 0
        buckets = 0
        batch_rows = 0
        fallbacks = 0
        if self.batched:
            # batched tick: residency-capacity-sized groups — touch
            # the whole group first (a restore's evictions can only
            # hit tenants outside the group, which are wave-current
            # between ticks), coalesce and update every member, then
            # ONE fused dispatch per pow2 bucket via the scheduler
            cap = max(1, self.residency.capacity)
            for i in range(0, len(known), cap):
                chunk = known[i:i + cap]
                group = self.residency.get_many(
                    [u for u, _b in chunk])
                for uuid, batch in chunk:
                    self._apply_batches(uuid, batch,
                                        sess=group.get(uuid),
                                        wave=False)
                self._scheduler.wave_fleet(
                    group, traces_by_uuid=traces_by_uuid)
                buckets += self._scheduler.last_buckets
                batch_rows += self._scheduler.last_batch_rows
                fallbacks += self._scheduler.last_fallbacks
        else:
            for uuid, batch in known:
                self._apply_batches(uuid, batch)
                if obs.enabled():
                    for tr in traces_by_uuid.get(uuid, ()):
                        xtrace.hop("wave", tr, uuid=uuid,
                                   path="per-tenant")
        wave_dispatches = (obs.counter("costmodel.dispatches").value
                           - disp0) if obs.enabled() else 0
        snap = None
        if self._live is not None and not self._live.closed:
            snap = self._live.poll(emit_snapshot=True)
        if snap is not None:
            self.controller.update(snap)
        ops = sum(e.ops for e in entries)
        if obs.enabled():
            obs.counter("serve.ticks").inc()
            obs.event("serve.tick", ops=ops,
                      tenants=len(by_tenant),
                      depth=self.queue.depth,
                      resident=self.residency.resident_docs,
                      t_batch_ms=round(self.controller.t_batch_ms, 3),
                      buckets=buckets, batch_rows=batch_rows,
                      wave_dispatches=wave_dispatches,
                      fallbacks=fallbacks)
            obs.event("run.heartbeat", stage="serve.tick",
                      ticks=self.ticks, ops=ops)
        return {"ops": ops, "tenants": len(by_tenant),
                "t_batch_ms": self.controller.t_batch_ms,
                "depth": self.queue.depth,
                "buckets": buckets, "batch_rows": batch_rows,
                "wave_dispatches": wave_dispatches}

    def run(self, seconds: float, max_ops: Optional[int] = None) -> int:
        """The paced loop: tick, then sleep the controller's current
        ``T_batch`` — but only when the queue is EMPTY. The coalescing
        sleep exists to build a batch worth waving; once a backlog
        exists the batch is already built, and sleeping would add pure
        admission lag. Returns ticks run. Starts the watchdog when
        ``watchdog_s`` is set."""
        self.start_watchdog()
        deadline = time.monotonic() + float(seconds)
        n = 0
        try:
            while time.monotonic() < deadline:
                self.tick(max_ops)
                n += 1
                if self.queue.depth == 0:
                    time.sleep(self.controller.t_batch_ms / 1000.0)
        finally:
            self.stop_watchdog()
        return n

    # ------------------------------------------------------ watchdog

    def start_watchdog(self) -> None:
        if self.watchdog_s is None or self._watchdog_thread is not None:
            return
        self._watchdog_stop.clear()

        def _watch():
            while not self._watchdog_stop.wait(self.watchdog_s / 4.0):
                last = self.last_tick_us
                if not last:
                    continue
                age_s = (time.time_ns() // 1000 - last) / 1e6
                if age_s > self.watchdog_s and not self._watchdog_firing:
                    # one event per excursion — tick() re-arms
                    self._watchdog_firing = True
                    if obs.enabled():
                        obs.counter("serve.watchdog").inc()
                        obs.event("serve.watchdog",
                                  age_s=round(age_s, 3),
                                  limit_s=self.watchdog_s)

        self._watchdog_thread = threading.Thread(
            target=_watch, name="serve-watchdog", daemon=True)
        self._watchdog_thread.start()

    def stop_watchdog(self) -> None:
        if self._watchdog_thread is None:
            return
        self._watchdog_stop.set()
        self._watchdog_thread.join(timeout=2.0)
        self._watchdog_thread = None

    def close(self) -> None:
        """Release the service's process-global hooks: stop the
        watchdog and detach the live subscription. A crash/restore
        loop builds a fresh SyncService per incarnation — without
        this, every dead incarnation's subscriber stays registered on
        the obs sink and every later record pays an enqueue into it.
        Idempotent; drain() calls it once the checkpoint lands."""
        self.stop_watchdog()
        if self._live is not None:
            self._live.close()
            self._live = None
        if self._ship is not None:
            # best-effort final flush then detach — whatever cannot
            # ship in the bounded window is counted in
            # stats["unshipped"], never waited on
            self._ship.close()
            self._ship = None
        if self.queue.tenant_known == self._knows_tenant:
            # a retired queue handle must not pin this service's whole
            # object graph (residency -> every tenant's device state)
            # through the bound predicate
            self.queue.tenant_known = None

    # -------------------------------------------------- checkpointing

    def checkpoint(self, out_dir: Optional[str] = None) -> str:
        """Persist the whole service: every tenant's pack (resident
        sessions are wave-current after any tick) plus ONE manifest
        carrying the per-tenant journal watermarks, atomically
        renamed last — a crash mid-checkpoint leaves the previous
        manifest intact and the journal replays the difference."""
        out_dir = out_dir or self.checkpoint_dir
        if not out_dir:
            raise ValueError("no checkpoint dir configured")
        with obs.span("serve.checkpoint", tenants=len(self.tenants)):
            files = self.residency.checkpoint_all(out_dir)
            # the minimum live watermark: every journal record at or
            # below it is applied by its tenant AND captured by the
            # packs just written — the WAL's GC retires segments
            # wholly below it once the manifest rename lands
            min_seq = min((t["applied_seq"]
                           for t in self.tenants.values()), default=0)
            manifest = {
                "~serve_manifest": MANIFEST_VERSION,
                "ts_us": time.time_ns() // 1000,
                "journal": (self.queue.journal.path
                            if self.queue.journal else None),
                "gc_watermark": min_seq,
                # the admission regime rides the manifest so a
                # queue-less restore() rebuilds the SAME bounds — a
                # restart must not quietly relax them
                "queue": {
                    "max_ops": self.queue.max_ops,
                    "defer_watermark": self.queue.defer_watermark,
                    "defer_max": self.queue.defer_max,
                    "deadline_ms": self.queue.deadline_ms,
                },
                "residency_capacity": self.residency.capacity,
                "tenants": {
                    uuid: {"file": files[uuid]["file"],
                           "seq": self.tenants[uuid]["applied_seq"]}
                    for uuid in self.tenants if uuid in files
                },
            }
            path = os.path.join(out_dir, MANIFEST_NAME)
            tmp = f"{path}.tmp.{os.getpid()}"
            # the rename below is the commit point the post-checkpoint
            # GC trusts before it unlinks superseded packs and WAL
            # segments — fsync the contents first (and the directory
            # after) so a crash cannot persist the unlinks while
            # losing the manifest that justified them
            with open(tmp, "w") as f:
                f.write(json.dumps(manifest))
                f.flush()
                os.fsync(f.fileno())
            try:
                if _chaos.enabled() \
                        and _chaos.disk_rename_fail("serve.checkpoint"):
                    raise OSError("chaos: injected rename failure")
                os.replace(tmp, path)
            except OSError as e:
                # the atomic swap failed: the PREVIOUS manifest is
                # untouched (that is the whole point of rename-last)
                # and the journal still covers everything since it —
                # evidence the fault, drop the orphan tmp, and let the
                # caller retry the checkpoint
                try:
                    os.unlink(tmp)
                except OSError:  # pragma: no cover - best-effort
                    pass
                if obs.enabled():
                    obs.counter("serve.disk_faults").inc()
                    obs.event("serve.disk", op="checkpoint",
                              why="rename-failed", path=path)
                raise s.CausalError(
                    "serve: checkpoint manifest rename failed "
                    "(previous manifest intact)",
                    {"causes": {"checkpoint-rename"},
                     "path": path}) from e
            fsync_dir(out_dir)
            if obs.enabled():
                obs.counter("serve.checkpoints").inc()
            self._storage_gc(out_dir, min_seq, manifest)
        return path

    def _storage_gc(self, out_dir: str, min_seq: int,
                    manifest: dict) -> None:
        """Post-checkpoint retention, one policy for all three
        storage surfaces: retire WAL segments wholly below the
        manifest's watermark (crash-safe inside ``wal.gc``), sweep
        superseded checkpoint packs + orphaned tmp files out of the
        checkpoint dir, and sweep stale residency spill packs. Runs
        only AFTER the manifest rename landed — everything removed is
        re-derivable from the manifest + surviving journal suffix.

        The checkpoint dir is assumed EXCLUSIVE to one service: the
        sweep removes every ``*.ckpt.json``/``.tmp.`` file the current
        manifest doesn't name, deliberately including debris a crashed
        prior incarnation left behind (whose in-memory ownership is
        unrecoverable). Two services — or an operator's manual
        checkpoint — sharing one directory WOULD have their packs
        swept by each other; point each at its own directory."""
        j = self.queue.journal
        wal_gc = None
        if j is not None and hasattr(j, "gc"):
            wal_gc = j.gc(min_seq)
        live = {info["file"] for info in manifest["tenants"].values()}
        live.add(MANIFEST_NAME)
        swept = swept_bytes = 0
        for name in os.listdir(out_dir):
            if name in live:
                continue
            if not (name.endswith(".ckpt.json") or ".tmp." in name):
                continue  # never touch files this service didn't write
            fp = os.path.join(out_dir, name)
            try:
                nb = os.path.getsize(fp)
                os.unlink(fp)
            except OSError:
                continue
            swept += 1
            swept_bytes += nb
        spill_bytes = self.residency.sweep_spill()
        if obs.enabled():
            obs.event("serve.gc", watermark=min_seq,
                      wal_retired=(wal_gc or {}).get("retired", 0),
                      wal_retired_bytes=(wal_gc or {}).get(
                          "retired_bytes", 0),
                      wal_aborted=bool((wal_gc or {}).get("aborted")),
                      packs_swept=swept,
                      packs_swept_bytes=swept_bytes,
                      spill_swept_bytes=spill_bytes)

    def drain(self, out_dir: Optional[str] = None) -> str:
        """Graceful drain: stop admission → flush the queue (deferred
        promotion included) → converge (every touched tenant waves in
        its flush tick; the fleet state IS a wave's output) →
        checkpoint. Returns the manifest path. The chaos crash point
        ``serve.drain`` fires between flush ticks — a crash mid-drain
        restores from the previous checkpoint + journal with zero
        admitted-op loss."""
        self.queue.close_admission()
        if obs.enabled():
            obs.event("serve.drain", phase="start",
                      depth=self.queue.depth,
                      deferred=self.queue.deferred)
        while self.queue.depth or self.queue.deferred:
            if _chaos.enabled() and _chaos.should_crash("serve.drain"):
                raise ServiceCrashed(
                    "chaos: crash point at serve.drain")
            before_depth = self.queue.depth
            before_def = self.queue.deferred
            self.tick()
            if before_depth == 0 and self.queue.depth == 0 \
                    and self.queue.deferred >= before_def:
                # a whole tick neither drained nor promoted anything:
                # the parked entries can never promote (a single batch
                # larger than the defer watermark) — shed them with
                # evidence rather than spin; they were never admitted
                # (never journaled), so the no-loss contract holds.
                # NOTE the exit condition is exact, not a heuristic:
                # the loop only ever ends with depth == 0 AND
                # deferred == 0 — a promotion that lands new admitted
                # (journaled) ops in the queue forces another flush
                # tick, so the checkpoint below can never strand an
                # admitted op (that hole is what the journal replay
                # would otherwise have to cover)
                self.queue.shed_stranded()
        path = self.checkpoint(out_dir)
        if obs.enabled():
            obs.event("serve.drain", phase="done",
                      tenants=len(self.tenants))
        self.close()
        return path

    def converged_digest(self, uuid: str) -> int:
        """The tenant's last wave digest — the drain/restart
        bit-identity gate's comparand (one int per tenant)."""
        sess = self.residency.get(uuid)
        return int(sess._last_digest[0])

    def materialize(self, uuid: str):
        """The tenant's converged document (host handle) from the
        resident wave state — the oracle comparison surface."""
        sess = self.residency.get(uuid)
        return sess.merged(0)

    # -------------------------------------------------------- restore

    @classmethod
    def restore(cls, checkpoint_dir: str,
                queue: Optional[IngestQueue] = None,
                controller: Optional[BatchController] = None,
                residency: Optional[ResidencyManager] = None,
                d_max: int = 64,
                watchdog_s: Optional[float] = None,
                batched: bool = True) -> "SyncService":
        """Rebuild a service from :meth:`checkpoint` output: every
        tenant restored through the digest gate, then the ingest
        journal replayed above each tenant's watermark (validated
        again at the boundary — a journal is a file, files tear).
        The restored tenants resume steady-state delta waves."""
        from ..parallel.session import FleetSession

        if os.path.basename(checkpoint_dir) == MANIFEST_NAME:
            # drain() returns the manifest PATH; accept it here too so
            # restore(drain()) round-trips without a dirname() dance
            checkpoint_dir = os.path.dirname(checkpoint_dir)
        mpath = os.path.join(checkpoint_dir, MANIFEST_NAME)
        with open(mpath) as f:
            manifest = json.load(f)
        if not (isinstance(manifest, dict)
                and manifest.get("~serve_manifest") == MANIFEST_VERSION):
            # causelint: disable-next-line=EVD001 -- restore() runs pre-stream at process start; the raise reaches the operator directly and there is no obs stream to evidence into yet
            raise s.CausalError(
                "not a serve manifest (or unknown version)",
                {"causes": {"checkpoint-mismatch"}})
        journal_path = manifest.get("journal")
        if queue is None:
            # open_journal routes a directory to the segmented WAL
            # and a legacy single-file path to IngestJournal — old
            # manifests restore unchanged
            journal = (open_journal(journal_path)
                       if journal_path else None)
            qcfg = manifest.get("queue") or {}
            queue = IngestQueue(
                max_ops=int(qcfg.get("max_ops", 4096)),
                defer_max=int(qcfg.get("defer_max", 256)),
                deadline_ms=qcfg.get("deadline_ms"),
                journal=journal)
            if "defer_watermark" in qcfg:
                queue.defer_watermark = int(qcfg["defer_watermark"])
        if residency is None and manifest.get("residency_capacity"):
            residency = ResidencyManager(
                capacity=int(manifest["residency_capacity"]))
        svc = cls(queue, controller=controller, residency=residency,
                  checkpoint_dir=checkpoint_dir, d_max=d_max,
                  watchdog_s=watchdog_s, batched=batched)
        with obs.span("serve.restore",
                      tenants=len(manifest.get("tenants") or {})):
            for uuid, info in (manifest.get("tenants") or {}).items():
                sess = FleetSession.restore(
                    os.path.join(checkpoint_dir, info["file"]))
                svc.residency.insert(uuid, sess)
                svc.tenants[uuid] = {"applied_seq": int(info["seq"])}
            replayed = svc._replay_journal(journal_path)
            if obs.enabled():
                obs.counter("serve.journal_replays").inc(replayed)
                obs.event("serve.restored",
                          tenants=len(svc.tenants), replayed=replayed)
        return svc

    def _replay_journal(self, journal_path: Optional[str]) -> int:
        """Apply journal entries above each tenant's watermark —
        admission-order, re-validated, grouped per tenant so each
        touched tenant pays one update+wave. Returns ops replayed.
        Idempotence: merges of already-present nodes are no-ops, and
        the lag tracer's lamport watermark keeps long-converged ops
        out of the distribution (PR 9)."""
        if not journal_path or not os.path.exists(journal_path):
            return 0
        min_seq = min((t["applied_seq"] for t in self.tenants.values()),
                      default=0)
        by_tenant: Dict[str, List] = {}
        # replay the MANIFEST's journal, not whatever journal the
        # caller's queue happens to carry — a restart that rotates to
        # a fresh journal file must still replay the old one, or every
        # op admitted after the last checkpoint silently vanishes
        qj = self.queue.journal
        if qj is not None and qj.path == journal_path:
            journal, borrowed = qj, True
        else:
            journal, borrowed = open_journal(journal_path), False
        for e in journal.iter_from(min_seq):
            uuid = str(e.get("uuid"))
            t = self.tenants.get(uuid)
            if t is None or int(e["seq"]) <= t["applied_seq"]:
                continue
            items = e.get("items")
            try:
                sync.validate_node_items(items)
            except s.CausalError:
                # a torn journal VALUE (valid JSON, poisoned payload)
                # cannot reach a merge — counted, skipped, loud in
                # the stream
                if obs.enabled():
                    obs.counter("serve.journal_rejects").inc()
                    obs.event("serve.journal_reject", seq=e.get("seq"),
                              uuid=uuid)
                continue
            from .ingest import _Entry

            # re-link the journey (PR 19): a journal row written by an
            # obs-on process carries its batch's trace ids — the
            # restored process continues those chains ("replay" hop,
            # ops re-bound for the lag join) instead of orphaning them
            traces = None
            if obs.enabled():
                raw = e.get("trace")
                if isinstance(raw, list):
                    traces = [str(tr) for tr in raw[:16]
                              if isinstance(tr, str) and tr]
                for tr in (traces or ()):
                    xtrace.hop("replay", tr, uuid=uuid,
                               seq=int(e["seq"]))
                    xtrace.bind_ops(
                        tr, [tuple(it[0]) for it in items])
            by_tenant.setdefault(uuid, []).append(
                _Entry(uuid, str(e.get("site")), items, len(items),
                       int(e["seq"]), int(e.get("ts_us") or 0),
                       traces=traces))
        ops = 0
        for uuid, batch in by_tenant.items():
            self._apply_batches(uuid, batch)
            ops += sum(x.ops for x in batch)
            if obs.enabled():
                # replayed entries never re-enter the queue (no tick
                # hop): the replay's own per-tenant wave is the
                # journey's next edge after "replay"
                seen: List[str] = []
                for x in batch:
                    for tr in (x.traces or ()):
                        if tr not in seen:
                            seen.append(tr)
                            xtrace.hop("wave", tr, uuid=uuid,
                                       path="replay")
        # torn/corrupt lines were COUNTED by the scan but invisible to
        # the dashboard until PR 15: any skip on a replay is evidence
        # (a torn tail is expected after a crash; CRC corruption never
        # is — both deserve an alert, not a buried counter)
        torn = int(getattr(journal, "skipped", 0) or 0)
        rot = int(getattr(journal, "corrupt", 0) or 0)
        if (torn or rot) and obs.enabled():
            obs.counter("serve.journal_torn").inc(torn + rot)
            obs.event("serve.journal_torn", skipped=torn, corrupt=rot,
                      journal=journal_path)
        if not borrowed:
            journal.close()
        return ops
