"""``python -m cause_tpu.serve`` — the storage scrubber CLI
(:mod:`cause_tpu.serve.scrub`). Jax-free: runs against a dead
service's directories from a bare operator shell."""

import sys

from .scrub import cli

if __name__ == "__main__":
    sys.exit(cli())
