"""Bounded-queue admission: the host side of the SafarDB offload split.

The admission queue is the seam where the service meets the world, and
it is designed around three refusals:

- **poison never enters the queue** — every offered payload runs
  ``sync.validate_node_items`` (plus the CRC when the frame carries
  one) AT THE BOUNDARY; a failing payload is rejected through the
  PR-11 offender machinery (``sync.note_reject`` → ``sync.reject``
  events, repeat offenders quarantined) and a quarantined site's
  offers are refused outright until the usual full-bag resync
  re-admits it. Validation happens once, here: everything downstream
  (journal, drain, replay) trusts admitted bytes.
- **admitted ops are never lost** — admission is WRITE-AHEAD: the op
  batch lands in the append-only ingest journal before the offer is
  acknowledged, so a crash at any later point replays it (idempotent:
  CRDT merges re-apply harmlessly and the PR-9 lamport watermark
  keeps converged ops out of the lag tracer). Only *unadmitted* work
  (deferred or rejected offers) can ever be shed.
- **overload is a declared policy, not an accident** — when depth
  crosses the ladder's watermarks the queue sheds in a fixed order:

  1. ``defer`` — offers for COLD tenants (below the hot-share
     threshold of the decaying per-tenant rate) are parked unadmitted
     in a bounded side buffer and promoted when depth falls;
  2. ``reject`` — at capacity (or when the deadline-aware estimate
     says the op would miss its admission deadline anyway), the offer
     is refused with a ``retry_after_ms`` hint;
  3. ``drop_oldest`` — the defer buffer overflowing drops its OLDEST
     *unadmitted* entry to make room.

  PR 15 adds an orthogonal ``durability`` rung: when the write-ahead
  journal itself refuses the append (ENOSPC, torn write — the chaos
  ``disk`` family or a real storage fault), the offer is refused with
  ``retry_after_ms`` instead of acknowledged — an unappendable
  journal must NEVER ack, or a crash would lose an "admitted" op.

  Every shed — every rung — is one evidenced ``serve.shed`` event
  plus counters, so ``scripts/serve_soak.py`` can gate "every shed
  evidenced" machine-to-machine against the queue's own stats.

Stdlib + ``cause_tpu.sync``/``serde`` only: admission is host work by
design (the accelerator owns merge, nothing else), and this module
must import without jax so a pure front-end process can run it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, Iterator, List, Optional

from .. import obs
from .. import sync
from ..collections import shared as s
from ..obs import xtrace

__all__ = ["Admission", "IngestJournal", "IngestQueue"]

# decaying per-tenant admission rate: half-life of the hotness score
_HOT_HALF_LIFE_S = 10.0
_HOT_MAX = 4096  # hotness registry LRU bound (entries)
_HOT_MEAN_TTL_US = 100_000  # cached fleet-mean hotness lifetime
# a tenant is COLD when its decayed score falls below this fraction of
# the mean tenant score (1.0 == exactly the fair share)
_COLD_FRAC = 0.5
# drain-rate EMA smoothing (per drain call)
_RATE_ALPHA = 0.3
# backpressure hint when the JOURNAL refuses the write and no drain
# rate is measured yet: storage faults are transient on the chaos
# timescale, so a short fixed retry beats no hint at all
_DURABILITY_RETRY_MS = 50.0


class Admission:
    """One offer's outcome. ``admitted`` with a journal ``seq`` on
    success; otherwise ``rung`` names the refusal (``"poison"`` /
    ``"quarantined"`` for boundary rejects, ``"defer"`` / ``"reject"``
    for sheds) and ``retry_after_ms`` carries the backpressure hint
    where one exists."""

    __slots__ = ("admitted", "seq", "rung", "reason", "retry_after_ms")

    def __init__(self, admitted: bool, seq: int = -1, rung: str = "",
                 reason: str = "", retry_after_ms: Optional[float] = None):
        self.admitted = admitted
        self.seq = seq
        self.rung = rung
        self.reason = reason
        self.retry_after_ms = retry_after_ms

    def __repr__(self):  # pragma: no cover - debugging nicety
        if self.admitted:
            return f"Admission(admitted, seq={self.seq})"
        return (f"Admission({self.rung}"
                + (f"/{self.reason}" if self.reason else "") + ")")


class IngestJournal:
    """The write-ahead ingest journal: one JSON line per admitted
    batch (``{"seq", "uuid", "site", "items", "ts_us"}``), O_APPEND +
    flush-per-append so a crashed process loses at most the torn
    trailing line it never acknowledged. ``iter_from`` replays
    entries above a watermark, skipping torn/garbage lines (counted,
    never silent)."""

    __slots__ = ("path", "_fh", "_seq", "_lock", "skipped")

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self.skipped = 0
        self._seq = 0
        # resume the seq counter past any existing journal (a restored
        # service appends to the same journal its checkpoint names)
        for e in self._scan():
            self._seq = max(self._seq, int(e.get("seq", 0)))
        self._fh = open(self.path, "a", encoding="utf-8")

    def _scan(self) -> Iterator[dict]:
        # ``skipped`` is the torn-line count of the LATEST scan, not a
        # lifetime accumulator — the constructor's seq-resume scan and
        # every replay walk the same file, and summing them would
        # over-report one torn line as several
        self.skipped = 0
        if not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    self.skipped += 1
                    continue
                if isinstance(e, dict) and "seq" in e:
                    yield e
                else:
                    self.skipped += 1

    def append(self, uuid: str, site: str, items: list,
               ts_us: Optional[int] = None,
               trace: Optional[list] = None) -> int:
        """Durably record one admitted batch; returns its seq. The
        write happens BEFORE the queue acknowledges admission — the
        no-admitted-op-lost contract hangs on that order. ``trace``
        (a list of trace ids, PR 19) is recorded only when given —
        obs-on callers pass it so replay re-links the journey; obs-off
        journal bytes stay pinned (scripts/obs_off_pin.py)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = {"seq": seq, "uuid": str(uuid), "site": str(site),
                   "items": items,
                   "ts_us": int(ts_us if ts_us is not None
                                else time.time_ns() // 1000)}
            if trace:
                rec["trace"] = list(trace)
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        return seq

    def iter_from(self, min_seq_exclusive: int = 0) -> Iterator[dict]:
        """Entries with ``seq > min_seq_exclusive``, journal order."""
        for e in self._scan():
            if int(e.get("seq", 0)) > int(min_seq_exclusive):
                yield e

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:  # pragma: no cover - close is best-effort
                pass


class _Entry:
    __slots__ = ("uuid", "site", "items", "ops", "seq", "ts_us",
                 "traces")

    def __init__(self, uuid, site, items, ops, seq, ts_us,
                 traces=None):
        self.uuid = uuid
        self.site = site
        self.items = items
        self.ops = ops
        self.seq = seq
        self.ts_us = ts_us
        # trace ids riding this batch (PR 19; None when obs is off)
        self.traces = traces


class IngestQueue:
    """The bounded admission queue (module docstring). Thread-safe:
    generators offer from their own threads while the service thread
    drains.

    ``max_ops`` bounds the ADMITTED depth (ops, not batches) — the
    structural guarantee the soak gates; ``defer_frac`` is the
    high-watermark fraction where cold-tenant deferral starts;
    ``defer_max`` bounds the unadmitted side buffer (entries);
    ``deadline_ms``, when set, refuses offers whose estimated queue
    wait already exceeds it (deadline-aware admission: shedding at
    the door beats admitting work that will miss its SLO anyway)."""

    def __init__(self, max_ops: int = 4096, defer_frac: float = 0.75,
                 defer_max: int = 256,
                 deadline_ms: Optional[float] = None,
                 journal: Optional[IngestJournal] = None,
                 tenant_known: Optional[Callable[[str], bool]] = None):
        if max_ops < 1:
            raise ValueError("max_ops must be >= 1")
        self.max_ops = int(max_ops)
        self.defer_watermark = max(1, int(defer_frac * max_ops))
        self.defer_max = int(defer_max)
        self.deadline_ms = deadline_ms
        self.journal = journal
        # optional tenant-existence predicate (SyncService wires its
        # registry in): an offer for a uuid nobody serves is refused
        # at the door — admitting it would journal an op no tenant
        # can ever apply
        self.tenant_known = tenant_known
        self._lock = threading.Lock()
        self._q: deque = deque()
        self._deferred: deque = deque()
        self._depth = 0              # admitted ops pending
        self._seq = 0                # journal-less fallback counter
        self._closed = False
        self._drain_ops_per_s = 0.0  # EMA, the deadline estimator
        # uuid -> [score, t_us]; LRU-bounded at _HOT_MAX (the repo's
        # every-registry-bounded invariant) — the LRU tail is by
        # construction the coldest claim, so evicting it never
        # promotes a hot tenant to "cold"
        self._hot: "OrderedDict[str, List[float]]" = OrderedDict()
        self._hot_mean = (None, 0)  # (cached mean, computed_at_us)
        self.stats = {
            "admitted_ops": 0, "admitted_batches": 0,
            "poison_rejects": 0, "quarantine_refusals": 0,
            "unknown_tenant_rejects": 0,
            "sheds": 0, "shed_ops": 0, "max_depth": 0,
            "shed_by_rung": {"defer": 0, "reject": 0,
                             "drop_oldest": 0, "durability": 0},
            "deferred_promoted": 0,
        }

    # ------------------------------------------------------- helpers

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    @property
    def deferred(self) -> int:
        with self._lock:
            return len(self._deferred)

    @property
    def closed(self) -> bool:
        return self._closed

    def _now_us(self, now_us):
        return int(now_us if now_us is not None
                   else time.time_ns() // 1000)

    def _touch_hot(self, uuid: str, ops: int, now_us: int) -> None:
        ent = self._hot.get(uuid)
        if ent is None:
            while len(self._hot) >= _HOT_MAX:
                self._hot.popitem(last=False)
            self._hot[uuid] = [float(ops), float(now_us)]
            return
        dt_s = max(0.0, (now_us - ent[1]) / 1e6)
        ent[0] = ent[0] * (0.5 ** (dt_s / _HOT_HALF_LIFE_S)) + ops
        ent[1] = float(now_us)
        self._hot.move_to_end(uuid)

    def _is_cold(self, uuid: str, now_us: int) -> bool:
        """Cold = decayed admission score below _COLD_FRAC of the mean
        tenant score. A tenant the queue has never seen is cold by
        definition (it has no claim on a congested queue yet).

        The mean is cached for ``_HOT_MEAN_TTL_US``: recomputing it is
        an O(registry) scan under the queue lock, and this method only
        runs on congested offers — exactly when offer latency matters
        most. Only the caller's own score is decayed per call (O(1));
        the mean moves on the half-life timescale, far slower than the
        TTL."""
        if not self._hot:
            return False
        mean, computed = self._hot_mean
        if mean is None or now_us - computed > _HOT_MEAN_TTL_US:
            total = 0.0
            for score, t in self._hot.values():
                total += score * (0.5 ** (max(0.0, (now_us - t) / 1e6)
                                          / _HOT_HALF_LIFE_S))
            mean = total / len(self._hot)
            self._hot_mean = (mean, now_us)
        ent = self._hot.get(uuid)
        mine = 0.0
        if ent is not None:
            mine = ent[0] * (0.5 ** (max(0.0, (now_us - ent[1]) / 1e6)
                                     / _HOT_HALF_LIFE_S))
        return mine < _COLD_FRAC * mean

    def _retry_after_ms(self, extra_ops: int = 0) -> Optional[float]:
        """How long until the queue has plausibly drained to its
        defer watermark — the backpressure hint a rejected producer
        should honor. None until a drain rate is measured."""
        if self._drain_ops_per_s <= 0:
            return None
        backlog = max(0, self._depth + extra_ops - self.defer_watermark)
        return round(1000.0 * backlog / self._drain_ops_per_s, 3)

    def _shed(self, rung: str, reason: str, uuid: str, site: str,
              ops: int, retry_after_ms: Optional[float] = None,
              traces=None) -> None:
        """The one funnel every shed goes through: stats + the
        evidenced ``serve.shed`` event. Called under the lock; the
        event emission is the obs no-op funnel (safe there)."""
        self.stats["sheds"] += 1
        self.stats["shed_ops"] += ops
        self.stats["shed_by_rung"][rung] += 1
        if obs.enabled():
            obs.counter("serve.sheds").inc()
            obs.counter("serve.shed_ops").inc(ops)
            fields = {"rung": rung, "reason": reason, "uuid": uuid,
                      "site": site, "ops": ops,
                      "depth": self._depth,
                      "deferred": len(self._deferred)}
            if retry_after_ms is not None:
                fields["retry_after_ms"] = retry_after_ms
            obs.event("serve.shed", **fields)
            # a shed ENDS the batch's journey — record where it died
            for tr in (traces or ()):
                xtrace.hop("shed", tr, rung=rung, reason=reason,
                           uuid=uuid, site=site)

    # ------------------------------------------------------ admission

    def offer(self, uuid: str, site: str, items: list,
              crc: Optional[int] = None,
              now_us: Optional[int] = None,
              traces: Optional[list] = None) -> Admission:
        """Offer one per-site delta batch (``serde.encode_node_items``
        wire form) for tenant ``uuid``. See the module docstring for
        the refusal ladder. Validation runs OUTSIDE the queue lock
        (it is O(ops) host work). ``traces`` (PR 19) carries the
        batch's trace ids from an upstream hop (the wire); with obs on
        and none given, admission MINTS one — every admitted batch has
        a causal identity."""
        uuid, site = str(uuid), str(site)
        now = self._now_us(now_us)
        # --- the trust boundary (poison never enters the queue)
        if sync.is_quarantined(site):
            with self._lock:
                self.stats["quarantine_refusals"] += 1
            if obs.enabled():
                obs.counter("serve.quarantine_refusals").inc()
            return Admission(False, rung="quarantined",
                             reason="site-quarantined")
        try:
            sync.validate_node_items(items)
            if crc is not None and sync.payload_checksum(items) != crc:
                raise s.CausalError(
                    "sync payload rejected",
                    {"causes": {"payload-checksum"},
                     "why": "checksum mismatch"})
        except s.CausalError as e:
            causes = e.info.get("causes", ("payload-invalid",))
            with self._lock:
                self.stats["poison_rejects"] += 1
            sync.note_reject(site, uuid=uuid, why=next(iter(causes)))
            return Admission(False, rung="poison",
                             reason=next(iter(causes)))
        if self.tenant_known is not None \
                and not self.tenant_known(uuid):
            # refuse at the door: an op for a uuid nobody serves must
            # not be journaled/acknowledged — it could never be
            # applied, and a crash replay would trip over it
            with self._lock:
                self.stats["unknown_tenant_rejects"] += 1
            if obs.enabled():
                obs.counter("serve.unknown_tenant_rejects").inc()
            return Admission(False, rung="reject",
                             reason="unknown-tenant")
        ops = len(items)
        if ops == 0:
            return Admission(True, seq=0)  # nothing to admit
        if obs.enabled() and not traces:
            # the Admission.offer mint point: a batch arriving with
            # no upstream context (local producer, not the wire).
            # Ops already bound in-process (the mutation funnel's
            # mint) continue THEIR traces — minting over them would
            # split one journey into two half-chains; only genuinely
            # unattributed batches get their causal identity here.
            # Past the trust boundary on purpose — poison earns no
            # trace.
            existing = xtrace.traces_of(it[0] for it in items)
            if existing:
                traces = existing[:16]
            else:
                tr = xtrace.new_trace()
                xtrace.hop("mint", tr, parent="", source="offer",
                           uuid=uuid, site=site, ops=ops)
                xtrace.bind_ops(tr, [it[0] for it in items])
                traces = [tr]
        with self._lock:
            if self._closed:
                # drain already started: admission is closed, the
                # producer retries against the restarted service
                self._shed("reject", "closed", uuid, site, ops,
                           traces=traces)
                return Admission(False, rung="reject", reason="closed")
            retry = self._retry_after_ms(ops)
            if (self.deadline_ms is not None and retry is not None
                    and retry > self.deadline_ms):
                # deadline-aware admission: the op would sit in the
                # queue past its own deadline — shed at the door
                self._shed("reject", "deadline", uuid, site, ops,
                           retry_after_ms=retry, traces=traces)
                return Admission(False, rung="reject",
                                 reason="deadline",
                                 retry_after_ms=retry)
            if self._depth + ops > self.max_ops:
                # rung 2: at capacity — reject with the hint
                self._shed("reject", "capacity", uuid, site, ops,
                           retry_after_ms=retry, traces=traces)
                return Admission(False, rung="reject",
                                 reason="capacity",
                                 retry_after_ms=retry)
            if self._depth >= self.defer_watermark \
                    and self._is_cold(uuid, now):
                # rung 1: the ADMITTED depth itself is past the
                # watermark (true congestion — never just an oversized
                # batch on a quiet queue, which must admit) and the
                # tenant is cold — park UNADMITTED; rung 3 drops the
                # oldest parked entry when the side buffer overflows.
                # A site's offers are cumulative, so a newer offer
                # SUPERSEDES its own parked entry (replaced, not
                # duplicated)
                if any(d.uuid == uuid and d.site == site
                       for d in self._deferred):
                    self._deferred = deque(
                        d for d in self._deferred
                        if not (d.uuid == uuid and d.site == site))
                elif len(self._deferred) >= self.defer_max:
                    old = self._deferred.popleft()
                    self._shed("drop_oldest", "defer-overflow",
                               old.uuid, old.site, old.ops,
                               traces=old.traces)
                self._deferred.append(
                    _Entry(uuid, site, items, ops, -1, now,
                           traces=traces))
                self._shed("defer", "cold-tenant", uuid, site, ops,
                           retry_after_ms=retry, traces=traces)
                return Admission(False, rung="defer",
                                 reason="cold-tenant",
                                 retry_after_ms=retry)
            return self._admit_locked(uuid, site, items, ops, now,
                                      traces=traces)

    def _admit_locked(self, uuid, site, items, ops, now,
                      traces=None) -> Admission:
        # a site's offers are cumulative: admitting this one makes any
        # parked older entry from the same (uuid, site) a strict
        # subset — drop it, or promotion would re-journal and
        # double-count ops already in the queue
        if self._deferred and any(d.uuid == uuid and d.site == site
                                  for d in self._deferred):
            self._deferred = deque(
                d for d in self._deferred
                if not (d.uuid == uuid and d.site == site))
        # WRITE-AHEAD: journal first, acknowledge after. An
        # unappendable journal must never ack — the durability rung
        # refuses the offer with a retry hint and the producer
        # re-offers once storage recovers (zero ADMITTED ops lost:
        # this op was never admitted)
        if obs.enabled():
            for tr in (traces or ()):
                xtrace.hop("admit", tr, uuid=uuid, site=site, ops=ops,
                           depth=self._depth)
        if self.journal is not None:
            try:
                seq = self.journal.append(uuid, site, items, ts_us=now,
                                          trace=traces)
            except (s.CausalError, OSError) as e:
                causes = getattr(e, "info", {}).get("causes", ())
                reason = next(iter(causes), "journal-error")
                retry = self._retry_after_ms(ops)
                if retry is None:
                    retry = _DURABILITY_RETRY_MS
                self._shed("durability", reason, uuid, site, ops,
                           retry_after_ms=retry, traces=traces)
                return Admission(False, rung="durability",
                                 reason=reason, retry_after_ms=retry)
            if obs.enabled():
                for tr in (traces or ()):
                    xtrace.hop("journal", tr, uuid=uuid, site=site,
                               seq=seq)
        else:
            self._seq += 1
            seq = self._seq
        self._q.append(_Entry(uuid, site, items, ops, seq, now,
                              traces=traces))
        self._depth += ops
        self._touch_hot(uuid, ops, now)
        self.stats["admitted_ops"] += ops
        self.stats["admitted_batches"] += 1
        if self._depth > self.stats["max_depth"]:
            self.stats["max_depth"] = self._depth
        if obs.enabled():
            obs.counter("serve.admitted_ops").inc(ops)
            obs.counter("serve.admitted_batches").inc()
            obs.gauge("serve.queue_depth").set(self._depth)
        return Admission(True, seq=seq)

    def close_admission(self) -> None:
        """Stop admitting (the drain's first step). Parked deferred
        entries remain eligible for promotion — they were offered in
        good faith and the drain flushes them if capacity allows."""
        with self._lock:
            self._closed = True

    def shed_stranded(self) -> int:
        """Drop every still-parked deferred entry with ``drop_oldest``
        evidence — the drain's last resort for entries that can never
        promote. They were never admitted (never journaled), so the
        no-admitted-op-loss contract is untouched. Returns entries
        shed."""
        n = 0
        with self._lock:
            while self._deferred:
                d = self._deferred.popleft()
                self._shed("drop_oldest", "drain-stranded",
                           d.uuid, d.site, d.ops, traces=d.traces)
                n += 1
        return n

    # ---------------------------------------------------------- drain

    def drain(self, max_ops: Optional[int] = None,
              now_us: Optional[int] = None) -> List[_Entry]:
        """Dequeue up to ``max_ops`` admitted ops (whole batches, FIFO)
        and, capacity permitting, promote deferred entries into
        admission. Updates the drain-rate EMA the deadline estimator
        reads."""
        now = self._now_us(now_us)
        out: List[_Entry] = []
        took = 0
        with self._lock:
            # the first batch always drains regardless of max_ops: a
            # single batch larger than the cap must degrade (one
            # oversized wave), never wedge the queue
            while self._q and (max_ops is None or took == 0
                               or took + self._q[0].ops <= max_ops):
                e = self._q.popleft()
                out.append(e)
                took += e.ops
                self._depth -= e.ops
            if took:
                # EMA over this drain's instantaneous rate: drained
                # ops against the elapsed span since the oldest
                # drained entry was admitted (coarse but stable)
                span_s = max(1e-3, (now - out[0].ts_us) / 1e6)
                inst = took / span_s
                self._drain_ops_per_s = (
                    inst if self._drain_ops_per_s == 0.0
                    else (1 - _RATE_ALPHA) * self._drain_ops_per_s
                    + _RATE_ALPHA * inst)
            # promotion: deferred entries admit once depth is back
            # under the watermark (FIFO — oldest deferred first). The
            # entry's own size is only checked against the HARD bound
            # (max_ops) — gating it on the watermark would starve a
            # parked batch larger than the remaining watermark slack
            # forever, even on an empty queue
            while self._deferred \
                    and self._depth < self.defer_watermark \
                    and self._depth + self._deferred[0].ops \
                    <= self.max_ops:
                d = self._deferred.popleft()
                adm = self._admit_locked(d.uuid, d.site, d.items,
                                         d.ops, now, traces=d.traces)
                self.stats["deferred_promoted"] += 1
                if obs.enabled():
                    obs.counter("serve.deferred_promoted").inc()
                # promoted entries are admitted but not drained this
                # call: the next drain picks them up in FIFO order
                assert adm.admitted
            if obs.enabled():
                obs.gauge("serve.queue_depth").set(self._depth)
        return out
